"""Headline benchmark: Llama training tokens/sec/chip on real TPU hardware.

The reference publishes no benchmark numbers (BASELINE.md — `published: {}`);
the north-star target from BASELINE.json is MaxText-class Llama throughput at
≥40% MFU. So ``vs_baseline`` reports **measured MFU / 0.40** — 1.0 means the
north-star MFU target is met on this chip.

Prints ONE JSON line:
  {"metric": ..., "value": tokens/s/chip, "unit": ..., "vs_baseline": ...}

Usage:
  python bench.py                    # full bench on the available accelerator
  python bench.py --preset tiny --platform cpu   # seconds-fast smoke
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="llama3-1b")
    parser.add_argument("--batch", type=int, default=0, help="0 = auto")
    parser.add_argument("--seq", type=int, default=0, help="0 = preset default")
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--warmup", type=int, default=2)
    parser.add_argument("--platform", default="", help="force jax platform")
    args = parser.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import dataclasses

    from tpu_docker_api.models.llama import llama_presets, param_count
    from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
    from tpu_docker_api.scheduler.topology import GENERATIONS
    from tpu_docker_api.train.trainer import (
        create_train_state,
        make_train_step,
        synthetic_batch,
    )

    preset = args.preset
    devices = jax.devices()[:1]  # tokens/sec **per chip**: bench on one
    platform = devices[0].platform
    on_tpu = platform == "tpu"
    # measured-optimal single-v5e batch per TPU preset (params + adam state
    # + activations must fit 16GB HBM): llama3-1b fits batch 4 since the
    # lean-remat/dense-lse memory work (13.0k tok/s vs 12.4k at batch 2;
    # batch 5+ OOM); 350m peaks at 8 (41.2k tok/s vs 39.0k at 16)
    tpu_preset_batch = {"llama3-1b": 4, "bench-350m": 8}
    if not on_tpu and preset in tpu_preset_batch:
        preset = "tiny"  # CPU fallback so the bench runs without hardware

    cfg = llama_presets()[preset]
    if args.seq:
        cfg = dataclasses.replace(cfg, max_seq_len=args.seq)
        seq = args.seq
    else:
        seq = min(cfg.max_seq_len, 2048)
    batch = args.batch or (tpu_preset_batch.get(preset, 8) if on_tpu else 2)

    mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=1), devices=devices)
    state, opt = create_train_state(cfg, mesh, jax.random.PRNGKey(0))
    n_params = param_count(state.params)
    step_fn = make_train_step(cfg, mesh, opt)

    tokens = synthetic_batch(jax.random.PRNGKey(1), batch, seq, cfg.vocab_size)

    t_compile = time.perf_counter()
    for _ in range(max(args.warmup, 1)):  # ≥1: the first step compiles
        state, metrics = step_fn(state, tokens)
    # host read, not block_until_ready: remote-tunnel platforms have been
    # seen returning from block_until_ready before execution finishes, which
    # inflates throughput ~1000x; a device→host value transfer cannot lie
    float(metrics["loss"])
    compile_s = time.perf_counter() - t_compile

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = step_fn(state, tokens)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0

    steps_per_s = args.steps / dt
    tokens_per_s = steps_per_s * batch * seq
    flops_per_token = cfg.flops_per_token(seq)
    achieved_flops = tokens_per_s * flops_per_token

    # peak flops for the chip actually benched
    device_kind = getattr(devices[0], "device_kind", "").lower()
    peak = None
    for gen_key, gen in GENERATIONS.items():
        probe = {"v5e": ("v5 lite", "v5e"), "v5p": ("v5p",), "v4": ("v4",),
                 "v6e": ("v6", "trillium"), "v3": ("v3",), "v2": ("v2",)}
        if any(p in device_kind for p in probe.get(gen_key, ())):
            peak = gen.peak_bf16_flops
            break
    if peak is None:
        peak = GENERATIONS["v5e"].peak_bf16_flops if on_tpu else 1e12
    mfu = achieved_flops / peak

    result = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_s, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {
            "preset": preset,
            "params": n_params,
            "batch": batch,
            "seq": seq,
            "steps_per_sec": round(steps_per_s, 4),
            "mfu": round(mfu, 4),
            "model_tflops_per_sec": round(achieved_flops / 1e12, 2),
            "compile_plus_warmup_s": round(compile_s, 1),
            "platform": platform,
            "device_kind": getattr(devices[0], "device_kind", ""),
            "final_loss": round(final_loss, 4),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
