"""Headline benchmark: Llama training tokens/sec/chip on real TPU hardware.

The reference publishes no benchmark numbers (BASELINE.md — `published: {}`);
the north-star target from BASELINE.json is MaxText-class Llama throughput at
≥40% MFU. So ``vs_baseline`` reports **measured MFU / 0.40** — 1.0 means the
north-star MFU target is met on this chip.

Prints ONE JSON line:
  {"metric": ..., "value": tokens/s/chip, "unit": ..., "vs_baseline": ...}

Usage:
  python bench.py                    # full bench on the available accelerator
  python bench.py --preset tiny --platform cpu   # seconds-fast smoke
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def measure_control_plane(iters: int = 100, runtime: str = "fake") -> dict:
    """create→ready latency through the full HTTP stack (BASELINE.md target
    row "Container create→ready latency p50"), on the REAL daemon wiring
    (daemon.Program, so the bench can never drift from production config).

    Each iteration POSTs /containers, confirms the runtime reports Running
    via GET, then deletes. The default fake runtime measures the control
    plane's own overhead (4-chip flow, exercising the slice scheduler);
    ``runtime="docker"`` drives dockerd with the CARDLESS flow (chipCount 0
    — no /dev/accel* nodes required) and needs ``busybox:latest`` already
    present locally (the adapter does not pull images)."""
    import statistics
    import urllib.request

    from tpu_docker_api.config import Config
    from tpu_docker_api.daemon import Program

    if iters < 2:
        raise ValueError(f"need iters >= 2 for quantiles, got {iters}")
    on_docker = runtime == "docker"
    prog = Program(Config(
        port=0, store_backend="memory",
        runtime_backend="docker" if on_docker else "fake",
        start_port=41000, end_port=41999, health_watch_interval=0,
    ), host="127.0.0.1")
    prog.init()
    prog.start()
    image = "busybox:latest" if on_docker else "jax"

    def call(method, path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{prog.api_server.port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            out = json.loads(resp.read())
        if out["code"] != 200:
            raise RuntimeError(f"{method} {path}: {out}")
        return out

    lat_ms = []
    created: set[str] = set()
    try:
        for i in range(iters):
            name = f"cp{i}"
            body = {"imageName": image, "containerName": name,
                    "chipCount": 0 if on_docker else 4,
                    "cmd": ["sleep", "60"] if on_docker else []}
            t0 = time.perf_counter()
            call("POST", "/api/v1/containers", body)
            created.add(f"{name}-0")
            info = call("GET", f"/api/v1/containers/{name}-0")
            if not (info["data"]["runtime"] or {}).get("running"):
                raise RuntimeError(f"{name}-0 not running after create")
            lat_ms.append((time.perf_counter() - t0) * 1e3)
            call("DELETE", f"/api/v1/containers/{name}-0", {
                "force": True, "delEtcdInfoAndVersionRecord": True})
            created.discard(f"{name}-0")
    finally:
        # a mid-loop failure must not strand real containers in dockerd
        # (they would break every later run with ContainerExisted)
        for leftover in created:
            try:
                prog.runtime.container_remove(leftover, force=True)
            except Exception:
                pass
        prog.stop()
    qs = statistics.quantiles(lat_ms, n=20)
    return {
        "iters": iters,
        "runtime": runtime,
        "create_ready_ms_p50": round(statistics.median(lat_ms), 2),
        "create_ready_ms_p95": round(qs[18], 2),
        "create_ready_ms_max": round(max(lat_ms), 2),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="llama3-1b")
    parser.add_argument("--batch", type=int, default=0, help="0 = auto")
    parser.add_argument("--seq", type=int, default=0, help="0 = preset default")
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--warmup", type=int, default=2)
    parser.add_argument("--platform", default="", help="force jax platform")
    parser.add_argument("--control-plane", action="store_true",
                        help="bench create→ready latency only")
    parser.add_argument("--cp-runtime", default="fake",
                        choices=["fake", "docker"])
    parser.add_argument("--cp-iters", type=int, default=100)
    args = parser.parse_args()

    if args.control_plane:
        cp = measure_control_plane(args.cp_iters, args.cp_runtime)
        print(json.dumps({
            "metric": "container_create_ready_ms_p50",
            "value": cp["create_ready_ms_p50"],
            "unit": "ms",
            # the reference publishes no latency numbers (BASELINE.md) —
            # this metric exists to be measured, not compared
            "vs_baseline": 1.0,
            "extra": cp,
        }))
        return

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import dataclasses

    from tpu_docker_api.models.llama import llama_presets, param_count
    from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
    from tpu_docker_api.scheduler.topology import GENERATIONS
    from tpu_docker_api.train.trainer import (
        create_train_state,
        make_train_step,
        synthetic_batch,
    )

    preset = args.preset
    devices = jax.devices()[:1]  # tokens/sec **per chip**: bench on one
    platform = devices[0].platform
    on_tpu = platform == "tpu"
    # measured-optimal single-v5e batch per TPU preset (params + adam state
    # + activations must fit 16GB HBM): llama3-1b fits batch 4 since the
    # lean-remat/dense-lse memory work (13.0k tok/s vs 12.4k at batch 2;
    # batch 5+ OOM); 350m peaks at 8 (41.2k tok/s vs 39.0k at 16)
    tpu_preset_batch = {"llama3-1b": 4, "bench-350m": 8}
    if not on_tpu and preset in tpu_preset_batch:
        preset = "tiny"  # CPU fallback so the bench runs without hardware

    cfg = llama_presets()[preset]
    if args.seq:
        cfg = dataclasses.replace(cfg, max_seq_len=args.seq)
        seq = args.seq
    else:
        seq = min(cfg.max_seq_len, 2048)
    batch = args.batch or (tpu_preset_batch.get(preset, 8) if on_tpu else 2)

    mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=1), devices=devices)
    state, opt = create_train_state(cfg, mesh, jax.random.PRNGKey(0))
    n_params = param_count(state.params)
    step_fn = make_train_step(cfg, mesh, opt)

    tokens = synthetic_batch(jax.random.PRNGKey(1), batch, seq, cfg.vocab_size)

    t_compile = time.perf_counter()
    for _ in range(max(args.warmup, 1)):  # ≥1: the first step compiles
        state, metrics = step_fn(state, tokens)
    # host read, not block_until_ready: remote-tunnel platforms have been
    # seen returning from block_until_ready before execution finishes, which
    # inflates throughput ~1000x; a device→host value transfer cannot lie
    float(metrics["loss"])
    compile_s = time.perf_counter() - t_compile

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = step_fn(state, tokens)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0

    steps_per_s = args.steps / dt
    tokens_per_s = steps_per_s * batch * seq
    flops_per_token = cfg.flops_per_token(seq)
    achieved_flops = tokens_per_s * flops_per_token

    # peak flops for the chip actually benched
    from tpu_docker_api.scheduler.topology import peak_bf16_flops_for

    peak = peak_bf16_flops_for(devices[0])
    if peak is None:
        peak = GENERATIONS["v5e"].peak_bf16_flops if on_tpu else 1e12
    mfu = achieved_flops / peak

    result = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_s, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {
            "preset": preset,
            "params": n_params,
            "batch": batch,
            "seq": seq,
            "steps_per_sec": round(steps_per_s, 4),
            "mfu": round(mfu, 4),
            "model_tflops_per_sec": round(achieved_flops / 1e12, 2),
            "compile_plus_warmup_s": round(compile_s, 1),
            "platform": platform,
            "device_kind": getattr(devices[0], "device_kind", ""),
            "final_loss": round(final_loss, 4),
        },
    }
    # BASELINE.md's second metric (create→ready p50) rides along in extras
    # so the driver's BENCH artifact always records it
    try:
        result["extra"]["control_plane"] = measure_control_plane(50)
    except Exception as e:  # never let the latency rider sink the headline
        result["extra"]["control_plane"] = {"error": str(e)}
    if on_tpu:
        # the north-star model size (BASELINE.json 'Llama-8B tokens/sec/
        # chip'): int8 llama3-8b serving throughput on this chip. The
        # training state above is ~14 GB of HBM — free it first or the
        # 8 GB weight synthesis OOMs.
        import gc

        del state, metrics, step_fn, tokens
        gc.collect()
        try:
            result["extra"]["llama3_8b_int8_infer"] = measure_8b_inference()
        except Exception as e:
            result["extra"]["llama3_8b_int8_infer"] = {"error": str(e)[:200]}
        jax.clear_caches()  # drop the 8 GB serving weights + programs
        gc.collect()        # before the next rider
        result["extra"]["serving"] = measure_serving()
        jax.clear_caches()
        gc.collect()
        result["extra"]["families"] = measure_family_trains()
    print(json.dumps(result))


def measure_family_trains() -> dict:
    """Secondary family throughputs for the BENCH artifact: ViT-B/16
    (non-causal, MFU vs this chip's peak) and bench-moe (sparse, gather
    dispatch). Shared harness: train.benchlib.time_train_steps. Each
    family measures independently — one failing must not erase the other
    (same rule as check_8b_inference's per-batch OOM handling)."""
    import gc

    import jax

    from tpu_docker_api.scheduler.topology import peak_bf16_flops_for
    from tpu_docker_api.train.benchlib import time_train_steps
    from tpu_docker_api.train.trainer import synthetic_batch

    out = {}
    peak = peak_bf16_flops_for(jax.devices()[0]) or 197e12

    try:
        from tpu_docker_api.models.vit import vit_presets, vit_synthetic_batch

        vcfg = vit_presets()["vit-b16"]
        r = time_train_steps(
            vcfg, vit_synthetic_batch(jax.random.PRNGKey(1), 128, vcfg))
        ips = r["steps_per_sec"] * 128
        out["vit_b16"] = {"images_per_sec": round(ips),
                          "mfu": round(vcfg.flops_per_image() * ips / peak, 3)}
    except Exception as e:
        out["vit_b16"] = {"error": str(e)[:160]}
    gc.collect()

    try:
        from tpu_docker_api.models.encdec import (
            encdec_presets, encdec_synthetic_batch)

        ecfg = encdec_presets()["encdec-base"]
        r = time_train_steps(
            ecfg, encdec_synthetic_batch(jax.random.PRNGKey(1), 32, 512,
                                         512, ecfg), steps=6)
        pairs = r["steps_per_sec"] * 32
        out["encdec_base"] = {
            "pairs_per_sec": round(pairs, 1),
            "mfu": round(ecfg.flops_per_pair(512, 512) * pairs / peak, 3)}
    except Exception as e:
        out["encdec_base"] = {"error": str(e)[:160]}
    gc.collect()

    try:
        import dataclasses as _dc

        from tpu_docker_api.models.moe import moe_presets

        mcfg = moe_presets()["bench-moe"]
        r = time_train_steps(
            mcfg, synthetic_batch(jax.random.PRNGKey(1), 8, 2048,
                                  mcfg.vocab_size), steps=6)
        tok_s = r["steps_per_sec"] * 8 * 2048
        # MFU by MODEL flops (flops_per_token counts only the top_k
        # active experts — hand-audited r3: wq/wk+wv/wo, router 2dE,
        # top_k×3 SwiGLU matmuls, causal attn, lm_head, ×3 fwd+bwd)
        out["bench_moe"] = {
            "tokens_per_sec": round(tok_s),
            "mfu": round(mcfg.flops_per_token(2048) * tok_s / peak, 3),
            "dispatch": "gather (single-device)"}
        # the multi-device dispatch form (one-hot einsum = the GSPMD
        # all-to-all path): single-device proxy recorded alongside, per
        # VERDICT r2 weak #5 — its hardware flops are n_experts/top_k
        # higher, so this model-flops MFU deliberately reads lower
        ecfg = _dc.replace(mcfg, dispatch_impl="einsum")
        re = time_train_steps(
            ecfg, synthetic_batch(jax.random.PRNGKey(1), 8, 2048,
                                  mcfg.vocab_size), steps=6)
        etok_s = re["steps_per_sec"] * 8 * 2048
        out["bench_moe"]["einsum_path"] = {
            "tokens_per_sec": round(etok_s),
            "mfu": round(mcfg.flops_per_token(2048) * etok_s / peak, 3)}
    except Exception as e:
        out["bench_moe"] = {"error": str(e)[:160]}
    gc.collect()
    # round 4: the "sort" (dense-packed, ep-constrained) mesh form —
    # single-device proxy; on one chip its math is gather + no-op
    # constraints, so ≈gather here is the claim that the MESH path no
    # longer needs the einsum form's (t, E, C) tensors (honest caveat:
    # multi-chip ICI behavior is not measurable in this environment —
    # dryrun proves compile+run, not speed). Own try-block: a sort
    # failure must not erase the gather/einsum numbers above.
    try:
        import dataclasses as _dc

        from tpu_docker_api.models.moe import moe_presets

        mcfg = moe_presets()["bench-moe"]
        scfg = _dc.replace(mcfg, dispatch_impl="sort")
        rs = time_train_steps(
            scfg, synthetic_batch(jax.random.PRNGKey(1), 8, 2048,
                                  mcfg.vocab_size), steps=6)
        stok_s = rs["steps_per_sec"] * 8 * 2048
        if isinstance(out.get("bench_moe"), dict):
            out["bench_moe"]["sort_path"] = {
                "tokens_per_sec": round(stok_s),
                "mfu": round(mcfg.flops_per_token(2048) * stok_s / peak,
                             3)}
    except Exception as e:
        if isinstance(out.get("bench_moe"), dict):
            out["bench_moe"]["sort_path"] = {"error": str(e)[:160]}
    gc.collect()

    try:
        from tpu_docker_api.infer.servebench import bench_moe_serving

        out["moe_serving"] = bench_moe_serving()
    except Exception as e:
        out["moe_serving"] = {"error": str(e)[:160]}
    gc.collect()
    return out


def measure_8b_inference() -> dict:
    """llama3-8b int8 serving throughput at the batch-64 throughput point
    (shared harness: infer/quantize.bench_int8_serving; validate_tpu.py's
    check_8b_inference covers the batch-4 latency point too), plus the
    decode-only roofline (VERDICT r2 item 2: decode_only_ms_per_tok and
    % of the weight-streaming HBM roof)."""
    from tpu_docker_api.infer.quantize import bench_int8_serving
    from tpu_docker_api.infer.servebench import bench_decode_roofline

    res = bench_int8_serving(batch=64, reps=2, fuse=True)
    res.pop("ok")
    try:
        # round 4: FUSED projections are the headline (bit-identical
        # math, fewer dispatches — measured 20.9 → 15.1 ms/tok, 50 →
        # 69% of roof on 2026-07 v5e); the unfused number rides along
        # for the cross-round comparison
        import gc as _gc

        import jax as _jax

        roof = bench_decode_roofline(batch=64, prompt_len=128, new_tok=64,
                                     max_seq=512, reps=2, fuse=True)
        for k in ("decode_only_ms_per_tok", "decode_tok_s", "pct_hbm_roof"):
            res[k] = roof[k]
        _jax.clear_caches()
        _gc.collect()
        unf = bench_decode_roofline(batch=64, prompt_len=128, new_tok=64,
                                    max_seq=512, reps=2)
        res["unfused"] = {
            k: unf[k] for k in ("decode_only_ms_per_tok", "decode_tok_s",
                                "pct_hbm_roof")}
    except Exception as e:
        res["roofline_error"] = str(e)[:160]
    return res


def measure_serving() -> dict:
    """Continuous-batching serving riders (VERDICT r2 item 1): aggregate
    tok/s of 8 concurrent streams through the slot engine vs the same 8
    serialized through the round-2 gen_lock path — llama3-1b bf16 and the
    llama3-8b int8 north star. Each point independent (per-point error
    reporting, same rule as the other riders)."""
    import gc

    from tpu_docker_api.infer.servebench import bench_concurrent_serving

    import jax

    out = {}
    for name, kwargs in (
        ("llama3_1b", dict(preset="llama3-1b", quantize=False, streams=8)),
        ("llama3_1b_16streams",
         dict(preset="llama3-1b", quantize=False, streams=16)),
        ("llama3_8b_int8",
         dict(preset="llama3-8b", quantize=True, streams=8)),
        ("llama3_8b_int8_16streams",
         dict(preset="llama3-8b", quantize=True, streams=16)),
    ):
        try:
            r = bench_concurrent_serving(
                prompt_len=128, new_tok=64, max_seq=512,
                chunk=8, fuse=True, **kwargs)
            r.pop("ok")
            out[name] = r
        except Exception as e:
            out[name] = {"error": str(e)[:160]}
        # free the point's compiled executables + their server-side
        # buffers before the next one: four points' accumulated caches
        # on a 16 GB chip have been seen starving the 8B engines into
        # allocator thrash (measured 18.8 tok/s on an otherwise-490
        # point). Costs a recompile per point; reliability wins.
        jax.clear_caches()
        gc.collect()
    # prefix caching (round 3): shared-header workload, suffix-only
    # prefill vs full prefill through the same slot engine
    try:
        from tpu_docker_api.infer.servebench import bench_prefix_serving

        r = bench_prefix_serving(preset="llama3-1b", requests=16,
                                 prefix_len=960, suffix_len=16, new_tok=8,
                                 max_seq=1024, slots=8, chunk=8, reps=2)
        r.pop("ok")
        out["llama3_1b_prefix_cache"] = r
    except Exception as e:
        out["llama3_1b_prefix_cache"] = {"error": str(e)[:160]}
    jax.clear_caches()
    gc.collect()
    # chunked prefill (round 3): max inter-token stall a long admission
    # inflicts on an active stream, whole vs segmented
    try:
        from tpu_docker_api.infer.servebench import bench_chunked_prefill

        r = bench_chunked_prefill(preset="llama3-1b", prompt_len=960,
                                  stream_new=96, chunk=8,
                                  prefill_chunk=128, max_seq=1024)
        r.pop("ok")
        out["llama3_1b_chunked_prefill"] = r
    except Exception as e:
        out["llama3_1b_chunked_prefill"] = {"error": str(e)[:160]}
    jax.clear_caches()
    gc.collect()
    # round 4 riders, each independent: paged capacity (the point the
    # dense cache cannot allocate), tail-latency SLO percentiles, and
    # seq2seq continuous batching
    try:
        from tpu_docker_api.infer.servebench import bench_paged_capacity

        r = bench_paged_capacity(preset="llama3-8b", streams=32,
                                 max_seq=3072, page_size=64,
                                 prompt_len=128, new_tok=64)
        r.pop("ok")
        out["llama3_8b_paged_capacity"] = r
    except Exception as e:
        out["llama3_8b_paged_capacity"] = {"error": str(e)[:160]}
    jax.clear_caches()
    gc.collect()
    try:
        from tpu_docker_api.infer.servebench import bench_tail_latency

        for streams in (8, 16):
            r = bench_tail_latency(preset="llama3-1b", streams=streams,
                                   n_requests=4 * streams,
                                   arrival_s=0.04, new_tok=48,
                                   max_seq=512, chunk=8)
            r.pop("ok")
            out[f"llama3_1b_tail_latency_{streams}s"] = r
            jax.clear_caches()
            gc.collect()
    except Exception as e:
        out["llama3_1b_tail_latency"] = {"error": str(e)[:160]}
    try:
        from tpu_docker_api.infer.servebench import (
            bench_encdec_slot_serving)

        r = bench_encdec_slot_serving(preset="encdec-base", streams=8,
                                      requests=16, src_len=128,
                                      new_tok=96, chunk=24)
        r.pop("ok")
        out["encdec_slot_serving"] = r
    except Exception as e:
        out["encdec_slot_serving"] = {"error": str(e)[:160]}
    jax.clear_caches()
    gc.collect()
    return out


if __name__ == "__main__":
    sys.exit(main())
