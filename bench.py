"""Headline benchmark: Llama training tokens/sec/chip on real TPU hardware.

The reference publishes no benchmark numbers (BASELINE.md — `published: {}`);
the north-star target from BASELINE.json is MaxText-class Llama throughput at
≥40% MFU. So ``vs_baseline`` reports **measured MFU / 0.40** — 1.0 means the
north-star MFU target is met on this chip.

Output contract (round 5 — VERDICT r4 item 1): MULTIPLE JSON lines, each
flushed the moment its measurement completes, each individually parseable
with the driver schema {"metric", "value", "unit", "vs_baseline"}:

  line 1:    the headline (train MFU + control-plane p50 in extra) — printed
             BEFORE any serving rider so a rider timeout can never erase it
             (BENCH_r04.json rc 124 erased everything; this fixes that class)
  lines 2..: one line per rider, flushed immediately
  last line: the headline re-printed with a compact {rider: value} digest —
             kept SMALL on purpose: BENCH_r03.json's `parsed: null` proved
             one giant line overflows the driver's bounded tail parse

A total time budget (env BENCH_BUDGET_S, default 1500) is enforced between
riders: when the remaining budget is smaller than a rider's estimated cost
the rider is skipped WITH an explicit line saying so, instead of running
into the driver's hard timeout and losing the artifact.

Usage:
  python bench.py                    # headline + core riders
  python bench.py --full             # + the long tail of riders (validate
                                     #   captures normally cover these)
  python bench.py --preset tiny --platform cpu   # seconds-fast smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time


def emit(obj: dict) -> None:
    """One compact JSON line, flushed immediately — the driver tails
    stdout, so every completed measurement must be durable the instant
    it exists, not buffered until the (possibly never-reached) end."""
    print(json.dumps(obj, separators=(",", ":")), flush=True)


def measure_control_plane(iters: int = 100, runtime: str = "fake") -> dict:
    """create→ready latency through the full HTTP stack (BASELINE.md target
    row "Container create→ready latency p50"), on the REAL daemon wiring
    (daemon.Program, so the bench can never drift from production config).

    Each iteration POSTs /containers, confirms the runtime reports Running
    via GET, then deletes. The default fake runtime measures the control
    plane's own overhead (4-chip flow, exercising the slice scheduler);
    ``runtime="docker"`` drives dockerd with the CARDLESS flow (chipCount 0
    — no /dev/accel* nodes required) and needs ``busybox:latest`` already
    present locally (the adapter does not pull images)."""
    import statistics
    import urllib.request

    from tpu_docker_api.config import Config
    from tpu_docker_api.daemon import Program

    if iters < 2:
        raise ValueError(f"need iters >= 2 for quantiles, got {iters}")
    on_docker = runtime == "docker"
    prog = Program(Config(
        port=0, store_backend="memory",
        runtime_backend="docker" if on_docker else "fake",
        start_port=41000, end_port=41999, health_watch_interval=0,
    ), host="127.0.0.1")
    prog.init()
    prog.start()
    image = "busybox:latest" if on_docker else "jax"

    def call(method, path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{prog.api_server.port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            out = json.loads(resp.read())
        if out["code"] != 200:
            raise RuntimeError(f"{method} {path}: {out}")
        return out

    lat_ms = []
    created: set[str] = set()
    try:
        for i in range(iters):
            name = f"cp{i}"
            body = {"imageName": image, "containerName": name,
                    "chipCount": 0 if on_docker else 4,
                    "cmd": ["sleep", "60"] if on_docker else []}
            t0 = time.perf_counter()
            call("POST", "/api/v1/containers", body)
            created.add(f"{name}-0")
            info = call("GET", f"/api/v1/containers/{name}-0")
            if not (info["data"]["runtime"] or {}).get("running"):
                raise RuntimeError(f"{name}-0 not running after create")
            lat_ms.append((time.perf_counter() - t0) * 1e3)
            call("DELETE", f"/api/v1/containers/{name}-0", {
                "force": True, "delEtcdInfoAndVersionRecord": True})
            created.discard(f"{name}-0")
    finally:
        # a mid-loop failure must not strand real containers in dockerd
        # (they would break every later run with ContainerExisted)
        for leftover in created:
            try:
                prog.runtime.container_remove(leftover, force=True)
            except Exception:
                pass
        prog.stop()
    qs = statistics.quantiles(lat_ms, n=20)
    return {
        "iters": iters,
        "runtime": runtime,
        "create_ready_ms_p50": round(statistics.median(lat_ms), 2),
        "create_ready_ms_p95": round(qs[18], 2),
        "create_ready_ms_max": round(max(lat_ms), 2),
    }


def measure_control_plane_churn(n_containers: int = 1000,
                                n_gangs: int = 100) -> dict:
    """Control-plane churn family (``--control-plane --cp-family churn``):
    create→ready→replace→delete for ``n_containers`` containers and
    ``n_gangs`` 4-host gangs through the full HTTP stack on the fake
    runtime, with the daemon's store wrapped in a ``CountingKV`` so every
    flow reports **store round trips** next to its latency quantiles.

    The audit phase then re-drives one instrumented iteration of each flow
    (work queue drained between snapshots, via the UNCOUNTED inner KV so
    the polling never pollutes the deltas) and self-gates the tentpole
    invariants: container create stays ≤ 3 atomic ``apply`` batches, and a
    gang's apply count is O(1) in its member count (a 4-host gang costs
    exactly what a 2-host gang costs). A violated gate flips
    ``gates.ok`` — main() turns that into a nonzero exit, so "batched"
    stays a measured invariant, not an adjective."""
    import statistics
    import urllib.request

    from tpu_docker_api.config import Config
    from tpu_docker_api.daemon import Program
    from tpu_docker_api.state import keys
    from tpu_docker_api.state.kv import CountingKV, MemoryKV
    from tpu_docker_api.state.workqueue import queue_depth

    if min(n_containers, n_gangs) < 2:
        raise ValueError("churn needs >= 2 iterations per flow for quantiles")
    counting = CountingKV(MemoryKV())
    prog = Program(Config(
        port=0, store_backend="memory", runtime_backend="fake",
        start_port=42000, end_port=43999, health_watch_interval=0,
        pod_hosts=(
            [{"host_id": "h0", "address": "10.0.0.1",
              "grid_coord": [0, 0, 0], "local": True}]
            + [{"host_id": f"h{i}", "address": f"10.0.0.{i + 1}",
                "grid_coord": [i, 0, 0], "runtime_backend": "fake"}
               for i in range(1, 4)]
        ),
    ), host="127.0.0.1", kv=counting)
    prog.init()
    prog.start()
    chips_per_host = prog.pod.chips_per_host

    def call(method, path, body=None, req_id=None):
        headers = {"Content-Type": "application/json"}
        if req_id:
            # the request id doubles as the trace id — the trace audit
            # below fetches each flow's span tree back by this name
            headers["X-Request-Id"] = req_id
        req = urllib.request.Request(
            f"http://127.0.0.1:{prog.api_server.port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers=headers)
        with urllib.request.urlopen(req) as resp:
            out = json.loads(resp.read())
        if out["code"] != 200:
            raise RuntimeError(f"{method} {path}: {out}")
        return out

    def drain(timeout_s: float = 10.0):
        """Wait for the async tail (copy/purge records) of the previous
        flow: queue empty AND journal empty. Polls the inner KV directly —
        the drain reads must never show up in a flow's counted delta."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if (queue_depth(prog.wq) == 0
                    and not counting.inner.range_prefix(
                        keys.QUEUE_TASKS_PREFIX)):
                return
            time.sleep(0.002)
        raise RuntimeError("work queue failed to drain within budget")

    def timed(fn):
        t0 = time.perf_counter()
        fn()
        return (time.perf_counter() - t0) * 1e3

    def container_cycle(name: str) -> tuple[float, float, float]:
        t_create = timed(lambda: call("POST", "/api/v1/containers", {
            "imageName": "jax", "containerName": name, "chipCount": 4,
            "containerPorts": [{"containerPort": 8080}]}))
        info = call("GET", f"/api/v1/containers/{name}-0")
        if not (info["data"]["runtime"] or {}).get("running"):
            raise RuntimeError(f"{name}-0 not running after create")
        t_replace = timed(lambda: call(
            "PATCH", f"/api/v1/containers/{name}-0/tpu", {"chipCount": 2}))
        t_delete = timed(lambda: call("DELETE", f"/api/v1/containers/{name}", {
            "force": True, "delEtcdInfoAndVersionRecord": True}))
        return t_create, t_replace, t_delete

    def gang_cycle(name: str, hosts: int) -> tuple[float, float]:
        t_create = timed(lambda: call("POST", "/api/v1/jobs", {
            "imageName": "jax", "jobName": name,
            "chipCount": chips_per_host * hosts}))
        info = call("GET", f"/api/v1/jobs/{name}")
        if info["data"].get("phase") not in ("running",):
            raise RuntimeError(f"gang {name} not running: {info['data']}")
        t_delete = timed(lambda: call("DELETE", f"/api/v1/jobs/{name}", {
            "force": True, "delStateAndVersionRecord": True}))
        return t_create, t_delete

    def quantiles(ms: list[float]) -> dict:
        # exclusive-method quantiles extrapolate past the sample extremes at
        # small n; clamp so p95 ≤ max always holds in the artifact
        qs = statistics.quantiles(ms, n=20)
        return {"p50": round(statistics.median(ms), 3),
                "p95": round(min(qs[18], max(ms)), 3),
                "max": round(max(ms), 3)}

    def audit(fn) -> dict:
        drain()
        before = counting.snapshot()
        fn()
        drain()
        return CountingKV.delta(before, counting.snapshot())

    c_lat: dict[str, list[float]] = {"create": [], "replace": [], "delete": []}
    g_lat: dict[str, list[float]] = {"create": [], "delete": []}
    try:
        for i in range(n_containers):
            cr, rp, dl = container_cycle(f"churn{i}")
            c_lat["create"].append(cr)
            c_lat["replace"].append(rp)
            c_lat["delete"].append(dl)
        for i in range(n_gangs):
            cr, dl = gang_cycle(f"gang{i}", hosts=4)
            g_lat["create"].append(cr)
            g_lat["delete"].append(dl)

        # round-trip audit: one quiesced iteration per flow
        rt: dict[str, dict] = {}
        rt["container_create"] = audit(lambda: call(
            "POST", "/api/v1/containers",
            {"imageName": "jax", "containerName": "audit", "chipCount": 4,
             "containerPorts": [{"containerPort": 8080}]}))
        rt["container_replace"] = audit(lambda: call(
            "PATCH", "/api/v1/containers/audit-0/tpu", {"chipCount": 2}))
        rt["container_delete"] = audit(lambda: call(
            "DELETE", "/api/v1/containers/audit",
            {"force": True, "delEtcdInfoAndVersionRecord": True}))
        for hosts in (2, 4):
            rt[f"gang_create_{hosts}host"] = audit(lambda: call(
                "POST", "/api/v1/jobs",
                {"imageName": "jax", "jobName": f"audit{hosts}",
                 "chipCount": chips_per_host * hosts}))
            rt[f"gang_delete_{hosts}host"] = audit(lambda: call(
                "DELETE", f"/api/v1/jobs/audit{hosts}",
                {"force": True, "delStateAndVersionRecord": True}))

        # -- trace audit: the completeness gate (ISSUE 14) -------------------
        # One traced iteration per flow, each request carrying an
        # X-Request-Id = trace id; the span tree is fetched back and gated:
        # exactly one root, child spans covering >= 80% of the root's wall
        # (no invisible time inside the handler), and the container
        # delete's async purge tail riding the SAME trace (the queue
        # journal carried the context past the HTTP response).
        def traced(flow, method, path, body=None):
            rid = f"trace-{flow}"
            t0 = time.perf_counter()
            call(method, path, body, req_id=rid)
            return rid, (time.perf_counter() - t0) * 1e3

        traced_flows = {}
        traced_flows["container_create"] = traced(
            "container_create", "POST", "/api/v1/containers",
            {"imageName": "jax", "containerName": "traudit", "chipCount": 4,
             "containerPorts": [{"containerPort": 8080}]})
        traced_flows["container_replace"] = traced(
            "container_replace", "PATCH", "/api/v1/containers/traudit-0/tpu",
            {"chipCount": 2})
        traced_flows["container_delete"] = traced(
            "container_delete", "DELETE", "/api/v1/containers/traudit",
            {"force": True, "delEtcdInfoAndVersionRecord": True})
        traced_flows["gang_create"] = traced(
            "gang_create", "POST", "/api/v1/jobs",
            {"imageName": "jax", "jobName": "traudit4",
             "chipCount": chips_per_host * 4})
        traced_flows["gang_delete"] = traced(
            "gang_delete", "DELETE", "/api/v1/jobs/traudit4",
            {"force": True, "delStateAndVersionRecord": True})
        drain()  # the async purge tail must have landed in its trace

        def trace_audit(rid: str, wall_ms: float) -> dict:
            spans = call("GET", f"/api/v1/traces/{rid}")["data"]["spans"]
            roots = [s for s in spans if s["isRoot"]]
            coverage = 0.0
            root_ms = 0.0
            if len(roots) == 1:
                root = roots[0]
                r0 = root["startMonoMs"]
                r1 = r0 + root["durationMs"]
                root_ms = root["durationMs"]
                ivs = sorted(
                    (max(s["startMonoMs"], r0),
                     min(s["startMonoMs"] + (s["durationMs"] or 0.0), r1))
                    for s in spans if s["parentId"] == root["spanId"])
                covered, cursor = 0.0, r0
                for a, b in ivs:
                    a = max(a, cursor)
                    if b > a:
                        covered += b - a
                        cursor = b
                coverage = covered / root_ms if root_ms > 0 else 1.0
            return {
                "traceId": rid, "spans": len(spans),
                "rooted": len(roots) == 1,
                "coverage": round(coverage, 4),
                "rootMs": round(root_ms, 3),
                "wallMs": round(wall_ms, 3),
                "asyncTailSpans": sum(
                    1 for s in spans
                    if s["name"].startswith("queue.task:")),
            }

        trace_flows = {flow: trace_audit(rid, wall)
                       for flow, (rid, wall) in traced_flows.items()}
        trace_stats = call("GET", "/api/v1/traces?limit=1")["data"]

        # disabled-mode overhead, by ACCOUNTING: measure what one span
        # site costs when tracing is off (a no-op scope / one context
        # read), multiply by the busiest flow's span count, and express
        # it against the measured create p50. A wall-clock A/B at the
        # <=1% level would gate on scheduler noise; the accounting bound
        # is deterministic and still non-vacuous (a disabled path that
        # grew real work fails it loudly).
        from tpu_docker_api.telemetry import trace as trace_mod

        probe = trace_mod.Tracer(buffer_size=4, enabled=False)
        reps = 20000
        t0 = time.perf_counter()
        for _ in range(reps):
            with probe.span("probe"):
                pass
        per_root_ms = (time.perf_counter() - t0) / reps * 1e3
        t0 = time.perf_counter()
        for _ in range(reps):
            with trace_mod.child("probe"):
                pass
        per_child_ms = (time.perf_counter() - t0) / reps * 1e3
        spans_per_flow = max(f["spans"] for f in trace_flows.values())
        disabled_overhead_ms = (per_root_ms
                                + (spans_per_flow - 1) * per_child_ms)

        # plus one real disabled-mode pass for the record (reported, not
        # gated: two tiny wall-clock runs differ by more than 1% noise)
        prog.tracer.set_enabled(False)
        disabled_ms = []
        for i in range(min(n_containers, 5)):
            cr, _, _ = container_cycle(f"trdis{i}")
            disabled_ms.append(cr)
        prog.tracer.set_enabled(True)
    finally:
        prog.stop()

    create_applies = rt["container_create"].get("apply", 0)
    gang_applies = rt["gang_create_4host"].get("apply", 0)
    # >= 1 keeps the gate honest: a write path that stopped routing
    # through the counted apply at all must FAIL, not pass vacuously
    gang_o1 = (gang_applies >= 1
               and rt["gang_create_2host"].get("apply", 0) == gang_applies)
    create_p50 = quantiles(c_lat["create"])["p50"]
    # the trace gate (ISSUE 14): every audited flow yields one rooted
    # trace, no invisible time (coverage >= 0.8), the async purge tail
    # rides the delete trace, and the disabled-mode accounting stays
    # under 1% of the flow p50
    coverage_worst = min(f["coverage"] for f in trace_flows.values())
    trace_rooted = all(f["rooted"] for f in trace_flows.values())
    async_tail = trace_flows["container_delete"]["asyncTailSpans"] >= 1
    overhead_pct = (disabled_overhead_ms / create_p50 * 100
                    if create_p50 > 0 else 0.0)
    trace_ok = bool(trace_rooted and coverage_worst >= 0.8 and async_tail
                    and overhead_pct <= 1.0)
    return {
        "family": "churn",
        "iters": {"containers": n_containers, "gangs": n_gangs},
        "create_ready_ms_p50": create_p50,
        "containers": {f"{flow}_ms_{q}": v
                       for flow, ms in c_lat.items()
                       for q, v in quantiles(ms).items()},
        "gangs": dict(
            {f"{flow}_ms_{q}": v
             for flow, ms in g_lat.items()
             for q, v in quantiles(ms).items()},
            members=4),
        "round_trips": rt,
        "trace": {
            "flows": trace_flows,
            "spans_per_flow_max": spans_per_flow,
            "disabled_span_cost_ms": round(per_root_ms, 6),
            "disabled_child_cost_ms": round(per_child_ms, 6),
            "disabled_overhead_ms": round(disabled_overhead_ms, 6),
            "disabled_create_ms_p50": round(
                statistics.median(disabled_ms), 3),
            "buffer_dropped": trace_stats["dropped"],
            "enabled": trace_stats["enabled"],
        },
        "gates": {
            "container_create_applies": create_applies,
            "container_create_applies_max": 3,
            "gang_apply_o1_in_members": gang_o1,
            "trace_rooted": trace_rooted,
            "trace_coverage_worst": round(coverage_worst, 4),
            "trace_coverage_min": 0.8,
            "trace_async_tail": async_tail,
            "trace_disabled_overhead_pct": round(overhead_pct, 4),
            "trace_disabled_overhead_budget_pct": 1.0,
            "trace_ok": trace_ok,
            "ok": bool(1 <= create_applies <= 3 and gang_o1 and trace_ok),
        },
    }


def measure_control_plane_failover(n_failovers: int = 5,
                                   ttl_s: float = 1.0) -> dict:
    """Control-plane failover family (``--control-plane --cp-family
    failover``): two HA daemons (``leader_election = true``,
    service/leader.py) over ONE shared store + fake runtime, with a churn
    worker issuing container create/delete cycles at the current leader the
    whole time. Each iteration HARD-kills the leader — heartbeat stopped
    with the lease left in place, API closed, writers halted, exactly what
    a SIGKILL leaves behind — and measures **time-to-recovered-writes**:
    kill to the first mutation the standby accepts AND commits after
    stealing the expired lease, replaying the dead leader's journal on the
    way up (docs/robustness.md "HA control plane").

    Self-gating like the churn family: every failover must recover, every
    deposed leader's epoch-fenced write must be REJECTED by the store
    (``errors.GuardFailed``), the fencing epoch must grow by exactly one
    per handoff, and recovery p95 must stay inside a generous
    TTL-derived budget. A violated gate flips ``gates.ok`` — main() turns
    that into a nonzero exit."""
    import statistics
    import threading
    import urllib.request

    from tpu_docker_api import errors
    from tpu_docker_api.config import Config
    from tpu_docker_api.daemon import Program
    from tpu_docker_api.runtime.fake import FakeRuntime
    from tpu_docker_api.state.kv import MemoryKV

    if n_failovers < 2:
        raise ValueError("failover needs >= 2 iterations for quantiles")
    kv = MemoryKV()
    runtime = FakeRuntime()

    def boot(holder: str) -> Program:
        prg = Program(Config(
            port=0, store_backend="memory", runtime_backend="fake",
            start_port=44000, end_port=44999, health_watch_interval=0,
            reconcile_interval=0, leader_election=True,
            leader_ttl_s=ttl_s, leader_id=holder,
        ), host="127.0.0.1", kv=kv, runtime=runtime)
        prg.init()
        prg.start()
        return prg

    def wait_leader(prg: Program, timeout_s: float = 10.0) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if prg.leader_elector.is_leader:
                return
            time.sleep(0.005)
        raise RuntimeError(f"{prg.leader_elector.holder_id} never acquired "
                           f"the lease within {timeout_s}s")

    def call(port: int, method, path, body=None, timeout=5.0):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            out = json.loads(resp.read())
        if out["code"] != 200:
            raise RuntimeError(f"{method} {path}: {out}")
        return out

    def hard_kill(prg: Program) -> None:
        """What SIGKILL leaves: the lease NOT released (the standby must
        wait out the TTL), no writer shutdown grace, API gone."""
        prg.leader_elector.close(release=False)
        prg.api_server.close()
        prg._stop_writers()

    # churn load: one container cycled at whichever daemon currently
    # leads; failures during the failover window are the point, not a
    # problem (a single reused name bounds any orphan to one container,
    # which the next cycle's delete — or the new leader's startup
    # reconcile — cleans up)
    leader_port = {"port": 0}
    stop_load = threading.Event()

    def churn_load() -> None:
        while not stop_load.is_set():
            port = leader_port["port"]
            try:
                call(port, "POST", "/api/v1/containers",
                     {"imageName": "jax", "containerName": "bench-load",
                      "chipCount": 1})
                call(port, "DELETE", "/api/v1/containers/bench-load",
                     {"force": True, "delEtcdInfoAndVersionRecord": True})
            except Exception:
                try:
                    call(port, "DELETE", "/api/v1/containers/bench-load",
                         {"force": True, "delEtcdInfoAndVersionRecord": True})
                except Exception:
                    pass
                stop_load.wait(0.01)

    leader = boot("bench-a")
    wait_leader(leader)
    standby = boot("bench-b")
    leader_port["port"] = leader.api_server.port
    load_thread = threading.Thread(target=churn_load, daemon=True)
    load_thread.start()

    hard_timeout_s = max(ttl_s * 10, 30.0)
    recoveries_ms: list[float] = []
    epochs: list[int] = []
    fenced_rejected = 0
    recovered_all = True
    try:
        for k in range(n_failovers):
            time.sleep(ttl_s / 2)  # let the churn worker actually churn
            t0 = time.perf_counter()
            hard_kill(leader)
            # first ACCEPTED+COMMITTED mutation on the survivor = recovery
            probe, recovered = f"fo{k}", False
            while time.perf_counter() - t0 < hard_timeout_s:
                try:
                    call(standby.api_server.port, "POST",
                         "/api/v1/containers",
                         {"imageName": "jax", "containerName": probe,
                          "chipCount": 1}, timeout=2.0)
                    recovered = True
                    break
                except Exception:
                    time.sleep(0.01)
            if not recovered:
                recovered_all = False
                break
            recoveries_ms.append((time.perf_counter() - t0) * 1e3)
            epochs.append(standby.leader_elector.epoch)
            leader_port["port"] = standby.api_server.port
            call(standby.api_server.port, "DELETE",
                 f"/api/v1/containers/{probe}",
                 {"force": True, "delEtcdInfoAndVersionRecord": True})
            # the deposed leader still believes it leads; the STORE must
            # reject its epoch-fenced write
            try:
                leader.kv.put("/apis/v1/bench/fence-probe", "stale")
            except errors.GuardFailed:
                fenced_rejected += 1
            except Exception:
                pass
            leader, standby = standby, boot(f"bench-{k}")
    finally:
        stop_load.set()
        load_thread.join(timeout=5)
        for prg in (leader, standby):
            try:
                prg.leader_elector.close(release=True)
                prg.api_server.close()
                prg._stop_writers()
            except Exception:
                pass

    if not recovered_all or not recoveries_ms:
        raise RuntimeError(
            f"failover {len(recoveries_ms)}: standby never recovered "
            f"writes within {hard_timeout_s}s")
    qs = statistics.quantiles(recoveries_ms, n=20)
    quants = {"p50": round(statistics.median(recoveries_ms), 3),
              "p95": round(min(qs[18], max(recoveries_ms)), 3),
              "max": round(max(recoveries_ms), 3)}
    epoch_monotonic = all(b == a + 1 for a, b in zip(epochs, epochs[1:]))
    # generous: expiry wait (ttl) + one renew interval of detection lag +
    # slack for writer boot, journal replay and a loaded CI host
    budget_ms = (ttl_s + ttl_s / 3.0 + 3.0) * 1e3
    return {
        "family": "failover",
        "iters": {"failovers": n_failovers},
        "ttl_s": ttl_s,
        "recovery_ms": quants,
        "recoveries_ms": [round(v, 3) for v in recoveries_ms],
        "epochs": epochs,
        "fenced": {"attempts": n_failovers, "rejected": fenced_rejected},
        "gates": {
            "recovered_all": recovered_all,
            "fenced_rejected_all": fenced_rejected == n_failovers,
            "epoch_monotonic": epoch_monotonic,
            "recovery_p95_budget_ms": round(budget_ms, 1),
            "ok": bool(recovered_all and fenced_rejected == n_failovers
                       and epoch_monotonic and quants["p95"] <= budget_ms),
        },
    }


def measure_control_plane_brownout(n_cycles: int = 12,
                                   latency_ms: float = 30.0,
                                   n_outages: int = 3,
                                   outage_s: float = 0.8,
                                   deadline_s: float = 2.0) -> dict:
    """Control-plane brownout family (``--control-plane --cp-family
    brownout``): ONE daemon (``leader_election = true`` so the informer
    mirror is live) over a :class:`~tpu_docker_api.state.faulty.FaultyKV`,
    churning containers through the full HTTP stack while the STORE — not
    a daemon, not an engine — is taken through the three acts of a real
    brownout (docs/robustness.md "Store brownouts"):

    1. **baseline** — healthy store, every churn cycle must land;
    2. **latency window** — every op slowed ``latency_ms``: a slow store
       is NOT a failure, every cycle must still land (the degraded-mode
       machinery must add zero false positives under mere slowness);
    3. **hard outage × heal, ``n_outages`` times** — every API call made
       mid-outage must RESOLVE (typed, bounded — never hang): GETs serve
       from the informer mirror with the staleness EXPLICITLY marked
       (envelope ``stale`` + ``X-Stale-Read``), mutations fail fast with
       the typed refusal (10506 + ``Retry-After``) or the single
       heal-probe's typed ``StoreUnavailable`` (10502); the steady gang
       pinned under the job supervisor must see ZERO engine calls (a
       store outage must never become a spurious gang restart); then the
       store heals and **time-to-recovered-writes** is measured from heal
       to the first accepted+committed mutation.

    Self-gating: all of the above as booleans, plus recovery p95 inside a
    probe-interval-derived budget and stale-read lag bounded by the outage
    duration. A violated gate flips ``gates.ok`` — main() turns that into
    a nonzero exit, so "rides through the store outage" stays a measured
    invariant, not an adjective."""
    import statistics
    import urllib.error
    import urllib.request

    from tpu_docker_api.config import Config
    from tpu_docker_api.daemon import Program
    from tpu_docker_api.runtime.fake import FakeRuntime
    from tpu_docker_api.state.faulty import FaultyKV
    from tpu_docker_api.state.kv import MemoryKV

    if n_cycles < 2 or n_outages < 2:
        raise ValueError("brownout needs >= 2 cycles and >= 2 outages "
                         "for quantiles")
    probe_interval_s = 0.2
    outage_grace_s = 0.25
    kv = FaultyKV(MemoryKV())
    runtime = FakeRuntime()
    prg = Program(Config(
        port=0, store_backend="memory", runtime_backend="fake",
        start_port=45000, end_port=45999, health_watch_interval=0,
        reconcile_interval=0, leader_election=True,
        # the lease must RIDE THROUGH the whole storm (renew failures are
        # typed and tolerated until expiry, and the short healthy gaps
        # between rounds can miss every ttl/3 renew tick): leadership
        # churn under a dead store is the failover family's subject, not
        # this one's
        leader_ttl_s=60.0, leader_id="bench-brownout",
        store_health_outage_grace_s=outage_grace_s,
        store_health_probe_interval_s=probe_interval_s,
    ), host="127.0.0.1", kv=kv, runtime=runtime)
    prg.init()
    prg.start()
    port = prg.api_server.port

    def call(method, path, body=None, timeout=deadline_s + 3.0):
        """Raw call: returns (app_code, headers, envelope) — outage-phase
        responses are typed refusals, not transport errors, so the
        non-200 app codes are data here, not exceptions."""
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                out = json.loads(resp.read())
                return out["code"], dict(resp.headers), out
        except urllib.error.HTTPError as e:
            out = json.loads(e.read())
            return out["code"], dict(e.headers), out

    def must(method, path, body=None):
        code, _, out = call(method, path, body)
        if code != 200:
            raise RuntimeError(f"{method} {path}: {out}")
        return out

    def cycle(name: str) -> float:
        t0 = time.perf_counter()
        must("POST", "/api/v1/containers",
             {"imageName": "jax", "containerName": name, "chipCount": 1})
        must("DELETE", f"/api/v1/containers/{name}",
             {"force": True, "delEtcdInfoAndVersionRecord": True})
        return (time.perf_counter() - t0) * 1e3

    def quants(ms: list[float]) -> dict:
        qs = statistics.quantiles(ms, n=20)
        return {"p50": round(statistics.median(ms), 3),
                "p95": round(min(qs[18], max(ms)), 3),
                "max": round(max(ms), 3)}

    deadline = time.monotonic() + 10.0
    while (not prg.leader_elector.is_leader
           and time.monotonic() < deadline):
        time.sleep(0.005)
    if not prg.leader_elector.is_leader:
        raise RuntimeError("brownout daemon never acquired the lease")

    recoveries_ms: list[float] = []
    outage_call_ms: list[float] = []
    stale_lags_ms: list[float] = []
    outage_codes: dict[str, int] = {}
    all_resolved = True
    mutations_typed = True
    stale_marked = True
    steady_untouched = True
    try:
        # a steady one-chip gang the supervisor owns for the whole run:
        # the canary for "a store outage must not restart healthy work"
        # (one chip, so the churn load beside it never starves). The
        # fresh leader refuses writes (10901) until its writer subsystems
        # finish booting — wait that window out, it is not under test
        t0 = time.monotonic()
        while True:
            code, _, out = call(
                "POST", "/api/v1/jobs",
                {"imageName": "jax", "jobName": "steady", "chipCount": 1})
            if code == 200:
                break
            if code != 10901 or time.monotonic() - t0 > 10.0:
                raise RuntimeError(f"steady gang create failed: {out}")
            time.sleep(0.02)
        steady = must("GET", "/api/v1/jobs/steady")["data"]
        if steady.get("phase") != "running":
            raise RuntimeError(f"steady gang not running: {steady}")

        baseline_ms = [cycle(f"bw{i}") for i in range(n_cycles)]

        kv.set_latency(latency_ms / 1e3)
        latency_cycles = max(n_cycles // 3, 4)
        latency_ms_samples = [cycle(f"lw{i}") for i in range(latency_cycles)]
        kv.set_latency(0.0)

        # staleness bound: a stale read's lag may never exceed how long
        # the storm has been running (plus pre-storm poll slack) — the
        # informer backoff can span a short heal window, so lag legally
        # accumulates ACROSS rounds, but never past the storm itself
        t_storm0 = time.monotonic()
        stale_margin_ms = 0.0
        probe_seq = 0
        for k in range(n_outages):
            engine_calls_before = len(runtime.calls)
            kv.set_outage(True)
            t0 = time.monotonic()
            while (prg.store_health.mode != "outage"
                   and time.monotonic() - t0 < 10.0):
                time.sleep(0.01)
            if prg.store_health.mode != "outage":
                raise RuntimeError(
                    f"outage {k}: mode stuck at {prg.store_health.mode}")
            hold_until = time.monotonic() + outage_s
            while time.monotonic() < hold_until:
                t = time.perf_counter()
                code, hdr, out = call("GET", "/api/v1/jobs/steady")
                wall = (time.perf_counter() - t) * 1e3
                outage_call_ms.append(wall)
                all_resolved &= wall <= (deadline_s + 1.0) * 1e3
                if code == 200 and out.get("stale"):
                    lag = float(out["stale"]["lagMs"])
                    stale_lags_ms.append(lag)
                    storm_ms = (time.monotonic() - t_storm0) * 1e3
                    stale_margin_ms = max(stale_margin_ms, lag - storm_ms)
                else:
                    stale_marked = False
                # unique name per attempt: a heal-probe mutation may have
                # HALF-landed (runtime container created, store write
                # refused) — reusing the name would collide on the orphan
                # and report the wrong error class
                probe_seq += 1
                t = time.perf_counter()
                code, hdr, out = call(
                    "POST", "/api/v1/containers",
                    {"imageName": "jax", "containerName": f"ow{probe_seq}",
                     "chipCount": 1})
                wall = (time.perf_counter() - t) * 1e3
                outage_call_ms.append(wall)
                all_resolved &= wall <= (deadline_s + 1.0) * 1e3
                outage_codes[str(code)] = outage_codes.get(str(code), 0) + 1
                mutations_typed &= code in (10502, 10506)
                time.sleep(0.05)
            # the canary: no engine mutation may have touched the steady
            # gang while the store was dark (inspect is not journaled)
            steady_untouched &= not any(
                name.startswith("steady")
                for _, name in runtime.calls[engine_calls_before:])
            t_heal = time.perf_counter()
            kv.set_outage(False)
            recovered = False
            probe = f"rw{k}"
            while time.perf_counter() - t_heal < 15.0:
                code, _, _ = call(
                    "POST", "/api/v1/containers",
                    {"imageName": "jax", "containerName": probe,
                     "chipCount": 1})
                if code == 200:
                    recovered = True
                    break
                time.sleep(0.01)
            if not recovered:
                raise RuntimeError(f"outage {k}: writes never recovered")
            recoveries_ms.append((time.perf_counter() - t_heal) * 1e3)
            must("DELETE", f"/api/v1/containers/{probe}",
                 {"force": True, "delEtcdInfoAndVersionRecord": True})

        # post-storm: the steady gang is still running and churn still lands
        final_ms = cycle("bwfinal")
        steady_after = must("GET", "/api/v1/jobs/steady")["data"]
        steady_alive = steady_after.get("phase") == "running"
        health = prg.store_health.status_view()
    finally:
        try:
            prg.leader_elector.close(release=True)
            prg.api_server.close()
            prg._stop_writers()
        except Exception:
            pass

    rq = quants(recoveries_ms)
    # recovery is driven by the heal probe: one probe slot to reach the
    # store and flip the mode, the probe itself IS the first accepted
    # mutation — probe interval + slack for a loaded CI host
    recovery_budget_ms = (probe_interval_s + 3.0) * 1e3
    # staleness can only accumulate while the store has been misbehaving:
    # each read's lag must stay within the storm's own elapsed time, plus
    # pre-storm watch-poll slack (the stale_margin_ms computed per read)
    stale_budget_ms = 3000.0
    stale_lag_ok = (bool(stale_lags_ms)
                    and stale_margin_ms <= stale_budget_ms)
    mode_healthy = health["mode"] == "healthy"
    outages_counted = health["outagesTotal"] == n_outages
    return {
        "family": "brownout",
        "iters": {"cycles": n_cycles, "latency_cycles": latency_cycles,
                  "outages": n_outages},
        "latency_ms_injected": latency_ms,
        "outage_s": outage_s,
        "deadline_s": deadline_s,
        "baseline_cycle_ms": quants(baseline_ms),
        "latency_cycle_ms": quants(latency_ms_samples),
        "final_cycle_ms": round(final_ms, 3),
        "outage_calls": len(outage_call_ms),
        "outage_call_ms": quants(outage_call_ms),
        "outage_mutation_codes": outage_codes,
        "stale_reads": len(stale_lags_ms),
        "stale_lag_ms_max": round(max(stale_lags_ms), 3) if stale_lags_ms
        else None,
        "stale_margin_ms": round(stale_margin_ms, 3),
        "recovery_ms": rq,
        "recoveries_ms": [round(v, 3) for v in recoveries_ms],
        "store_health": {k: health[k] for k in
                         ("mode", "outagesTotal", "opsOk",
                          "opsUnavailable", "staleReads")},
        "gates": {
            "all_calls_resolved": all_resolved,
            "mutations_typed": mutations_typed,
            "stale_reads_marked": stale_marked,
            "stale_lag_budget_ms": round(stale_budget_ms, 1),
            "stale_lag_bounded": stale_lag_ok,
            "steady_gang_untouched": steady_untouched,
            "steady_gang_alive": steady_alive,
            "mode_healed": mode_healthy,
            "outages_counted": outages_counted,
            "recovery_p95_budget_ms": round(recovery_budget_ms, 1),
            "ok": bool(all_resolved and mutations_typed and stale_marked
                       and stale_lag_ok and steady_untouched
                       and steady_alive and mode_healthy
                       and outages_counted
                       and rq["p95"] <= recovery_budget_ms),
        },
    }


def measure_control_plane_shard(n_cycles: int = 60, shard_count: int = 3,
                                ttl_s: float = 1.5,
                                store_rtt_ms: float = 40.0,
                                clients: int = 24,
                                speedup_min: float = 2.2) -> dict:
    """Control-plane shard family (``--control-plane --cp-family shard``):
    the sharded writer plane measured (service/shard.py, docs/robustness.md
    "Sharded writer plane"). Two cells over identical hardware and an
    identical store model: a classic single-leader daemon
    (``shard_count = 1``) versus a ``shard_count``-shard fleet — one real
    daemon per shard over ONE shared store — churning the same total
    number of chip-free container create/stop/delete cycles through the
    full HTTP stack, each mutation routed to its family's owning shard.

    The store is a MemoryKV wrapped with a modeled write round trip
    (``store_rtt_ms`` of GIL-free sleep per atomic apply — the fanout
    family's latency-injection idiom). That is the point, not a cheat: a
    raw MemoryKV commits in microseconds, so an unmodeled run measures
    Python request parsing, not the control plane. Against a real etcd's
    millisecond RTTs the binding constraint is the per-shard writer
    serialization — every version bump for a family holds that shard's
    version-map lock across a store round trip — and THAT is exactly the
    lock the shard map partitions. One shard ⇒ every family in the
    keyspace queues on one lock; N shards ⇒ N independent queues.

    Self-gating (ISSUE 17 acceptance): the sharded cell's churn
    throughput must reach ≥ 2.2× the single-shard cell (near-linear
    scaling for 3 shards), and a **blast-radius** phase hard-kills one
    shard's leader mid-load — survivor shards' writes must see ZERO
    failures with p95 inside budget while the victim shard recovers on a
    surviving daemon within a TTL-derived budget. A violated gate flips
    ``gates.ok``; main() turns that into a nonzero exit."""
    import queue as queue_mod
    import statistics
    import threading
    import urllib.request

    from tpu_docker_api.config import Config
    from tpu_docker_api.daemon import Program
    from tpu_docker_api.runtime.fake import FakeRuntime
    from tpu_docker_api.service.shard import ShardMap
    from tpu_docker_api.state.kv import MemoryKV

    if n_cycles < 2 or shard_count < 2:
        raise ValueError("shard family needs >= 2 cycles and >= 2 shards")

    class RttKV(MemoryKV):
        """MemoryKV plus a modeled write round trip: every atomic apply
        sleeps ``rtt`` OUTSIDE the store lock (concurrent writers overlap
        their round trips, exactly like concurrent etcd requests)."""

        def __init__(self, rtt_s: float) -> None:
            super().__init__()
            self._rtt_s = rtt_s

        def _apply(self, ops, guards=None):
            time.sleep(self._rtt_s)
            super()._apply(ops, guards)

    smap = ShardMap(shard_count)

    def names_for_shard(shard: int, tag: str, n: int) -> list[str]:
        out, i = [], 0
        while len(out) < n:
            name = f"{tag}{i}"
            i += 1
            if smap.shard_of(name) == shard:
                out.append(name)
        return out

    def boot(kv, runtime, holder: str, shards: int,
             preferred: tuple = ()) -> Program:
        prg = Program(Config(
            port=0, store_backend="memory", runtime_backend="fake",
            start_port=45000, end_port=45999, health_watch_interval=0,
            host_probe_interval_s=0, reconcile_interval=0,
            job_supervise_interval=0, leader_election=True,
            leader_ttl_s=ttl_s, leader_id=holder,
            shard_count=shards, shard_preferred=list(preferred),
            shard_standby_delay_s=(60.0 if shards > 1 else 0.0),
        ), host="127.0.0.1", kv=kv, runtime=runtime)
        prg.init()
        prg.start()
        return prg

    def call(port: int, method, path, body=None, timeout=10.0):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            out = json.loads(resp.read())
        if out["code"] != 200:
            raise RuntimeError(f"{method} {path}: {out}")
        return out

    def wait_ready(port: int, probe: str, timeout_s: float = 30.0) -> None:
        """A daemon is ready when it ACCEPTS a mutation for a family it
        owns (503s while the shard lease + writer boot settle)."""
        deadline = time.monotonic() + timeout_s
        last: Exception | None = None
        while time.monotonic() < deadline:
            try:
                call(port, "POST", "/api/v1/containers", {
                    "imageName": "jax", "containerName": probe,
                    "chipCount": 0})
                call(port, "DELETE", f"/api/v1/containers/{probe}", {
                    "force": True, "delEtcdInfoAndVersionRecord": True})
                return
            except Exception as e:  # noqa: BLE001 — 503 until the lease
                # and writer boot settle
                last = e
                time.sleep(0.02)
        raise RuntimeError(f"daemon on :{port} never accepted a {probe} "
                           f"mutation within {timeout_s}s (last: {last})")

    def cycle(port: int, base: str) -> None:
        call(port, "POST", "/api/v1/containers", {
            "imageName": "jax", "containerName": base, "chipCount": 0})
        call(port, "POST", f"/api/v1/containers/{base}-0/stop")
        call(port, "DELETE", f"/api/v1/containers/{base}", {
            "force": True, "delEtcdInfoAndVersionRecord": True})

    def run_cell(port_of_shard: dict, work: list) -> tuple[float, list]:
        """Drive ``work`` (fresh family names) through ``clients`` client
        threads, each mutation at its family's owning daemon. Returns
        (wall seconds, errors)."""
        qq = queue_mod.Queue()
        for base in work:
            qq.put(base)
        errs: list[str] = []

        def worker():
            while True:
                try:
                    base = qq.get_nowait()
                except queue_mod.Empty:
                    return
                try:
                    cycle(port_of_shard[smap.shard_of(base)], base)
                except Exception as e:  # noqa: BLE001 — a failed cycle is
                    # itself a finding, reported via the gate
                    errs.append(f"{base}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0, errs

    total_cycles = n_cycles * shard_count
    rtt_s = store_rtt_ms / 1e3
    results: dict = {}
    cleanup: list[Program] = []
    try:
        # -- cell 1: the classic single-leader plane ------------------------------
        one = boot(RttKV(rtt_s), FakeRuntime(), "bench-one", shards=1)
        cleanup.append(one)
        wait_ready(one.api_server.port, "probeone")
        ports_one = {s: one.api_server.port for s in range(shard_count)}
        work_one = [f"one{i}" for i in range(total_cycles)]
        wall_one, errs_one = run_cell(ports_one, work_one)

        # -- cell 2: one daemon per shard over ONE shared store -------------------
        kv3, rt3 = RttKV(rtt_s), FakeRuntime()
        fleet = [boot(kv3, rt3, f"bench-s{s}", shards=shard_count,
                      preferred=(s,)) for s in range(shard_count)]
        cleanup.extend(fleet)
        ports = {}
        for s, prg in enumerate(fleet):
            wait_ready(prg.api_server.port, names_for_shard(
                s, f"probes{s}x", 1)[0])
            ports[s] = prg.api_server.port
        # interleave round-robin across shards: the work queue is FIFO, so
        # a shard-grouped list would drain shard 0 completely before shard
        # 1 sees load — serializing the very parallelism under test
        per_shard = [names_for_shard(s, f"sh{s}x", n_cycles)
                     for s in range(shard_count)]
        work_sharded = [n for group in zip(*per_shard) for n in group]
        wall_sh, errs_sh = run_cell(ports, work_sharded)

        rate_one = total_cycles / wall_one
        rate_sh = total_cycles / wall_sh
        speedup = rate_sh / rate_one

        # -- blast radius: hard-kill one shard's leader mid-load ------------------
        victim_shard = shard_count - 1
        survivors = [s for s in range(shard_count) if s != victim_shard]
        surv_stats = {"lat_ms": [], "failures": 0, "requests": 0}
        surv_mu = threading.Lock()
        stop_load = threading.Event()

        def survivor_churn(shard: int) -> None:
            pool = names_for_shard(shard, f"blast{shard}x", 4000)
            k = 0
            while not stop_load.is_set():
                base, k = pool[k], k + 1
                t0 = time.perf_counter()
                try:
                    cycle(ports[shard], base)
                except Exception:  # noqa: BLE001
                    with surv_mu:
                        surv_stats["failures"] += 1
                else:
                    with surv_mu:
                        surv_stats["lat_ms"].append(
                            (time.perf_counter() - t0) * 1e3)
                with surv_mu:
                    surv_stats["requests"] += 1

        load = [threading.Thread(target=survivor_churn, args=(s,),
                                 daemon=True) for s in survivors]
        for t in load:
            t.start()
        time.sleep(0.5)  # steady churn before the kill

        victim = fleet[victim_shard]
        # what SIGKILL leaves behind: lease NOT released, API gone
        victim.shard_plane.close(release=False)
        victim.api_server.close()

        hard_timeout_s = max(ttl_s * 10, 30.0)
        probe_pool = names_for_shard(victim_shard, "recover", 4000)
        t0 = time.perf_counter()
        recovered, attempt = False, 0
        while time.perf_counter() - t0 < hard_timeout_s:
            for s in survivors:
                name, attempt = probe_pool[attempt], attempt + 1
                try:
                    call(ports[s], "POST", "/api/v1/containers", {
                        "imageName": "jax", "containerName": name,
                        "chipCount": 0}, timeout=5.0)
                    recovered = True
                    break
                except Exception:  # noqa: BLE001 — 503 until stolen
                    pass
            if recovered:
                break
            time.sleep(0.02)
        recovery_ms = (time.perf_counter() - t0) * 1e3
        stop_load.set()
        for t in load:
            t.join(timeout=10)

        if not recovered:
            raise RuntimeError(
                f"victim shard {victim_shard} never recovered on a "
                f"survivor within {hard_timeout_s}s")
        lat = surv_stats["lat_ms"]
        if len(lat) >= 2:
            qs = statistics.quantiles(lat, n=20)
            surv_p95 = round(min(qs[18], max(lat)), 3)
        else:
            surv_p95 = round(max(lat), 3) if lat else 0.0

        # lease remainder (≤ ttl) + detection lag + writer reseed slack
        recovery_budget_ms = (ttl_s * 1.4 + 3.0) * 1e3
        surv_p95_budget_ms = max(1000.0, store_rtt_ms * 25)
        gates = {
            "speedup_min": speedup_min,
            "speedup_ok": speedup >= speedup_min,
            "cells_error_free": not errs_one and not errs_sh,
            "survivors_zero_failures": surv_stats["failures"] == 0,
            "survivor_p95_budget_ms": surv_p95_budget_ms,
            "survivor_p95_ok": surv_p95 <= surv_p95_budget_ms,
            "recovery_budget_ms": round(recovery_budget_ms, 1),
            "victim_recovered_in_budget": recovery_ms <= recovery_budget_ms,
        }
        gates["ok"] = bool(
            gates["speedup_ok"] and gates["cells_error_free"]
            and gates["survivors_zero_failures"] and gates["survivor_p95_ok"]
            and gates["victim_recovered_in_budget"])
        results = {
            "family": "shard",
            "iters": {"cycles_per_cell": total_cycles, "clients": clients},
            "shard_count": shard_count,
            "ttl_s": ttl_s,
            "store_rtt_ms": store_rtt_ms,
            "cells": {
                "one_shard": {"cycles": total_cycles,
                              "wall_s": round(wall_one, 3),
                              "cycles_per_s": round(rate_one, 3),
                              "errors": errs_one[:5]},
                "sharded": {"cycles": total_cycles,
                            "wall_s": round(wall_sh, 3),
                            "cycles_per_s": round(rate_sh, 3),
                            "errors": errs_sh[:5]},
            },
            "speedup": round(speedup, 3),
            "blast_radius": {
                "victim_shard": victim_shard,
                "recovery_ms": round(recovery_ms, 3),
                "survivor": {"requests": surv_stats["requests"],
                             "failures": surv_stats["failures"],
                             "p95_ms": surv_p95},
            },
            "gates": gates,
        }
    finally:
        for prg in cleanup:
            try:
                prg.stop()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
    return results



def measure_control_plane_reads(n_reads: int = 2000, readers: int = 4,
                                audit_reads: int = 25) -> dict:
    """Control-plane reads family (``--control-plane --cp-family reads``):
    the read-scaling half of the HA story, measured. Three real daemons
    over ONE shared store + fake runtime (the failover family's harness
    shape): a leader, a standby with the watch-fed informer read cache
    (``read_cache = "informer"``, state/informer.py), and a standby on the
    old per-request read-through path. Concurrent readers hammer the GET
    surface of each role over real HTTP, reporting reads/sec and p50/p95
    latency per role — and a ``CountingKV`` audit of **store round trips
    per request** (quiesced window, sequential requests, per-method
    deltas divided by request count).

    Self-gating like churn/failover: the informer standby must serve at
    ~0 store reads per request (watch traffic is amortized, not
    per-request), a leader write must become visible on the informer
    standby within the documented lag budget, and the read-through
    standby must still audit at ≥ 1 read per request — so a bypassed or
    miswired counter fails the gate loudly instead of passing a vacuous
    0 == 0. A violated gate flips ``gates.ok``; main() turns that into a
    nonzero exit."""
    import statistics
    import threading
    import urllib.request

    from tpu_docker_api.config import Config
    from tpu_docker_api.daemon import Program
    from tpu_docker_api.runtime.fake import FakeRuntime
    from tpu_docker_api.state.kv import CountingKV, MemoryKV

    if n_reads < readers * 2:
        raise ValueError(f"need n_reads >= 2 per reader, got {n_reads}")
    counting = CountingKV(MemoryKV())
    runtime = FakeRuntime()
    # TTL far beyond the bench's wall time: after the boot-time election
    # steps, the heartbeat threads sleep through the whole measurement, so
    # elector lease reads can never pollute the per-request audit windows
    ttl_s = 120.0

    progs: list = []

    def boot(holder: str, read_cache: str) -> Program:
        prg = Program(Config(
            port=0, store_backend="memory", runtime_backend="fake",
            start_port=45000, end_port=45999, health_watch_interval=0,
            host_probe_interval_s=0, job_supervise_interval=0,
            reconcile_interval=0, leader_election=True,
            leader_ttl_s=ttl_s, leader_id=holder, read_cache=read_cache,
        ), host="127.0.0.1", kv=counting, runtime=runtime)
        # registered BEFORE init: stop() tolerates partial init, so a
        # daemon that dies mid-boot still gets torn down by the finally
        progs.append(prg)
        prg.init()
        prg.start()
        return prg

    def call(port: int, method, path, body=None, timeout=5.0):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            out = json.loads(resp.read())
        if out["code"] != 200:
            raise RuntimeError(f"{method} {path}: {out}")
        return out

    def wait_for(cond, what: str, timeout_s: float = 15.0) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if cond():
                return
            time.sleep(0.005)
        raise RuntimeError(f"reads family: timed out waiting for {what}")

    names = [f"read{i}" for i in range(4)]
    quants = None
    try:
        # boot INSIDE the guard: a failed acquisition wait or a standby
        # boot error must still stop the daemons already running (this
        # path runs under tier-1 pytest — leaked HTTP/elector threads
        # holding the port range would poison the rest of the suite)
        leader = boot("reads-leader", "informer")
        wait_for(lambda: leader.leader_elector.accepts_mutations,
                 "leader acquisition")
        standby_inf = boot("reads-standby-informer", "informer")
        standby_rt = boot("reads-standby-readthrough", "read-through")

        for name in names:
            call(leader.api_server.port, "POST", "/api/v1/containers",
                 {"imageName": "jax", "containerName": name, "chipCount": 1})
        # the informer standby must be synced AND see the seeds before the
        # clock starts — a cold mirror would measure the fallback path
        wait_for(lambda: standby_inf.informer.synced
                 and all(standby_inf.container_versions.get(n) == 0
                         for n in names),
                 "informer standby syncing the seed data")

        roles = [("leader", leader), ("standby_informer", standby_inf),
                 ("standby_read_through", standby_rt)]

        def hammer(port: int) -> tuple[list[float], float]:
            lat_ms: list[list[float]] = [[] for _ in range(readers)]
            per_reader = n_reads // readers

            def reader(slot: int) -> None:
                for i in range(per_reader):
                    path = f"/api/v1/containers/{names[i % len(names)]}-0"
                    t0 = time.perf_counter()
                    call(port, "GET", path)
                    lat_ms[slot].append((time.perf_counter() - t0) * 1e3)

            threads = [threading.Thread(target=reader, args=(s,))
                       for s in range(readers)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall_s = time.perf_counter() - t0
            return [v for chunk in lat_ms for v in chunk], wall_s

        def audit(port: int) -> float:
            """Store READ round trips per GET, over a quiesced sequential
            window (get + range_prefix — the methods a read can cost)."""
            before = counting.snapshot()
            for i in range(audit_reads):
                call(port, "GET",
                     f"/api/v1/containers/{names[i % len(names)]}-0")
            delta = CountingKV.delta(before, counting.snapshot())
            reads = delta.get("get", 0) + delta.get("range_prefix", 0)
            return round(reads / audit_reads, 4)

        out_roles: dict[str, dict] = {}
        for role, prg in roles:
            lat, wall_s = hammer(prg.api_server.port)
            qs = statistics.quantiles(lat, n=20)
            out_roles[role] = {
                "rps": round(len(lat) / wall_s, 1),
                "p50_ms": round(statistics.median(lat), 3),
                "p95_ms": round(min(qs[18], max(lat)), 3),
                "max_ms": round(max(lat), 3),
                "reads_per_req": audit(prg.api_server.port),
            }

        # leader-write → informer-standby-visible lag: the staleness bound
        # the read cache trades its zero round trips for
        lag_budget_ms = 2000.0
        t0 = time.perf_counter()
        call(leader.api_server.port, "POST", "/api/v1/containers",
             {"imageName": "jax", "containerName": "visprobe",
              "chipCount": 1})
        lag_ms = None
        while time.perf_counter() - t0 < lag_budget_ms / 1e3 * 2:
            try:
                call(standby_inf.api_server.port, "GET",
                     "/api/v1/containers/visprobe-0")
                lag_ms = round((time.perf_counter() - t0) * 1e3, 3)
                break
            except Exception:
                time.sleep(0.002)

        inf_reads = out_roles["standby_informer"]["reads_per_req"]
        rt_reads = out_roles["standby_read_through"]["reads_per_req"]
        # ≤ 0.1 = "~0 with slack for a stray background read", not "small":
        # a single per-request store read would audit at 1.0 and fail
        inf_budget = 0.1
        quants = {
            "family": "reads",
            "iters": {"reads": n_reads, "readers": readers,
                      "audit_reads": audit_reads, "seeded": len(names)},
            "roles": out_roles,
            "visibility_lag_ms": lag_ms,
            "gates": {
                "standby_informer_reads_per_req": inf_reads,
                "standby_informer_reads_budget": inf_budget,
                "read_through_reads_per_req": rt_reads,
                "visibility_lag_ms": lag_ms,
                "visibility_lag_budget_ms": lag_budget_ms,
                "ok": bool(inf_reads <= inf_budget
                           and rt_reads >= 1.0
                           and lag_ms is not None
                           and lag_ms <= lag_budget_ms),
            },
        }
    finally:
        for prg in progs:
            try:
                prg.stop()
            except Exception:
                pass
    return quants


def measure_control_plane_fanout(latency_ms: float = 50.0,
                                 iters: int = 3,
                                 fanout_workers: int = 8) -> dict:
    """Control-plane fan-out family (``--control-plane --cp-family
    fanout``): gang create→start→stop→delete at several member counts
    against per-host ``FaultyRuntime`` engines with an injected per-call
    latency — the multi-host-pod shape where every engine round trip
    costs real wall time. All engines journal into ONE shared call log,
    so ordering is auditable *across* hosts.

    Self-gating on the tentpole invariants:

    - **wall-clock is O(slowest host), not O(members)**: 8-member gang
      create must stay within 2.5× the 2-member wall (serial would be
      ~4×, since a create is O(members) engine calls);
    - **ordering audit**: in the shared journal, the coordinator's start
      is strictly before any worker's start and the coordinator's stop is
      strictly after every worker's stop — concurrency must never break
      the gang barriers;
    - **store round trips unchanged**: gang create still audits at ≤ 3
      atomic ``apply`` batches and O(1) in member count (the PR 6
      CountingKV gate) — concurrency must not add store round trips.

    A violated gate flips ``gates.ok``; main() turns that into a nonzero
    exit."""
    import threading
    import urllib.request

    from tpu_docker_api.config import Config
    from tpu_docker_api.daemon import Program
    from tpu_docker_api.runtime.fake import FakeRuntime
    from tpu_docker_api.runtime.faulty import FaultPlan, FaultRule, FaultyRuntime
    from tpu_docker_api.state.kv import CountingKV, MemoryKV

    if iters < 1:
        raise ValueError(f"fanout family needs iters >= 1, got {iters}")
    # fixed, not a parameter: the gate key (wall_ratio_8v2), the schema
    # checker and main()'s headline all name the 2- and 8-member points
    members = (2, 4, 8)
    n_hosts = max(members)
    latency_s = latency_ms / 1e3
    journal: list = []
    journal_lock = threading.Lock()

    def slow_engine() -> FaultyRuntime:
        """One host's engine: every lifecycle op pays the injected
        latency, forever; all hosts share one journal."""
        rules = [FaultRule(op=op, mode="latency", latency_s=latency_s,
                           times=-1)
                 for op in ("container_create", "container_start",
                            "container_stop", "container_remove")]
        return FaultyRuntime(FakeRuntime(), FaultPlan(rules=rules),
                             journal=journal, journal_lock=journal_lock)

    counting = CountingKV(MemoryKV())
    pod_runtimes = {f"h{i}": slow_engine() for i in range(1, n_hosts)}
    prog = Program(Config(
        port=0, store_backend="memory", runtime_backend="fake",
        start_port=46000, end_port=47999, health_watch_interval=0,
        host_probe_interval_s=0, job_supervise_interval=0,
        reconcile_interval=0, fanout_workers=fanout_workers,
        pod_hosts=[
            {"host_id": "h0", "address": "10.0.0.1",
             "grid_coord": [0, 0, 0], "local": True}
        ] + [
            {"host_id": f"h{i}", "address": f"10.0.0.{i + 1}",
             "grid_coord": [i, 0, 0], "runtime_backend": "fake"}
            for i in range(1, n_hosts)
        ],
    ), host="127.0.0.1", kv=counting, runtime=slow_engine(),
        pod_runtimes=pod_runtimes)
    prog.init()
    prog.start()
    chips_per_host = prog.pod.chips_per_host

    def call(method, path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{prog.api_server.port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            out = json.loads(resp.read())
        if out["code"] != 200:
            raise RuntimeError(f"{method} {path}: {out}")
        return out

    def timed(fn):
        t0 = time.perf_counter()
        fn()
        return (time.perf_counter() - t0) * 1e3

    def audit_ordering(vname: str, m: int) -> list[str]:
        """Gang barriers in the SHARED journal: coordinator start first,
        coordinator stop last. Returns the violations found (empty=ok)."""
        coord = f"{vname}-p0"
        workers = {f"{vname}-p{i}" for i in range(1, m)}
        with journal_lock:
            snap = list(journal)
        starts = [(i, t) for i, (op, t, _) in enumerate(snap)
                  if op == "container_start" and (t == coord or t in workers)]
        stops = [(i, t) for i, (op, t, _) in enumerate(snap)
                 if op == "container_stop" and (t == coord or t in workers)]
        problems = []
        coord_starts = [i for i, t in starts if t == coord]
        worker_starts = [i for i, t in starts if t != coord]
        if not coord_starts or len(worker_starts) != m - 1:
            problems.append(f"{vname}: start log incomplete "
                            f"({len(coord_starts)} coord, "
                            f"{len(worker_starts)} workers)")
        elif coord_starts[0] >= min(worker_starts):
            problems.append(f"{vname}: a worker started before the "
                            f"coordinator")
        coord_stops = [i for i, t in stops if t == coord]
        worker_stops = [i for i, t in stops if t != coord]
        if not coord_stops or len(worker_stops) != m - 1:
            problems.append(f"{vname}: stop log incomplete")
        elif coord_stops[-1] <= max(worker_stops):
            problems.append(f"{vname}: the coordinator stopped before "
                            f"some worker")
        return problems

    per_members: dict[str, dict] = {}
    ordering_problems: list[str] = []
    applies: dict[int, int] = {}
    try:
        for m in members:
            walls: dict[str, list[float]] = {
                "create": [], "stop": [], "delete": []}
            for k in range(iters):
                name = f"fan{m}i{k}"
                walls["create"].append(timed(lambda: call(
                    "POST", "/api/v1/jobs",
                    {"imageName": "jax", "jobName": name,
                     "chipCount": chips_per_host * m})))
                info = call("GET", f"/api/v1/jobs/{name}")
                if info["data"].get("phase") != "running":
                    raise RuntimeError(f"gang {name} not running: "
                                       f"{info['data']}")
                walls["stop"].append(timed(lambda: call(
                    "POST", f"/api/v1/jobs/{name}/stop")))
                walls["delete"].append(timed(lambda: call(
                    "DELETE", f"/api/v1/jobs/{name}", {
                        "force": True, "delStateAndVersionRecord": True})))
                ordering_problems += audit_ordering(f"{name}-0", m)
            # store round-trip audit: one quiesced create per member count
            before = counting.snapshot()
            call("POST", "/api/v1/jobs",
                 {"imageName": "jax", "jobName": f"audit{m}",
                  "chipCount": chips_per_host * m})
            applies[m] = CountingKV.delta(
                before, counting.snapshot()).get("apply", 0)
            call("DELETE", f"/api/v1/jobs/audit{m}", {
                "force": True, "delStateAndVersionRecord": True})
            per_members[str(m)] = {
                f"{flow}_ms_min": round(min(ms), 3)
                for flow, ms in walls.items()
            } | {
                f"{flow}_ms_max": round(max(ms), 3)
                for flow, ms in walls.items()
            }
    finally:
        prog.stop()

    lo, hi = str(min(members)), str(max(members))
    ratio = (per_members[hi]["create_ms_min"]
             / max(per_members[lo]["create_ms_min"], 1e-9))
    ratio_budget = 2.5
    gang_applies = applies[max(members)]
    # >= 1 keeps the gate honest: a write path that stopped routing
    # through the counted apply must FAIL, not pass vacuously
    applies_o1 = (gang_applies >= 1
                  and all(v == gang_applies for v in applies.values()))
    return {
        "family": "fanout",
        "iters": {"iters": iters, "members": list(members),
                  "latency_ms": latency_ms,
                  "fanout_workers": fanout_workers},
        "members": per_members,
        "gang_create_applies": {str(m): v for m, v in applies.items()},
        "ordering_problems": ordering_problems,
        "gates": {
            "wall_ratio_8v2": round(ratio, 3),
            "wall_ratio_budget": ratio_budget,
            "ordering_ok": not ordering_problems,
            "gang_create_applies": gang_applies,
            "gang_create_applies_max": 3,
            "gang_apply_o1_in_members": applies_o1,
            "ok": bool(ratio <= ratio_budget and not ordering_problems
                       and 1 <= gang_applies <= 3 and applies_o1),
        },
    }


def measure_control_plane_preempt(n_low: int = 4, n_high: int = 3,
                                  chips_per_job: int = 2,
                                  interval_s: float = 0.05,
                                  timeout_s: float = 30.0) -> dict:
    """Control-plane capacity-market family (``--control-plane
    --cp-family preempt``): fill the pool with preemptible gangs, submit
    production gangs over real HTTP, and measure time-to-placed while the
    admission loop preempts for them. Self-gating on the tentpole
    invariants:

    - **every high-priority job places** (phase ``running`` within the
      timeout) — the market never strands a production ask a preemption
      could satisfy;
    - **zero preemptions when holes suffice** — an identical production
      burst into FREE capacity places immediately without touching any
      running gang (backfill proven, not asserted);
    - **legacy refusal preserved** — a second daemon with
      ``admission_enabled=false`` still answers a full pool with the
      byte-for-byte 10601 hard-fail (data: null).

    A violated gate flips ``gates.ok``; main() turns that into a nonzero
    exit."""
    import urllib.request

    from tpu_docker_api.config import Config
    from tpu_docker_api.daemon import Program

    if n_low < 1 or n_high < 1:
        raise ValueError("preempt family needs n_low/n_high >= 1")

    def boot(enabled: bool) -> Program:
        prog = Program(Config(
            port=0, store_backend="memory", runtime_backend="fake",
            start_port=48000, end_port=48999, health_watch_interval=0,
            host_probe_interval_s=0, job_supervise_interval=0,
            reconcile_interval=0, admission_enabled=enabled,
            admission_interval_s=interval_s,
        ), host="127.0.0.1")
        prog.init()
        prog.start()
        return prog

    def call(prog, method, path, body=None, expect_error=False):
        req = urllib.request.Request(
            f"http://127.0.0.1:{prog.api_server.port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        if not expect_error and out["code"] != 200:
            raise RuntimeError(f"{method} {path}: {out}")
        return out

    def submit(prog, name, klass):
        return call(prog, "POST", "/api/v1/jobs", {
            "imageName": "jax", "jobName": name,
            "chipCount": chips_per_job, "priorityClass": klass})

    def wait_placed(prog, name) -> None:
        deadline = time.perf_counter() + timeout_s
        info = {}
        while time.perf_counter() < deadline:
            info = call(prog, "GET", f"/api/v1/jobs/{name}")["data"]
            if info.get("phase") == "running":
                return
            time.sleep(0.005)
        raise RuntimeError(f"{name} never placed (still "
                           f"{info.get('phase')!r}) within {timeout_s}s")

    prog = boot(enabled=True)
    try:
        n_chips = prog.pod.n_chips
        if n_low * chips_per_job < n_chips:
            raise ValueError(
                f"{n_low} low jobs x {chips_per_job} chips do not fill the "
                f"{n_chips}-chip pool — the pressure phase would be vacuous")

        def admission_view() -> dict:
            return call(prog, "GET", "/api/v1/admission")["data"]

        # phase A — holes: production burst into FREE capacity
        holes_ms: list[float] = []
        for i in range(n_high):
            t0 = time.perf_counter()
            out = submit(prog, f"hole{i}", "production")
            if out["data"].get("phase") == "queued":
                raise RuntimeError(f"hole{i} queued on a free pool: {out}")
            holes_ms.append((time.perf_counter() - t0) * 1e3)
        preempt_holes = admission_view()["preemptionsTotal"]
        for i in range(n_high):
            call(prog, "DELETE", f"/api/v1/jobs/hole{i}",
                 {"force": True, "delStateAndVersionRecord": True})

        # phase B — pressure: fill the pool with preemptible gangs, then
        # submit the same production burst; the loop must preempt for it
        filled = 0
        for i in range(n_low):
            out = submit(prog, f"low{i}", "preemptible")
            if out["data"].get("phase") != "queued":
                filled += 1
        placed_ms: list[float] = []
        queued_positions: list[int] = []
        for i in range(n_high):
            # time-to-placed = submit wall + queue wait + preemption +
            # placement, observed the way a client would (polling GET)
            t0 = time.perf_counter()
            out = submit(prog, f"high{i}", "production")
            queued_positions.append(out["data"].get("queuePosition", 0))
            wait_placed(prog, f"high{i}")
            placed_ms.append((time.perf_counter() - t0) * 1e3)
        view = admission_view()
        preempt_total = view["preemptionsTotal"]
        admissions = view["admissionsTotal"]
    finally:
        prog.stop()

    # phase C — legacy: admission disabled keeps today's refusal exactly
    legacy = boot(enabled=False)
    try:
        call(legacy, "POST", "/api/v1/jobs", {
            "imageName": "jax", "jobName": "fill",
            "chipCount": legacy.pod.n_chips})
        refusal = call(legacy, "POST", "/api/v1/jobs", {
            "imageName": "jax", "jobName": "denied", "chipCount": 2},
            expect_error=True)
    finally:
        legacy.stop()

    def quantiles(ms: list[float]) -> dict:
        s = sorted(ms)
        return {"p50": round(s[len(s) // 2], 3),
                "p95": round(s[min(len(s) - 1, int(len(s) * 0.95))], 3),
                "max": round(s[-1], 3)}

    pressure_preempts = preempt_total - preempt_holes
    all_placed = len(placed_ms) == n_high
    gates = {
        "all_placed": all_placed,
        "zero_preempt_with_holes": preempt_holes == 0,
        "preemptions_with_holes": preempt_holes,
        "preempted_under_pressure": pressure_preempts >= 1,
        "legacy_refusal_code": refusal.get("code"),
        "legacy_refusal_ok": (refusal.get("code") == 10601
                              and refusal.get("data") is None),
    }
    gates["ok"] = bool(all_placed and gates["zero_preempt_with_holes"]
                       and gates["preempted_under_pressure"]
                       and gates["legacy_refusal_ok"])
    return {
        "family": "preempt",
        "iters": {"low_jobs": filled, "high_jobs": n_high,
                  "chips_per_job": chips_per_job,
                  "pool_chips": n_chips,
                  "admission_interval_s": interval_s},
        "time_to_placed_ms": quantiles(placed_ms),
        "placed_ms": [round(v, 3) for v in placed_ms],
        "holes_time_to_placed_ms": quantiles(holes_ms),
        "queued_positions": queued_positions,
        "preemptions": {
            "with_holes": preempt_holes,
            "under_pressure": pressure_preempts,
            "per_admission": round(
                pressure_preempts / max(admissions, 1), 3),
        },
        "gates": gates,
    }


def measure_control_plane_resize(iters: int = 3, n_hosts: int = 4,
                                 interval_s: float = 0.05,
                                 shrink_budget_ms: float = 5000.0,
                                 down_grace_s: float = 0.2,
                                 timeout_s: float = 30.0) -> dict:
    """Elastic-gang resize family (``--control-plane --cp-family
    resize``; docs/robustness.md "Elastic gangs"). Two scenarios, both
    self-gating:

    **Partial preemption + grow-back** (over real HTTP): an elastic
    preemptible gang fills the pod; a production one-host burst must be
    satisfied by SHRINKING the gang (spare members donated, time-to-shrunk
    measured submit→both-running) with **zero full preemptions** — the
    victim keeps training at reduced batch size. Deleting the production
    job must GROW the gang BACK through the admission queue (the journaled
    grow-back record, preempted-grade precedence), proven by the
    ``job-partially-preempted`` / ``job-growback-queued`` / grow-back
    ``job-admitted`` events in the merged ring.

    **Host loss** (in-process, FaultyRuntime): killing one host's engine
    must shrink the gang to its survivors — zero gang restarts charged,
    zero migrations, the restart budget untouched — within the same
    time-to-shrunk budget (measured kill→shrunken-and-running, so the
    down-grace window is part of the honest number).

    A violated gate flips ``gates.ok``; main() turns that into a nonzero
    exit."""
    import urllib.request

    from tpu_docker_api.config import Config
    from tpu_docker_api.daemon import Program
    from tpu_docker_api.runtime.faulty import FaultyRuntime

    if iters < 1 or n_hosts < 3:
        raise ValueError("resize family needs iters >= 1, n_hosts >= 3")

    def pod_hosts():
        return [{"host_id": f"h{i}", "address": f"10.0.0.{i + 1}",
                 "grid_coord": [i, 0, 0],
                 **({"local": True} if i == 0
                    else {"runtime_backend": "fake"})}
                for i in range(n_hosts)]

    def call(prog, method, path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{prog.api_server.port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        if out["code"] != 200:
            raise RuntimeError(f"{method} {path}: {out}")
        return out

    def wait_until(fn, what: str) -> float:
        t0 = time.perf_counter()
        deadline = t0 + timeout_s
        while time.perf_counter() < deadline:
            if fn():
                return (time.perf_counter() - t0) * 1e3
            time.sleep(0.005)
        raise RuntimeError(f"timed out waiting for {what}")

    # ── scenario A: partial preemption shrinks, grow-back restores ──────
    prog = Program(Config(
        port=0, store_backend="memory", runtime_backend="fake",
        start_port=47000, end_port=47999, health_watch_interval=0,
        host_probe_interval_s=0, job_supervise_interval=0,
        reconcile_interval=0, admission_enabled=True,
        admission_interval_s=interval_s, pod_hosts=pod_hosts(),
    ), host="127.0.0.1")
    prog.init()
    prog.start()
    shrink_ms: list[float] = []
    growback_ms: list[float] = []
    try:
        per_host = prog.pod.chips_per_host
        full = n_hosts * per_host

        def members(name) -> int:
            return call(prog, "GET", f"/api/v1/jobs/{name}")["data"].get(
                "membersActual", -1)

        def phase(name) -> str:
            return call(prog, "GET", f"/api/v1/jobs/{name}")["data"]["phase"]

        def admission_view() -> dict:
            return call(prog, "GET", "/api/v1/admission")["data"]

        out = call(prog, "POST", "/api/v1/jobs", {
            "imageName": "jax", "jobName": "don", "chipCount": full,
            "priorityClass": "preemptible", "elastic": True,
            "minMembers": 1})
        if out["data"]["phase"] != "running":
            raise RuntimeError(f"elastic filler never placed: {out}")
        for i in range(iters):
            t0 = time.perf_counter()
            call(prog, "POST", "/api/v1/jobs", {
                "imageName": "jax", "jobName": f"prod{i}",
                "chipCount": per_host, "priorityClass": "production"})
            wait_until(lambda: phase(f"prod{i}") == "running"
                       and members("don") == n_hosts - 1,
                       f"prod{i} placed via shrink of don")
            shrink_ms.append((time.perf_counter() - t0) * 1e3)
            call(prog, "DELETE", f"/api/v1/jobs/prod{i}",
                 {"force": True, "delStateAndVersionRecord": True})
            growback_ms.append(wait_until(
                lambda: members("don") == n_hosts
                and phase("don") == "running",
                "don grown back through the queue"))
        view = admission_view()
        full_preempts = view["preemptionsTotal"]
        partial_preempts = view["partialPreemptionsTotal"]
        events = call(prog, "GET", "/api/v1/events?limit=500")["data"]
        kinds = [e.get("event") for e in events]
        growback_admits = sum(
            1 for e in events
            if e.get("event") == "job-admitted" and e.get("via") == "growback")
    finally:
        prog.stop()

    # ── scenario B: host loss shrinks instead of migrating/failing ──────
    from tpu_docker_api.runtime.fake import FakeRuntime
    from tpu_docker_api.runtime.faulty import FaultPlan
    from tpu_docker_api.state.kv import MemoryKV

    rts = {f"h{i}": FaultyRuntime(FakeRuntime(), FaultPlan())
           for i in range(n_hosts)}
    prog = Program(Config(
        port=0, store_backend="memory", runtime_backend="fake",
        start_port=47000, end_port=47999, health_watch_interval=0,
        host_probe_interval_s=0.02, host_down_grace_s=down_grace_s,
        job_supervise_interval=0.02, reconcile_interval=0,
        admission_enabled=True, admission_interval_s=interval_s,
        pod_hosts=pod_hosts(),
    ), host="127.0.0.1", kv=MemoryKV(), runtime=rts["h0"],
        pod_runtimes={h: r for h, r in rts.items() if h != "h0"})
    prog.init()
    prog.start()
    try:
        per_host = prog.pod.chips_per_host
        out = call(prog, "POST", "/api/v1/jobs", {
            "imageName": "jax", "jobName": "train",
            "chipCount": n_hosts * per_host,
            "priorityClass": "batch", "elastic": True, "minMembers": 1})
        if out["data"]["phase"] != "running":
            raise RuntimeError(f"elastic gang never placed: {out}")
        victim_host = f"h{n_hosts - 1}"
        rts[victim_host].set_unreachable(True)
        t0 = time.perf_counter()

        def shrunk() -> bool:
            d = call(prog, "GET", "/api/v1/jobs/train")["data"]
            return (d["phase"] == "running"
                    and d.get("membersActual") == n_hosts - 1
                    and all(p["hostId"] != victim_host
                            for p in d["processes"]))

        wait_until(shrunk, "host-loss shrink of train")
        host_loss_ms = (time.perf_counter() - t0) * 1e3
        shrink_ms.append(host_loss_ms)
        d = call(prog, "GET", "/api/v1/jobs/train")["data"]
        restarts_burned = d.get("restarts", 0)
        migrations_burned = d.get("migrations", 0)
        growback_queued = d.get("growbackQueuePosition") is not None
    finally:
        prog.stop()

    def quantiles(ms: list[float]) -> dict:
        s = sorted(ms)
        return {"p50": round(s[len(s) // 2], 3),
                "p95": round(s[min(len(s) - 1, int(len(s) * 0.95))], 3),
                "max": round(s[-1], 3)}

    gates = {
        "shrink_budget_ms": shrink_budget_ms,
        "time_to_shrunk_p95_ok": quantiles(shrink_ms)["p95"]
        <= shrink_budget_ms,
        # the tentpole invariant: when shrink suffices, NOTHING dies whole
        "zero_full_preemptions": full_preempts == 0,
        "full_preemptions": full_preempts,
        "partial_preemptions": partial_preempts,
        "partial_preempted": partial_preempts >= iters,
        "partial_preempt_event": "job-partially-preempted" in kinds,
        "growback_queued_event": "job-growback-queued" in kinds,
        # grow-back landed THROUGH the queue, not via a private retry
        "growback_via_queue": growback_admits >= iters,
        "growback_admits": growback_admits,
        # host loss: shrink absorbed it — no restart/migration budget burn
        "host_loss_shrunk": True,
        "host_loss_zero_restarts": restarts_burned == 0,
        "host_loss_zero_migrations": migrations_burned == 0,
        "host_loss_growback_queued": growback_queued,
    }
    gates["ok"] = bool(
        gates["time_to_shrunk_p95_ok"] and gates["zero_full_preemptions"]
        and gates["partial_preempted"] and gates["partial_preempt_event"]
        and gates["growback_queued_event"] and gates["growback_via_queue"]
        and gates["host_loss_zero_restarts"]
        and gates["host_loss_zero_migrations"]
        and gates["host_loss_growback_queued"])
    return {
        "family": "resize",
        "iters": {"cycles": iters, "hosts": n_hosts,
                  "admission_interval_s": interval_s,
                  "down_grace_s": down_grace_s},
        "time_to_shrunk_ms": quantiles(shrink_ms),
        "shrunk_ms": [round(v, 3) for v in shrink_ms],
        "growback_ms": quantiles(growback_ms),
        "host_loss_ms": round(host_loss_ms, 3),
        "gates": gates,
    }


def measure_control_plane_serve_scale(iters: int = 3,
                                      chips_per_replica: int = 2,
                                      max_replicas: int = 3,
                                      interval_s: float = 0.05,
                                      budget_ms: float = 5000.0,
                                      timeout_s: float = 30.0) -> dict:
    """Service autoscaling family (``--control-plane --cp-family
    serve-scale``): a production-class service beside a batch training
    gang on a full-ish pool; an offered-load step must scale the service
    to its target replica count THROUGH the capacity market (the last
    replica preempts the batch gang) with zero manual operations, the SLO
    must recover, and shedding the load must scale back down (releasing
    capacity that re-admits the preempted batch gang). Self-gating on:

    - **time-to-scaled**: offered-load step → all target replicas ready
      AND SLO recovered, p50 under ``budget_ms``;
    - **admitted via the queue**: at least one scale-up replica entered
      through the admission journal (queued → admitted events present) —
      the market path proven, not assumed;
    - **zero manual operations**: every replica-count change carries
      trigger "autoscale" (the manual-scale counter stays 0);
    - **scale-down converges** and the preempted batch gang re-admits
      when the burst ends (capacity flows back to training).

    A violated gate flips ``gates.ok``; main() turns that into a nonzero
    exit."""
    import urllib.request

    from tpu_docker_api.config import Config
    from tpu_docker_api.daemon import Program

    if iters < 1:
        raise ValueError("serve-scale family needs iters >= 1")

    prog = Program(Config(
        port=0, store_backend="memory", runtime_backend="fake",
        start_port=49000, end_port=49999, health_watch_interval=0,
        host_probe_interval_s=0, job_supervise_interval=0,
        reconcile_interval=0, admission_enabled=True,
        admission_interval_s=interval_s,
        autoscale_interval_s=interval_s,
        autoscale_up_cooldown_s=interval_s,
        autoscale_down_cooldown_s=interval_s * 2,
    ), host="127.0.0.1")
    prog.init()
    prog.start()

    def call(method, path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{prog.api_server.port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        if out["code"] != 200:
            raise RuntimeError(f"{method} {path}: {out}")
        return out["data"]

    def wait_until(cond, what: str) -> bool:
        """False on timeout — recorded as a failed gate observation, not
        raised: a stuck autoscaler must yield a red ARTIFACT (gates.ok
        false with the observations that failed), not a stack trace."""
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            if cond():
                return True
            time.sleep(0.005)
        return False

    try:
        n_chips = prog.pod.n_chips
        filler_chips = n_chips // 2
        # a batch training gang holds half the pool: the last scale-up
        # replica can only place by preempting it through the market
        call("POST", "/api/v1/jobs", {
            "imageName": "jax", "jobName": "filler",
            "chipCount": filler_chips, "priorityClass": "batch"})
        high_rps = 100.0 * max_replicas - 20.0   # needs max_replicas
        low_rps = 20.0                           # needs min (1)
        call("POST", "/api/v1/services", {
            "serviceName": "svc", "imageName": "serve",
            "chipsPerReplica": chips_per_replica, "replicas": 1,
            "minReplicas": 1, "maxReplicas": max_replicas,
            "ttftP95TargetMs": 200, "queueDepthTarget": 4,
            "replicaCapacityRps": 100.0})

        def svc():
            return call("GET", "/api/v1/services/svc")

        def filler_phase():
            return call("GET", "/api/v1/jobs/filler")["phase"]

        def slo_ok(info):
            sig = info["slo"]["lastObserved"]
            return (sig is not None
                    and sig["ttftP95Ms"] <= info["slo"]["ttftP95TargetMs"]
                    and sig["queueDepth"] <= info["slo"]["queueDepthTarget"])

        scaled_ms: list[float] = []
        down_ms: list[float] = []
        # per-iteration observations, each RE-READ after its wait so the
        # gates below are independent facts, not one "the wait returned"
        # fact duplicated three times
        reached_flags: list[bool] = []
        slo_flags: list[bool] = []
        down_flags: list[bool] = []
        readmit_flags: list[bool] = []
        preempted_seen = 0
        for _ in range(iters):
            t0 = time.perf_counter()
            call("POST", "/api/v1/services/svc/load", {"rps": high_rps})
            scaled = wait_until(
                lambda: (lambda i: i["readyReplicas"] >= max_replicas
                         and slo_ok(i))(svc()),
                f"{max_replicas} ready replicas with SLO recovered")
            info = svc()
            reached_flags.append(info["readyReplicas"] >= max_replicas)
            slo_flags.append(slo_ok(info))
            if scaled:
                scaled_ms.append((time.perf_counter() - t0) * 1e3)
            if filler_phase() in ("preempted", "queued"):
                preempted_seen += 1
            t1 = time.perf_counter()
            call("POST", "/api/v1/services/svc/load", {"rps": low_rps})
            down = wait_until(lambda: svc()["replicas"] == 1
                              and svc()["readyReplicas"] == 1,
                              "scale-down to 1 replica")
            down_flags.append(down)
            if down:
                down_ms.append((time.perf_counter() - t1) * 1e3)
            # the burst is over: the freed capacity must flow back to the
            # preempted training gang before the next step
            readmit_flags.append(wait_until(
                lambda: filler_phase() == "running",
                "preempted batch gang re-admitted"))
            if not (scaled and down):
                break  # the fleet is wedged; later steps would only time out

        info = svc()
        events = call("GET", "/api/v1/events?limit=250")
        queued = [e for e in events if e.get("event") == "job-queued"
                  and str(e.get("job", "")).startswith("svc.r")]
        admitted = [e for e in events if e.get("event") == "job-admitted"
                    and str(e.get("job", "")).startswith("svc.r")]
        admission_view = call("GET", "/api/v1/admission")
    finally:
        prog.stop()

    def quantiles(ms: list[float]) -> dict:
        if not ms:
            return {"p50": 0, "p95": 0, "max": 0}
        s = sorted(ms)
        return {"p50": round(s[len(s) // 2], 3),
                "p95": round(s[min(len(s) - 1, int(len(s) * 0.95))], 3),
                "max": round(s[-1], 3)}

    ttq = quantiles(scaled_ms)
    gates = {
        "reached_target": (len(reached_flags) == iters
                           and all(reached_flags)),
        "slo_recovered": len(slo_flags) == iters and all(slo_flags),
        "time_to_scaled_p50_ms": ttq["p50"],
        "time_to_scaled_budget_ms": budget_ms,
        "admitted_via_queue": len(admitted),
        "journal_records_seen": len(queued),
        "manual_ops": info["manualScaleTotal"],
        "zero_manual_ops": info["manualScaleTotal"] == 0,
        "scale_down_converged": (len(down_flags) == iters
                                 and all(down_flags)),
        "batch_readmitted": (len(readmit_flags) == iters
                             and all(readmit_flags)),
        "batch_preempted": preempted_seen >= 1,
    }
    gates["ok"] = bool(
        gates["reached_target"] and gates["slo_recovered"]
        and len(scaled_ms) == iters and 0 < ttq["p50"] <= budget_ms
        and gates["admitted_via_queue"] >= 1
        and gates["zero_manual_ops"] and gates["scale_down_converged"]
        and gates["batch_preempted"] and gates["batch_readmitted"])
    return {
        "family": "serve-scale",
        "iters": {"steps": iters, "chips_per_replica": chips_per_replica,
                  "max_replicas": max_replicas, "pool_chips": n_chips,
                  "filler_chips": filler_chips,
                  "tick_interval_s": interval_s},
        "time_to_scaled_ms": ttq,
        "scaled_ms": [round(v, 3) for v in scaled_ms],
        "time_to_scaled_down_ms": quantiles(down_ms),
        "autoscale_ops": info["autoscaleTotal"],
        "admission": {"queued_events": len(queued),
                      "admitted_events": len(admitted),
                      "preemptions_total":
                          admission_view["preemptionsTotal"]},
        "gates": gates,
    }


def measure_control_plane_workflow(
        iters: int = 3, interval_s: float = 0.02,
        budget_ms: float = 20000.0, timeout_s: float = 20.0) -> dict:
    """Durable-workflow family (``--control-plane --cp-family workflow``
    / ``make bench-workflow``): a train → eval → promote DAG submitted
    over real HTTP against an in-process Program with every writer loop
    live (admission, supervision, the workflow engine). Step gangs run on
    the fake runtime; the bench simulates the WORKLOAD finishing (each
    member exits 0 via the runtime fault seam) and everything after that
    is the control plane's job: the supervisor marks the gang completed,
    the engine journals the completion marker, launches the successor,
    and the promote step rolls the target Service through the
    rolling-update machinery. Self-gating on:

    - **time-to-DAG-complete**: POST /workflows → phase ``succeeded``,
      p50 under ``budget_ms``;
    - **exactly-once step effects**: every member container created
      exactly once across the run (the runtime create ledger holds no
      duplicate names) and no step burned a retry attempt — the journal
      markers, not luck, carried each effect;
    - **promote rolled the service**: after each DAG the target Service
      reports the step's image with its replica ready — the roll went
      through the real update path, not a spec overwrite;
    - **admitted via the queue**: step gangs entered through the
      admission journal (queued → admitted events present) — workflows
      pay for capacity like everyone else;
    - **zero manual operations**: the bench touches jobs only by
      simulating container exits; no job/step API mutation is issued.

    A violated gate flips ``gates.ok``; main() turns that into a nonzero
    exit."""
    import urllib.request

    from tpu_docker_api.config import Config
    from tpu_docker_api.daemon import Program

    if iters < 1:
        raise ValueError("workflow family needs iters >= 1")

    prog = Program(Config(
        port=0, store_backend="memory", runtime_backend="fake",
        start_port=49000, end_port=49999, health_watch_interval=0,
        host_probe_interval_s=0, job_supervise_interval=interval_s,
        reconcile_interval=0, admission_enabled=True,
        admission_interval_s=interval_s,
        workflow_interval_s=interval_s,
        workflow_backoff_base_s=0.0, workflow_backoff_max_s=0.0,
    ), host="127.0.0.1")
    prog.init()
    prog.start()

    def call(method, path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{prog.api_server.port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        if out["code"] != 200:
            raise RuntimeError(f"{method} {path}: {out}")
        return out["data"]

    def wait_until(cond, what: str) -> bool:
        """False on timeout — recorded as a failed gate observation, not
        raised: a wedged DAG must yield a red ARTIFACT (gates.ok false
        with the observations that failed), not a stack trace."""
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            if cond():
                return True
            time.sleep(0.005)
        return False

    crashed: set[str] = set()

    def finish_running_steps(wf: str) -> None:
        """Simulate the workload of every currently-running step gang
        exiting 0 — the only 'completion' signal the control plane gets,
        exactly as a real training process would deliver it."""
        info = call("GET", f"/api/v1/workflows/{wf}")
        for step in info["steps"]:
            if step["state"] != "running" or step.get("jobPhase") != "running":
                continue
            job = call("GET", f"/api/v1/jobs/{step['job']}")
            if job["phase"] != "running":
                continue
            for proc in job["processes"]:
                cname = proc["container"]
                if cname in crashed:
                    continue
                crashed.add(cname)
                prog.runtime.crash_container(cname, exit_code=0)

    try:
        call("POST", "/api/v1/services", {
            "serviceName": "web", "imageName": "model:v1",
            "chipsPerReplica": 1, "replicas": 1})
        if not wait_until(
                lambda: call("GET", "/api/v1/services/web")[
                    "readyReplicas"] >= 1,
                "promote target service ready"):
            raise RuntimeError("promote target service never became ready")
        # a preemptible filler holds every remaining chip: the first step
        # of each DAG can only place by queueing and preempting it
        # through the market — the admission path proven, not assumed
        filler_chips = prog.pod.n_chips - 1
        call("POST", "/api/v1/jobs", {
            "imageName": "jax", "jobName": "filler",
            "chipCount": filler_chips, "priorityClass": "preemptible"})

        dag_ms: list[float] = []
        completed_flags: list[bool] = []
        promote_flags: list[bool] = []
        retry_attempts = 0
        for i in range(iters):
            wf = f"pipe{i}"
            target_image = f"model:v{i + 2}"
            call("POST", "/api/v1/workflows", {
                "workflowName": wf,
                "priorityClass": "production",
                "binds": ["/mnt/artifacts:/artifacts"],
                "steps": [
                    {"name": "train", "image": "jax:train", "chipCount": 1},
                    {"name": "evaluate", "image": "jax:eval", "chipCount": 1,
                     "deps": ["train"]},
                    {"name": "promote", "kind": "promote", "service": "web",
                     "image": target_image, "deps": ["evaluate"]},
                ]})
            t0 = time.perf_counter()

            def dag_done():
                finish_running_steps(wf)
                return call("GET",
                            f"/api/v1/workflows/{wf}")["phase"] == "succeeded"

            done = wait_until(dag_done, f"{wf} DAG complete")
            completed_flags.append(done)
            if done:
                dag_ms.append((time.perf_counter() - t0) * 1e3)
            info = call("GET", f"/api/v1/workflows/{wf}")
            retry_attempts += sum(s["attempts"] for s in info["steps"])
            svc = call("GET", "/api/v1/services/web")
            promote_flags.append(
                done and svc["image"] == target_image
                and wait_until(
                    lambda: call("GET", "/api/v1/services/web")[
                        "readyReplicas"] >= 1,
                    "rolled replica ready"))
            if not done:
                break  # the engine is wedged; later DAGs would only time out

        events = call("GET", "/api/v1/events?limit=500")
        queued = [e for e in events if e.get("event") == "job-queued"
                  and ".s" in str(e.get("job", ""))]
        admitted = [e for e in events if e.get("event") == "job-admitted"
                    and ".s" in str(e.get("job", ""))]
        # exactly-once audit over WORKFLOW-owned containers only: the
        # preempted filler legitimately re-creates its members on every
        # re-admission, so it must not pollute the step-effect ledger
        creates = [c[1] for c in prog.runtime.calls
                   if c[0] == "create" and c[1].startswith("pipe")]
    finally:
        prog.stop()

    def quantiles(ms: list[float]) -> dict:
        if not ms:
            return {"p50": 0, "p95": 0, "max": 0}
        s = sorted(ms)
        return {"p50": round(s[len(s) // 2], 3),
                "p95": round(s[min(len(s) - 1, int(len(s) * 0.95))], 3),
                "max": round(s[-1], 3)}

    ttq = quantiles(dag_ms)
    gates = {
        "dag_completed_all": (len(completed_flags) == iters
                              and all(completed_flags)),
        "dag_complete_p50_ms": ttq["p50"],
        "dag_complete_budget_ms": budget_ms,
        "promote_rolled_all": (len(promote_flags) == iters
                               and all(promote_flags)),
        "member_creates": len(creates),
        "steps_exactly_once": (len(creates) == len(set(creates))
                               and len(creates) >= 1),
        "step_retries": retry_attempts,
        "zero_step_retries": retry_attempts == 0,
        "admitted_via_queue": len(admitted),
    }
    gates["ok"] = bool(
        gates["dag_completed_all"] and gates["promote_rolled_all"]
        and len(dag_ms) == iters and 0 < ttq["p50"] <= budget_ms
        and gates["steps_exactly_once"] and gates["zero_step_retries"]
        and gates["admitted_via_queue"] >= 1)
    return {
        "family": "workflow",
        "iters": {"dags": iters, "steps_per_dag": 3,
                  "tick_interval_s": interval_s},
        "dag_complete_ms": ttq,
        "dag_ms": [round(v, 3) for v in dag_ms],
        "admission": {"queued_events": len(queued),
                      "admitted_events": len(admitted)},
        "gates": gates,
    }


def measure_control_plane_serve_traffic(
        duration_s: float = 4.0, rps: float = 40.0,
        ttft_overhead_budget_ms: float = 75.0, interval_s: float = 0.05,
        timeout_s: float = 30.0) -> dict:
    """L7 gateway traffic family (``--control-plane --cp-family
    serve-traffic`` / ``make bench-serve-traffic``): open-loop streaming
    load through the REAL gateway listener against real (stub) replica
    HTTP servers, while the control plane rolls the service, autoscales
    it, and a replica is hard-killed mid-load. Self-gating on:

    - **zero dropped requests**: across the rolling update, the
      autoscale event and the hard-kill, every request completes 200
      with an intact stream — no 5xx, no connect error surfaced, no
      truncation, no unexpected shed;
    - **TTFT overhead**: p95 time-to-first-token through the gateway
      minus p95 direct-to-replica stays within ``ttft_overhead_budget_ms``
      (the proxy hop must be cheap, not a second queue);
    - **prefix affinity beats random**: the per-key modal-endpoint hit
      rate exceeds the 1/replicas random-routing baseline (rendezvous
      hashing actually pins prefixes);
    - **shed is typed**: an over-capacity probe returns HTTP 429 with a
      Retry-After header and the typed error code — back-pressure,
      never collapse.

    A violated gate flips ``gates.ok``; main() turns that into a nonzero
    exit."""
    import http.client as hc
    import urllib.request
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from tpu_docker_api import errors as _errors
    from tpu_docker_api.config import Config
    from tpu_docker_api.daemon import Program

    prog = Program(Config(
        port=0, store_backend="memory", runtime_backend="fake",
        start_port=49000, end_port=49999, health_watch_interval=0,
        host_probe_interval_s=0, job_supervise_interval=interval_s,
        reconcile_interval=0, admission_enabled=True,
        admission_interval_s=interval_s,
        autoscale_interval_s=interval_s,
        autoscale_up_cooldown_s=interval_s,
        autoscale_down_cooldown_s=interval_s * 2,
        gateway_enabled=True, gateway_port=0,
        gateway_heartbeat_s=0.05, gateway_drain_deadline_s=5.0,
        gateway_retry_limit=3, gateway_retry_budget_ratio=1.0,
        gateway_connect_timeout_s=1.0, gateway_request_timeout_s=10.0,
        gateway_breaker_threshold=5, gateway_breaker_cooldown_s=0.1,
    ), host="127.0.0.1")
    prog.init()
    prog.start()

    # -- stub replica data plane -------------------------------------------
    class _StubHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _chunk(self, data: bytes) -> None:
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")

        def do_GET(self):
            body = b'{"status":"ok"}'
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            if n:
                self.rfile.read(n)
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            for i in range(3):
                self._chunk(json.dumps({"t": i}).encode() + b"\n")
                time.sleep(0.002)
            self._chunk(b"")

    class _ReplicaSyncer:
        """Binds a stub HTTP server on every routable endpoint's
        coordinator port the moment the routing table folds it in — the
        data-plane half of each fake-runtime replica. A quarantined port
        (hard-kill window) is left dead until its deadline so the
        gateway genuinely has to route around the corpse."""

        def __init__(self, gw):
            self.gw = gw
            self.servers: dict[int, ThreadingHTTPServer] = {}
            self.quarantine: dict[int, float] = {}
            self._stop = threading.Event()
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

        def _loop(self):
            while not self._stop.wait(0.005):
                try:
                    desired = {ep.port for ep in
                               self.gw.table.endpoints("svc")
                               if ep.routable and ep.port > 0}
                except Exception:  # pragma: no cover — table mid-fold
                    continue
                now = time.monotonic()
                for port in desired - set(self.servers):
                    if self.quarantine.get(port, 0) > now:
                        continue
                    try:
                        srv = ThreadingHTTPServer(("127.0.0.1", port),
                                                  _StubHandler)
                    except OSError:
                        continue  # port race with a closing server
                    threading.Thread(target=srv.serve_forever,
                                     daemon=True).start()
                    self.servers[port] = srv
                for port in set(self.servers) - desired:
                    self.kill(port, quarantine_s=0.0)

        def kill(self, port: int, quarantine_s: float) -> None:
            srv = self.servers.pop(port, None)
            if quarantine_s > 0:
                self.quarantine[port] = time.monotonic() + quarantine_s
            if srv is not None:
                threading.Thread(target=lambda: (srv.shutdown(),
                                                 srv.server_close()),
                                 daemon=True).start()

        def close(self):
            self._stop.set()
            self._thread.join(timeout=2)
            for port in list(self.servers):
                self.kill(port, quarantine_s=0.0)

    def call(method, path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{prog.api_server.port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        if out["code"] != 200:
            raise RuntimeError(f"{method} {path}: {out}")
        return out["data"]

    def wait_until(cond, what: str) -> bool:
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            if cond():
                return True
            time.sleep(0.005)
        return False

    # -- open-loop generator -----------------------------------------------
    results: list[dict] = []
    results_mu = threading.Lock()
    prefix_keys = [f"prefix-{i}" for i in range(8)]
    gen_stop = threading.Event()
    workers: list[threading.Thread] = []

    def one_request(key: str):
        rec = {"key": key, "status": 0, "endpoint": "", "ttft_ms": None,
               "truncated": False, "error": ""}
        t0 = time.perf_counter()
        try:
            conn = hc.HTTPConnection("127.0.0.1", gw_port, timeout=15)
            conn.request("POST", "/v1/svc/generate", body=b"{}",
                         headers={"Idempotency-Key": f"{key}-{t0}",
                                  "X-Prefix-Key": key})
            resp = conn.getresponse()
            rec["status"] = resp.status
            rec["endpoint"] = resp.getheader("X-Gateway-Endpoint") or ""
            body = b""
            while True:
                chunk = resp.read1(65536)
                if rec["ttft_ms"] is None:
                    rec["ttft_ms"] = (time.perf_counter() - t0) * 1e3
                if not chunk:
                    break
                body += chunk
            rec["truncated"] = b"gatewayTruncated" in body
            conn.close()
        except Exception as e:  # noqa: BLE001 — a failure IS the datum
            rec["error"] = f"{type(e).__name__}: {str(e)[:120]}"
        with results_mu:
            results.append(rec)

    def generator():
        i = 0
        period = 1.0 / rps
        while not gen_stop.is_set():
            t = threading.Thread(target=one_request,
                                 args=(prefix_keys[i % len(prefix_keys)],),
                                 daemon=True)
            t.start()
            workers.append(t)
            i += 1
            time.sleep(period)

    syncer = _ReplicaSyncer(prog.gateway)
    events = {"scaled": False, "rolled": False, "kill_recovered": False}
    try:
        gw_port = prog.gateway_server.port
        call("POST", "/api/v1/services", {
            "serviceName": "svc", "imageName": "serve",
            "chipsPerReplica": 2, "replicas": 1, "minReplicas": 1,
            "maxReplicas": 3, "ttftP95TargetMs": 200,
            "queueDepthTarget": 4, "replicaCapacityRps": 100.0})
        if not wait_until(lambda: syncer.servers, "first replica bound"):
            raise RuntimeError("first replica's stub never came up")

        gen = threading.Thread(target=generator, daemon=True)
        gen.start()
        slice_s = max(duration_s / 4, 0.3)
        time.sleep(slice_s)                      # steady on 1 replica

        # event 1: autoscale 1 -> 3 THROUGH the market, under live load
        call("POST", "/api/v1/services/svc/load", {"rps": 280.0})
        events["scaled"] = wait_until(
            lambda: len([ep for ep in prog.gateway.table.endpoints("svc")
                         if ep.routable]) >= 3 and len(syncer.servers) >= 3,
            "3 routable replicas")
        time.sleep(slice_s)

        # event 2: rolling spec update, replica by replica, under load
        # (job versions start at 0: "rolled" = every family's table
        # version moved PAST where it was before the PATCH)
        pre_roll = {ep.family: ep.version
                    for ep in prog.gateway.table.endpoints("svc")}
        t_roll = time.perf_counter()
        call("PATCH", "/api/v1/services/svc", {"imageName": "serve:v2"})
        roll_s = time.perf_counter() - t_roll
        events["rolled"] = wait_until(
            lambda: all(ep.version > pre_roll.get(ep.family, -1) for ep in
                        prog.gateway.table.endpoints("svc"))
            and len([ep for ep in prog.gateway.table.endpoints("svc")
                     if ep.routable]) >= 3,
            "all replicas rolled and routable")
        time.sleep(slice_s)

        # event 3: hard-kill one replica mid-load — data plane first
        # (connects refused for the quarantine window), then the
        # containers, so the supervisor must also notice and restart
        victim = next(ep for ep in prog.gateway.table.endpoints("svc")
                      if ep.routable)
        syncer.kill(victim.port, quarantine_s=0.3)
        st = prog.store.get_job(
            f"{victim.family}-{prog.job_versions.get(victim.family)}")
        for _, cname, *_rest in st.placements:
            prog.runtime.crash_container(cname)
        events["kill_recovered"] = wait_until(
            lambda: len([ep for ep in prog.gateway.table.endpoints("svc")
                         if ep.routable and ep.port in syncer.servers]) >= 3,
            "killed replica recovered")
        time.sleep(slice_s)

        gen_stop.set()
        gen.join(timeout=5)
        for w in workers:
            w.join(timeout=15)

        # direct-to-replica TTFT baseline with the SAME client code
        direct_ttfts: list[float] = []
        direct_port = next(iter(syncer.servers))
        for _ in range(40):
            t0 = time.perf_counter()
            conn = hc.HTTPConnection("127.0.0.1", direct_port, timeout=15)
            conn.request("POST", "/generate", body=b"{}")
            resp = conn.getresponse()
            resp.read1(65536)
            direct_ttfts.append((time.perf_counter() - t0) * 1e3)
            resp.read()
            conn.close()

        # shed probe: force the global in-flight cap to zero — the
        # refusal must be HTTP 429 + Retry-After + the typed error code
        old_cap = prog.gateway.max_inflight
        prog.gateway.max_inflight = 0
        try:
            conn = hc.HTTPConnection("127.0.0.1", gw_port, timeout=15)
            conn.request("POST", "/v1/svc/generate", body=b"{}",
                         headers={"Idempotency-Key": "shed-probe"})
            resp = conn.getresponse()
            shed_body = json.loads(resp.read())
            shed = {"status": resp.status,
                    "retry_after": resp.getheader("Retry-After"),
                    "code": shed_body.get("code")}
            conn.close()
        finally:
            prog.gateway.max_inflight = old_cap
        gateway_status = prog.gateway.status_view()
    finally:
        gen_stop.set()
        syncer.close()
        prog.stop()

    def p(ms: list[float], q: float) -> float:
        if not ms:
            return 0.0
        s = sorted(ms)
        return round(s[min(len(s) - 1, int(len(s) * q))], 3)

    ok = [r for r in results if r["status"] == 200 and not r["truncated"]
          and not r["error"]]
    failed = [r for r in results if r["error"] or r["status"] >= 500]
    sheds_inline = [r for r in results if r["status"] == 429]
    truncated = [r for r in results if r["truncated"]]
    ttfts = [r["ttft_ms"] for r in ok if r["ttft_ms"] is not None]
    ttft_p95 = p(ttfts, 0.95)
    direct_p95 = p(direct_ttfts, 0.95)

    by_key: dict[str, dict[str, int]] = {}
    for r in ok:
        if r["endpoint"]:
            by_key.setdefault(r["key"], {})
            by_key[r["key"]][r["endpoint"]] = (
                by_key[r["key"]].get(r["endpoint"], 0) + 1)
    modal = sum(max(eps.values()) for eps in by_key.values())
    keyed = sum(sum(eps.values()) for eps in by_key.values())
    affinity = round(modal / keyed, 4) if keyed else 0.0
    random_baseline = round(1 / 3, 4)

    gates = {
        "requests_total": len(results),
        "zero_dropped": (len(failed) == 0 and len(truncated) == 0
                         and len(sheds_inline) == 0 and len(ok) > 0),
        "scaled_under_load": events["scaled"],
        "rolled_under_load": events["rolled"],
        "roll_patch_s": round(roll_s, 3),
        # roll acks (not deadline expiry) must release each replica: a
        # 3-replica roll that burns even ONE full drain deadline is the
        # marker-behind-the-pointer regression
        "roll_acked_fast": roll_s < 5.0,
        "kill_recovered": events["kill_recovered"],
        "ttft_p95_ms": ttft_p95,
        "ttft_direct_p95_ms": direct_p95,
        "ttft_overhead_ms": round(ttft_p95 - direct_p95, 3),
        "ttft_overhead_budget_ms": ttft_overhead_budget_ms,
        "ttft_overhead_ok": ttft_p95 - direct_p95 <= ttft_overhead_budget_ms,
        "affinity_rate": affinity,
        "affinity_random_baseline": random_baseline,
        "affinity_beats_random": affinity > random_baseline,
        "shed_typed": (shed["status"] == 429
                       and shed["retry_after"] is not None
                       and shed["code"] == _errors.GatewayShed.code),
    }
    gates["ok"] = bool(
        gates["zero_dropped"] and gates["scaled_under_load"]
        and gates["rolled_under_load"] and gates["roll_acked_fast"]
        and gates["kill_recovered"]
        and gates["ttft_overhead_ok"] and gates["affinity_beats_random"]
        and gates["shed_typed"] and len(results) >= 20)
    return {
        "family": "serve-traffic",
        "iters": {"duration_s": duration_s, "rps": rps,
                  "prefix_keys": len(prefix_keys)},
        "requests": {"total": len(results), "ok": len(ok),
                     "failed": len(failed), "shed": len(sheds_inline),
                     "truncated": len(truncated),
                     "errors": sorted({r["error"] for r in failed
                                       if r["error"]})[:5]},
        "ttft_ms": {"p50": p(ttfts, 0.5), "p95": ttft_p95,
                    "direct_p95": direct_p95,
                    "overhead_p95": round(ttft_p95 - direct_p95, 3)},
        "affinity": {"rate": affinity, "random": random_baseline,
                     "keys": len(by_key)},
        "events": events,
        "shed_probe": shed,
        "gateway": {"retries": gateway_status["counters"].get(
                        "retries", 0),
                    "hedges": gateway_status["counters"].get("hedges", 0),
                    "breakerOpens": gateway_status["counters"].get(
                        "breakerOpens", 0)},
        "gates": gates,
    }


#: every control-plane family name — the one list argparse, the degraded
#: path and the dispatchers validate against (a typo'd family must fail
#: loudly, never silently fall through to a different benchmark)
def measure_control_plane_scale(n_objects: int = 50000, n_small: int = 1000,
                                n_gangs: int = 200, retention: int = 4,
                                list_limit: int = 100, list_iters: int = 40,
                                churn_families: int = 25,
                                steady_read_budget: int = 12) -> dict:
    """O(100k)-object scale family (``--control-plane --cp-family scale``):
    seed ``n_objects`` fake-runtime container families + ``n_gangs`` job
    families DIRECTLY into the store (consistent, drift-free world), boot
    a daemon with the event-driven reconciler and the history compactor
    armed, and gate the three tentpole claims:

    - **steady-state reconcile is O(changes), not O(objects)**: after one
      settling full pass, a zero-change auto pass must run in ``dirty``
      mode and cost ≤ ``steady_read_budget`` CountingKV reads. The
      contrast is measured, not assumed: a forced full dry-run pass must
      cost ≥ ``n_objects`` reads — so a reconciler that silently fell
      back to the O(N) scan blows the steady budget and FAILS, and a
      bypassed counter fails the contrast gate (no vacuous 0 ≤ budget);
    - **list p95 flat 1k → N**: a ``limit``-bounded list page must cost
      the same at ``n_small`` and at ``n_objects`` families (ratio-gated
      with a small absolute floor so tiny CI runs don't gate on noise),
      and a full continue-token walk must visit every family exactly
      once;
    - **history stays ≤ retention under churn**: families seeded with
      ``retention + 3`` versions compact down to exactly ``retention``
      version records — except the latest pointer's version and any
      version a live runtime member still references, which must
      SURVIVE.

    A violated gate flips ``gates.ok``; main() turns that into a nonzero
    exit."""
    import statistics
    import urllib.request

    from tpu_docker_api.config import Config
    from tpu_docker_api.daemon import Program
    from tpu_docker_api.runtime.fake import FakeRuntime
    from tpu_docker_api.runtime.spec import ContainerSpec
    from tpu_docker_api.schemas.job import JobState
    from tpu_docker_api.schemas.state import ContainerState
    from tpu_docker_api.state import keys
    from tpu_docker_api.state.keys import Resource
    from tpu_docker_api.state.kv import CountingKV, MemoryKV

    if min(n_objects, n_small) < 2 * list_limit:
        raise ValueError("scale family needs n >= 2 pages of families")
    if retention < 2 or churn_families < 4:
        raise ValueError("scale family needs retention >= 2 and >= 4 "
                         "churn families")
    churn_versions = retention + 3
    live_ref_families = 3  # churn families that keep an OLD member alive

    def seed_world(n_containers: int) -> tuple[CountingKV, FakeRuntime, dict]:
        """A drift-free world: n_containers running container families,
        churn_families over-retention families, n_gangs stopped job
        families — version records + latest pointers + version maps +
        runtime containers, batch-applied straight into the inner store
        (seeding is setup, not the thing measured)."""
        inner = MemoryKV(log_retain=16384)
        runtime = FakeRuntime(allow_exec=True)
        spec0 = ContainerSpec(name="seed", image="jax")
        ops: list[tuple] = []
        cmap: dict[str, int] = {}

        def flush():
            if ops:
                inner.apply(ops)
                ops.clear()

        names = []
        for i in range(n_containers):
            base = f"s{i}"
            name = f"{base}-0"
            st = ContainerState(container_name=name, version=0,
                                spec=dict(spec0.to_dict(), name=name))
            ops.append(("put", keys.version_key(Resource.CONTAINERS, base, 0),
                        json.dumps(st.to_dict())))
            ops.append(("put", keys.latest_key(Resource.CONTAINERS, base), "0"))
            cmap[base] = 0
            names.append(name)
            if len(ops) >= 100:
                flush()
        runtime.seed_running(names, spec0)
        live_names = []
        for i in range(churn_families):
            base = f"c{i}"
            latest = churn_versions - 1
            for v in range(churn_versions):
                name = f"{base}-{v}"
                st = ContainerState(container_name=name, version=v,
                                    spec=dict(spec0.to_dict(), name=name),
                                    desired_running=(v == latest))
                ops.append(("put",
                            keys.version_key(Resource.CONTAINERS, base, v),
                            json.dumps(st.to_dict())))
            ops.append(("put", keys.latest_key(Resource.CONTAINERS, base),
                        str(latest)))
            cmap[base] = latest
            live_names.append(f"{base}-{latest}")
            flush()
        runtime.seed_running(live_names, spec0)
        # a few OLD versions keep a stopped-but-present member (the
        # post-replace shape): the compactor must spare exactly those
        # versions, and the reconciler must see zero drift in them
        runtime.seed_running(
            [f"c{i}-0" for i in range(live_ref_families)], spec0,
            running=False)
        jmap: dict[str, int] = {}
        for i in range(n_gangs):
            base = f"g{i}"
            st = JobState(job_name=f"{base}-0", version=0, image="jax",
                          cmd=[], env=[], binds=[], chip_count=0,
                          coordinator_port=0, placements=[],
                          desired_running=False, phase="stopped")
            ops.append(("put", keys.version_key(Resource.JOBS, base, 0),
                        json.dumps(st.to_dict())))
            ops.append(("put", keys.latest_key(Resource.JOBS, base), "0"))
            jmap[base] = 0
            if len(ops) >= 100:
                flush()
        ops.append(("put", keys.VERSIONS_CONTAINER_KEY,
                    json.dumps(cmap, sort_keys=True)))
        ops.append(("put", keys.VERSIONS_JOB_KEY,
                    json.dumps(jmap, sort_keys=True)))
        flush()
        return CountingKV(inner), runtime, cmap

    def boot(counting: CountingKV, runtime: FakeRuntime) -> Program:
        prog = Program(Config(
            port=0, store_backend="memory", runtime_backend="fake",
            start_port=45000, end_port=45999, health_watch_interval=0,
            host_probe_interval_s=0, job_supervise_interval=0,
            reconcile_on_start=False, reconcile_interval=0,
            autoscale_interval_s=0,
            reconcile_full_interval_s=3600,  # event-driven; full never due
            history_retention_versions=retention,
            history_compact_interval_s=3600,  # passes run via the route
        ), host="127.0.0.1", kv=counting, runtime=runtime)
        prog.init()
        prog.start()
        return prog

    def call(prog, method, path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{prog.api_server.port}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=600) as resp:
            out = json.loads(resp.read())
        if out["code"] != 200:
            raise RuntimeError(f"{method} {path}: {out}")
        return out["data"]

    def wait_synced(prog, timeout_s: float = 180.0) -> None:
        """Block until the dirty-feed reflector finished its initial
        sync. Measurements are STEADY-STATE claims: during the initial
        100k-event replay the informer thread is CPU-bound and competes
        with every request for the GIL/store lock — that cold-start cost
        is real but one-time, and it is not what the gates are about."""
        deadline = time.monotonic() + timeout_s
        while not prog.reconcile_informer.synced:
            if time.monotonic() > deadline:
                raise RuntimeError("dirty-feed informer never synced")
            time.sleep(0.05)
        # synced flips before the initial synthetic diff finishes FIRING
        # (100k+ dirty-set observes on the informer thread) — wait for
        # the mark counter to go quiet so measurements don't race the
        # one-time replay storm
        last = -1
        while True:
            cur = prog.reconciler.dirty_view()["marksTotal"]
            if cur == last:
                return
            if time.monotonic() > deadline:
                raise RuntimeError("dirty feed never went quiet")
            last = cur
            time.sleep(0.2)

    def list_p95_ms(prog) -> float:
        # bench and daemon share one CPython process, so generational GC
        # passes walk the 100k+ seeded state objects and land as ~60 ms
        # pauses in the p95 — an artifact of the in-process harness (a
        # real deployment's store lives out of process), not of the list
        # path this gate is about. Freeze the (static) seeded world for
        # the measurement window; must run AFTER wait_synced or the
        # informer's still-allocating sync re-creates the pressure.
        import gc

        gc.collect()
        gc.freeze()
        try:
            lat = []
            for _ in range(list_iters):
                t0 = time.perf_counter()
                page = call(prog, "GET",
                            f"/api/v1/containers?limit={list_limit}")
                lat.append((time.perf_counter() - t0) * 1e3)
                if not page["items"]:
                    raise RuntimeError(
                        "empty first list page on a seeded world")
        finally:
            gc.unfreeze()
        qs = statistics.quantiles(lat, n=20)
        return round(min(qs[18], max(lat)), 3)

    # -- small-scale anchor: the flat-list baseline ---------------------------
    counting, runtime, _ = seed_world(n_small)
    prog = boot(counting, runtime)
    try:
        wait_synced(prog)
        p95_small = list_p95_ms(prog)
    finally:
        prog.stop()

    # -- the big world --------------------------------------------------------
    counting, runtime, cmap = seed_world(n_objects)
    expected_families = n_objects + churn_families
    prog = boot(counting, runtime)
    try:
        wait_synced(prog)
        p95_large = list_p95_ms(prog)

        # full continue-token walk: every family exactly once, no dup/skip
        seen: set[str] = set()
        walked = 0
        token = ""
        while True:
            q = f"/api/v1/containers?limit=2000" + (
                f"&continue={token}" if token else "")
            page = call(prog, "GET", q)
            for it in page["items"]:
                walked += 1
                seen.add(it["name"])
            token = page["continue"]
            if not token:
                break
        walk_exact = (walked == expected_families
                      and len(seen) == expected_families)

        # settle: one real full pass consumes the startup/relist dirty
        # backlog; the seeded world must be drift-free
        settle = call(prog, "GET", "/api/v1/reconcile?mode=full")
        steady_clean = (settle["mode"] == "full"
                        and settle["driftCount"] == 0)

        # steady state: a zero-change AUTO pass must choose dirty mode and
        # cost O(changes) — here, O(0) plus the bounded adoption scans
        before = counting.reads()
        steady = call(prog, "GET", "/api/v1/reconcile")
        steady_reads = counting.reads() - before
        steady_mode = steady["mode"]

        # contrast, measured not assumed: the full scan really is O(N)
        before = counting.reads()
        call(prog, "GET", "/api/v1/reconcile?mode=full&dryRun=true")
        full_reads = counting.reads() - before

        # bounded history: compact, then audit the churned families
        compact = call(prog, "POST", "/api/v1/compact")
        inner = counting.inner
        latest_ok = live_ok = True
        worst_nonlive = 0
        for i in range(churn_families):
            base = f"c{i}"
            vkeys = inner.keys_prefix(
                f"{keys.PREFIX}/containers/{base}/v/")
            versions = {int(k.rsplit("/", 1)[1]) for k in vkeys}
            if cmap[base] not in versions:
                latest_ok = False
            if i < live_ref_families:
                if 0 not in versions:  # the live OLD member's version
                    live_ok = False
                # the spared live version rides above retention by design
                worst_nonlive = max(worst_nonlive, len(versions - {0}))
            else:
                worst_nonlive = max(worst_nonlive, len(versions))
    finally:
        prog.stop()

    flat_budget = 4.0
    flat_floor_ms = 5.0
    flat_ratio = round(p95_large / max(p95_small, 1e-6), 2)
    gates = {
        "steady_mode": steady_mode,
        "steady_reads": steady_reads,
        "steady_read_budget": steady_read_budget,
        "steady_reads_bounded": (steady_mode == "dirty"
                                 and steady_reads <= steady_read_budget),
        "steady_clean": steady_clean,
        "full_scan_reads": full_reads,
        "full_scan_counted": full_reads >= n_objects,
        "list_p95_small_ms": p95_small,
        "list_p95_large_ms": p95_large,
        "list_flat_ratio": flat_ratio,
        "list_flat_budget": flat_budget,
        "list_flat_floor_ms": flat_floor_ms,
        "list_flat": (flat_ratio <= flat_budget
                      or p95_large <= flat_floor_ms),
        "walk_exact": walk_exact,
        "retention": retention,
        "retention_worst_versions": worst_nonlive,
        "retention_ok": worst_nonlive <= retention,
        "latest_protected": latest_ok,
        "live_version_protected": live_ok,
    }
    gates["ok"] = bool(
        gates["steady_reads_bounded"] and gates["steady_clean"]
        and gates["full_scan_counted"] and gates["list_flat"]
        and gates["walk_exact"] and gates["retention_ok"]
        and gates["latest_protected"] and gates["live_version_protected"])
    return {
        "family": "scale",
        "iters": {"objects": n_objects, "small": n_small,
                  "gangs": n_gangs, "churn_families": churn_families,
                  "list_iters": list_iters, "list_limit": list_limit},
        "steady_reads": steady_reads,
        "full_scan_reads": full_reads,
        "list_p95_ms": {"small": p95_small, "large": p95_large,
                        "ratio": flat_ratio},
        "compact": {k: compact[k] for k in
                    ("trimmedTotal", "protectedLive", "chunks")},
        "gates": gates,
    }


CP_FAMILIES = ("create", "churn", "failover", "brownout", "reads", "fanout",
               "preempt", "resize", "serve-scale", "serve-traffic",
               "scale", "shard", "workflow")


# control-plane family dispatch — shared by the --control-plane branch
# and the degraded-backend evidence path (ROADMAP item 5: a dead TPU
# backend degrades the artifact instead of erasing it)
def _run_cp_family(family: str, args) -> dict:
    if family not in CP_FAMILIES:
        raise ValueError(f"unknown control-plane family {family!r}: "
                         f"choose from {CP_FAMILIES}")
    if family == "churn":
        return measure_control_plane_churn(
            args.cp_iters, args.churn_gangs or max(args.cp_iters // 10, 2))
    if family == "failover":
        return measure_control_plane_failover(
            args.failovers, ttl_s=args.failover_ttl)
    if family == "brownout":
        return measure_control_plane_brownout(
            n_cycles=args.brownout_cycles, n_outages=args.brownout_outages,
            outage_s=args.brownout_outage_s,
            latency_ms=args.brownout_latency_ms)
    if family == "shard":
        return measure_control_plane_shard(
            n_cycles=args.shard_cycles, ttl_s=args.shard_ttl,
            store_rtt_ms=args.shard_rtt_ms)
    if family == "reads":
        return measure_control_plane_reads(
            args.cp_iters, readers=args.read_workers)
    if family == "fanout":
        return measure_control_plane_fanout(
            iters=args.fanout_iters, latency_ms=args.fanout_latency_ms)
    if family == "preempt":
        return measure_control_plane_preempt(
            n_low=args.preempt_low, n_high=args.preempt_high)
    if family == "resize":
        return measure_control_plane_resize(iters=args.resize_iters)
    if family == "serve-scale":
        return measure_control_plane_serve_scale(iters=args.serve_iters)
    if family == "workflow":
        return measure_control_plane_workflow(iters=args.workflow_iters)
    if family == "serve-traffic":
        return measure_control_plane_serve_traffic(
            duration_s=args.traffic_duration, rps=args.traffic_rps)
    if family == "scale":
        return measure_control_plane_scale(
            n_objects=args.scale_objects, n_small=args.scale_small,
            n_gangs=args.scale_gangs, retention=args.scale_retention)
    return measure_control_plane(args.cp_iters, args.cp_runtime)


def _run_cp_family_budgeted(family: str, args, budget_s: float) -> dict:
    """Run one control-plane family under its own WALL budget. The family
    runs in a worker thread; when the budget expires the caller gets a
    ``TimeoutError`` immediately instead of blocking until the driver's
    hard kill — so this family's structured line (and every later
    family's) reaches the artifact before the deadline. The abandoned
    worker is a daemon thread: it dies with the process and its result,
    if one ever materializes, is discarded."""
    box: dict = {}

    def run():
        try:
            box["cp"] = _run_cp_family(family, args)
        except Exception as e:  # noqa: BLE001 — re-raised on the caller
            box["err"] = e

    t0 = time.monotonic()
    worker = threading.Thread(target=run, daemon=True,
                              name=f"cp-family-{family}")
    worker.start()
    worker.join(timeout=max(budget_s, 1e-3))
    if "err" in box:
        raise box["err"]
    if "cp" not in box:
        raise TimeoutError(
            f"family wall budget exhausted after {budget_s:.1f}s")
    cp = box["cp"]
    if isinstance(cp, dict):
        cp.setdefault("wall_s", round(time.monotonic() - t0, 3))
    return cp


def _family_budget_s(args, fallback_s: float) -> float:
    """Per-family budget: ``--family-budget`` wins, then
    ``BENCH_FAMILY_BUDGET_S``, then the caller's fallback (the remaining
    share of the run's total budget)."""
    if getattr(args, "family_budget", 0.0):
        return float(args.family_budget)
    try:
        env = float(os.environ.get("BENCH_FAMILY_BUDGET_S", 0) or 0)
    except ValueError:
        env = 0.0
    return env if env > 0 else fallback_s


def _cp_headline(family: str, cp: dict) -> tuple[str, float, str]:
    if family not in CP_FAMILIES:
        raise ValueError(f"unknown control-plane family {family!r}")
    if family == "failover":
        return ("control_plane_failover_recovery_ms_p50",
                cp["recovery_ms"]["p50"], "ms")
    if family == "brownout":
        return ("control_plane_brownout_recovery_ms_p50",
                cp["recovery_ms"]["p50"], "ms")
    if family == "shard":
        return ("control_plane_shard_churn_speedup", cp["speedup"], "x")
    if family == "churn":
        return ("control_plane_churn_create_ready_ms_p50",
                cp["create_ready_ms_p50"], "ms")
    if family == "reads":
        return ("control_plane_reads_standby_informer_rps",
                cp["roles"]["standby_informer"]["rps"], "reads/s")
    if family == "fanout":
        return ("control_plane_fanout_gang8_create_ms",
                cp["members"]["8"]["create_ms_min"], "ms")
    if family == "preempt":
        return ("control_plane_preempt_time_to_placed_ms_p50",
                cp["time_to_placed_ms"]["p50"], "ms")
    if family == "resize":
        return ("control_plane_resize_time_to_shrunk_ms_p50",
                cp["time_to_shrunk_ms"]["p50"], "ms")
    if family == "serve-scale":
        return ("control_plane_serve_scale_time_to_scaled_ms_p50",
                cp["time_to_scaled_ms"]["p50"], "ms")
    if family == "serve-traffic":
        return ("control_plane_serve_traffic_ttft_p95_ms",
                cp["ttft_ms"]["p95"], "ms")
    if family == "scale":
        return ("control_plane_scale_steady_reconcile_reads",
                cp["steady_reads"], "reads")
    if family == "workflow":
        return ("control_plane_workflow_dag_complete_ms_p50",
                cp["dag_complete_ms"]["p50"], "ms")
    return ("container_create_ready_ms_p50", cp["create_ready_ms_p50"], "ms")


def degraded_control_plane_evidence(args, deadline: float) -> int:
    """The partial-but-green path (ROADMAP item 5 first slice): the TPU
    backend is dead, so no compute point can run — but none of the
    control-plane families needs a TPU. Run them, emitting each family's
    gated JSON line INCREMENTALLY (a later hang cannot erase an earlier
    family's evidence), then exit 0 when at least one family is green:
    the artifact degrades instead of vanishing (the BENCH_r04/r05 class).
    ``BENCH_DEGRADED_FAMILIES`` (comma list) overrides the default set."""
    families = [f.strip() for f in os.environ.get(
        "BENCH_DEGRADED_FAMILIES",
        "churn,preempt,resize,serve-scale,serve-traffic,scale,shard,"
        "workflow,brownout"
        ).split(",")
        if f.strip()]
    green = 0
    for idx, family in enumerate(families):
        if family not in CP_FAMILIES:
            emit({"metric": f"control_plane_{family}", "value": None,
                  "unit": "ms", "vs_baseline": None, "rc": 1,
                  "error": {"error": f"unknown family {family!r} in "
                                     f"BENCH_DEGRADED_FAMILIES "
                                     f"(choose from {list(CP_FAMILIES)})",
                            "family": family}})
            continue
        if time.monotonic() > deadline:
            emit({"metric": f"control_plane_{family}", "value": None,
                  "unit": "ms", "vs_baseline": None, "rc": 1,
                  "error": {"error": "budget exhausted", "family": family}})
            continue
        # each family gets an equal share of what's left, so one slow
        # family consumes ITS slice of the wall, never the families
        # behind it in line
        remaining = max(deadline - time.monotonic(), 1e-3)
        share = remaining / max(len(families) - idx, 1)
        try:
            cp = _run_cp_family_budgeted(
                family, args, min(_family_budget_s(args, share), remaining))
        except Exception as e:  # noqa: BLE001 — one family must not
            # erase the others' evidence
            emit({"metric": f"control_plane_{family}", "value": None,
                  "unit": "ms", "vs_baseline": None, "rc": 1,
                  "error": {"error": f"{type(e).__name__}: {str(e)[:300]}",
                            "family": family}})
            continue
        metric, value, unit = _cp_headline(family, cp)
        gates_ok = bool(cp.get("gates", {"ok": True}).get("ok"))
        emit({"metric": metric, "value": value, "unit": unit,
              "vs_baseline": 1.0, "rc": 0 if gates_ok else 1, "extra": cp})
        if gates_ok:
            green += 1
    emit({"metric": "bench_degraded", "value": green, "unit": "families",
          "vs_baseline": 1.0 if green else 0.0, "rc": 0 if green else 1,
          "extra": {"families": families, "green": green,
                    "note": "TPU backend dead; control-plane evidence "
                            "emitted instead of an empty rc-1 artifact"}})
    return 0 if green else 1


def main() -> int | None:
    """Returns a nonzero exit code on backend-init failure (consumed by
    the ``sys.exit(main())`` entry); None = success."""
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="llama3-1b")
    parser.add_argument("--batch", type=int, default=0, help="0 = auto")
    parser.add_argument("--seq", type=int, default=0, help="0 = preset default")
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--warmup", type=int, default=2)
    parser.add_argument("--platform", default="", help="force jax platform")
    parser.add_argument("--control-plane", action="store_true",
                        help="bench the control plane only (no JAX)")
    parser.add_argument("--cp-runtime", default="fake",
                        choices=["fake", "docker"])
    parser.add_argument("--cp-family", default="create",
                        choices=list(CP_FAMILIES),
                        help="create = create→ready latency; churn = "
                             "create→ready→replace→delete for containers "
                             "AND gangs with store round-trips per flow; "
                             "failover = kill the HA leader under churn "
                             "load, time-to-recovered-writes on the "
                             "standby; brownout = slow then kill the "
                             "STORE under churn, gating typed+bounded "
                             "calls, marked stale reads, zero spurious "
                             "gang restarts and time-to-recovered-writes "
                             "after heal; reads = hammer the GET surface on "
                             "leader + informer standby + read-through "
                             "standby, with a store-reads-per-request "
                             "audit; fanout = gang lifecycle at member "
                             "counts {2,4,8} against slow engines, "
                             "gating wall-clock O(slowest host), gang "
                             "ordering and store round trips; preempt = "
                             "fill the pool with preemptible gangs, "
                             "submit production gangs, time-to-placed "
                             "p50/p95 + preemptions-per-admission, gating "
                             "all-high-placed / zero-preempt-with-holes / "
                             "legacy refusal preserved; resize = elastic "
                             "gangs: partial-preempt shrink + grow-back "
                             "through the queue + host-loss shrink, "
                             "gating time-to-shrunk and zero full "
                             "preemptions when shrink suffices; "
                             "serve-scale = "
                             "offered-load step against a Service beside "
                             "batch training, gating time-to-scaled, SLO "
                             "recovery, scale-up-through-the-admission-"
                             "queue and zero manual operations; scale = "
                             "seed 50-100k fake-runtime objects, gating "
                             "zero-change reconcile reads O(changes) vs "
                             "the measured O(N) full scan, flat list p95 "
                             "1k->N, and version history <= retention "
                             "under churn; workflow = train->eval->promote "
                             "DAG over real HTTP, gating "
                             "time-to-DAG-complete, exactly-once step "
                             "effects, promote-through-rolling-update and "
                             "admission-queue entry")
    parser.add_argument("--cp-iters", type=int, default=100,
                        help="iterations (create family) / container "
                             "cycles (churn family) / total GETs per role "
                             "(reads family)")
    parser.add_argument("--read-workers", type=int, default=4,
                        help="concurrent reader threads for the reads "
                             "family")
    parser.add_argument("--churn-gangs", type=int, default=0,
                        help="gang cycles for the churn family; 0 = "
                             "cp-iters // 10 (min 2)")
    parser.add_argument("--failovers", type=int, default=5,
                        help="leader kills for the failover family")
    parser.add_argument("--brownout-cycles", type=int, default=12,
                        help="baseline churn cycles for the brownout "
                             "family (latency window runs a third)")
    parser.add_argument("--brownout-outages", type=int, default=3,
                        help="hard outage + heal rounds for the brownout "
                             "family")
    parser.add_argument("--brownout-outage-s", type=float, default=0.8,
                        help="seconds the store stays dark per brownout "
                             "round")
    parser.add_argument("--brownout-latency-ms", type=float, default=30.0,
                        help="injected per-op store latency for the "
                             "brownout family's slow-store window")
    parser.add_argument("--fanout-iters", type=int, default=3,
                        help="gang lifecycle cycles per member count for "
                             "the fanout family (min wall is gated)")
    parser.add_argument("--fanout-latency-ms", type=float, default=50.0,
                        help="injected per-engine-call latency for the "
                             "fanout family")
    parser.add_argument("--preempt-low", type=int, default=4,
                        help="preemptible gangs filling the pool for the "
                             "preempt family")
    parser.add_argument("--preempt-high", type=int, default=3,
                        help="production gangs submitted under pressure "
                             "for the preempt family")
    parser.add_argument("--resize-iters", type=int, default=3,
                        help="partial-preempt shrink + grow-back cycles "
                             "for the resize family")
    parser.add_argument("--serve-iters", type=int, default=3,
                        help="offered-load step cycles for the serve-scale "
                             "family")
    parser.add_argument("--workflow-iters", type=int, default=3,
                        help="train->eval->promote DAG runs for the "
                             "workflow family")
    parser.add_argument("--traffic-duration", type=float, default=4.0,
                        help="open-loop load seconds for the serve-traffic "
                             "family (split across steady / autoscale / "
                             "roll / hard-kill phases)")
    parser.add_argument("--traffic-rps", type=float, default=40.0,
                        help="open-loop request rate through the gateway "
                             "for the serve-traffic family")
    parser.add_argument("--scale-objects", type=int, default=50000,
                        help="container families seeded for the scale "
                             "family's big world")
    parser.add_argument("--scale-small", type=int, default=1000,
                        help="container families in the scale family's "
                             "small-world list-latency anchor")
    parser.add_argument("--scale-gangs", type=int, default=200,
                        help="job families seeded beside the containers "
                             "for the scale family")
    parser.add_argument("--scale-retention", type=int, default=4,
                        help="history_retention_versions under test in "
                             "the scale family")
    parser.add_argument("--skip-cp-evidence", action="store_true",
                        help="on backend-init failure, keep the legacy "
                             "fast rc-1 exit instead of running the no-TPU "
                             "control-plane families as degraded evidence")
    parser.add_argument("--failover-ttl", type=float, default=1.0,
                        help="leader lease TTL seconds for the failover "
                             "family (the recovery ceiling under test)")
    parser.add_argument("--shard-cycles", type=int, default=60,
                        help="churn cycles per shard per cell for the "
                             "shard family")
    parser.add_argument("--shard-ttl", type=float, default=1.5,
                        help="per-shard lease TTL seconds for the shard "
                             "family's blast-radius phase")
    parser.add_argument("--shard-rtt-ms", type=float, default=40.0,
                        help="modeled store write round trip for the "
                             "shard family (an etcd-like regime; the "
                             "per-shard writer serialization under test "
                             "is invisible at MemoryKV microseconds)")
    parser.add_argument("--full", action="store_true",
                        help="also run the long-tail riders (the second "
                             "stream-count per serving point, unfused "
                             "roofline, prefix, chunked prefill, encdec, "
                             "family trains)")
    parser.add_argument("--budget", type=float, default=0.0,
                        help="total seconds budget; 0 = env BENCH_BUDGET_S "
                             "or 1500")
    parser.add_argument("--family-budget", type=float, default=0.0,
                        help="per-control-plane-family wall budget "
                             "seconds; a family that exceeds it emits a "
                             "structured timeout line and the run moves "
                             "on. 0 = env BENCH_FAMILY_BUDGET_S, else an "
                             "equal share of the remaining total budget")
    args = parser.parse_args()
    try:
        budget_s = args.budget or float(
            os.environ.get("BENCH_BUDGET_S", 1500))
    except ValueError:  # malformed env must not produce an empty artifact
        budget_s = 1500.0
    deadline = time.monotonic() + budget_s

    if args.control_plane:
        # loud-failure contract (same as bench_boot): a dead control-plane
        # probe must exit nonzero with a structured line, never silently
        # produce an empty artifact the driver reads as "pass"
        try:
            cp = _run_cp_family_budgeted(
                args.cp_family, args, _family_budget_s(args, budget_s))
        except Exception as e:
            emit({"metric": f"control_plane_{args.cp_family}", "value": None,
                  "unit": "ms", "vs_baseline": None, "rc": 1,
                  "error": {"error": f"{type(e).__name__}: {str(e)[:300]}",
                            "family": args.cp_family}})
            return 1
        metric, value, unit = _cp_headline(args.cp_family, cp)
        emit({
            "metric": metric,
            "value": value,
            "unit": unit,
            # the reference publishes no latency numbers (BASELINE.md) —
            # this metric exists to be measured, not compared
            "vs_baseline": 1.0,
            "extra": cp,
        })
        if not cp.get("gates", {"ok": True})["ok"]:
            emit({"metric": f"control_plane_{args.cp_family}_gate",
                  "value": 0,
                  "unit": "bool", "vs_baseline": 0.0, "rc": 1,
                  "error": {"error": f"regression gate failed: "
                                     f"{cp['gates']}",
                            "family": args.cp_family}})
            return 1
        return

    # first line of every run: a schema-valid diagnostic emitted BEFORE any
    # backend-dependent work, so the artifact is never empty — a dead TPU
    # driver used to hang silently inside the first compile and the
    # driver's kill erased everything (the BENCH_r04/MULTICHIP_r05 class).
    # Backend init failure is itself a structured line + fast nonzero exit.
    try:
        import jax

        if args.platform:
            jax.config.update("jax_platforms", args.platform)
        boot_devices = jax.devices()
    except Exception as e:
        emit({"metric": "bench_boot", "value": None, "unit": "devices",
              "vs_baseline": None, "rc": 1,
              "error": f"backend-init: {type(e).__name__}: {str(e)[:200]}"})
        if args.skip_cp_evidence:
            return 1
        # evidence degrades instead of vanishing (ROADMAP item 5): none of
        # the control-plane families needs a TPU, so a dead backend still
        # produces a partial-but-green artifact with gated family lines
        return degraded_control_plane_evidence(args, deadline)
    emit({"metric": "bench_boot", "value": len(boot_devices),
          "unit": "devices", "vs_baseline": 1.0, "rc": 0,
          "extra": {"platform": boot_devices[0].platform,
                    "device_count": len(boot_devices),
                    "device_kind": getattr(boot_devices[0], "device_kind",
                                           "")}})

    import dataclasses

    from tpu_docker_api.models.llama import llama_presets, param_count
    from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
    from tpu_docker_api.scheduler.topology import GENERATIONS
    from tpu_docker_api.train.trainer import (
        create_train_state,
        make_train_step,
        synthetic_batch,
    )

    preset = args.preset
    devices = jax.devices()[:1]  # tokens/sec **per chip**: bench on one
    platform = devices[0].platform
    on_tpu = platform == "tpu"
    # measured-optimal single-v5e batch per TPU preset (params + adam state
    # + activations must fit 16GB HBM): llama3-1b fits batch 4 since the
    # lean-remat/dense-lse memory work (13.0k tok/s vs 12.4k at batch 2;
    # batch 5+ OOM); 350m peaks at 8 (41.2k tok/s vs 39.0k at 16)
    tpu_preset_batch = {"llama3-1b": 4, "bench-350m": 8}
    if not on_tpu and preset in tpu_preset_batch:
        preset = "tiny"  # CPU fallback so the bench runs without hardware

    cfg = llama_presets()[preset]
    if args.seq:
        cfg = dataclasses.replace(cfg, max_seq_len=args.seq)
        seq = args.seq
    else:
        seq = min(cfg.max_seq_len, 2048)
    batch = args.batch or (tpu_preset_batch.get(preset, 8) if on_tpu else 2)

    mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=1), devices=devices)
    state, opt = create_train_state(cfg, mesh, jax.random.PRNGKey(0))
    n_params = param_count(state.params)
    step_fn = make_train_step(cfg, mesh, opt)

    tokens = synthetic_batch(jax.random.PRNGKey(1), batch, seq, cfg.vocab_size)

    t_compile = time.perf_counter()
    for _ in range(max(args.warmup, 1)):  # ≥1: the first step compiles
        state, metrics = step_fn(state, tokens)
    # host read, not block_until_ready: remote-tunnel platforms have been
    # seen returning from block_until_ready before execution finishes, which
    # inflates throughput ~1000x; a device→host value transfer cannot lie
    float(metrics["loss"])
    compile_s = time.perf_counter() - t_compile

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = step_fn(state, tokens)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0

    steps_per_s = args.steps / dt
    tokens_per_s = steps_per_s * batch * seq
    flops_per_token = cfg.flops_per_token(seq)
    achieved_flops = tokens_per_s * flops_per_token

    # peak flops for the chip actually benched
    from tpu_docker_api.scheduler.topology import peak_bf16_flops_for

    peak = peak_bf16_flops_for(devices[0])
    if peak is None:
        peak = GENERATIONS["v5e"].peak_bf16_flops if on_tpu else 1e12
    mfu = achieved_flops / peak

    result = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_s, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {
            "preset": preset,
            "params": n_params,
            "batch": batch,
            "seq": seq,
            "steps_per_sec": round(steps_per_s, 4),
            "mfu": round(mfu, 4),
            "model_tflops_per_sec": round(achieved_flops / 1e12, 2),
            "compile_plus_warmup_s": round(compile_s, 1),
            "platform": platform,
            "device_kind": getattr(devices[0], "device_kind", ""),
            "final_loss": round(final_loss, 4),
        },
    }
    # BASELINE.md's second metric (create→ready p50) rides along in extras
    # so the driver's BENCH artifact always records it
    try:
        result["extra"]["control_plane"] = measure_control_plane(50)
    except Exception as e:  # never let the latency rider sink the headline,
        # but never let its death pass silently either: structured error in
        # extra AND a dedicated nonzero-signal line (the bench_boot
        # loud-failure contract) so the driver sees the dead probe
        cp_err = {"error": f"{type(e).__name__}: {str(e)[:300]}",
                  "family": "create"}
        result["extra"]["control_plane"] = cp_err
        emit({"metric": "control_plane_create", "value": None, "unit": "ms",
              "vs_baseline": None, "rc": 1, "error": cp_err})
    # headline FIRST — durable before any rider runs (VERDICT r4 item 1)
    emit(result)

    summary: dict = {}
    skipped: list[str] = []
    if on_tpu:
        # the training state above is ~14 GB of HBM — free it before the
        # serving riders or the 8 GB weight synthesis OOMs
        import gc

        del state, metrics, step_fn, tokens
        gc.collect()
        run_riders(riders(full=args.full), deadline, summary, skipped)

    # final line: headline again with a compact rider digest, so a
    # last-line tail parse lands on the headline. Deliberately small —
    # full rider detail already went out on the per-rider lines.
    final = {k: result[k] for k in ("metric", "value", "unit",
                                    "vs_baseline")}
    final["extra"] = {
        "preset": result["extra"]["preset"],
        "mfu": result["extra"]["mfu"],
        "platform": result["extra"]["platform"],
        "control_plane_p50_ms": result["extra"]["control_plane"].get(
            "create_ready_ms_p50"),
        "riders": summary,
        "riders_skipped": skipped,
    }
    emit(final)


def run_riders(plan, deadline: float, summary: dict,
               skipped: list[str]) -> None:
    """Run each (name, est_s, fn) rider, flushing one schema-valid JSON
    line per rider the moment it completes. A rider whose estimated cost
    exceeds the remaining budget is skipped LOUDLY (its own line) —
    running into the driver's hard timeout loses everything after the
    kill point, which is exactly what emptied BENCH_r04.json."""
    import gc

    import jax

    for name, est_s, fn in plan:
        remaining = deadline - time.monotonic()
        if remaining < est_s:
            skipped.append(name)
            emit({"metric": f"rider_{name}", "value": None, "unit": "",
                  "vs_baseline": None, "skipped": True,
                  "reason": f"budget: {remaining:.0f}s left < "
                            f"~{est_s:.0f}s estimated"})
            continue
        t0 = time.monotonic()
        try:
            value, unit, vs, extra = fn()
            extra["rider_wall_s"] = round(time.monotonic() - t0, 1)
            emit({"metric": f"rider_{name}", "value": value, "unit": unit,
                  "vs_baseline": vs, "extra": extra})
            summary[name] = value
        except Exception as e:
            emit({"metric": f"rider_{name}", "value": None, "unit": "",
                  "vs_baseline": None, "error": str(e)[:200]})
            summary[name] = None
        # free the rider's compiled executables + weights before the
        # next one: accumulated caches on a 16 GB chip starve the 8B
        # engines into allocator thrash (measured 18.8 tok/s on an
        # otherwise-490 point, round 3). Costs a recompile per rider;
        # reliability wins.
        jax.clear_caches()
        gc.collect()


def riders(full: bool = False):
    """The rider plan: (name, estimated_seconds, fn) in priority order.

    Estimates are deliberately generous (weight synthesis + one compile
    each) — an over-estimate skips a rider that might have fit, an
    under-estimate risks the driver's kill, and only one of those
    failure modes loses data. Default = the VERDICT r4 "done" set: 8B
    decode (fused), slot serving, paged capacity — plus tail latency.
    The --full tail re-adds the round-3/4 riders that validate captures
    normally cover."""
    plan = [
        ("llama3_8b_decode_fused", 340, rider_8b_decode_fused),
        ("slot_serving_1b", 200, rider_slot_serving_1b),
        ("slot_serving_8b_int8", 340, rider_slot_serving_8b),
        ("paged_capacity_8b", 340, rider_paged_capacity),
        ("tail_latency_1b", 200, rider_tail_latency),
    ]
    if full:
        plan += [
            ("decode_unfused", 300, rider_8b_decode_unfused),
            ("slot_serving_1b_16s", 200, rider_slot_serving_1b_16),
            ("slot_serving_8b_int8_8s", 340, rider_slot_serving_8b_8),
            ("prefix_cache_1b", 240, rider_prefix_cache),
            ("paged_prefix_8b", 340, rider_paged_prefix),
            ("paged_admission_8b", 340, rider_paged_admission),
            ("chunked_prefill_1b", 240, rider_chunked_prefill),
            ("tail_latency_1b_16s", 200, rider_tail_latency_16),
            ("encdec_slot_serving", 240, rider_encdec_serving),
            ("family_trains", 420, rider_family_trains),
        ]
    return plan


def rider_8b_decode_fused():
    """North-star 8B int8 serving + the fused decode roofline (the
    round-4 headline: 69-71% of the weight-streaming roof)."""
    from tpu_docker_api.infer.quantize import bench_int8_serving
    from tpu_docker_api.infer.servebench import bench_decode_roofline

    res = bench_int8_serving(batch=64, reps=2, fuse=True)
    res.pop("ok")
    try:
        roof = bench_decode_roofline(batch=64, prompt_len=128, new_tok=64,
                                     max_seq=512, reps=2, fuse=True)
    except Exception as e:
        # a roofline failure must not discard the minutes-long int8
        # serving measurement already in hand (same containment the
        # pre-r5 measure_8b_inference applied)
        res["roofline_error"] = str(e)[:160]
        return (res["new_tok_s_incl_prefill"], "tok/s incl prefill",
                None, res)
    for k in ("decode_only_ms_per_tok", "decode_tok_s", "pct_hbm_roof"):
        res[k] = roof[k]
    # vs_baseline: measured % of the weight-streaming HBM roof over the
    # 60% bar set in round 3 (fused projections cleared it in round 4);
    # null — not 0, which would read as a total regression — when the
    # roof is unknown for this chip generation
    vs = (round(roof["pct_hbm_roof"] / 60.0, 3)
          if roof["pct_hbm_roof"] is not None else None)
    return roof["decode_tok_s"], "decode tok/s", vs, res


def rider_8b_decode_unfused():
    from tpu_docker_api.infer.servebench import bench_decode_roofline

    roof = bench_decode_roofline(batch=64, prompt_len=128, new_tok=64,
                                 max_seq=512, reps=2)
    roof.pop("ok")
    vs = (round(roof["pct_hbm_roof"] / 60.0, 3)
          if roof["pct_hbm_roof"] is not None else None)
    return roof["decode_tok_s"], "decode tok/s", vs, roof


def _slot_serving(preset: str, quantize: bool, streams: int):
    from tpu_docker_api.infer.servebench import bench_concurrent_serving

    r = bench_concurrent_serving(preset=preset, quantize=quantize,
                                 streams=streams, prompt_len=128,
                                 new_tok=64, max_seq=512, chunk=8,
                                 fuse=True)
    r.pop("ok")
    # vs_baseline = speedup over the same streams serialized through the
    # round-2 gen_lock path (the reference has no serving story at all)
    return r["slot_tok_s"], "aggregate tok/s", r["speedup"], r


def rider_slot_serving_1b():
    return _slot_serving("llama3-1b", False, 8)


def rider_slot_serving_1b_16():
    return _slot_serving("llama3-1b", False, 16)


def rider_slot_serving_8b():
    return _slot_serving("llama3-8b", True, 16)


def rider_slot_serving_8b_8():
    return _slot_serving("llama3-8b", True, 8)


def rider_paged_capacity():
    """32 streams × 3072 ADDRESSABLE positions each on 8B-int8 — per-slot
    reach, not 32×3072 simultaneously-resident tokens; HBM scales with
    live tokens, which is the whole point of paging (the dense cache for
    the same reach is arithmetically impossible on this chip)."""
    from tpu_docker_api.infer.servebench import bench_paged_capacity

    r = bench_paged_capacity(preset="llama3-8b", streams=32, max_seq=3072,
                             page_size=64, prompt_len=128, new_tok=64)
    r.pop("ok")
    vs = round(r["dense_cache_gb"] / max(r["paged_pool_gb"], 1e-9), 1)
    return r["aggregate_tok_s"], "aggregate tok/s", vs, r


def _tail_latency(streams: int):
    from tpu_docker_api.infer.servebench import bench_tail_latency

    r = bench_tail_latency(preset="llama3-1b", streams=streams,
                           n_requests=4 * streams, arrival_s=0.04,
                           new_tok=48, max_seq=512, chunk=8)
    r.pop("ok")
    return r["ttft_p99_ms"], "ms ttft p99", 1.0, r


def rider_tail_latency():
    return _tail_latency(8)


def rider_tail_latency_16():
    return _tail_latency(16)


def rider_prefix_cache():
    from tpu_docker_api.infer.servebench import bench_prefix_serving

    r = bench_prefix_serving(preset="llama3-1b", requests=16,
                             prefix_len=960, suffix_len=16, new_tok=8,
                             max_seq=1024, slots=8, chunk=8, reps=2)
    r.pop("ok")
    return r["prefix_tok_s"], "tok/s", r["speedup"], r


def rider_paged_prefix():
    """Shared-header workload on the paged engine at the 32×3072
    addressable point (dense cache arithmetically impossible)."""
    from tpu_docker_api.infer.servebench import bench_paged_prefix

    r = bench_paged_prefix(preset="llama3-8b", requests=16, slots=32,
                           prefix_len=960, suffix_len=16, new_tok=8,
                           max_seq=3072, page_size=64)
    r.pop("ok")
    return r["prefix_tok_s"], "tok/s", r["speedup"], r


def rider_paged_admission():
    """Grow-vs-full reservation A/B on 8B-int8: admission concurrency
    when clients over-promise max_new (the production shape)."""
    from tpu_docker_api.infer.servebench import bench_paged_admission

    r = bench_paged_admission(preset="llama3-8b", streams=32,
                              prompt_len=128, promised_new=1024,
                              actual_new=16, max_seq=2048,
                              page_size=64, total_pages=104)
    r.pop("ok")
    return (r["admission_ratio"], "x first-wave admissions",
            r["speedup"], r)


def rider_chunked_prefill():
    from tpu_docker_api.infer.servebench import bench_chunked_prefill

    r = bench_chunked_prefill(preset="llama3-1b", prompt_len=960,
                              stream_new=96, chunk=8, prefill_chunk=128,
                              max_seq=1024)
    r.pop("ok")
    return (r["chunked"]["max_gap_ms"], "ms max stall",
            r["stall_reduction"], r)


def rider_encdec_serving():
    from tpu_docker_api.infer.servebench import bench_encdec_slot_serving

    r = bench_encdec_slot_serving(preset="encdec-base", streams=8,
                                  requests=16, src_len=128, new_tok=96,
                                  chunk=24)
    r.pop("ok")
    return r["slot_tok_s"], "aggregate tok/s", r["speedup"], r


def rider_family_trains():
    out = measure_family_trains()
    vit = out.get("vit_b16", {})
    return vit.get("images_per_sec"), "images/s (vit)", 1.0, out


def measure_family_trains() -> dict:
    """Secondary family throughputs for the BENCH artifact: ViT-B/16
    (non-causal, MFU vs this chip's peak) and bench-moe (sparse, gather
    dispatch). Shared harness: train.benchlib.time_train_steps. Each
    family measures independently — one failing must not erase the other
    (same rule as check_8b_inference's per-batch OOM handling)."""
    import gc

    import jax

    from tpu_docker_api.scheduler.topology import peak_bf16_flops_for
    from tpu_docker_api.train.benchlib import time_train_steps
    from tpu_docker_api.train.trainer import synthetic_batch

    out = {}
    peak = peak_bf16_flops_for(jax.devices()[0]) or 197e12

    try:
        from tpu_docker_api.models.vit import vit_presets, vit_synthetic_batch

        vcfg = vit_presets()["vit-b16"]
        r = time_train_steps(
            vcfg, vit_synthetic_batch(jax.random.PRNGKey(1), 128, vcfg))
        ips = r["steps_per_sec"] * 128
        out["vit_b16"] = {"images_per_sec": round(ips),
                          "mfu": round(vcfg.flops_per_image() * ips / peak, 3)}
    except Exception as e:
        out["vit_b16"] = {"error": str(e)[:160]}
    gc.collect()

    try:
        from tpu_docker_api.models.encdec import (
            encdec_presets, encdec_synthetic_batch)

        ecfg = encdec_presets()["encdec-base"]
        r = time_train_steps(
            ecfg, encdec_synthetic_batch(jax.random.PRNGKey(1), 32, 512,
                                         512, ecfg), steps=6)
        pairs = r["steps_per_sec"] * 32
        out["encdec_base"] = {
            "pairs_per_sec": round(pairs, 1),
            "mfu": round(ecfg.flops_per_pair(512, 512) * pairs / peak, 3)}
    except Exception as e:
        out["encdec_base"] = {"error": str(e)[:160]}
    gc.collect()

    try:
        import dataclasses as _dc

        from tpu_docker_api.models.moe import moe_presets

        mcfg = moe_presets()["bench-moe"]
        r = time_train_steps(
            mcfg, synthetic_batch(jax.random.PRNGKey(1), 8, 2048,
                                  mcfg.vocab_size), steps=6)
        tok_s = r["steps_per_sec"] * 8 * 2048
        # MFU by MODEL flops (flops_per_token counts only the top_k
        # active experts — hand-audited r3: wq/wk+wv/wo, router 2dE,
        # top_k×3 SwiGLU matmuls, causal attn, lm_head, ×3 fwd+bwd)
        out["bench_moe"] = {
            "tokens_per_sec": round(tok_s),
            "mfu": round(mcfg.flops_per_token(2048) * tok_s / peak, 3),
            "dispatch": "gather (single-device)"}
        # the multi-device dispatch form (one-hot einsum = the GSPMD
        # all-to-all path): single-device proxy recorded alongside, per
        # VERDICT r2 weak #5 — its hardware flops are n_experts/top_k
        # higher, so this model-flops MFU deliberately reads lower
        ecfg = _dc.replace(mcfg, dispatch_impl="einsum")
        re = time_train_steps(
            ecfg, synthetic_batch(jax.random.PRNGKey(1), 8, 2048,
                                  mcfg.vocab_size), steps=6)
        etok_s = re["steps_per_sec"] * 8 * 2048
        out["bench_moe"]["einsum_path"] = {
            "tokens_per_sec": round(etok_s),
            "mfu": round(mcfg.flops_per_token(2048) * etok_s / peak, 3)}
    except Exception as e:
        out["bench_moe"] = {"error": str(e)[:160]}
    gc.collect()
    # round 4: the "sort" (dense-packed, ep-constrained) mesh form —
    # single-device proxy; on one chip its math is gather + no-op
    # constraints, so ≈gather here is the claim that the MESH path no
    # longer needs the einsum form's (t, E, C) tensors (honest caveat:
    # multi-chip ICI behavior is not measurable in this environment —
    # dryrun proves compile+run, not speed). Own try-block: a sort
    # failure must not erase the gather/einsum numbers above.
    try:
        import dataclasses as _dc

        from tpu_docker_api.models.moe import moe_presets

        mcfg = moe_presets()["bench-moe"]
        scfg = _dc.replace(mcfg, dispatch_impl="sort")
        rs = time_train_steps(
            scfg, synthetic_batch(jax.random.PRNGKey(1), 8, 2048,
                                  mcfg.vocab_size), steps=6)
        stok_s = rs["steps_per_sec"] * 8 * 2048
        if isinstance(out.get("bench_moe"), dict):
            out["bench_moe"]["sort_path"] = {
                "tokens_per_sec": round(stok_s),
                "mfu": round(mcfg.flops_per_token(2048) * stok_s / peak,
                             3)}
    except Exception as e:
        if isinstance(out.get("bench_moe"), dict):
            out["bench_moe"]["sort_path"] = {"error": str(e)[:160]}
    gc.collect()

    try:
        from tpu_docker_api.infer.servebench import bench_moe_serving

        out["moe_serving"] = bench_moe_serving()
    except Exception as e:
        out["moe_serving"] = {"error": str(e)[:160]}
    gc.collect()
    return out


if __name__ == "__main__":
    sys.exit(main())
