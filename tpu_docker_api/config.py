"""Process configuration.

Parity: the reference's 6-field TOML config (``internal/config/config.go:9-24``,
defaults in ``etc/config.toml``). Fields here are the TPU-shaped equivalents:
the GPU count becomes a TPU topology description (accelerator type + per-host
chip count), ``detect_gpu_addr`` becomes the telemetry sidecar address, and the
state store grows a backend selector so tests run hermetically without etcd.
"""

from __future__ import annotations

import dataclasses

try:
    import tomllib  # Python >= 3.11
except ModuleNotFoundError:  # pragma: no cover — 3.10 containers
    import tomli as tomllib


@dataclasses.dataclass
class Config:
    # HTTP serve address, reference `Port` (config.go:10)
    port: int = 2378
    # state store: "memory" | "sqlite" | "etcd"
    store_backend: str = "memory"
    # etcd grpc-gateway address (used when store_backend == "etcd"),
    # reference `EtcdAddr` (config.go:11)
    etcd_addr: str = "http://localhost:2379"
    # sqlite database path (used when store_backend == "sqlite")
    sqlite_path: str = "/var/lib/tpu-docker-api/state.db"
    # telemetry sidecar address, reference `DetectGPUAddr` (config.go:12);
    # empty ⇒ local probe via tpu_docker_api.telemetry
    detect_tpu_addr: str = ""
    # accelerator type of this host's slice, e.g. "v5e-8", "v5p-8";
    # replaces the reference's bare `AvailableGpuNums` (config.go:13)
    accelerator_type: str = "v5e-8"
    # host port pool, reference `StartPort`/`EndPort` (config.go:14-15)
    start_port: int = 40000
    end_port: int = 65535
    # container runtime: "docker" | "fake"
    runtime_backend: str = "docker"
    # docker engine socket (runtime_backend == "docker")
    docker_host: str = "unix:///var/run/docker.sock"
    # runtime fan-out (runtime/fanout.py): max concurrent engine calls per
    # multi-member batch — gang create/start/stop/remove, host probes,
    # liveness scans, reconciler scrubs. 1 (the default) is byte-for-byte
    # the old serial loops; raise toward the pod's host count on
    # multi-host pods so lifecycle wall time is O(slowest host) not
    # O(sum). Must be >= 1.
    fanout_workers: int = 1
    # keep-alive connection pool per docker engine: max IDLE sockets
    # retained (concurrent demand beyond this still opens fresh
    # connections; only retention is bounded). 0 disables reuse.
    engine_pool_size: int = 4
    # path to libtpu.so to bind-mount into TPU containers ("" ⇒ image's own)
    libtpu_path: str = ""
    # health watcher (service/watch.py): poll interval; 0 disables the watcher
    health_watch_interval: float = 5.0
    # startup reconcile (service/reconcile.py): sweep KV desired state vs
    # runtime actual state before serving — repairs drift left by a crash
    reconcile_on_start: bool = True
    # periodic reconcile interval; 0 disables the background sweep (the
    # startup pass still runs when reconcile_on_start is true)
    reconcile_interval: float = 0.0
    # event-driven reconcile (service/reconcile.py DirtySet): > 0 turns
    # periodic passes O(changes) — a watch-fed dirty-set of family base
    # names decides what each pass visits, and the full O(objects) scan is
    # demoted to an anti-entropy pass at most every this many seconds
    # (out-of-band runtime drift like a manual `docker rm` emits no KV
    # event, so the full pass must survive — just rarely). 0 (default)
    # keeps every pass a full scan, byte-for-byte today's behavior.
    reconcile_full_interval_s: float = 0.0
    # bounded history (service/compactor.py): keep at most this many
    # version records per resource family — the latest pointer's version
    # and any version a live runtime member still references are NEVER
    # trimmed regardless of age. 0 (default) disables compaction
    # (unbounded history, today's behavior). >= 2 recommended: a rolling
    # replace briefly references latest-1.
    history_retention_versions: int = 0
    # compaction cadence (a writer: leader-only under leader_election)
    history_compact_interval_s: float = 60.0
    # list pagination (state/pager.py): limit applied when a list request
    # names none (0 = unlimited full scan, the legacy shape) and the hard
    # cap a request's ?limit is clamped to
    list_default_limit: int = 0
    list_max_limit: int = 5000
    # "none" (observe only) | "on-failure" (bounded auto-restart)
    restart_policy: str = "none"
    # per-container restart backoff (service/watch.py): base seconds between
    # automatic restarts, doubled per attempt up to the max; 0 = immediate
    # restarts (the pre-backoff behavior)
    restart_backoff_s: float = 1.0
    restart_backoff_max_s: float = 30.0
    # gang supervisor (service/job_supervisor.py): member-liveness poll
    # interval over all pod hosts; 0 disables supervision
    job_supervise_interval: float = 5.0
    # whole-gang restarts before a crash-looping job goes terminal "failed"
    job_max_restarts: int = 3
    # exponential backoff between gang restarts: base·2^n seconds, clamped
    # to the max, ±jitter fraction so gangs don't restart in lockstep
    job_backoff_base_s: float = 1.0
    job_backoff_max_s: float = 60.0
    job_backoff_jitter: float = 0.1
    # host failure domains (service/host_health.py): engine-probe interval
    # over every pod host; 0 disables the monitor — and with it automatic
    # host-down detection / gang migration, the drain route, and
    # GET /api/v1/health/hosts; cordon/uncordon (pure scheduler state)
    # keep working
    host_probe_interval_s: float = 5.0
    # continuous probe failure longer than this confirms a host "down"
    # (scheduler stops placing, gangs migrate off); anything shorter is a
    # blip and causes ZERO restarts
    host_down_grace_s: float = 15.0
    # circuit breaker around each non-local host engine: consecutive
    # connection failures before it opens (open ⇒ calls fast-fail instead
    # of hanging on a dead socket); 0 disables the breakers
    breaker_threshold: int = 3
    # host-fault migrations before a job goes terminal "failed" — a budget
    # SEPARATE from job_max_restarts, so dead hosts never eat the
    # crash-restart budget (and crash loops never eat this one)
    job_max_migrations: int = 3
    # elastic gangs (docs/robustness.md "Elastic gangs"): when true, jobs
    # submitted with elastic=true SHRINK to their surviving hosts on host
    # loss / drain / partial preemption (never below minMembers) and grow
    # back through the admission queue, instead of migrating whole or
    # dying. False disables every automatic resize decision (supervisor,
    # drain, admission) — non-elastic jobs behave identically either way.
    job_resize_enabled: bool = True
    # attempts of ONE resize before adoption gives up on a thrashing gang
    # (terminal "failed") — a loop bound for crash-adoption retries, not
    # a policy budget: it reads last_resize.attempts, never the lifetime
    # resize counter, so a long-lived elastic gang's normal shrink/grow
    # history can never trip it
    job_resize_max: int = 8
    # durable work queue (state/workqueue.py): how long a producer (API
    # thread) may block on a full queue before the typed QueueSaturated
    # error (HTTP 429) — never forever
    queue_submit_timeout_s: float = 5.0
    # shutdown drain deadline: close() waits this long for the sync loop to
    # finish the backlog, then abandons it — journaled records replay under
    # the next daemon, so a hung engine can't block shutdown indefinitely
    queue_close_deadline_s: float = 10.0
    # store-outage tolerance (EtcdKV): idempotent READS retry up to
    # store_retry_attempts times with capped exponential backoff
    # (base·2^n clamped to max) before raising the typed StoreUnavailable;
    # writes are normalized but never blind-retried
    store_retry_attempts: int = 3
    store_retry_base_s: float = 0.05
    store_retry_max_s: float = 1.0
    # per-op store deadline (state/kv.py): bounds EVERY store round trip —
    # the EtcdKV socket timeout, the SqliteKV busy wait — so a hung store
    # surfaces as a typed StoreUnavailable instead of a wedged thread.
    # 0 (default) keeps each backend's historical timeout byte-for-byte
    store_op_deadline_s: float = 0.0
    # store brownout machine (service/store_health.py, docs/robustness.md
    # "Store brownouts"): consecutive StoreUnavailable failures before
    # healthy → degraded (blips below the threshold cause zero mode flips) …
    store_health_fail_threshold: int = 3
    # … continuous failure past the threshold for this long ⇒ outage
    # (mutations fail fast 503, reads serve stale, writer loops hold) …
    store_health_outage_grace_s: float = 2.0
    # … and while in outage, one probe mutation per interval is admitted
    # through so a healed store is re-detected even without elector traffic
    store_health_probe_interval_s: float = 1.0
    # HA control plane (service/leader.py): when true, this daemon is one
    # replica of a fleet sharing the state store — API serving is always-on,
    # but the writer subsystems (work-queue sync loop, reconciler, job
    # supervisor, host monitor, health watcher) run only while this replica
    # holds the leader lease; standbys serve reads and answer mutations
    # with 503 + a leader hint. False (the default) keeps today's
    # single-process behavior exactly: no lease, no fencing, writers start
    # unconditionally.
    leader_election: bool = False
    # lease time-to-live: a dead leader's lease is stealable this long
    # after its last renewal — the failover ceiling
    leader_ttl_s: float = 15.0
    # heartbeat renewal interval; 0 ⇒ ttl/3 (renew well inside the TTL so
    # one missed heartbeat never costs the lease)
    leader_renew_interval_s: float = 0.0
    # identity in the lease record; "" ⇒ hostname:pid
    leader_id: str = ""
    # sharded writer plane (service/shard.py, docs/robustness.md "Sharded
    # writer plane"; requires leader_election = true when > 1): partition
    # the keyspace into this many shards, each with its own lease + epoch
    # + writer loops, so one lease loss halts <= 1/N of the keyspace
    # instead of every write. 1 (the default) keeps the single-lease
    # PR 7 plane byte-for-byte — no shard keys, no coordination record.
    shard_count: int = 1
    # shards THIS replica should contest immediately at boot (by id);
    # everything else waits shard_standby_delay_s before contesting a
    # VACANT lease, so a fleet booting together spreads shards instead of
    # the fastest process grabbing all of them. Expired leases are always
    # contested immediately — failover never waits on this.
    shard_preferred: list = dataclasses.field(default_factory=list)
    shard_standby_delay_s: float = 0.0
    # standby read path (state/informer.py; only meaningful with
    # leader_election = true): "informer" (default) serves standby GETs
    # from a watch-fed local mirror — zero store round trips per request,
    # staleness bounded by watch lag — falling back to per-read
    # read-through whenever the informer is unsynced/degraded;
    # "read-through" keeps PR 7's per-read store re-seeding unconditionally.
    # Leader and single-process read behavior is identical either way.
    read_cache: str = "informer"
    # capacity market (service/admission.py, docs/robustness.md "Capacity
    # market"): when true, a POST /jobs that cannot place is parked in a
    # durable admission queue (phase "queued") instead of hard-failing,
    # higher-priority jobs may preempt strictly-lower-priority gangs, and
    # queued work backfills holes. False (the default) keeps today's
    # first-fit-or-refuse behavior byte-for-byte.
    admission_enabled: bool = False
    # admission-loop tick (a writer: leader-only under leader_election);
    # 0 disables the loop — passes then run only via the reconciler's
    # adoption and explicit admit_once() calls (test/bench hook)
    admission_interval_s: float = 1.0
    # starvation bound for EASY backfill: how many out-of-order admissions
    # may overtake a blocked head-of-queue entry before the queue stalls
    # behind it (the head then places before anything else moves)
    admission_max_skips: int = 4
    # the priority ladder: class name -> weight. Preemption is strictly
    # lower-weight-only, so equal-weight classes never preempt each other.
    # Weights resolve at decision time — retuning takes effect on the next
    # admission pass without rewriting stored JobState.
    priority_class_weights: dict = dataclasses.field(default_factory=lambda: {
        "system": 1000, "production": 100, "batch": 10, "preemptible": 1,
    })
    # class assigned when POST /jobs carries no priorityClass
    priority_class_default: str = "batch"
    # Service resource (service/serving.py, docs/robustness.md "Service &
    # autoscaler"): class assigned when POST /services carries no
    # priorityClass — production by default, so a traffic-driven scale-up
    # outranks batch/preemptible training in the capacity market
    service_default_class: str = "production"
    # autoscaler tick (a writer: leader-only under leader_election);
    # 0 disables the loop — services still converge via the reconciler's
    # adoption and explicit tick() calls (test/bench hook)
    autoscale_interval_s: float = 2.0
    # minimum seconds between scale-UPs of one service (a breach inside
    # the window waits; the pending scale-up usually resolves it)
    autoscale_up_cooldown_s: float = 10.0
    # minimum seconds after ANY scale before a scale-DOWN — deliberately
    # longer than up: shedding capacity is cheap to delay, re-acquiring
    # it may need a preemption
    autoscale_down_cooldown_s: float = 30.0
    # hysteresis: scale down only when the worst replica signal sits
    # below watermark x target. The (watermark, 1.0] band is a dead zone,
    # so a signal oscillating around the target never flaps the fleet
    autoscale_down_watermark: float = 0.5
    # control-plane tracing (telemetry/trace.py, docs/observability.md):
    # always-on-sampled span trees from the HTTP handler down to store
    # applies, scheduler claims, lock waits, runtime fan-out and the async
    # queue tail, exported at GET /api/v1/traces. False turns every span
    # site into a no-op (one context-local read) — the churn benchmark
    # gates the disabled-mode cost at <= 1% of the flow p50.
    tracing_enabled: bool = True
    # bounded in-process trace ring: how many recent traces are kept
    # (O(buffer) memory; eviction is normal and counted loudly in
    # trace_dropped_total)
    trace_buffer_size: int = 256
    # slow-trace threshold (ms): a root span slower than this emits a
    # "slow-trace" event into the merged /api/v1/events ring; 0 disables
    trace_slow_ms: float = 0.0
    # L7 serving gateway (service/gateway.py, docs/robustness.md
    # "Serving gateway"): a stateless ingress in front of Service
    # replicas — drain-aware routing, retry/hedge budgets, breakers,
    # outlier ejection and typed load shedding. Off by default: gateway
    # deployments opt in, everything else keeps the direct-to-replica
    # path byte-for-byte.
    gateway_enabled: bool = False
    # gateway listener port; 0 = ephemeral (tests), daemon default 2380
    gateway_port: int = 0
    # end-to-end deadline per proxied request (connect + retries +
    # upstream headers); streams are bounded per-read, not end-to-end
    gateway_request_timeout_s: float = 30.0
    gateway_connect_timeout_s: float = 2.0
    # retries per request (idempotent requests only), and the token
    # budget that bounds retry AMPLIFICATION fleet-wide: each completed
    # request earns `ratio` tokens, each retry spends one
    gateway_retry_limit: int = 2
    gateway_retry_budget_ratio: float = 0.2
    # hedge: fire a second attempt at a different replica when the first
    # byte hasn't arrived within this many ms (0 = off; idempotent only)
    gateway_hedge_ms: float = 0.0
    # per-endpoint circuit breaker: open after N consecutive failures,
    # half-open single-flight probe after the cooldown
    gateway_breaker_threshold: int = 3
    gateway_breaker_cooldown_s: float = 5.0
    # eject an endpoint whose EWMA latency exceeds factor x the fleet
    # median (0 = off); ejection lasts one breaker cooldown
    gateway_outlier_latency_factor: float = 0.0
    # load shedding: global and per-endpoint in-flight caps (typed 429 /
    # skip-in-pick respectively), and the bounded upstream conn pool
    gateway_max_inflight: int = 256
    gateway_max_inflight_per_endpoint: int = 64
    gateway_pool_size: int = 8
    # drain handshake: how long a roll/scale-down/preemption waits for
    # every live gateway to ack zero in-flight before the first member
    # stop, and how often gateways heartbeat/sweep acks
    gateway_drain_deadline_s: float = 10.0
    gateway_heartbeat_s: float = 1.0
    # Workflow resource (service/workflow.py, docs/robustness.md
    # "Workflows"): DAG engine tick (a writer: leader-only under
    # leader_election); 0 disables the loop — workflows still converge via
    # the reconciler's adoption and explicit tick() calls (test/bench hook)
    workflow_interval_s: float = 2.0
    # class assigned when POST /workflows carries no priorityClass —
    # batch by default: pipelines are throughput work, a production
    # serving scale-up should outrank them in the capacity market
    workflow_default_class: str = "batch"
    # per-step retry budget when a step spec carries no maxRetries: failed
    # attempts beyond this settle the WHOLE workflow terminal "failed"
    workflow_max_step_retries: int = 2
    # exponential backoff between step retry attempts: base·2^n seconds,
    # clamped to the max
    workflow_backoff_base_s: float = 0.5
    workflow_backoff_max_s: float = 30.0
    # dead-letter hygiene (state/workqueue.py): how many times one dead
    # record may be revived through POST /api/v1/dead-letters/retry before
    # the typed RetryBudgetExhausted refusal — the count is durable on the
    # record, so the cap survives restarts
    queue_dead_letter_retry_budget: int = 3
    # multi-host pod: [[pod_hosts]] tables, each {host_id, address,
    # grid_coord=[x,y,z], docker_host?, runtime_backend?, local?}. Set
    # local=true on the entry for THIS machine so it shares the container
    # service's runtime/schedulers (one accounting for local chips). Empty ⇒
    # a single-host pod wrapping this host (jobs still work, sub-host slices
    # only). All hosts share accelerator_type.
    pod_hosts: list = dataclasses.field(default_factory=list)


def load(path: str | None = None) -> Config:
    """Load TOML config from ``path``; missing file or None ⇒ all defaults.

    Reference: ``NewConfigWithFile`` (config.go:18-24) errors on a missing
    file; we default instead so the hermetic test path needs no fixture file.
    """
    cfg = Config()
    data: dict = {}
    if path:
        with open(path, "rb") as f:
            data = tomllib.load(f)
        for field in dataclasses.fields(Config):
            if field.name in data:
                setattr(cfg, field.name, data[field.name])
    if cfg.restart_policy not in ("none", "on-failure"):
        raise ValueError(
            f"restart_policy must be 'none' or 'on-failure', "
            f"got {cfg.restart_policy!r}")
    if cfg.read_cache not in ("informer", "read-through"):
        raise ValueError(
            f"read_cache must be 'informer' or 'read-through', "
            f"got {cfg.read_cache!r}")
    if cfg.fanout_workers < 1:
        raise ValueError(
            f"fanout_workers must be >= 1, got {cfg.fanout_workers}")
    if cfg.admission_max_skips < 0:
        raise ValueError(
            f"admission_max_skips must be >= 0, got {cfg.admission_max_skips}")
    if not isinstance(cfg.job_resize_enabled, bool):
        raise ValueError(
            f"job_resize_enabled must be a boolean, "
            f"got {cfg.job_resize_enabled!r}")
    if isinstance(cfg.job_resize_max, bool) \
            or not isinstance(cfg.job_resize_max, int) \
            or cfg.job_resize_max < 1:
        raise ValueError(
            f"job_resize_max must be an integer >= 1, "
            f"got {cfg.job_resize_max!r}")
    if (not isinstance(cfg.priority_class_weights, dict)
            or not cfg.priority_class_weights):
        raise ValueError("priority_class_weights must be a non-empty "
                         "table of class -> integer weight")
    for klass, weight in cfg.priority_class_weights.items():
        if not isinstance(klass, str) or not klass:
            raise ValueError(f"priority class names must be non-empty "
                             f"strings, got {klass!r}")
        if isinstance(weight, bool) or not isinstance(weight, int):
            raise ValueError(f"priority_class_weights[{klass!r}] must be "
                             f"an integer, got {weight!r}")
    if cfg.priority_class_default not in cfg.priority_class_weights:
        raise ValueError(
            f"priority_class_default {cfg.priority_class_default!r} is not "
            f"in priority_class_weights "
            f"{sorted(cfg.priority_class_weights)}")
    if cfg.service_default_class not in cfg.priority_class_weights:
        if "service_default_class" in data:
            raise ValueError(
                f"service_default_class {cfg.service_default_class!r} is "
                f"not in priority_class_weights "
                f"{sorted(cfg.priority_class_weights)}")
        # a custom ladder without "production": the un-set service default
        # follows the job default instead of failing the whole config
        cfg.service_default_class = cfg.priority_class_default
    if cfg.reconcile_full_interval_s < 0:
        raise ValueError(f"reconcile_full_interval_s must be >= 0, "
                         f"got {cfg.reconcile_full_interval_s}")
    if cfg.history_retention_versions < 0:
        raise ValueError(f"history_retention_versions must be >= 0, "
                         f"got {cfg.history_retention_versions}")
    if cfg.history_compact_interval_s <= 0:
        raise ValueError(f"history_compact_interval_s must be > 0, "
                         f"got {cfg.history_compact_interval_s}")
    if cfg.list_max_limit < 1:
        raise ValueError(f"list_max_limit must be >= 1, "
                         f"got {cfg.list_max_limit}")
    if cfg.list_default_limit < 0 or cfg.list_default_limit > cfg.list_max_limit:
        raise ValueError(
            f"list_default_limit must be in [0, list_max_limit], "
            f"got {cfg.list_default_limit} (max {cfg.list_max_limit})")
    if cfg.store_op_deadline_s < 0:
        raise ValueError(f"store_op_deadline_s must be >= 0 (0 = backend "
                         f"default), got {cfg.store_op_deadline_s}")
    if isinstance(cfg.store_health_fail_threshold, bool) \
            or not isinstance(cfg.store_health_fail_threshold, int) \
            or cfg.store_health_fail_threshold < 1:
        raise ValueError(
            f"store_health_fail_threshold must be an integer >= 1, "
            f"got {cfg.store_health_fail_threshold!r}")
    if cfg.store_health_outage_grace_s < 0:
        raise ValueError(f"store_health_outage_grace_s must be >= 0, "
                         f"got {cfg.store_health_outage_grace_s}")
    if cfg.store_health_probe_interval_s <= 0:
        raise ValueError(f"store_health_probe_interval_s must be > 0, "
                         f"got {cfg.store_health_probe_interval_s}")
    if cfg.trace_buffer_size < 1:
        raise ValueError(f"trace_buffer_size must be >= 1, "
                         f"got {cfg.trace_buffer_size}")
    if cfg.trace_slow_ms < 0:
        raise ValueError(f"trace_slow_ms must be >= 0, "
                         f"got {cfg.trace_slow_ms}")
    if isinstance(cfg.shard_count, bool) \
            or not isinstance(cfg.shard_count, int) or cfg.shard_count < 1:
        raise ValueError(
            f"shard_count must be an integer >= 1, got {cfg.shard_count!r}")
    if cfg.shard_count > 1 and not cfg.leader_election:
        raise ValueError(
            "shard_count > 1 requires leader_election = true "
            "(each shard is a lease)")
    if not isinstance(cfg.shard_preferred, list) or any(
            isinstance(i, bool) or not isinstance(i, int)
            or i < 0 or i >= cfg.shard_count
            for i in cfg.shard_preferred):
        raise ValueError(
            f"shard_preferred must be a list of shard ids in "
            f"[0, {cfg.shard_count - 1}], got {cfg.shard_preferred!r}")
    if cfg.shard_standby_delay_s < 0:
        raise ValueError(f"shard_standby_delay_s must be >= 0, "
                         f"got {cfg.shard_standby_delay_s}")
    if not isinstance(cfg.gateway_enabled, bool):
        raise ValueError(f"gateway_enabled must be a boolean, "
                         f"got {cfg.gateway_enabled!r}")
    if isinstance(cfg.gateway_port, bool) \
            or not isinstance(cfg.gateway_port, int) \
            or not 0 <= cfg.gateway_port <= 65535:
        raise ValueError(f"gateway_port must be an integer in [0, 65535], "
                         f"got {cfg.gateway_port!r}")
    if cfg.gateway_request_timeout_s <= 0:
        raise ValueError(f"gateway_request_timeout_s must be > 0, "
                         f"got {cfg.gateway_request_timeout_s}")
    if cfg.gateway_connect_timeout_s <= 0:
        raise ValueError(f"gateway_connect_timeout_s must be > 0, "
                         f"got {cfg.gateway_connect_timeout_s}")
    if isinstance(cfg.gateway_retry_limit, bool) \
            or not isinstance(cfg.gateway_retry_limit, int) \
            or cfg.gateway_retry_limit < 0:
        raise ValueError(f"gateway_retry_limit must be an integer >= 0, "
                         f"got {cfg.gateway_retry_limit!r}")
    if cfg.gateway_retry_budget_ratio < 0:
        raise ValueError(f"gateway_retry_budget_ratio must be >= 0, "
                         f"got {cfg.gateway_retry_budget_ratio}")
    if cfg.gateway_hedge_ms < 0:
        raise ValueError(f"gateway_hedge_ms must be >= 0, "
                         f"got {cfg.gateway_hedge_ms}")
    if isinstance(cfg.gateway_breaker_threshold, bool) \
            or not isinstance(cfg.gateway_breaker_threshold, int) \
            or cfg.gateway_breaker_threshold < 0:
        raise ValueError(
            f"gateway_breaker_threshold must be an integer >= 0, "
            f"got {cfg.gateway_breaker_threshold!r}")
    if cfg.gateway_breaker_cooldown_s < 0:
        raise ValueError(f"gateway_breaker_cooldown_s must be >= 0, "
                         f"got {cfg.gateway_breaker_cooldown_s}")
    if cfg.gateway_outlier_latency_factor < 0:
        raise ValueError(f"gateway_outlier_latency_factor must be >= 0, "
                         f"got {cfg.gateway_outlier_latency_factor}")
    for knob in ("gateway_max_inflight", "gateway_max_inflight_per_endpoint"):
        v = getattr(cfg, knob)
        if isinstance(v, bool) or not isinstance(v, int) or v < 1:
            raise ValueError(f"{knob} must be an integer >= 1, got {v!r}")
    if isinstance(cfg.gateway_pool_size, bool) \
            or not isinstance(cfg.gateway_pool_size, int) \
            or cfg.gateway_pool_size < 0:
        raise ValueError(f"gateway_pool_size must be an integer >= 0, "
                         f"got {cfg.gateway_pool_size!r}")
    if cfg.gateway_drain_deadline_s < 0:
        raise ValueError(f"gateway_drain_deadline_s must be >= 0, "
                         f"got {cfg.gateway_drain_deadline_s}")
    if cfg.gateway_heartbeat_s <= 0:
        raise ValueError(f"gateway_heartbeat_s must be > 0, "
                         f"got {cfg.gateway_heartbeat_s}")
    if cfg.autoscale_interval_s < 0:
        raise ValueError(f"autoscale_interval_s must be >= 0, "
                         f"got {cfg.autoscale_interval_s}")
    if cfg.workflow_interval_s < 0:
        raise ValueError(f"workflow_interval_s must be >= 0, "
                         f"got {cfg.workflow_interval_s}")
    if cfg.workflow_default_class not in cfg.priority_class_weights:
        if "workflow_default_class" in data:
            raise ValueError(
                f"workflow_default_class {cfg.workflow_default_class!r} is "
                f"not in priority_class_weights "
                f"{sorted(cfg.priority_class_weights)}")
        # a custom ladder without "batch": the un-set workflow default
        # follows the job default instead of failing the whole config
        cfg.workflow_default_class = cfg.priority_class_default
    if isinstance(cfg.workflow_max_step_retries, bool) \
            or not isinstance(cfg.workflow_max_step_retries, int) \
            or cfg.workflow_max_step_retries < 0:
        raise ValueError(
            f"workflow_max_step_retries must be an integer >= 0, "
            f"got {cfg.workflow_max_step_retries!r}")
    if cfg.workflow_backoff_base_s < 0:
        raise ValueError(f"workflow_backoff_base_s must be >= 0, "
                         f"got {cfg.workflow_backoff_base_s}")
    if cfg.workflow_backoff_max_s < cfg.workflow_backoff_base_s:
        raise ValueError(
            f"workflow_backoff_max_s must be >= workflow_backoff_base_s, "
            f"got {cfg.workflow_backoff_max_s} < "
            f"{cfg.workflow_backoff_base_s}")
    if isinstance(cfg.queue_dead_letter_retry_budget, bool) \
            or not isinstance(cfg.queue_dead_letter_retry_budget, int) \
            or cfg.queue_dead_letter_retry_budget < 1:
        raise ValueError(
            f"queue_dead_letter_retry_budget must be an integer >= 1, "
            f"got {cfg.queue_dead_letter_retry_budget!r}")
    if cfg.autoscale_up_cooldown_s < 0 or cfg.autoscale_down_cooldown_s < 0:
        raise ValueError("autoscale cooldowns must be >= 0")
    if not 0 < cfg.autoscale_down_watermark <= 1:
        raise ValueError(
            f"autoscale_down_watermark must be in (0, 1], "
            f"got {cfg.autoscale_down_watermark}")
    return cfg
