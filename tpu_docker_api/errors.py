"""Error taxonomy for the control plane.

Parity: the reference's string-sentinel errors + ``Is*`` predicates
(``internal/xerrors/{common,container,volume,etcd,scheduler}.go``). Here each
sentinel is a distinct exception class so callers use ``except``/``isinstance``
instead of string matching, and every class carries the API error code it maps
to (``tpu_docker_api.api.codes``) so the HTTP layer needs no lookup table.
"""

from __future__ import annotations


class ApiError(Exception):
    """Base class: every control-plane error maps to one API response code."""

    #: numeric code from tpu_docker_api.api.codes (filled per subclass)
    code: int = 500
    #: HTTP status the envelope rides on. The reference answers everything
    #: with 200 + app code; backpressure errors are the one exception —
    #: intermediaries and clients must see queue saturation as a retryable
    #: transport-level condition (429), not a success
    http_status: int = 200
    #: optional structured payload for the envelope's ``data`` field —
    #: normally None (the legacy error shape, byte-for-byte); a raiser may
    #: set it on the INSTANCE to attach machine-readable context (e.g. the
    #: capacity market's ``{"queueable": false}`` on a ChipNotEnough)
    data = None

    def __init__(self, msg: str = ""):
        super().__init__(msg or self.__class__.__doc__ or self.__class__.__name__)


def as_int(value, field: str) -> int:
    """Coerce a user-supplied request field to int, mapping malformed input
    to :class:`BadRequest` (code 10001) instead of letting ``ValueError``
    escape the handler as a 500 SERVER_ERROR. For request DTO ``from_dict``
    sites; internal state parsing should keep plain ``int()`` so corruption
    surfaces as a server error.

    Rejects bool (JSON ``true`` would coerce to 1), non-integral numbers
    (``3.9`` would silently truncate to 3), and digit strings (JSON callers
    must send numbers, not ``"3"``)."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise BadRequest(f"{field} must be an integer")
    try:
        coerced = int(value)
        if coerced != value:
            raise ValueError
    except (TypeError, ValueError, OverflowError):  # nan/inf raise here too
        raise BadRequest(f"{field} must be an integer") from None
    return coerced


def as_float(value, field: str) -> float:
    """The float analog of :func:`as_int`: coerce a user-supplied request
    field, mapping malformed input to :class:`BadRequest` instead of a
    500. Rejects bool and NON-FINITE values — JSON's lax ``NaN`` /
    ``Infinity`` would otherwise slide through every ``< 0`` validation
    (NaN compares False against everything) and silently wedge or
    saturate whatever policy consumes the number."""
    import math

    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise BadRequest(f"{field} must be a number")
    coerced = float(value)
    if not math.isfinite(coerced):
        raise BadRequest(f"{field} must be a finite number")
    return coerced


# --- common (xerrors/common.go:7-10) ------------------------------------------

class NoPatchRequired(ApiError):
    """The patch requests the state the resource is already in."""
    code = 10201


class VersionNotMatch(ApiError):
    """Optimistic-concurrency failure: request names version N but latest is M."""
    code = 10202


class BadRequest(ApiError):
    """Request validation failure (missing field, malformed name, bad unit)."""
    code = 10001


# --- container (xerrors/container.go:7) ---------------------------------------

class ContainerExisted(ApiError):
    """A container family with this base name already exists."""
    code = 10301


class ContainerNotExist(ApiError):
    """No such container (neither running nor in the state store)."""
    code = 10302


# --- volume (xerrors/volume.go:8-10) ------------------------------------------

class VolumeExisted(ApiError):
    """A volume family with this base name already exists."""
    code = 10401


class VolumeNotExist(ApiError):
    """No such volume."""
    code = 10402


class VolumeSizeUsedGreaterThanReduced(ApiError):
    """Shrink guard: bytes in use exceed the requested new size."""
    code = 10403


# --- state store (xerrors/etcd.go:8) ------------------------------------------

class NotExistInStore(ApiError):
    """Key not found in the state store."""
    code = 10501


class StoreUnavailable(ApiError):
    """The state-store backend cannot be reached (connection refused/reset,
    timeout). Distinct from NotExistInStore: the KEY's presence is unknown,
    only the path to the store failed — the KV analog of HostUnreachable.
    EtcdKV normalizes every connection-class failure to this type (bounded
    retry+backoff on idempotent reads first); the work queue's journal
    writes catch it and degrade loudly instead of wedging the sync loop."""
    code = 10502


class GuardFailed(ApiError):
    """A guarded KV write (``KV.apply(..., guards=...)`` / ``KV.cas``) lost
    its compare: the store's current value no longer matches what the
    writer asserted. This is the typed contention-loser signal — a lease
    CAS that raced another elector, or an epoch-fenced write from a leader
    that was deposed mid-flight. NEVER blind-retried at the KV layer: the
    caller must re-read and re-decide (an elector demotes; a fenced writer
    abandons the flow for the new leader to own)."""
    code = 10503


class WatchLost(ApiError):
    """A ``KV.watch`` stream can no longer deliver a gapless event
    sequence: the changelog was compacted past the watcher's revision, a
    slow consumer overflowed its buffer, or the server canceled the
    stream. The continuation contract is broken — the ONLY correct
    recovery is a full relist (``range_prefix_with_rev``) and a fresh
    watch from the new revision, which is exactly what the informer
    (state/informer.py) does. Never silently swallowed: a cache that kept
    serving across a gap would hide deletes forever."""
    code = 10504


class ContinueExpired(ApiError):
    """A paginated list's ``continue`` token can no longer be honored: the
    page sequence is rev-anchored (every page serves the SAME store
    revision the first page did, so a walk never duplicates or skips a
    key), and either the prefix was mutated past that revision or the
    backend compacted the history needed to prove it wasn't. The
    Kubernetes analog is the list API's 410 Gone — surfaced with a real
    HTTP 410 so clients restart the walk from a fresh first page instead
    of treating a broken snapshot as data."""
    code = 10505
    http_status = 410


class StoreDegraded(ApiError):
    """The control plane is riding through a store outage (StoreHealth mode
    ``outage``, service/store_health.py): mutations are refused up front —
    typed, bounded, and with zero store round trips — because an intent
    that cannot be journaled must never half-apply. HTTP 503 with a
    ``Retry-After`` hint (``retry_after_s``, surfaced as the response
    header) so retry-aware clients back off until the store heals instead
    of burning their budget against a brownout. Reads are NOT gated: they
    serve from the informer mirror with explicit staleness, or pay the
    deadline-bounded store attempt."""
    code = 10506
    http_status = 503

    def __init__(self, msg: str = "", retry_after_s: float = 1.0,
                 data=None) -> None:
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        if data is not None:
            self.data = data


# --- schedulers (xerrors/scheduler.go:8-10) -----------------------------------

class ChipNotEnough(ApiError):
    """Not enough free TPU chips (or no ICI-contiguous block) to satisfy the ask."""
    code = 10601


class PortNotEnough(ApiError):
    """Host-port pool exhausted."""
    code = 10602


class TopologyUnknown(ApiError):
    """The requested slice shape/type is not a known TPU topology."""
    code = 10603


# --- work queue (state/workqueue.py) ------------------------------------------

class QueueSaturated(ApiError):
    """The work queue is full and the bounded submit timed out — the daemon
    is falling behind its async backlog. Surfaced as HTTP 429 so callers
    (and proxies) treat it as retryable backpressure, never as success."""
    code = 10801
    http_status = 429


class QueueClosed(ApiError):
    """Submit raced shutdown: the sync loop is gone, so enqueueing would
    silently strand the task in a consumerless queue. Callers see a typed
    error instead; journaled records are replayed by the next daemon.
    HTTP 503 for the same reason QueueSaturated is 429: the identical
    request succeeds against the next daemon, so retry-aware clients and
    proxies must see transient backpressure, not a final app error."""
    code = 10802
    http_status = 503


# --- leader election (service/leader.py) --------------------------------------

class NotLeader(ApiError):
    """This replica is a standby: it serves reads, but mutations belong to
    the lease holder. HTTP 503 (like QueueClosed) so retry-aware clients
    and proxies treat it as transient routing, not a final app error — the
    message carries the current leader's identity as the redirect hint."""
    code = 10901
    http_status = 503


# --- host failure domains (service/host_health.py) ----------------------------

class ServiceExisted(ApiError):
    """POST /services of a name that already has a service family."""
    code = 11001


class ServiceNotExist(ApiError):
    """A /services/{name} op on an unknown service family."""
    code = 11002


class GatewayShed(ApiError):
    """The serving gateway refused admission under load — the global
    in-flight cap is reached or every candidate endpoint is saturated.
    Surfaced as HTTP 429 with Retry-After so callers treat it as
    retryable backpressure (shed, don't collapse), never as a
    connection-level failure."""
    code = 11201
    http_status = 429


class GatewayNoEndpoints(ApiError):
    """The serving gateway has no routable replica for the service —
    every endpoint is draining, ejected, or breaker-open (or the service
    has no ready replicas at all). Surfaced as HTTP 503 with Retry-After:
    the condition is transient by construction (drains finish, breakers
    half-open, the autoscaler reacts)."""
    code = 11202
    http_status = 503


class WorkflowExisted(ApiError):
    """POST /workflows of a name that already has a workflow family."""
    code = 11301


class WorkflowNotExist(ApiError):
    """A /workflows/{name} op on an unknown workflow family."""
    code = 11302


class RetryBudgetExhausted(ApiError):
    """POST /api/v1/dead-letters/retry refused for a record whose durable
    operator-retry count reached the cap — a permanently-poisoned task
    must not be re-driven forever. HTTP 409: the refusal is final for
    this record until it is deleted or the cap is raised, not transient
    backpressure."""
    code = 10803
    http_status = 409


class HostUnreachable(ApiError):
    """A pod host's container engine cannot be reached — connection refused,
    socket timeout, or the host's circuit breaker is open and fast-failing.
    Distinct from ContainerNotExist: the CONTAINER's state is unknown, only
    the path to the engine failed."""
    code = 10701


#: everything that means "the path to a host's engine is broken": the
#: normalized HostUnreachable a circuit breaker raises, plus the raw
#: socket errors (ConnectionRefused/Reset, timeouts — OSError subclasses)
#: that docker_http surfaces when a runtime is NOT breaker-wrapped (the
#: local pod host always; every host when breaker_threshold = 0). Every
#: scanner that classifies member state (supervisor, reconciler,
#: invariants, job service) must catch THIS tuple, not HostUnreachable
#: alone, or an unwrapped engine's outage reads as a scan crash instead
#: of an unreachable host.
HOST_PATH_ERRORS = (HostUnreachable, OSError)
