"""Dapper-shaped control-plane tracer (docs/observability.md).

SURVEY.md §5.1: the reference observes itself through process logs only.
The metrics registry (telemetry/metrics.py) answered "how much/how often";
this module answers "where did *this* request's 23 ms go" — a causally
linked span tree from the HTTP handler down through the store apply, the
scheduler claim, the family-lock wait, the runtime fan-out batch and the
async work-queue tail.

Design (always-on sampled, stdlib-only):

- :class:`Span` — traceId / spanId / parentId, name, attrs, status, a wall
  timestamp for display and a **monotonic** start for duration/coverage
  math. Spans live in context-local storage (``contextvars``) while open,
  so child creation needs no plumbing: ``trace.child("kv.apply")`` finds
  its parent wherever the call happens to run.
- :class:`Tracer` — per-process (per-``Program``) span sink: a bounded
  ring of recent traces (O(``buffer_size``) memory; eviction is normal
  ring behavior but LOUD — ``trace_dropped_total``), exported at
  ``GET /api/v1/traces`` (+ ``/{traceId}``). One tracer per daemon keeps
  multi-daemon test processes (the failover bench boots three) from
  cross-contaminating buffers: a child span records into its PARENT's
  tracer, not a global.
- **Links, not parentage, across process death.** The work queue journals
  the submitting span's (traceId, spanId) into each ``TaskRecord`` and the
  admission journal carries the enqueueing request's traceId; the daemon
  that executes a record in the same process CONTINUES the trace (same
  traceId, parent = the submit span), while a replayed/adopted record —
  a different daemon, or this one after a reboot — starts a fresh trace
  carrying ``links=[originTraceId]``: the origin's span tree ended with
  the dead process, so pretending parentage would fabricate a timeline.
- **Crash parity.** Spans close in ``finally``; an ``Exception`` marks
  ``status="error"``, a ``BaseException`` (the chaos harness's
  ``SimulatedCrash`` — the kill -9 model) marks ``status="lost"``. Spans
  still open when a tracer shuts down (``close()``) are force-finished as
  ``lost`` — a reboot never inherits open spans, and the buffer is
  readable after any crash.
- **Disabled mode is a no-op, not a code path.** ``tracing_enabled=false``
  means root creation returns the shared no-op context manager and every
  ``child()`` call is one ``ContextVar.get`` returning None — the churn
  benchmark gates this accounting at ≤ 1% of the flow p50.

Writer loops (reconciler passes, admission ticks, autoscaler ticks,
compactor passes) open self-rooted spans with ``trim_idle=True``: a pass
that finished ``ok`` without recording a single child span (nothing
written, nothing claimed, nothing waited on) is discarded instead of
buffered, so a quiet daemon's tick loops cannot evict the request traces
an operator actually wants.
"""

from __future__ import annotations

import collections
import contextvars
import threading
import time
import uuid

#: context-local open span (the parent for the next child() call)
_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "tpu_docker_api_trace_span", default=None)

#: per-trace span cap: a runaway loop inside one request must not grow the
#: buffer unboundedly — further spans are counted, not stored
MAX_SPANS_PER_TRACE = 512


class Span:
    """One timed operation. Open until :meth:`Tracer._finish` runs (always
    via the context manager's ``finally``)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "attrs",
                 "status", "links", "start_ts", "start_mono", "duration_ms",
                 "tracer", "trim_idle", "is_root")

    def __init__(self, tracer: "Tracer", trace_id: str, span_id: str,
                 parent_id: str, name: str, attrs: dict,
                 links: tuple = (), trim_idle: bool = False,
                 is_root: bool = False) -> None:
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.attrs = attrs
        self.links = tuple(links)
        self.status = "open"
        self.start_ts = time.time()
        self.start_mono = time.perf_counter()
        self.duration_ms: float | None = None
        self.trim_idle = trim_idle
        #: LOCAL root: opened with no in-process parent span. Distinct
        #: from parent_id == "" — a traceparent-continued request has a
        #: REMOTE parent id yet is still this process's root (it must
        #: count as rooted and fire slow-trace events)
        self.is_root = is_root

    def to_dict(self) -> dict:
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "status": self.status,
            "startTs": round(self.start_ts, 6),
            "startMonoMs": round(self.start_mono * 1e3, 3),
            "durationMs": (None if self.duration_ms is None
                           else round(self.duration_ms, 3)),
            "isRoot": self.is_root,
            "attrs": dict(self.attrs),
            "links": list(self.links),
        }


class _SpanScope:
    """Context manager binding one span to the context-local slot.
    ``Exception`` → status ``error`` (the flow failed but unwound);
    ``BaseException`` → ``lost`` (the kill -9 model: the flow never
    finished and never will)."""

    __slots__ = ("_span", "_token")

    def __init__(self, span: Span) -> None:
        self._span = span

    def __enter__(self) -> Span:
        self._token = _current.set(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        _current.reset(self._token)
        if exc_type is None:
            # a caller that enveloped its own failure (the HTTP handler)
            # may pre-set status; untouched spans close ok
            status = ("ok" if self._span.status == "open"
                      else self._span.status)
        elif issubclass(exc_type, Exception):
            status = "error"
        else:
            status = "lost"
        self._span.tracer._finish(self._span, status)
        return False


class _Noop:
    """Shared no-op scope: the disabled / no-active-trace fast path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


NOOP = _Noop()


class _TraceEntry:
    __slots__ = ("spans", "dropped_spans")

    def __init__(self) -> None:
        self.spans: list[Span] = []
        self.dropped_spans = 0


class Tracer:
    """Bounded in-process trace sink. One per daemon (``Program``)."""

    def __init__(self, buffer_size: int = 256, enabled: bool = True,
                 registry=None, slow_ms: float = 0.0,
                 max_events: int = 128) -> None:
        self._mu = threading.Lock()
        self.buffer_size = max(1, int(buffer_size))
        self.enabled = bool(enabled)
        self._registry = registry
        self.slow_ms = float(slow_ms)
        #: trace_id -> entry, oldest first (OrderedDict as ring)
        self._traces: "collections.OrderedDict[str, _TraceEntry]" = (
            collections.OrderedDict())
        self._open: dict[str, Span] = {}
        #: open spans per trace (a root with trim_idle must not be
        #: discarded while a cross-thread child is still in flight)
        self._open_by_trace: dict[str, int] = {}
        self._dropped = 0
        #: slow-trace events for the merged /api/v1/events ring
        self._events: collections.deque = collections.deque(maxlen=max_events)

    # -- span creation ------------------------------------------------------------

    def span(self, name: str, parent: Span | None = None,
             trace_id: str = "", parent_id: str = "",
             links: tuple = (), attrs: dict | None = None,
             trim_idle: bool = False, root: bool | None = None):
        """Open a span scope. Parent resolution: an explicit ``parent``
        Span wins (the cross-thread fan-out case), else the context-local
        current span, else this is a root. ``trace_id`` / ``parent_id``
        seed the span from a REMOTE context (the HTTP layer's traceparent
        / X-Request-Id, a journaled queue record); ``links`` attach origin
        traces without claiming parentage (queue replay). ``root``
        overrides local-rootness: the HTTP handler passes True because a
        traceparent-continued request is still THIS process's serving root
        despite its remote parent id, while a queue continuation (also
        parentless in-process, also remote parent id) is NOT — its trace
        already has the submitting request as root. Default: root iff no
        parent of any kind. Disabled tracer ⇒ shared no-op."""
        if not self.enabled:
            return NOOP
        if parent is None:
            parent = _current.get()
        if parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        else:
            trace_id = trace_id or uuid.uuid4().hex
        if root is None:
            root = parent is None and not parent_id
        span = Span(self, trace_id, uuid.uuid4().hex[:16], parent_id,
                    name, dict(attrs or ()), links=links,
                    trim_idle=trim_idle, is_root=root)
        with self._mu:
            self._open[span.span_id] = span
            self._open_by_trace[trace_id] = (
                self._open_by_trace.get(trace_id, 0) + 1)
        return _SpanScope(span)

    def _finish(self, span: Span, status: str) -> None:
        with self._mu:
            if self._open.pop(span.span_id, None) is None:
                # already finished — close_orphans racing the owning
                # thread's scope exit; a second append would duplicate
                # the span (two identical roots) in the buffer
                return
            left = self._open_by_trace.get(span.trace_id, 1) - 1
            if left <= 0:
                self._open_by_trace.pop(span.trace_id, None)
            else:
                self._open_by_trace[span.trace_id] = left
        span.duration_ms = (time.perf_counter() - span.start_mono) * 1e3
        span.status = status
        with self._mu:
            entry = self._traces.get(span.trace_id)
            if entry is None:
                entry = _TraceEntry()
                self._traces[span.trace_id] = entry
            self._traces.move_to_end(span.trace_id)
            if span.trim_idle and status == "ok" and not entry.spans \
                    and not self._open_by_trace.get(span.trace_id):
                # an idle loop pass: nothing beneath it happened — keep the
                # ring for traces that carry information
                if span.trace_id in self._traces:
                    del self._traces[span.trace_id]
                return
            if len(entry.spans) >= MAX_SPANS_PER_TRACE:
                entry.dropped_spans += 1
                self._count_drop("span")
            else:
                entry.spans.append(span)
            while len(self._traces) > self.buffer_size:
                self._traces.popitem(last=False)
                self._dropped += 1
                self._count_drop("trace")
        if (self.slow_ms > 0 and span.is_root
                and span.duration_ms >= self.slow_ms):
            self._events.append({
                "ts": time.time(), "event": "slow-trace",
                "traceId": span.trace_id, "name": span.name,
                "durationMs": round(span.duration_ms, 3),
            })

    def _count_drop(self, kind: str) -> None:
        if self._registry is not None:
            self._registry.counter_inc(
                "trace_dropped_total", {"kind": kind},
                help="Traces evicted from (or spans dropped by) the "
                     "bounded trace buffer")

    # -- views (GET /api/v1/traces) -----------------------------------------------

    def summaries(self, limit: int = 100) -> dict:
        """Recent trace summaries, newest first."""
        items = []
        with self._mu:
            entries = list(self._traces.items())
            dropped = self._dropped
            open_n = len(self._open)
            for trace_id, entry in reversed(entries[-limit:] if limit > 0
                                            else entries):
                if not entry.spans:
                    continue
                roots = [s for s in entry.spans if s.is_root]
                head = roots[0] if roots else entry.spans[0]
                t0 = min(s.start_mono for s in entry.spans)
                t1 = max(s.start_mono + (s.duration_ms or 0.0) / 1e3
                         for s in entry.spans)
                links = sorted({ln for s in entry.spans for ln in s.links})
                items.append({
                    "traceId": trace_id,
                    "root": head.name,
                    "rootCount": len(roots),
                    "spans": len(entry.spans),
                    "status": ("lost" if any(s.status == "lost"
                                             for s in entry.spans)
                               else head.status),
                    "startTs": round(min(s.start_ts for s in entry.spans), 6),
                    "durationMs": round((t1 - t0) * 1e3, 3),
                    "links": links,
                })
        return {"items": items, "dropped": dropped, "openSpans": open_n,
                "enabled": self.enabled, "bufferSize": self.buffer_size}

    def trace_view(self, trace_id: str) -> dict | None:
        """Full span tree for one trace (spans in start order), or None."""
        with self._mu:
            entry = self._traces.get(trace_id)
            if entry is None:
                return None
            spans = sorted(entry.spans, key=lambda s: s.start_mono)
            return {"traceId": trace_id,
                    "spans": [s.to_dict() for s in spans],
                    "droppedSpans": entry.dropped_spans}

    def find_by_request_id(self, request_id: str) -> dict | None:
        """Newest trace whose root span carries ``requestId == request_id``
        in its attrs — the fallback for requests that arrived with BOTH a
        ``traceparent`` (which keys the trace) and an ``X-Request-Id``
        (which the envelope echoed). O(buffer) scan of a bounded ring."""
        with self._mu:
            match = None
            for trace_id, entry in self._traces.items():
                # only the HTTP handler's request spans carry the attr —
                # and a traceparent-continued one has a REMOTE parentId,
                # so the attr (not rootness) is the match criterion
                for s in entry.spans:
                    if s.attrs.get("requestId") == request_id:
                        match = trace_id  # keep scanning: newest wins
                        break
        return None if match is None else self.trace_view(match)

    def stats(self) -> dict:
        with self._mu:
            return {"traces": len(self._traces), "openSpans": len(self._open),
                    "dropped": self._dropped, "enabled": self.enabled,
                    "bufferSize": self.buffer_size}

    def events_view(self, limit: int = 100) -> list[dict]:
        if limit <= 0:
            return []
        return list(self._events)[-limit:]  # deque snapshots are thread-safe

    # -- lifecycle ----------------------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    def close_orphans(self) -> int:
        """Force-finish every still-open span as ``lost`` — the reboot
        contract: no daemon ever inherits (or reports) an open span from a
        dead flow. Returns how many were closed."""
        with self._mu:
            orphans = list(self._open.values())
        for span in orphans:
            self._finish(span, "lost")
        return len(orphans)

    def close(self) -> None:
        self.close_orphans()


# -- module helpers (the instrumentation surface) ------------------------------


def current() -> Span | None:
    """The context-local open span, or None."""
    return _current.get()


def current_trace_id() -> str:
    span = _current.get()
    return span.trace_id if span is not None else ""


def child(name: str, **attrs):
    """Child scope of the context-local current span; shared no-op when no
    trace is active (ONE ContextVar.get — the disabled-mode cost the churn
    family's overhead gate accounts)."""
    parent = _current.get()
    if parent is None:
        return NOOP
    return parent.tracer.span(name, parent=parent, attrs=attrs)


def child_of(parent: Span | None, name: str, **attrs):
    """Explicit-parent child scope — for worker threads (the fan-out pool)
    where the caller's context does not propagate."""
    if parent is None:
        return NOOP
    return parent.tracer.span(name, parent=parent, attrs=attrs)


def pass_span(tracer: "Tracer | None", name: str, **attrs):
    """Span scope for one writer-loop pass (reconcile, admission tick,
    autoscale tick, compaction). Called from a loop thread it opens a
    SELF-ROOTED trace with ``trim_idle`` (a pass that did nothing beneath
    it is discarded, so quiet tick loops can't evict request traces);
    called inside an active trace (the HTTP ?mode=/compact routes) it is
    an ordinary child span of that request."""
    parent = _current.get()
    if parent is not None:
        return parent.tracer.span(name, parent=parent, attrs=attrs)
    if tracer is None:
        return NOOP
    return tracer.span(name, attrs=attrs, trim_idle=True)


def traced(name: str):
    """Decorator form of :func:`child` for hot entry points (scheduler
    claims): zero-overhead pass-through when no trace is active."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            parent = _current.get()
            if parent is None:
                return fn(*args, **kwargs)
            with parent.tracer.span(name, parent=parent):
                return fn(*args, **kwargs)
        return wrapper
    return deco


def stamp(event: dict) -> dict:
    """Attach the current traceId to an event-ring entry (in place), so
    ``GET /api/v1/events?traceId=`` joins events to traces. No active
    trace ⇒ untouched (the legacy event shape)."""
    span = _current.get()
    if span is not None:
        event["traceId"] = span.trace_id
    return event


# -- W3C traceparent (the remote-context handshake) ----------------------------


def parse_traceparent(header: str | None) -> tuple[str, str] | None:
    """``00-<trace32>-<span16>-<flags>`` → (trace_id, parent_span_id), or
    None for anything malformed (a bad header must never fail a request)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_id, _ = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None  # all-zero ids are explicitly invalid per the spec
    return trace_id, span_id


def format_traceparent(span: Span) -> str:
    trace_id = span.trace_id
    if len(trace_id) != 32 or not all(c in "0123456789abcdef"
                                      for c in trace_id):
        # opaque request ids (X-Request-Id) are legal trace ids internally
        # but not on the wire; no valid traceparent can carry them
        return ""
    return f"00-{trace_id}-{span.span_id}-01"
