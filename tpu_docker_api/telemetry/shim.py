"""ctypes binding to the native telemetry shim (tpu_native/libtpushim.so).

The C++ shim is the NVML-analog native component (SURVEY.md §2.2): device
enumeration, per-chip HBM/duty metrics, libtpu version probing. This binding
loads it lazily and raises if absent — callers (telemetry.probe) fall back to
the pure-Python walk, so the control plane works unbuilt, just with less
telemetry.
"""

from __future__ import annotations

import ctypes
import dataclasses
import functools
import os


@dataclasses.dataclass
class ChipMetrics:
    chip_id: int
    device_path: str
    hbm_total: int
    hbm_used: int
    duty_cycle: float
    pid: int


class _CChipMetrics(ctypes.Structure):
    _fields_ = [
        ("chip_id", ctypes.c_int32),
        ("device_path", ctypes.c_char * 64),
        ("hbm_total_bytes", ctypes.c_int64),
        ("hbm_used_bytes", ctypes.c_int64),
        ("duty_cycle_pct", ctypes.c_double),
        ("pid", ctypes.c_int32),
    ]


class TpuShim:
    def __init__(self, lib: ctypes.CDLL) -> None:
        self._lib = lib
        lib.tpushim_chip_count.restype = ctypes.c_int32
        lib.tpushim_chip_metrics.restype = ctypes.c_int32
        lib.tpushim_chip_metrics.argtypes = [
            ctypes.c_int32, ctypes.POINTER(_CChipMetrics)
        ]
        lib.tpushim_libtpu_version.restype = ctypes.c_int32
        lib.tpushim_libtpu_version.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int32
        ]
        abi = lib.tpushim_abi_version()
        if abi != 1:
            raise RuntimeError(f"libtpushim ABI mismatch: {abi}")

    def chip_count(self) -> int:
        return int(self._lib.tpushim_chip_count())

    def chip_metrics(self, index: int) -> ChipMetrics:
        raw = _CChipMetrics()
        rc = self._lib.tpushim_chip_metrics(index, ctypes.byref(raw))
        if rc != 0:
            raise IndexError(f"no TPU chip {index}")
        return ChipMetrics(
            chip_id=int(raw.chip_id),
            device_path=raw.device_path.decode(),
            hbm_total=int(raw.hbm_total_bytes),
            hbm_used=int(raw.hbm_used_bytes),
            duty_cycle=float(raw.duty_cycle_pct),
            pid=int(raw.pid),
        )

    def libtpu_version(self, libtpu_path: str = "") -> str:
        buf = ctypes.create_string_buffer(256)
        rc = self._lib.tpushim_libtpu_version(libtpu_path.encode(), buf, 256)
        return buf.value.decode() if rc == 0 else ""


_SHIM_PATHS = (
    os.path.join(os.path.dirname(__file__), "..", "..", "tpu_native",
                 "libtpushim.so"),
    "/usr/local/lib/libtpushim.so",
    "libtpushim.so",
)


@functools.lru_cache(maxsize=1)
def load_shim() -> TpuShim:
    """Load the native shim; raises OSError when not built/installed."""
    last: Exception | None = None
    for path in _SHIM_PATHS:
        try:
            return TpuShim(ctypes.CDLL(os.path.abspath(path)
                                       if os.path.sep in path else path))
        except OSError as e:
            last = e
    raise OSError(f"libtpushim.so not found ({last}); run make -C tpu_native")
