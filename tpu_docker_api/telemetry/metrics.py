"""In-process metrics registry with Prometheus text exposition.

SURVEY.md §5.5: the reference has no metrics at all (logging only, two pull
endpoints for scheduler maps). This supplies the missing layer: counters,
gauges and histograms behind one lock, rendered in Prometheus text format at
``GET /metrics``. Stdlib-only — no prometheus_client dependency to gate.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable

_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                    10.0)


def _esc_label(v) -> str:
    """Prometheus text-exposition label-value escaping: backslash, double
    quote AND newline (an unescaped newline would split the series line in
    two and ship a silently malformed exposition)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _esc_help(v: str) -> str:
    """HELP-text escaping per the exposition format: backslash and
    newline only (quotes are legal in help text)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_esc_label(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class MetricsRegistry:
    """Thread-safe metric store. All mutators take a labels dict; each
    distinct label set is its own series, Prometheus-style."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, dict[tuple, float]] = {}
        self._gauges: dict[str, dict[tuple, float]] = {}
        self._hists: dict[str, dict[tuple, list]] = {}
        self._hist_buckets: dict[str, tuple[float, ...]] = {}
        self._help: dict[str, tuple[str, str]] = {}  # name -> (type, help)
        self._gauge_fns: dict[str, Callable[[], float]] = {}
        self._gauge_series_fns: dict[str, Callable[[], list]] = {}
        self._counter_fns: dict[str, Callable[[], float]] = {}
        self._label_names: dict[str, tuple[str, ...]] = {}

    def _series_key(self, name: str, labels: dict | None) -> tuple:
        labels = labels or {}
        self._label_names.setdefault(name, tuple(sorted(labels)))
        return tuple(sorted(labels.items()))

    def _declare(self, name: str, typ: str, help: str) -> None:
        """Register (or re-assert) a metric's type. A name reused with a
        DIFFERENT type is a programming error that would render a
        duplicate/contradictory exposition — fail loudly at the mutation
        site instead of shipping a malformed /metrics page silently."""
        cur = self._help.get(name)
        if cur is None:
            self._help[name] = (typ, help)
        elif cur[0] != typ:
            raise ValueError(
                f"metric {name!r} already registered as {cur[0]}, "
                f"cannot re-register as {typ}")

    def counter_inc(self, name: str, labels: dict | None = None,
                    value: float = 1.0, help: str = "") -> None:
        with self._lock:
            self._declare(name, "counter", help)
            series = self._counters.setdefault(name, {})
            key = self._series_key(name, labels)
            series[key] = series.get(key, 0.0) + value

    def gauge_set(self, name: str, value: float, labels: dict | None = None,
                  help: str = "") -> None:
        with self._lock:
            self._declare(name, "gauge", help)
            self._gauges.setdefault(name, {})[
                self._series_key(name, labels)] = value

    def gauge_fn(self, name: str, fn: Callable[[], float], help: str = "") -> None:
        """Register a pull-time gauge (queue depth, free chips, ...)."""
        with self._lock:
            self._declare(name, "gauge", help)
            self._gauge_fns[name] = fn

    def gauge_series_fn(self, name: str,
                        fn: Callable[[], list], help: str = "") -> None:
        """Register a pull-time LABELED gauge family: ``fn()`` returns
        ``[(labels_dict, value), ...]`` rendered fresh at every scrape.
        For per-entity views whose entity set changes at runtime (e.g.
        per-endpoint connection-pool stats) — a plain ``gauge_set``
        would leave stale series behind when an entity disappears.
        Callers must keep the label set BOUNDED (hosts, endpoints — not
        request ids)."""
        with self._lock:
            self._declare(name, "gauge", help)
            self._gauge_series_fns[name] = fn

    def counter_value(self, name: str, labels: dict | None = None) -> float:
        """Read a counter's current value (0.0 if never incremented).
        Lets a subsystem keep the registry as its ONE set of books — the
        informer's status view (healthz, GET /api/v1/leader) reads back
        exactly the counters it exports at /metrics, so the two surfaces
        can never disagree."""
        with self._lock:
            series = self._counters.get(name, {})
            return series.get(tuple(sorted((labels or {}).items())), 0.0)

    def counter_sum(self, name: str) -> float:
        """Sum a counter across ALL label series (0.0 if never
        incremented). The labeled-counter analogue of
        :meth:`counter_value` — status views that aggregate a labeled
        family (gateway requests by service/code) read the same books
        they export."""
        with self._lock:
            return sum(self._counters.get(name, {}).values())

    def counter_fn(self, name: str, fn: Callable[[], float],
                   help: str = "") -> None:
        """Register a pull-time COUNTER (a monotonically increasing
        value owned elsewhere, e.g. an engine's completed-request
        count). Rendered with TYPE counter so Prometheus consumers can
        apply rate()/increase() with reset handling — exporting a
        monotonic series as a gauge breaks exactly that."""
        with self._lock:
            self._declare(name, "counter", help)
            self._counter_fns[name] = fn

    def observe(self, name: str, value: float, labels: dict | None = None,
                buckets: Iterable[float] = _DEFAULT_BUCKETS,
                help: str = "") -> None:
        with self._lock:
            self._declare(name, "histogram", help)
            bks = self._hist_buckets.setdefault(name, tuple(buckets))
            series = self._hists.setdefault(name, {})
            key = self._series_key(name, labels)
            if key not in series:
                series[key] = [[0] * (len(bks) + 1), 0.0, 0]  # bucket counts, sum, n
            counts, total, n = series[key]
            for i, b in enumerate(bks):
                if value <= b:
                    counts[i] += 1
            counts[-1] += 1  # +Inf
            series[key] = [counts, total + value, n + 1]

    def render(self) -> str:
        """Prometheus text exposition format."""
        out: list[str] = []
        with self._lock:
            for name, (typ, hlp) in sorted(self._help.items()):
                if hlp:
                    out.append(f"# HELP {name} {_esc_help(hlp)}")
                out.append(f"# TYPE {name} {typ}")
                if typ == "counter":
                    if name in self._counter_fns:
                        try:
                            v = float(self._counter_fns[name]())
                        except Exception:  # pragma: no cover — never break /metrics
                            continue
                        out.append(f"{name} {v:g}")
                    for key, v in sorted(self._counters.get(name, {}).items()):
                        out.append(f"{name}{_fmt_labels(dict(key))} {v:g}")
                elif typ == "gauge":
                    if name in self._gauge_fns:
                        try:
                            v = float(self._gauge_fns[name]())
                        except Exception:  # pragma: no cover — never break /metrics
                            continue
                        out.append(f"{name} {v:g}")
                    if name in self._gauge_series_fns:
                        try:
                            series = list(self._gauge_series_fns[name]())
                        except Exception:  # pragma: no cover — never break /metrics
                            series = []
                        for labels, v in sorted(
                                series, key=lambda s: sorted(s[0].items())):
                            out.append(
                                f"{name}{_fmt_labels(labels)} {float(v):g}")
                    for key, v in sorted(self._gauges.get(name, {}).items()):
                        out.append(f"{name}{_fmt_labels(dict(key))} {v:g}")
                else:  # histogram
                    bks = self._hist_buckets.get(name, ())
                    for key, (counts, total, n) in sorted(
                            self._hists.get(name, {}).items()):
                        labels = dict(key)
                        # counts are already cumulative (observe() increments
                        # every bucket the value fits in)
                        for i, b in enumerate(bks):
                            out.append(
                                f"{name}_bucket"
                                f"{_fmt_labels({**labels, 'le': f'{b:g}'})} "
                                f"{counts[i]}")
                        out.append(
                            f"{name}_bucket{_fmt_labels({**labels, 'le': '+Inf'})} {n}")
                        out.append(f"{name}_sum{_fmt_labels(labels)} {total:g}")
                        out.append(f"{name}_count{_fmt_labels(labels)} {n}")
        return "\n".join(out) + "\n"


#: process-wide default registry (api/app.py, service watchers)
REGISTRY = MetricsRegistry()


class Timer:
    """Context manager: observe elapsed seconds into a histogram."""

    def __init__(self, registry: MetricsRegistry, name: str,
                 labels: dict | None = None):
        self._r, self._name, self._labels = registry, name, labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._r.observe(self._name, time.perf_counter() - self._t0,
                        self._labels)
