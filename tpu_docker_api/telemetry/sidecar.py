"""tpu-detect sidecar: the HTTP telemetry service.

Parity: the reference's external ``detect-gpu`` sidecar (go-nvml wrapper,
README.md:194-195) serving ``GET /api/v1/detect/gpu``. This one serves:

    GET /api/v1/detect/tpu   — HostTopologyInfo JSON (chips, coords, HBM,
                               duty cycle, holder pids, libtpu version)
    GET /healthz

Run: ``python -m tpu_docker_api.telemetry.sidecar --port 2112``. The main
daemon seeds its chip scheduler from this endpoint when ``detect_tpu_addr``
is configured (daemon._discover_topology), exactly as the reference's
scheduler seeds from detect-gpu on first boot (gpuscheduler/scheduler.go:48-55).
With no TPU hardware, ``--fake v5e-8`` serves a synthesized topology (the
test seam of SURVEY.md §4 item 3).
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tpu_docker_api.scheduler.topology import HostTopology
from tpu_docker_api.schemas.tpu import ChipInfo, HostTopologyInfo
from tpu_docker_api.telemetry.probe import probe_host_info

log = logging.getLogger(__name__)


def fake_host_info(acc_type: str) -> HostTopologyInfo:
    """Synthesized topology for hardware-less environments."""
    topo = HostTopology.build(acc_type)
    gen = topo.generation
    chips = [
        ChipInfo(
            chip_id=cid,
            device_path=f"/dev/accel{cid}",
            coords=coords,
            cores_per_chip=gen.cores_per_chip,
            hbm_total_bytes=gen.hbm_bytes_per_chip,
        )
        for cid, coords in sorted(topo.coords.items())
    ]
    return HostTopologyInfo(
        accelerator_type=acc_type,
        generation=gen.name,
        chips=chips,
        mesh_shape=topo.mesh_shape,
        libtpu_version="fake",
    )


class SidecarServer:
    def __init__(self, host: str = "0.0.0.0", port: int = 2112,
                 fake: str = "") -> None:
        if fake:
            fake_host_info(fake)  # fail fast on a bad --fake type

        def topology() -> HostTopologyInfo | None:
            if fake:
                return fake_host_info(fake)
            return probe_host_info()

        class Handler(BaseHTTPRequestHandler):
            server_version = "tpu-detect"

            def log_message(self, fmt, *args):  # noqa: N802
                log.debug("sidecar: " + fmt, *args)

            def do_GET(self):  # noqa: N802
                path = self.path.split("?")[0]
                status = 200
                if path == "/healthz":
                    body = {"code": 200, "msg": "success",
                            "data": {"status": "ok"}}
                elif path in ("/api/v1/detect/tpu", "/api/v1/detect/gpu"):
                    info = topology()
                    if info is None:
                        # real HTTP error so naive clients (raise_for_status)
                        # fail cleanly instead of parsing data: null
                        status = 503
                        body = {"code": 10603, "msg": "no TPU hardware found",
                                "data": None}
                    else:
                        body = {"code": 200, "msg": "success",
                                "data": info.to_dict()}
                else:
                    status = 404
                    body = {"code": 10001, "msg": f"no route {path}",
                            "data": None}
                payload = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join()


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog="tpu-detect")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=2112)
    parser.add_argument("--fake", default="",
                        help="serve a synthesized topology, e.g. v5e-8")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    srv = SidecarServer(args.host, args.port, fake=args.fake)
    srv.start()
    log.info("tpu-detect serving on %s:%d (fake=%s)", args.host, srv.port,
             args.fake or "no")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.close()


if __name__ == "__main__":
    main()
