"""Local TPU discovery.

Replaces the NVML path of the reference's detect-gpu sidecar with what a TPU
host actually exposes: ``/dev/accel*`` device nodes (one per chip) and
``/sys/class/accel/accel*`` attributes. When the native shim
(``tpu_native/libtpushim.so``) is built, it supplies chip count and HBM
telemetry; otherwise a pure-Python walk of the device tree is used.
"""

from __future__ import annotations

import glob
import os
import re

from tpu_docker_api.scheduler.topology import (
    GENERATIONS,
    HostTopology,
    default_mesh_shape,
)
from tpu_docker_api.schemas.tpu import ChipInfo, HostTopologyInfo


def _detect_generation() -> str:
    """Best-effort generation from env or sysfs; defaults to v5e."""
    env = os.environ.get("TPU_ACCELERATOR_TYPE", "")
    m = re.match(r"(v\d+[a-z]*)", env)
    if m and m.group(1) in GENERATIONS:
        return m.group(1)
    return "v5e"


def list_accel_devices() -> list[str]:
    """Sorted /dev/accel* paths present on this host."""
    devs = glob.glob("/dev/accel*")
    return sorted(devs, key=lambda p: int(re.sub(r"\D", "", p) or 0))


def probe_host_info() -> HostTopologyInfo | None:
    """Hardware truth for the sidecar endpoint; None when no TPU present."""
    devices = list_accel_devices()
    if not devices:
        return None
    gen_name = _detect_generation()
    gen = GENERATIONS[gen_name]
    n = len(devices)
    shape = default_mesh_shape(gen, n)

    shim = None
    try:
        from tpu_docker_api.telemetry.shim import load_shim

        shim = load_shim()
    except Exception:  # pragma: no cover — shim optional
        shim = None

    chips = []
    cid = 0
    for z in range(shape[2]):
        for y in range(shape[1]):
            for x in range(shape[0]):
                if cid >= n:
                    break
                hbm_total = hbm_used = 0
                duty = 0.0
                if shim is not None:
                    # native shim supplies everything incl. the holder pid —
                    # avoid a second /proc walk from Python
                    m = shim.chip_metrics(cid)
                    hbm_total, hbm_used, duty = m.hbm_total, m.hbm_used, m.duty_cycle
                    pid = m.pid
                else:
                    pid = _device_holder_pid(devices[cid])
                if hbm_total == 0:
                    hbm_total = gen.hbm_bytes_per_chip
                chips.append(ChipInfo(
                    chip_id=cid,
                    device_path=devices[cid],
                    coords=(x, y, z),
                    cores_per_chip=gen.cores_per_chip,
                    hbm_total_bytes=hbm_total,
                    hbm_used_bytes=hbm_used,
                    duty_cycle_pct=duty,
                    pid=pid,
                ))
                cid += 1
    return HostTopologyInfo(
        accelerator_type=f"{gen_name}-{n * gen.cores_per_chip if gen.cores_per_chip > 1 else n}",
        generation=gen_name,
        chips=chips,
        mesh_shape=shape,
        libtpu_version=(shim.libtpu_version() if shim else ""),
    )


def _device_holder_pid(dev_path: str) -> int:
    """Which pid (if any) holds the device node open — the process view the
    NVML ProcessInfo carried (model/gpu.go:16-28). Scans /proc/*/fd."""
    try:
        target = os.stat(dev_path).st_rdev
    except OSError:
        return 0
    for pid_dir in glob.glob("/proc/[0-9]*/fd"):
        try:
            for fd in os.listdir(pid_dir):
                try:
                    st = os.stat(os.path.join(pid_dir, fd))
                except OSError:
                    continue
                if st.st_rdev == target:
                    return int(pid_dir.split("/")[2])
        except OSError:
            continue
    return 0


def topology_from_info(info: HostTopologyInfo) -> HostTopology:
    gen = GENERATIONS[info.generation]
    return HostTopology.from_chips(
        gen, {c.chip_id: c.coords for c in info.chips}
    )


def probe_local_topology() -> HostTopology | None:
    info = probe_host_info()
    return None if info is None else topology_from_info(info)
