"""TPU telemetry (parity: the reference's external ``detect-gpu`` NVML sidecar,
README.md:194-195, consumed at gpuscheduler/scheduler.go:142-158).

Three pieces:

- ``probe``: local chip discovery — ``/dev/accel*`` + ``/sys/class/accel``
  (optionally through the native C++ shim in ``tpu_native/``);
- ``sidecar``: the standalone HTTP service exporting
  ``GET /api/v1/detect/tpu`` (the reference's ``GET /api/v1/detect/gpu``);
- ``shim``: ctypes binding to the native ``libtpushim.so`` with a pure-Python
  fallback.
"""

from tpu_docker_api.telemetry.probe import (  # noqa: F401
    probe_host_info,
    probe_local_topology,
    topology_from_info,
)
