from tpu_docker_api.daemon import main

main()
