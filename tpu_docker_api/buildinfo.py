"""Build identification — the reference's ldflags-injected vars.

Parity: ``cmd/gpu-docker-api/main.go:25-31`` + ``Makefile:15`` inject
``BRANCH/VERSION/COMMIT`` at link time. The Python analog: values come from
``TPU_DOCKER_API_{VERSION,BRANCH,COMMIT}`` env — the root Makefile's
``BUILDINFO_ENV`` renders them for packaged/imaged deployments (see the
``run`` target) — falling back to a best-effort git probe of the source
checkout, else "dev"/"unknown". Surfaced in the startup log line and
``/healthz``.
"""

from __future__ import annotations

import functools
import os
import pathlib
import subprocess


@functools.lru_cache(maxsize=1)
def build_info() -> dict[str, str]:
    def from_git(*args: str) -> str:
        try:
            out = subprocess.run(
                ["git", *args], capture_output=True, text=True, timeout=2.0,
                cwd=str(pathlib.Path(__file__).resolve().parent),
            )
            return out.stdout.strip() if out.returncode == 0 else ""
        except (OSError, subprocess.TimeoutExpired):
            return ""

    return {
        "version": os.environ.get("TPU_DOCKER_API_VERSION")
        or from_git("describe", "--tags", "--always") or "dev",
        "branch": os.environ.get("TPU_DOCKER_API_BRANCH")
        or from_git("rev-parse", "--abbrev-ref", "HEAD") or "unknown",
        "commit": os.environ.get("TPU_DOCKER_API_COMMIT")
        or from_git("rev-parse", "--short", "HEAD") or "unknown",
    }
