"""Async reconciliation work queue.

Parity: reference ``internal/workQueue/workQueue.go`` — a buffered channel
(cap 110) drained by ``SyncLoop`` which type-switches on task kind. Fixes
applied (SURVEY.md §5.3):

- **bounded retry with exponential backoff** instead of infinite re-enqueue
  with no backoff (workQueue.go:33-47);
- **dead-letter list** instead of silent poison-pill spin;
- **ordered task chains** (``FnTask`` sequences) so data migration can run
  quiesce→copy→start instead of racing the old container's writes
  (the reference fires copy async and stops the old container immediately,
  service/container.go:255-266).

Graceful close drains in-flight tasks (waitgroup semantics, main.go:117-119).
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import random
import threading
import time
from typing import Any, Callable

log = logging.getLogger(__name__)

#: reference channel capacity (workQueue/workQueue.go:12)
DEFAULT_CAPACITY = 110
DEFAULT_MAX_RETRIES = 5
BACKOFF_BASE_S = 0.05
#: retry sleeps clamp here — an unbounded 2^attempt would stall the single
#: sync thread for minutes on a flaky engine
BACKOFF_MAX_S = 2.0
#: ±fraction of jitter on every retry sleep, so N daemons hammered by the
#: same engine outage don't retry in lockstep
BACKOFF_JITTER = 0.25


@dataclasses.dataclass
class PutKVTask:
    """Persist a key/value (reference PutKeyValue, etcd/common.go:34-39)."""
    key: str
    value: str


@dataclasses.dataclass
class DelKeyTask:
    """Delete a key or prefix (reference DelKey, etcd/common.go:41-43)."""
    key: str
    prefix: bool = False


@dataclasses.dataclass
class CopyTask:
    """Copy resource data old→new (reference CopyTask, workQueue/copy.go:19-23).

    Paths are resolved lazily via ``resolve`` at execution time, mirroring the
    reference's inspect-at-copy-time (copy.go:34-58), so the task tolerates the
    runtime recreating a resource between enqueue and execution.
    """
    resource: str          # "containers" | "volumes", for logs
    old_name: str
    new_name: str
    resolve: Callable[[str], str]  # name → host directory to copy
    on_done: Callable[[], None] | None = None  # e.g. start the new container
    on_fail: Callable[[], None] | None = None  # compensation when dead-lettered
                                               # (e.g. restart the old container)


@dataclasses.dataclass
class FnTask:
    """Arbitrary ordered work (the reference has no equivalent; used for
    quiesce→copy→start chains and scheduler state flushes)."""
    fn: Callable[[], None]
    description: str = ""


Task = PutKVTask | DelKeyTask | CopyTask | FnTask


class WorkQueue:
    def __init__(
        self,
        kv,
        copy_fn: Callable[[str, str], None] | None = None,
        capacity: int = DEFAULT_CAPACITY,
        max_retries: int = DEFAULT_MAX_RETRIES,
        backoff_base_s: float = BACKOFF_BASE_S,
        backoff_max_s: float = BACKOFF_MAX_S,
        backoff_jitter: float = BACKOFF_JITTER,
        seed: int | None = None,
    ) -> None:
        from tpu_docker_api.utils.files import copy_dir_contents

        self._kv = kv
        self._copy = copy_fn or copy_dir_contents
        self._q: queue.Queue[Task | None] = queue.Queue(maxsize=capacity)
        self._max_retries = max_retries
        self._backoff_base_s = backoff_base_s
        self._backoff_max_s = backoff_max_s
        self._backoff_jitter = backoff_jitter
        self._rng = random.Random(seed)
        self._thread: threading.Thread | None = None
        self.dead_letters: list[tuple[Task, str]] = []
        self._dl_mu = threading.Lock()
        self._lifecycle_mu = threading.Lock()

    # -- producer side -----------------------------------------------------------

    def submit(self, task: Task) -> None:
        self._q.put(task)

    # -- lifecycle ---------------------------------------------------------------

    def start(self) -> None:
        """Launch the sync loop thread (reference: go workQueue.SyncLoop,
        main.go:112)."""
        self._thread = threading.Thread(
            target=self._sync_loop, name="workqueue-sync", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        """Drain queued tasks, then stop the loop (reference drains only
        in-flight tasks and drops queued ones, workQueue.go:20-22 — we do
        better and finish everything already submitted)."""
        # _lifecycle_mu orders close vs retry_dead_letters: a retry that
        # wins the lock enqueues before the sentinel (and is drained); one
        # that loses sees _thread None and no-ops
        with self._lifecycle_mu:
            if self._thread is None:
                return
            self._q.put(None)  # sentinel
            self._thread.join()
            self._thread = None

    def drain(self) -> None:
        """Block until everything submitted so far is processed (test hook)."""
        self._q.join()

    # -- consumer side -----------------------------------------------------------

    def _sync_loop(self) -> None:
        while True:
            task = self._q.get()
            if task is None:
                self._q.task_done()
                return
            try:
                self._run_with_retry(task)
            finally:
                self._q.task_done()

    def _run_with_retry(self, task: Task) -> None:
        last_err = ""
        for attempt in range(self._max_retries):
            try:
                self._execute(task)
                return
            except Exception as e:  # noqa: BLE001 — queue must never die
                last_err = f"{type(e).__name__}: {e}"
                log.warning("workqueue task %r failed (attempt %d/%d): %s",
                            task, attempt + 1, self._max_retries, last_err)
                time.sleep(self.retry_delay_s(attempt))
        log.error("workqueue task %r dead-lettered: %s", task, last_err)
        with self._dl_mu:
            self.dead_letters.append((task, last_err))
        if isinstance(task, CopyTask) and task.on_fail is not None:
            try:
                task.on_fail()
            except Exception:  # noqa: BLE001
                log.exception("copy-task compensation for %s failed", task.new_name)

    def retry_delay_s(self, attempt: int) -> float:
        """Capped, jittered exponential backoff: min(cap, base·2^attempt)
        with ±``backoff_jitter`` spread (seedable for deterministic tests)."""
        from tpu_docker_api.utils.backoff import backoff_delay_s

        return backoff_delay_s(attempt, self._backoff_base_s,
                               self._backoff_max_s, self._backoff_jitter,
                               self._rng)

    def dead_letter_view(self) -> list[dict]:
        """Snapshot for the debug endpoint — dead letters must be observable,
        not an in-memory secret."""
        with self._dl_mu:
            return [{"task": repr(t), "error": e} for t, e in self.dead_letters]

    def retry_dead_letters(self) -> int:
        """Re-enqueue every dead-lettered task (POST /api/v1/dead-letters/
        retry) — the operator fixed the underlying fault (disk full, engine
        down) and wants the lost work to run, not a process restart. Each
        task gets a fresh retry budget; tasks that fail again dead-letter
        again. Returns how many were re-enqueued."""
        with self._lifecycle_mu:
            if self._thread is None:
                # queue closed: keep the letters observable in
                # dead_letter_view rather than stranding them behind the
                # shutdown sentinel in a consumerless queue
                return 0
            with self._dl_mu:
                tasks = [t for t, _ in self.dead_letters]
                self.dead_letters.clear()
            for task in tasks:
                self._q.put(task)
            return len(tasks)

    def _execute(self, task: Task) -> None:
        if isinstance(task, PutKVTask):
            self._kv.put(task.key, task.value)
        elif isinstance(task, DelKeyTask):
            if task.prefix:
                self._kv.delete_prefix(task.key)
            else:
                self._kv.delete(task.key)
        elif isinstance(task, CopyTask):
            src = task.resolve(task.old_name)
            dst = task.resolve(task.new_name)
            log.info("copying %s data %s -> %s (%s -> %s)",
                     task.resource, task.old_name, task.new_name, src, dst)
            self._copy(src, dst)
            if task.on_done is not None:
                task.on_done()
        elif isinstance(task, FnTask):
            task.fn()
        else:  # pragma: no cover
            raise TypeError(f"unknown task type {type(task)}")


def queue_depth(wq: WorkQueue) -> int:
    return wq._q.qsize()


def submit_state_put(wq: WorkQueue, key: str, payload: Any) -> None:
    """Convenience used by services: async JSON persist (reference
    Queue <- PutKeyValue, service/container.go:528-532)."""
    import json

    wq.submit(PutKVTask(key=key, value=json.dumps(payload)))
