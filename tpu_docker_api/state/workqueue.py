"""Durable async reconciliation work queue.

Parity: reference ``internal/workQueue/workQueue.go`` — a buffered channel
(cap 110) drained by ``SyncLoop`` which type-switches on task kind. Earlier
fixes (SURVEY.md §5.3): bounded retry with backoff instead of infinite
re-enqueue, a dead-letter list instead of silent poison-pill spin, ordered
quiesce→copy→start chains. This revision closes the queue's last gap — the
reference's channel (and our port of it) was pure process memory, so a
daemon crash lost every queued persist, data copy and compensation:

- **declarative task records** (:class:`TaskRecord`: kind + JSON params)
  replace closure-bearing tasks at every service submit site; kinds resolve
  at execution time through a registry the services bind their context into
  (:meth:`WorkQueue.register`), so a record written by a dead daemon is
  executable by the next one;
- **a crash-safe journal** under ``keys.QUEUE_TASKS_PREFIX``: every record
  is journaled at submit (state ``pending``), claimed by the sync loop
  (``inflight``), and acked on success (key deleted = ``done``) or marked
  ``dead`` after the bounded retries. Three labeled crash points —
  ``queue.claim`` / ``queue.exec`` / ``queue.ack`` — cover the lifecycle
  boundaries for the chaos harness;
- **replay-on-restart**: :meth:`replay_journal` (driven by the reconciler)
  re-executes pending/in-flight records exactly once in submit order.
  Non-idempotent steps (data copies) prove completion via per-task
  **markers** (``keys.queue_marker_key``) written *before* the follow-up
  start, so a replayed copy never re-clobbers a started container;
- **durable dead letters**: exhausted records stay in the journal with
  ``state="dead"`` and survive restarts; ``GET /api/v1/dead-letters`` reads
  and ``POST /api/v1/dead-letters/retry`` drains the durable set;
- **store-outage tolerance**: journal writes catch ``StoreUnavailable``
  (and any other store fault) and degrade LOUDLY — event + counter, task
  still runs in-memory — instead of wedging submit or the sync loop;
- **bounded submit**: ``put`` with a timeout raising typed
  ``errors.QueueSaturated`` (HTTP 429) instead of blocking an API thread
  forever on a full queue, and submit-after-close raises
  ``errors.QueueClosed`` instead of stranding tasks in a consumerless
  queue; ``close()`` has a drain deadline so a hung engine cannot block
  daemon shutdown indefinitely.

The legacy closure tasks (``PutKVTask``/``DelKeyTask``/``CopyTask``/
``FnTask``) remain accepted by :meth:`submit` for tests and ad-hoc chains,
but they are EPHEMERAL: never journaled, lost with the process.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
import logging
import queue
import random
import threading
import time
import uuid
from typing import Any, Callable

from tpu_docker_api import errors
from tpu_docker_api.state import keys
from tpu_docker_api.telemetry import trace as trace_mod

log = logging.getLogger(__name__)

#: reference channel capacity (workQueue/workQueue.go:12)
DEFAULT_CAPACITY = 110
DEFAULT_MAX_RETRIES = 5
BACKOFF_BASE_S = 0.05
#: retry sleeps clamp here — an unbounded 2^attempt would stall the single
#: sync thread for minutes on a flaky engine
BACKOFF_MAX_S = 2.0
#: ±fraction of jitter on every retry sleep, so N daemons hammered by the
#: same engine outage don't retry in lockstep
BACKOFF_JITTER = 0.25
#: bounded submit: how long a producer may wait on a full queue before the
#: typed QueueSaturated (config queue_submit_timeout_s)
DEFAULT_SUBMIT_TIMEOUT_S = 5.0
#: close(): how long shutdown waits for the backlog to drain before
#: abandoning the loop thread (config queue_close_deadline_s); journaled
#: records survive for the next daemon's replay either way
DEFAULT_CLOSE_DEADLINE_S = 10.0
#: dead-letter hygiene: how many times the OPERATOR may re-drive one dead
#: record through POST /api/v1/dead-letters/retry before the typed
#: RetryBudgetExhausted refusal (config queue_dead_letter_retry_budget) —
#: the count is durable on the record, so the cap survives daemon restarts
#: and a permanently-poisoned task can't be blind-retried forever
DEFAULT_DEAD_LETTER_RETRY_BUDGET = 3


# -- legacy ephemeral tasks (tests / ad-hoc chains; NOT journaled) -------------

@dataclasses.dataclass
class PutKVTask:
    """Persist a key/value (reference PutKeyValue, etcd/common.go:34-39)."""
    key: str
    value: str


@dataclasses.dataclass
class DelKeyTask:
    """Delete a key or prefix (reference DelKey, etcd/common.go:41-43)."""
    key: str
    prefix: bool = False


@dataclasses.dataclass
class CopyTask:
    """Copy resource data old→new (reference CopyTask, workQueue/copy.go:19-23).

    Paths are resolved lazily via ``resolve`` at execution time, mirroring the
    reference's inspect-at-copy-time (copy.go:34-58). Closure-bearing and
    therefore ephemeral — the services submit ``copy_container_data`` /
    ``copy_volume_data`` records instead.
    """
    resource: str          # "containers" | "volumes", for logs
    old_name: str
    new_name: str
    resolve: Callable[[str], str]  # name → host directory to copy
    on_done: Callable[[], None] | None = None  # e.g. start the new container
    on_fail: Callable[[], None] | None = None  # compensation when dead-lettered
                                               # (e.g. restart the old container)


@dataclasses.dataclass
class FnTask:
    """Arbitrary ordered work — ephemeral by construction (a closure cannot
    be journaled); kept for tests and internal chains only."""
    fn: Callable[[], None]
    description: str = ""


Task = PutKVTask | DelKeyTask | CopyTask | FnTask


# -- declarative records (journaled, replayable) -------------------------------

@dataclasses.dataclass
class TaskRecord:
    """One unit of durable async work: a kind resolved through the registry
    plus JSON-serializable params — everything the NEXT daemon needs to
    finish work this one started. ``trace_id``/``span_id`` persist the
    submitting request's trace context, so the async tail continues that
    trace in-process and a post-crash replay can LINK back to it (span
    links, not parentage — the origin's span tree died with its daemon)."""

    task_id: str
    kind: str
    params: dict
    seq: int                      # journal key ordinal = submit order
    state: str = "pending"        # pending | inflight | dead (done = deleted)
    attempts: int = 0
    error: str = ""
    idempotency_key: str = ""
    trace_id: str = ""
    span_id: str = ""
    #: owning writer-plane shard (sharded mode: each shard journals under
    #: its own sub-prefix and replays only its own records on takeover);
    #: legacy records with no field parse to shard 0 — the legacy keyspace
    shard: int = 0
    #: durable operator-retry count: how many times this record has been
    #: revived through POST /api/v1/dead-letters/retry. Distinct from
    #: ``attempts`` (the per-revival automatic retry loop, which resets on
    #: revival): this one only grows, so the retry budget holds across
    #: restarts. Legacy records with no field parse to 0 — full budget.
    op_retries: int = 0

    def to_json(self) -> str:
        d = {
            "id": self.task_id, "kind": self.kind, "params": self.params,
            "seq": self.seq, "state": self.state, "attempts": self.attempts,
            "error": self.error, "idempotencyKey": self.idempotency_key,
            "traceId": self.trace_id, "spanId": self.span_id,
        }
        if self.shard:
            d["shard"] = self.shard
        if self.op_retries:
            d["opRetries"] = self.op_retries
        return json.dumps(d, sort_keys=True)

    @classmethod
    def from_json(cls, raw: str) -> "TaskRecord":
        d = json.loads(raw)
        return cls(task_id=d["id"], kind=d["kind"], params=d["params"],
                   seq=int(d["seq"]), state=d.get("state", "pending"),
                   attempts=int(d.get("attempts", 0)),
                   error=d.get("error", ""),
                   idempotency_key=d.get("idempotencyKey", ""),
                   trace_id=d.get("traceId", ""),
                   span_id=d.get("spanId", ""),
                   shard=int(d.get("shard", 0)),
                   op_retries=int(d.get("opRetries", 0)))

    def label(self) -> str:
        return f"{self.kind}:{self.task_id}"


@dataclasses.dataclass
class TaskHandler:
    """Registry entry: how to execute a kind, and (optionally) how to
    compensate when the record dead-letters."""
    execute: Callable[[TaskRecord], None]
    on_fail: Callable[[TaskRecord], None] | None = None


class WorkQueue:
    def __init__(
        self,
        kv,
        copy_fn: Callable[[str, str], None] | None = None,
        capacity: int = DEFAULT_CAPACITY,
        max_retries: int = DEFAULT_MAX_RETRIES,
        backoff_base_s: float = BACKOFF_BASE_S,
        backoff_max_s: float = BACKOFF_MAX_S,
        backoff_jitter: float = BACKOFF_JITTER,
        seed: int | None = None,
        submit_timeout_s: float = DEFAULT_SUBMIT_TIMEOUT_S,
        close_deadline_s: float = DEFAULT_CLOSE_DEADLINE_S,
        dead_letter_retry_budget: int = DEFAULT_DEAD_LETTER_RETRY_BUDGET,
        metrics=None,
        tracer=None,
        shard_fn: Callable[[str, dict], int] | None = None,
        owned_shards: Callable[[], frozenset[int]] | None = None,
        store_gate=None,
    ) -> None:
        from tpu_docker_api.utils.files import copy_dir_contents

        self._kv = kv
        self._copy = copy_fn or copy_dir_contents
        self._q: queue.Queue[Task | TaskRecord | None] = queue.Queue(
            maxsize=capacity)
        self._max_retries = max_retries
        self._backoff_base_s = backoff_base_s
        self._backoff_max_s = backoff_max_s
        self._backoff_jitter = backoff_jitter
        self._rng = random.Random(seed)
        self._submit_timeout_s = submit_timeout_s
        self._close_deadline_s = close_deadline_s
        self._dl_retry_budget = dead_letter_retry_budget
        self._thread: threading.Thread | None = None
        self._closed = False
        #: ephemeral dead letters (legacy closure tasks only; records
        #: dead-letter durably in the journal)
        self._ephemeral_dead: list[tuple[Task, str]] = []
        self._dl_mu = threading.Lock()
        self._lifecycle_mu = threading.Lock()
        #: task_ids alive in THIS process (queued or executing): replay
        #: skips them so adoption never double-runs local work
        self._local_ids: set[str] = set()
        self._local_mu = threading.Lock()
        #: serializes replay_journal callers (periodic reconcile vs the
        #: HTTP route): overlapping replays would both adopt the same
        #: record and double-run its side effects
        self._replay_mu = threading.Lock()
        #: idempotency_key → task_id for ACTIVE records; lazily seeded
        #: from the journal so keyed submits don't re-scan the whole
        #: prefix (including the unbounded dead set) every time
        self._active_keys: dict[str, str] | None = None
        #: seed-scan race guard: records acked/dead-lettered while a seed
        #: scan is reading the journal outside the lock would otherwise be
        #: installed as permanently stale key→task_id entries
        self._seeding = 0
        self._dropped_while_seeding: set[str] = set()
        #: per-shard journal sequence counters; a shard is absent until its
        #: first scan (lazy so a store outage at construction degrades
        #: instead of failing the boot). The unsharded queue only ever
        #: uses shard 0 — the legacy flat journal prefix.
        self._seq: dict[int, int] = {}
        self._seq_mu = threading.Lock()
        #: sharded writer plane (daemon wiring): maps a submit to its
        #: owning shard (None ⇒ everything is shard 0), and names the
        #: shards THIS process currently leads so replay adopts only its
        #: own journal sub-prefixes (None ⇒ every record is adoptable —
        #: single-writer semantics, exactly today's behavior)
        self._shard_fn = shard_fn
        self._owned_shards = owned_shards
        #: store-outage hold (service/store_health.py): the sync loop keeps
        #: draining submits into its hands but PAUSES execution while the
        #: gate holds — a task run against a dead store would burn its
        #: bounded retries on guaranteed failures and dead-letter work that
        #: only needed to wait. Close overrides the hold: an unexecuted
        #: journaled record is exactly what replay adopts. None ⇒ ungated.
        self._store_gate = store_gate
        self.store_skips = 0
        self._journal_failures = 0
        self._events: collections.deque = collections.deque(maxlen=128)
        if metrics is None:
            from tpu_docker_api.telemetry.metrics import REGISTRY
            metrics = REGISTRY
        self._metrics = metrics
        #: trace sink for task-execution spans (daemon wires the Program's
        #: tracer); None ⇒ records still CARRY trace context, execution
        #: just records no spans of its own
        self._tracer = tracer
        self._registry: dict[str, TaskHandler] = {}
        # built-in declarative kinds every deployment has
        self.register("put_kv",
                      lambda rec: self._kv.put(rec.params["key"],
                                               rec.params["value"]))
        self.register("del_key", self._exec_del_key)
        self.register("delete_state_family", self._exec_delete_state_family)

    # -- registry -----------------------------------------------------------------

    def register(self, kind: str,
                 execute: Callable[[TaskRecord], None],
                 on_fail: Callable[[TaskRecord], None] | None = None) -> None:
        """Bind a task kind to service context. Services self-register at
        construction, so any process that can build the service can execute
        (and replay) its records. Last registration wins."""
        self._registry[kind] = TaskHandler(execute=execute, on_fail=on_fail)

    def _exec_del_key(self, rec: TaskRecord) -> None:
        if rec.params.get("prefix"):
            self._kv.delete_prefix(rec.params["key"])
        else:
            self._kv.delete(rec.params["key"])

    def _exec_delete_state_family(self, rec: TaskRecord) -> None:
        from tpu_docker_api.state.store import StateStore

        # one delete_prefix round trip: the whole family subtree (every
        # version + the latest pointer) drops atomically on every backend
        # (single sqlite txn / single etcd DeleteRange) — a replayed purge
        # can never leave half a family behind
        StateStore(self._kv).delete_family(
            keys.Resource(rec.params["resource"]), rec.params["base"])

    # -- markers (exec-level idempotency for replayed records) --------------------

    def marker_done(self, task_id: str, shard: int = 0) -> bool:
        return (self._kv.get_or(keys.queue_marker_key(task_id, shard))
                is not None)

    def mark_done(self, task_id: str, shard: int = 0) -> None:
        self._kv.put(keys.queue_marker_key(task_id, shard), "1")

    def copy_dirs(self, src: str, dst: str) -> None:
        """The data-migration primitive (swappable via ``copy_fn``)."""
        self._copy(src, dst)

    # -- producer side ------------------------------------------------------------

    def submit_record(self, kind: str, params: dict,
                      idempotency_key: str = "") -> str:
        """Journal a declarative record (durable intent), then enqueue it.
        Raises :class:`errors.QueueClosed` after shutdown began and
        :class:`errors.QueueSaturated` when the queue stays full past the
        submit timeout (the journal entry is removed again — a rejected
        submit must not execute later by surprise). A store outage on the
        durability path degrades loudly: the task still runs in-memory."""
        if self._closed:
            raise errors.QueueClosed(
                f"work queue is shut down; rejected {kind} task")
        rec: TaskRecord | None = None
        journaled = False
        try:
            if idempotency_key:
                dup_id = self._find_active(idempotency_key)
                if dup_id is not None:
                    log.info("workqueue: %s submit deduplicated against "
                             "active record %s:%s", kind, kind, dup_id)
                    return dup_id
            shard = self._shard_of(kind, params)
            cur = trace_mod.current()
            rec = TaskRecord(task_id=uuid.uuid4().hex[:12], kind=kind,
                             params=dict(params),
                             seq=self._next_seq(shard),
                             idempotency_key=idempotency_key,
                             trace_id=cur.trace_id if cur else "",
                             span_id=cur.span_id if cur else "",
                             shard=shard)
            # claim local ownership BEFORE the journal write: once the
            # record is visible in KV, a concurrent reconcile's replay
            # must already see it as ours, or it would double-run it
            with self._local_mu:
                self._local_ids.add(rec.task_id)
            self._kv.put(keys.queue_task_key(rec.seq, rec.shard),
                         rec.to_json())
            journaled = True
        except Exception as e:  # noqa: BLE001 — durability degrades, loudly
            self._degrade("journal-write-failed", f"{kind}: {e}")
            if rec is None:
                cur = trace_mod.current()
                rec = TaskRecord(task_id=uuid.uuid4().hex[:12], kind=kind,
                                 params=dict(params), seq=-1,
                                 idempotency_key=idempotency_key,
                                 trace_id=cur.trace_id if cur else "",
                                 span_id=cur.span_id if cur else "")
                with self._local_mu:
                    self._local_ids.add(rec.task_id)
            else:
                # the journal write itself failed: mark the record
                # in-memory-only (seq=-1) so the dead-letter path parks it
                # observably instead of "journaling" dead state into a
                # store that never held the record
                rec.seq = -1
        self._track_key(rec)
        try:
            self._q.put(rec, timeout=self._submit_timeout_s)
        except queue.Full:
            if journaled:
                # the caller gets an error; the record must not linger and
                # execute later behind their back. Journal delete FIRST,
                # then release local ownership — the reverse order opens a
                # window where a concurrent replay adopts the still-
                # journaled record after the caller was told 429
                with contextlib.suppress(Exception):
                    self._kv.delete(keys.queue_task_key(rec.seq, rec.shard))
            self._forget_local(rec)
            raise errors.QueueSaturated(
                f"work queue full ({self._q.maxsize} tasks) after "
                f"{self._submit_timeout_s}s; retry later") from None
        return rec.task_id

    def submit(self, task: Task) -> None:
        """Enqueue a legacy EPHEMERAL task (never journaled). Same bounded
        put / closed-queue semantics as :meth:`submit_record`."""
        if self._closed:
            raise errors.QueueClosed(
                f"work queue is shut down; rejected {task!r}")
        try:
            self._q.put(task, timeout=self._submit_timeout_s)
        except queue.Full:
            raise errors.QueueSaturated(
                f"work queue full ({self._q.maxsize} tasks) after "
                f"{self._submit_timeout_s}s; retry later") from None

    def reset_shard_cache(self, shard: int) -> None:
        """Shard-takeover cache invalidation (daemon's on-acquire hook):
        drop the shard's lazy seq counter and the idempotency-key map so
        both re-seed from the journal — the previous holder appended
        records this process never saw, and a stale counter would
        overwrite them."""
        with self._seq_mu:
            self._seq.pop(shard, None)
        with self._local_mu:
            self._active_keys = None

    def _shard_of(self, kind: str, params: dict) -> int:
        if self._shard_fn is None:
            return 0
        try:
            return int(self._shard_fn(kind, params))
        except Exception:  # noqa: BLE001 — misclassification must not
            # lose the task; shard 0 is the singleton-of-last-resort
            log.exception("workqueue: shard classification failed for %s; "
                          "routing to shard 0", kind)
            return 0

    def _next_seq(self, shard: int = 0) -> int:
        with self._seq_mu:
            if shard not in self._seq:
                prefix = keys.queue_tasks_prefix(shard)
                top = -1
                for k in self._kv.range_prefix(prefix):
                    tail = k[len(prefix):]
                    # shard 0's flat prefix is the PARENT of the s<i>/
                    # sub-prefixes: skip nested keys or a shard-0 scan
                    # would absorb every other shard's counter
                    if tail.isdigit():
                        top = max(top, int(tail))
                self._seq[shard] = top + 1
            out = self._seq[shard]
            self._seq[shard] = out + 1
            return out

    def _find_active(self, idempotency_key: str) -> str | None:
        """task_id of an active (pending/inflight) record with this key.
        Served from an in-memory map seeded ONCE from the journal (so a
        restarted daemon still dedups against a dead daemon's records) —
        a per-submit prefix scan would grow with the never-GC'd dead set."""
        with self._local_mu:
            needs_seed = self._active_keys is None
            if needs_seed:
                self._seeding += 1
        if needs_seed:
            # scan OUTSIDE the lock: on etcd this can retry with backoff
            # for seconds, and the sync loop acks through the same lock
            seeded: dict[str, str] | None = None
            try:
                scan: dict[str, str] = {}
                for rec in self._journal_records():
                    if (rec.idempotency_key
                            and rec.state in ("pending", "inflight")):
                        scan[rec.idempotency_key] = rec.task_id
                seeded = scan
            finally:
                with self._local_mu:
                    self._seeding -= 1
                    # only a CLEAN scan installs (a failed one leaves None
                    # so the next submit re-seeds), minus entries for
                    # records the sync loop finished while the scan was
                    # mid-read — installing those would swallow future
                    # keyed submits forever
                    if seeded is not None and self._active_keys is None:
                        self._active_keys = {
                            k: tid for k, tid in seeded.items()
                            if tid not in self._dropped_while_seeding}
                    if self._seeding == 0:
                        self._dropped_while_seeding.clear()
        with self._local_mu:
            return self._active_keys.get(idempotency_key)

    def _track_key(self, rec: TaskRecord) -> None:
        if not rec.idempotency_key:
            return
        with self._local_mu:
            if self._active_keys is not None:
                self._active_keys[rec.idempotency_key] = rec.task_id

    # -- lifecycle ----------------------------------------------------------------

    def start(self) -> None:
        """Launch the sync loop thread (reference: go workQueue.SyncLoop,
        main.go:112)."""
        self._closed = False
        self._thread = threading.Thread(
            target=self._sync_loop, name="workqueue-sync", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        """Drain queued tasks, then stop the loop — bounded by the close
        deadline: a hung engine call must not block daemon shutdown forever.
        An abandoned backlog is not lost — journaled records replay under
        the next daemon (the ephemeral remainder dies with the process, as
        it always did)."""
        # reject new submits as early as possible; the flag (not the
        # lifecycle lock) guards submit so a producer blocked in put()
        # cannot deadlock shutdown
        self._closed = True
        # _lifecycle_mu orders close vs retry_dead_letters: a retry that
        # wins the lock enqueues before the sentinel (and is drained); one
        # that loses sees _thread None and no-ops
        with self._lifecycle_mu:
            if self._thread is None:
                return
            deadline = time.monotonic() + self._close_deadline_s
            try:
                self._q.put(None, timeout=self._close_deadline_s)  # sentinel
            except queue.Full:
                pass  # hung consumer; the bounded join below handles it
            self._thread.join(timeout=max(0.0, deadline - time.monotonic()))
            if self._thread.is_alive():
                self._degrade(
                    "queue-close-abandoned",
                    f"sync loop still busy after {self._close_deadline_s}s; "
                    "journaled backlog will replay on next start")
            self._thread = None

    def drain(self) -> None:
        """Block until everything submitted so far is processed (test hook)."""
        self._q.join()

    @property
    def closed(self) -> bool:
        return self._closed

    def _degrade(self, kind: str, detail: str) -> None:
        """Durability-path failure: LOUD (log + counter + event), never
        blocking — a queue whose safety net wedges the daemon is worse
        than the crash it guards against."""
        self._journal_failures += 1
        log.error("workqueue %s: %s", kind, detail)
        self._metrics.counter_inc(
            "workqueue_degraded_total", {"kind": kind},
            help="Durability-path failures the queue degraded through")
        self._events.append(trace_mod.stamp(
            {"ts": time.time(), "event": kind, "detail": detail}))

    # -- consumer side ------------------------------------------------------------

    def _hold_for_store(self) -> None:
        """Pause task execution while the store gate holds (edge-triggered
        event, per-episode counter). Returns immediately once the gate
        lifts OR the queue is closing — a task executed against a down
        store on shutdown simply fails into the journal for replay."""
        if self._store_gate is None or self._store_gate():
            return
        self.store_skips += 1
        self._events.append(trace_mod.stamp(
            {"ts": time.time(), "event": "store-outage-hold", "detail": ""}))
        while not self._closed and not self._store_gate():
            time.sleep(0.05)
        self._events.append(trace_mod.stamp(
            {"ts": time.time(), "event": "store-outage-over", "detail": ""}))

    def _sync_loop(self) -> None:
        while True:
            task = self._q.get()
            if task is None:
                self._q.task_done()
                return
            try:
                self._hold_for_store()
                if isinstance(task, TaskRecord):
                    self._run_record(task)
                else:
                    self._run_with_retry(task)
            finally:
                self._q.task_done()

    def _task_scope(self, rec: TaskRecord, adopted: bool):
        """Span scope for one record execution. Same-process execution
        CONTINUES the submitting trace (same traceId, parent = the submit
        span); an adopted replay — this daemon did not submit the record,
        or a reboot reclaimed it — starts a fresh self-rooted trace with
        ``link=originTraceId``: the origin's span tree ended with its
        process, so parentage would fabricate a timeline."""
        if self._tracer is None:
            return trace_mod.NOOP
        attrs = {"taskId": rec.task_id, "seq": rec.seq}
        if not rec.trace_id:
            # a record submitted with no active trace (tracing was off at
            # submit, or a bare internal submit): its FIRST execution is
            # an ordinary task, never a "replay" — a self-rooted span,
            # trimmed like a loop pass when nothing happened beneath it
            return self._tracer.span(f"queue.task:{rec.kind}", attrs=attrs,
                                     trim_idle=True)
        if not adopted:
            return self._tracer.span(f"queue.task:{rec.kind}",
                                     trace_id=rec.trace_id,
                                     parent_id=rec.span_id, attrs=attrs)
        return self._tracer.span(f"queue.replay:{rec.kind}",
                                 links=(rec.trace_id,), attrs=attrs)

    def _run_record(self, rec: TaskRecord, adopted: bool = False) -> None:
        """Full record lifecycle: claim (journal ``inflight``) → execute
        with bounded retries → ack (journal delete) or dead-letter
        (journal ``dead`` + compensation). The three ``queue.*`` crash
        points mark the boundaries the chaos harness kills at."""
        with self._task_scope(rec, adopted):
            self._run_record_inner(rec)

    def _run_record_inner(self, rec: TaskRecord) -> None:
        from tpu_docker_api.service.crashpoints import crash_point

        rec.state = "inflight"
        self._journal_write(rec)
        crash_point("queue.claim")
        handler = self._registry.get(rec.kind)
        last_err = ""
        if handler is None:
            # deterministic failure — retrying with backoff would only
            # stall the loop (or the reconciler's inline replay) for a
            # record that can never succeed on this deployment
            rec.attempts = 1
            last_err = f"no handler registered for task kind {rec.kind!r}"
        else:
            for attempt in range(self._max_retries):
                rec.attempts = attempt + 1
                try:
                    handler.execute(rec)
                except Exception as e:  # noqa: BLE001 — queue must never die
                    last_err = f"{type(e).__name__}: {e}"
                    log.warning(
                        "workqueue record %s failed (attempt %d/%d): %s",
                        rec.label(), attempt + 1, self._max_retries,
                        last_err)
                    if attempt + 1 < self._max_retries:
                        # no sleep after the FINAL attempt: it would stall
                        # the sync loop (or an inline reconciler replay)
                        # on the way to the dead-letter verdict
                        time.sleep(self.retry_delay_s(attempt))
                    continue
                crash_point("queue.exec")
                self._ack(rec)
                crash_point("queue.ack")
                return
        log.error("workqueue record %s dead-lettered: %s", rec.label(),
                  last_err)
        rec.state = "dead"
        rec.error = last_err
        self._journal_write(rec)
        if rec.seq < 0:
            # degraded at submit (store outage): there is no journal entry
            # to hold the dead state, so park it with the ephemeral dead
            # letters — exhausted work must stay observable and retryable,
            # never silently dropped
            with self._dl_mu:
                self._ephemeral_dead.append((rec, last_err))
        self._forget_local(rec)
        self._metrics.counter_inc(
            "workqueue_dead_letters_total", {"kind": rec.kind},
            help="Tasks that exhausted their retry budget")
        if handler is not None and handler.on_fail is not None:
            try:
                handler.on_fail(rec)
            except Exception:  # noqa: BLE001
                log.exception("compensation for %s failed", rec.label())

    def _ack(self, rec: TaskRecord) -> None:
        """Done: drop the journal entry and its marker in ONE atomic apply
        — the old two-delete sequence had a crash window (entry gone,
        marker leaked) that the orphan sweep existed to mop up; batching
        closes it and halves the ack's store round trips. The local claim
        releases LAST so a concurrent replayer can never adopt the record
        while its marker is going away. A store outage leaves the entry
        inflight — the next replay re-runs it, which the marker makes safe
        — so degrade loudly rather than retry-looping."""
        rec.state = "done"
        try:
            ops: list[tuple] = []
            if rec.seq >= 0:
                ops.append(("delete",
                            keys.queue_task_key(rec.seq, rec.shard)))
            # degraded (seq<0) records may still have written a marker
            ops.append(("delete",
                        keys.queue_marker_key(rec.task_id, rec.shard)))
            self._kv.apply(ops)
        except Exception as e:  # noqa: BLE001
            self._degrade("journal-ack-failed", f"{rec.label()}: {e}")
        finally:
            self._forget_local(rec)

    def _journal_write(self, rec: TaskRecord) -> None:
        if rec.seq < 0:
            return  # degraded at submit: in-memory only
        try:
            self._kv.put(keys.queue_task_key(rec.seq, rec.shard),
                         rec.to_json())
        except Exception as e:  # noqa: BLE001
            self._degrade("journal-write-failed", f"{rec.label()}: {e}")

    def _forget_local(self, rec: TaskRecord) -> None:
        with self._local_mu:
            self._local_ids.discard(rec.task_id)
            # the key maps ACTIVE records only: once acked or dead it must
            # not absorb a fresh submit (a dead record needs operator
            # retry; a new keyed submit is new intent)
            if (self._active_keys is not None and rec.idempotency_key
                    and self._active_keys.get(rec.idempotency_key)
                    == rec.task_id):
                del self._active_keys[rec.idempotency_key]
            if self._seeding and rec.idempotency_key:
                # a seed scan is mid-read: it may have already copied this
                # record as active; veto it before the scan installs
                self._dropped_while_seeding.add(rec.task_id)

    def _run_with_retry(self, task: Task) -> None:
        last_err = ""
        for attempt in range(self._max_retries):
            try:
                self._execute(task)
                return
            except Exception as e:  # noqa: BLE001 — queue must never die
                last_err = f"{type(e).__name__}: {e}"
                log.warning("workqueue task %r failed (attempt %d/%d): %s",
                            task, attempt + 1, self._max_retries, last_err)
                if attempt + 1 < self._max_retries:
                    time.sleep(self.retry_delay_s(attempt))
        log.error("workqueue task %r dead-lettered: %s", task, last_err)
        with self._dl_mu:
            self._ephemeral_dead.append((task, last_err))
        if isinstance(task, CopyTask) and task.on_fail is not None:
            try:
                task.on_fail()
            except Exception:  # noqa: BLE001
                log.exception("copy-task compensation for %s failed", task.new_name)

    def retry_delay_s(self, attempt: int) -> float:
        """Capped, jittered exponential backoff: min(cap, base·2^attempt)
        with ±``backoff_jitter`` spread (seedable for deterministic tests)."""
        from tpu_docker_api.utils.backoff import backoff_delay_s

        return backoff_delay_s(attempt, self._backoff_base_s,
                               self._backoff_max_s, self._backoff_jitter,
                               self._rng)

    def _execute(self, task: Task) -> None:
        if isinstance(task, PutKVTask):
            self._kv.put(task.key, task.value)
        elif isinstance(task, DelKeyTask):
            if task.prefix:
                self._kv.delete_prefix(task.key)
            else:
                self._kv.delete(task.key)
        elif isinstance(task, CopyTask):
            src = task.resolve(task.old_name)
            dst = task.resolve(task.new_name)
            log.info("copying %s data %s -> %s (%s -> %s)",
                     task.resource, task.old_name, task.new_name, src, dst)
            self._copy(src, dst)
            if task.on_done is not None:
                task.on_done()
        elif isinstance(task, FnTask):
            task.fn()
        else:  # pragma: no cover
            raise TypeError(f"unknown task type {type(task)}")

    # -- journal views / replay ---------------------------------------------------

    def _journal_records(self) -> list[TaskRecord]:
        out = []
        for key, raw in sorted(
                self._kv.range_prefix(keys.QUEUE_TASKS_PREFIX).items()):
            try:
                out.append(TaskRecord.from_json(raw))
            except (ValueError, KeyError, TypeError):
                log.warning("workqueue: unreadable journal entry at %s", key)
        return out  # key-sorted == seq order (zero-padded)

    def journal_replayable(self, include_local: bool = False
                           ) -> list[TaskRecord]:
        """Pending/in-flight records in submit order. By default records
        owned by THIS process (queued or executing right now) are excluded
        — they are not adoptable, they are simply not done yet.
        ``include_local=True`` processes them too (test hook: drive the
        sync loop's work inline, under armed crash points)."""
        return self._filter_replayable(self._journal_records(), include_local)

    def _filter_replayable(self, records: list[TaskRecord],
                           include_local: bool) -> list[TaskRecord]:
        with self._local_mu:
            local = set() if include_local else set(self._local_ids)
        owned = (self._owned_shards() if self._owned_shards is not None
                 else None)
        return [rec for rec in records
                if rec.state in ("pending", "inflight")
                and rec.task_id not in local
                # sharded plane: adopt ONLY the shards this process leads
                # — another shard's journal belongs to its own (live!)
                # leader, and replaying it here would double-run work
                and (owned is None or rec.shard in owned)]

    def replay_journal(self, include_local: bool = False) -> list[dict]:
        """Adopt the journal: execute every replayable record inline, in
        submit order, through the same claim→exec→ack lifecycle the loop
        uses (so retries, dead-lettering, markers and crash points all
        apply). Exactly-once EFFECT comes from the markers and the
        idempotent handlers, not from suppressing the re-run."""
        outcomes = []
        # one replayer at a time, and the journal is re-read INSIDE the
        # lock: the periodic reconcile and the HTTP route would otherwise
        # both adopt the same record and double-run its side effects.
        # One scan serves both the replay pass and the marker sweep — on
        # etcd each full-prefix read is a network round trip per pass
        with self._replay_mu:
            records = self._journal_records()
            for rec in self._filter_replayable(records, include_local):
                # re-check at adoption time: the sync loop may have acked
                # (journal entry deleted — and with it the marker, so a
                # blind re-run would re-copy into a LIVE container) or
                # dead-lettered this record since the scan / since its
                # local-ownership snapshot was taken
                if rec.seq >= 0:
                    try:
                        raw = self._kv.get_or(
                            keys.queue_task_key(rec.seq, rec.shard))
                        if (raw is None or TaskRecord.from_json(raw).state
                                not in ("pending", "inflight")):
                            continue
                    except Exception as e:  # noqa: BLE001 — skip, not
                        # double-run: an unverifiable record replays on the
                        # next pass
                        log.warning("workqueue: adoption re-check for %s "
                                    "failed, skipping: %s", rec.label(), e)
                        continue
                log.info("workqueue: replaying adopted record %s (%s)",
                         rec.label(), rec.state)
                self._run_record(rec, adopted=True)
                outcomes.append({
                    "target": rec.label(), "kind": rec.kind,
                    "state": "dead" if rec.state == "dead" else "done",
                })
                self._metrics.counter_inc(
                    "workqueue_replayed_total", {"kind": rec.kind},
                    help="Journal records adopted and replayed after a restart")
            self._sweep_orphan_markers(records)
        return outcomes

    def sweep_orphan_markers(self) -> None:
        """Public GC entry (service/compactor.py rides it): drop acked
        copy-complete markers whose journal record is gone. Same
        best-effort contract as the replay-time sweep — a failure logs
        and waits for the next pass, never raises."""
        self._sweep_orphan_markers()

    def _sweep_orphan_markers(self, records: list[TaskRecord] | None = None
                              ) -> None:
        """GC markers whose record is gone — a daemon death between _ack's
        two deletes (journal entry first, marker second: the safe order,
        since a marker must outlive its record or replay would re-copy)
        leaks the marker forever otherwise. Markers of records alive in
        this process are kept: a local handler may be between its
        mark_done and the follow-up start. A stale ``records`` list is
        safe — it only retains a marker longer, never deletes a live one,
        since acked records drop their own markers in :meth:`_ack`."""
        try:
            if records is None:
                records = self._journal_records()
            live = {rec.task_id for rec in records}
            with self._local_mu:
                live |= self._local_ids
            owned = (self._owned_shards() if self._owned_shards is not None
                     else None)
            doomed = [
                # keys-only: marker values are never inspected here, and at
                # scale the orphan sweep must not deserialize the backlog
                key for key in self._kv.keys_prefix(keys.QUEUE_MARKERS_PREFIX)
                if key.rsplit("/", 1)[-1] not in live
                # sharded plane: GC only our own shards' markers — another
                # shard's fence would (rightly) reject the delete anyway
                and (owned is None or _marker_shard(key) in owned)
            ]
            # batched deletes, chunked under etcd's max-txn-ops (default
            # 128) so a huge orphan backlog still GCs incrementally instead
            # of failing wholesale forever (sweep is GC: no atomicity need)
            for i in range(0, len(doomed), 100):
                self._kv.apply([("delete", key)
                                for key in doomed[i:i + 100]])
        except Exception as e:  # noqa: BLE001 — GC, never required
            log.warning("workqueue: marker sweep skipped: %s", e)

    # -- dead letters -------------------------------------------------------------

    @property
    def dead_letters(self) -> list[tuple[Any, str]]:
        """Durable dead records (journal) + ephemeral legacy dead tasks."""
        out: list[tuple[Any, str]] = []
        with contextlib.suppress(Exception):
            out.extend((rec, rec.error) for rec in self._journal_records()
                       if rec.state == "dead")
        with self._dl_mu:
            out.extend(self._ephemeral_dead)
        return out

    def dead_letter_view(self) -> list[dict]:
        """Snapshot for the API — dead letters must be observable, not an
        in-memory secret (and since the journal, not a process secret)."""
        out = []
        with contextlib.suppress(Exception):
            for rec in self._journal_records():
                if rec.state == "dead":
                    out.append({
                        "id": rec.task_id, "kind": rec.kind,
                        "params": rec.params, "attempts": rec.attempts,
                        "task": f"{rec.kind}({json.dumps(rec.params, sort_keys=True)})",
                        "error": rec.error, "durable": True,
                        "opRetries": rec.op_retries,
                        "retryable": rec.op_retries < self._dl_retry_budget,
                    })
        with self._dl_mu:
            for t, e in self._ephemeral_dead:
                if isinstance(t, TaskRecord):  # degraded-submit record
                    out.append({
                        "id": t.task_id, "kind": t.kind, "params": t.params,
                        "attempts": t.attempts,
                        "task": f"{t.kind}({json.dumps(t.params, sort_keys=True)})",
                        "error": e, "durable": False,
                    })
                else:
                    out.append({"task": repr(t), "error": e,
                                "durable": False})
        return out

    def retry_dead_letters(self) -> int:
        """Re-enqueue dead-lettered tasks (POST /api/v1/dead-letters/retry)
        — the operator fixed the underlying fault (disk full, engine down)
        and wants the lost work to run, not a process restart. Each task
        gets a fresh AUTOMATIC retry budget, but its durable operator-retry
        count (``opRetries``) only grows: a record past
        ``dead_letter_retry_budget`` revivals is refused, and when EVERY
        dead letter is past budget the call raises the typed
        :class:`errors.RetryBudgetExhausted` instead of silently requeueing
        nothing — a permanently-poisoned task must be deleted or fixed, not
        re-driven forever. Returns how many were re-enqueued."""
        exhausted: list[str] = []
        with self._lifecycle_mu:
            if self._thread is None:
                # queue closed: durable letters stay observable in the
                # journal (and in dead_letter_view) rather than stranding
                # behind the shutdown sentinel in a consumerless queue
                return 0
            n = 0
            owned = (self._owned_shards() if self._owned_shards is not None
                     else None)
            for rec in self._journal_records():
                if rec.state != "dead":
                    continue
                if owned is not None and rec.shard not in owned:
                    continue  # that shard's leader revives its own dead
                if rec.op_retries >= self._dl_retry_budget:
                    exhausted.append(rec.label())
                    continue  # refused: stays dead, stays observable
                rec.state = "pending"
                rec.error = ""
                rec.attempts = 0
                rec.op_retries += 1
                # claim local ownership BEFORE the record becomes pending
                # in the journal: a concurrent reconcile replay must see
                # it as ours, or it double-runs the revived task
                with self._local_mu:
                    self._local_ids.add(rec.task_id)
                self._journal_write(rec)
                # active again BEFORE the enqueue: tracking after it races
                # an immediate ack, whose cleanup would find no entry to
                # remove and leave a done record's key swallowing every
                # future keyed submit
                self._track_key(rec)
                try:
                    self._q.put(rec, timeout=self._submit_timeout_s)
                except queue.Full:
                    # roll the state back so the letter stays visible;
                    # the operator retries once there is room
                    rec.state = "dead"
                    self._journal_write(rec)
                    self._forget_local(rec)
                    return n
                n += 1
            with self._dl_mu:
                entries = list(self._ephemeral_dead)
                self._ephemeral_dead.clear()
            for i, (task, err) in enumerate(entries):
                try:
                    # bounded, like every other producer: an unbounded put
                    # here would block the API thread HOLDING _lifecycle_mu,
                    # deadlocking close() past its own deadline
                    self._q.put(task, timeout=self._submit_timeout_s)
                except queue.Full:
                    with self._dl_mu:
                        self._ephemeral_dead.extend(entries[i:])
                    return n
                n += 1
            if n == 0 and exhausted:
                # nothing revived and at least one letter was refused:
                # surface the refusal as a typed 409, not {"requeued": 0}
                raise errors.RetryBudgetExhausted(
                    f"{len(exhausted)} dead letter(s) past the "
                    f"operator-retry budget ({self._dl_retry_budget}): "
                    + ", ".join(sorted(exhausted)[:5]))
            return n

    # -- stats (GET /api/v1/queue) -------------------------------------------------

    def stats(self) -> dict:
        """Depth / journal / degradation view for the operator."""
        counts = {"pending": 0, "inflight": 0, "dead": 0}
        journal_error = ""
        try:
            records = self._journal_records()
            for rec in records:
                counts[rec.state] = counts.get(rec.state, 0) + 1
        except Exception as e:  # noqa: BLE001 — a store outage must not 500
            records = []
            journal_error = f"{type(e).__name__}: {e}"
        out = {
            "depth": self._q.qsize(),
            "capacity": self._q.maxsize,
            "closed": self._closed,
            "journal": {"entries": len(records), **counts},
            "journalWriteFailures": self._journal_failures,
            "events": list(self._events),
        }
        if journal_error:
            out["journal"]["error"] = journal_error
        return out


def _marker_shard(marker_key: str) -> int:
    """Owning shard of a marker key: ``.../markers/s<i>/<tid>`` → i,
    the legacy flat layout → 0."""
    rest = marker_key[len(keys.QUEUE_MARKERS_PREFIX):]
    if rest.startswith("s"):
        sid, sep, _ = rest[1:].partition("/")
        if sep and sid.isdigit():
            return int(sid)
    return 0


def queue_depth(wq: WorkQueue) -> int:
    return wq._q.qsize()


def submit_state_put(wq: WorkQueue, key: str, payload: Any) -> None:
    """Convenience used by services: async durable JSON persist (reference
    Queue <- PutKeyValue, service/container.go:528-532)."""
    wq.submit_record("put_kv", {"key": key, "value": json.dumps(payload)})
