"""Watch-fed read caches: the client-go informer/reflector pattern.

The reference reads etcd on demand for every request; PR 7's HA split made
standby replicas re-seed from the store on EVERY read (`state/version.py`
read-through), so read fan-out still scaled with store capacity. This
module flips that: **list once, then watch** (`KV.range_prefix_with_rev` +
`KV.watch`, state/kv.py), replaying the event stream into a local mirror so
a standby serves GETs with ZERO store round trips per request — staleness
bounded by watch lag instead of by replica uptime, and the read path scales
with replica count.

Two pieces:

- :class:`Informer` — the reflector. One background thread: initial
  ``range_prefix`` + revision snapshot, then watch replay into the mirror,
  firing registered per-prefix handlers per event. On :class:`WatchLost`
  (compaction, overflow) or a store outage it RELISTS with capped backoff
  and emits a degradation event — the same loud-degrade stance as the
  durable work queue (docs/robustness.md): the cache never silently serves
  across a gap, and while unsynced the read path falls back to
  read-through.

- :class:`InformerReadKV` — the read-path switch. Wraps the daemon's store
  so ``get``/``range_prefix`` are served from the mirror while ``active()``
  (standby role) AND the informer is synced; every other call — and every
  read while degraded — delegates to the inner store untouched. Leader and
  ``leader_election = false`` behavior is byte-for-byte the old path.

Telemetry (the registry is the one set of books — status_view reads the
same counters /metrics exports): ``informer_events_total``,
``informer_relists_total``, ``informer_cache_hits_total``,
``informer_cache_misses_total`` and the ``informer_watch_lag_ms`` gauge.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable

from tpu_docker_api import errors
from tpu_docker_api.telemetry import trace
from tpu_docker_api.state.kv import KV, Watch, WatchEvent
from tpu_docker_api.utils.backoff import backoff_delay_s

log = logging.getLogger(__name__)


class Informer:
    """Mirror of one KV subtree, kept current by watch replay.

    Reads (:meth:`get`, :meth:`range_prefix`) are lock-guarded dict lookups
    — never a store round trip. ``synced`` is True only while the gapless
    contract holds: initial list done and the watch stream alive; any gap
    or outage flips it False (readers fall back to the store) until the
    relist completes. Handlers registered via :meth:`register` see every
    mutation exactly once in revision order — including the synthetic
    diff events a relist emits for changes the gap swallowed — so a
    derived cache (e.g. a VersionMap shadow) can never drift from the
    mirror it feeds on.
    """

    POLL_TIMEOUT_S = 0.25

    def __init__(self, kv: KV, prefix: str, registry=None,
                 relist_backoff_base_s: float = 0.1,
                 relist_backoff_max_s: float = 5.0,
                 poll_timeout_s: float = POLL_TIMEOUT_S) -> None:
        from tpu_docker_api.telemetry.metrics import MetricsRegistry

        self._kv = kv
        self.prefix = prefix
        self.registry = registry if registry is not None else MetricsRegistry()
        self._backoff_base_s = relist_backoff_base_s
        self._backoff_max_s = relist_backoff_max_s
        self._poll_timeout_s = poll_timeout_s
        self._mu = threading.Lock()
        self._mirror: dict[str, str] = {}
        self._synced = False
        self._last_rev = 0
        #: monotonic timestamp of the last successful store contact (a
        #: drained poll — even an empty one — proves the mirror is current
        #: up to that instant); None = never synced
        self._last_contact: float | None = None
        self._handlers: list[tuple[str, Callable[[WatchEvent], None]]] = []
        self._relist_hooks: list[Callable[[], None]] = []
        self._events: collections.deque = collections.deque(maxlen=64)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.registry.gauge_fn(
            "informer_watch_lag_ms", self.watch_lag_ms,
            help="ms since the informer last proved its mirror current "
                 "(-1 = never synced)")

    # -- read surface -------------------------------------------------------------

    @property
    def synced(self) -> bool:
        return self._synced

    @property
    def last_rev(self) -> int:
        return self._last_rev

    def get(self, key: str) -> str | None:
        with self._mu:
            return self._mirror.get(key)

    def range_prefix(self, prefix: str) -> dict[str, str]:
        with self._mu:
            return {k: v for k, v in sorted(self._mirror.items())
                    if k.startswith(prefix)}

    def range_prefix_with_rev(self, prefix: str) -> tuple[dict[str, str], int]:
        """Snapshot + the revision it reflects under ONE lock hold — the
        pair must be atomic or a consumer doing list-then-watch against
        the mirror would lose the events applied between the two reads."""
        with self._mu:
            snap = {k: v for k, v in sorted(self._mirror.items())
                    if k.startswith(prefix)}
            return snap, self._last_rev

    def watch_lag_ms(self) -> float:
        last = self._last_contact
        if last is None:
            return -1.0
        return round((time.monotonic() - last) * 1e3, 3)

    def status_view(self) -> dict:
        """Operator block for /healthz and GET /api/v1/leader — counters
        read back from the registry, so this view and /metrics are one."""
        rv = self.registry.counter_value
        return {
            "synced": self._synced,
            "lastRev": self._last_rev,
            "watchLagMs": self.watch_lag_ms(),
            "eventsTotal": int(rv("informer_events_total")),
            "relistsTotal": int(rv("informer_relists_total")),
            "cacheHits": int(rv("informer_cache_hits_total")),
            "cacheMisses": int(rv("informer_cache_misses_total")),
        }

    def events_view(self, limit: int = 100) -> list[dict]:
        return list(self._events)[-limit:]  # deque snapshots are thread-safe

    # -- handler registration -----------------------------------------------------

    def register(self, prefix: str,
                 fn: Callable[[WatchEvent], None]) -> None:
        """Subscribe ``fn`` to every event whose key starts with ``prefix``
        (fired from the informer thread, in revision order). Register
        BEFORE :meth:`start` so the initial list's synthetic events are
        seen too."""
        self._handlers.append((prefix, fn))

    def on_relist(self, fn: Callable[[], None]) -> None:
        """Subscribe to every full list+rewatch cycle — fired AFTER the
        mirror swap, BEFORE the synthetic diff events. A consumer that
        derives incremental state from the event stream (the reconciler's
        dirty-set) uses this to fall back to treat-everything-as-changed:
        a relist means a gap swallowed an unknown set of events, and the
        synthetic diff only re-emits what the MIRROR noticed — a derived
        store with wider state than the mirror must reset, not trust it."""
        self._relist_hooks.append(fn)

    def _fire(self, events: list[WatchEvent]) -> None:
        for ev in events:
            for prefix, fn in self._handlers:
                if not ev.key.startswith(prefix):
                    continue
                try:
                    fn(ev)
                except Exception:  # noqa: BLE001 — one bad handler must
                    log.exception("informer handler failed for %s", ev.key)

    # -- the reflector loop -------------------------------------------------------

    def _relist(self) -> Watch:
        """List + swap the mirror + open the watch from the snapshot's
        revision. Changes the gap swallowed are re-emitted as synthetic
        diff events (vs the OLD mirror), so handlers stay exactly mirror-
        consistent without ever seeing a double."""
        snapshot, rev = self._kv.range_prefix_with_rev(self.prefix)
        with self._mu:
            old = self._mirror
            diff = [WatchEvent(rev, "put", k, v)
                    for k, v in snapshot.items() if old.get(k) != v]
            diff += [WatchEvent(rev, "delete", k, None)
                     for k in old if k not in snapshot]
            self._mirror = dict(snapshot)
            self._last_rev = rev
            self._synced = True
            self._last_contact = time.monotonic()
        self.registry.counter_inc(
            "informer_relists_total",
            help="Full list+rewatch cycles (1 = the initial sync; more = "
                 "WatchLost or store-outage recoveries)")
        for hook in self._relist_hooks:
            try:
                hook()
            except Exception:  # noqa: BLE001 — one bad hook must not
                log.exception("informer relist hook failed")
        self._fire(diff)
        return self._kv.watch(self.prefix, rev)

    def _apply(self, events: list[WatchEvent]) -> None:
        with self._mu:
            for ev in events:
                if ev.op == "put":
                    self._mirror[ev.key] = ev.value
                else:
                    self._mirror.pop(ev.key, None)
                self._last_rev = max(self._last_rev, ev.rev)
        self.registry.counter_inc("informer_events_total",
                                  value=float(len(events)),
                                  help="Watch events replayed into the "
                                       "informer mirror")
        self._fire(events)

    def _degrade(self, reason: str, detail: str) -> None:
        """Loud degradation: the mirror can no longer prove itself gapless
        — stop serving it (readers fall back to the store) and say so."""
        self._synced = False
        log.warning("informer[%s] degraded (%s): %s",
                    self.prefix, reason, detail)
        self._events.append(trace.stamp(
            {"ts": time.time(), "event": "informer-degraded",
             "reason": reason, "detail": detail[:300]}))

    def _loop(self) -> None:
        attempt = 0
        watch: Watch | None = None
        while not self._stop.is_set():
            try:
                watch = self._relist()
                attempt = 0
                while not self._stop.is_set():
                    events = watch.poll(self._poll_timeout_s)
                    # a drained poll — even empty — proves currency
                    self._last_contact = time.monotonic()
                    if events:
                        self._apply(events)
            except errors.WatchLost as e:
                self._degrade("watch-lost", str(e))
                # no backoff: a lost watch is the store TELLING us to
                # relist, not the store being down
            except Exception as e:  # noqa: BLE001 — store outage et al.
                self._degrade("store-outage", f"{type(e).__name__}: {e}")
                self._stop.wait(backoff_delay_s(
                    attempt, self._backoff_base_s, self._backoff_max_s))
                attempt += 1
            finally:
                if watch is not None:
                    watch.close()
                    watch = None

    # -- lifecycle ----------------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="informer", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._poll_timeout_s * 4 + 5)
            self._thread = None


class InformerReadKV(KV):
    """Read-path switch (see module docstring). ``get``/``range_prefix``
    serve from the informer mirror while ``active()`` and synced; every
    write — and every read while inactive or degraded — delegates to the
    inner store unchanged. The mirror is authoritative for ABSENCE too: a
    key the synced mirror lacks raises NotExistInStore without a store
    round trip (that is a cache hit, not a miss).

    One more mode when a ``store_health`` monitor is attached
    (service/store_health.py): while the store is in **outage**, reads
    serve from the mirror EVEN THOUGH it is unsynced — and regardless of
    role, leader included — with the staleness marked per request
    (``note_stale_read`` → envelope ``stale`` field + ``X-Stale-Read``
    header). An explicitly-stale answer beats burning a deadline-bounded
    store attempt per GET against a store known to be down; absence stays
    authoritative against the last-known mirror. Paginated walks are the
    exception — they are rev-anchored against the store's history, which
    a stale mirror cannot prove, so they keep paying the bounded attempt."""

    def __init__(self, inner: KV, informer: Informer,
                 active: Callable[[], bool], store_health=None) -> None:
        self.inner = inner
        self.informer = informer
        self._active = active
        self.store_health = store_health

    def _stale(self) -> bool:
        return (self.store_health is not None
                and self.store_health.serve_stale_reads())

    def _stale_hit(self) -> None:
        self.store_health.note_stale_read(self.informer.watch_lag_ms())

    def _serving(self) -> bool:
        if not self._active():
            return False  # leader/single: never counted, never mirrored
        if self.informer.synced:
            return True
        # configured for cached reads but degraded/unsynced: read-through
        # fallback, counted as a miss so the degradation is visible
        self.informer.registry.counter_inc(
            "informer_cache_misses_total",
            help="Standby reads that fell through to the store (informer "
                 "unsynced/degraded)")
        return False

    def _hit(self) -> None:
        self.informer.registry.counter_inc(
            "informer_cache_hits_total",
            help="Standby reads served from the informer mirror (zero "
                 "store round trips)")

    def get(self, key: str) -> str:
        if self._stale():
            self._stale_hit()
            value = self.informer.get(key)
            if value is None:
                raise errors.NotExistInStore(key)
            return value
        if self._serving():
            self._hit()
            value = self.informer.get(key)
            if value is None:
                raise errors.NotExistInStore(key)
            return value
        return self.inner.get(key)

    def range_prefix(self, prefix: str) -> dict[str, str]:
        if self._stale():
            self._stale_hit()
            return self.informer.range_prefix(prefix)
        if self._serving():
            self._hit()
            return self.informer.range_prefix(prefix)
        return self.inner.range_prefix(prefix)

    def range_prefix_with_rev(self, prefix: str) -> tuple[dict[str, str], int]:
        if self._stale():
            self._stale_hit()
            return self.informer.range_prefix_with_rev(prefix)
        if self._serving():
            self._hit()
            # one informer lock hold: snapshot and rev must be atomic or
            # the list-then-watch handshake would lose in-between events
            return self.informer.range_prefix_with_rev(prefix)
        return self.inner.range_prefix_with_rev(prefix)

    def keys_prefix(self, prefix: str, limit: int = 0,
                    start_after: str = "") -> list[str]:
        if self._stale():
            self._stale_hit()
            ks = [k for k in self.informer.range_prefix(prefix)
                  if k > start_after]
            return ks[:limit] if limit > 0 else ks
        if self._serving():
            self._hit()
            ks = [k for k in self.informer.range_prefix(prefix)
                  if k > start_after]
            return ks[:limit] if limit > 0 else ks
        return self.inner.keys_prefix(prefix, limit=limit,
                                      start_after=start_after)

    def range_prefix_page(self, prefix: str, limit: int,
                          start_after: str = "",
                          at_rev: int = 0) -> tuple[dict[str, str], int]:
        # always the inner store: a page sequence is rev-anchored against
        # the STORE's revision history, which the mirror cannot prove (its
        # revs advance with watch lag) — a standby pays the read rather
        # than risking a silently inconsistent walk
        return self.inner.range_prefix_page(prefix, limit,
                                            start_after=start_after,
                                            at_rev=at_rev)

    def current_rev(self) -> int:
        return self.inner.current_rev()

    def watch(self, prefix: str, start_rev: int = 0) -> Watch:
        return self.inner.watch(prefix, start_rev)

    # -- writes: delegate untouched ----------------------------------------------

    def put(self, key: str, value: str) -> None:
        self.inner.put(key, value)

    def delete(self, key: str) -> None:
        self.inner.delete(key)

    def delete_prefix(self, prefix: str) -> None:
        self.inner.delete_prefix(prefix)

    def _apply(self, ops: list[tuple], guards: list[tuple] | None = None) -> None:
        # the base template (our public ``apply``) already validated and
        # fired the txn crash points — delegate to the inner BACKEND's
        # atomic ``_apply`` so they never fire twice per batch
        self.inner._apply(ops, guards)

    def close(self) -> None:
        self.inner.close()
