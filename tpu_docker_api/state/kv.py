"""Pluggable key-value backends.

Parity: reference ``internal/etcd/{client,common}.go`` — a clientv3 wrapper with
``Put/Get/Del``. Here the surface is an abstract ``KV`` with three backends:

- ``MemoryKV`` — hermetic tests (the seam SURVEY.md §4 calls for),
- ``SqliteKV`` — durable single-host deployments without an etcd cluster,
- ``EtcdKV``  — etcd v3 via its grpc-gateway JSON API (``/v3/kv/*``), keeping
  the reference's deployment shape without a grpc/protobuf dependency.

All backends add ``range_prefix``/``delete_prefix``, which the reference lacks
and which per-version key layout (state/keys.py) needs, and ``apply`` — an
atomic multi-key put/delete batch (the etcd txn / Kubernetes-apiserver write
pattern) so a version transition is ONE store round trip instead of a
sequence of windows a crash can land between.

``apply`` also takes **guards** — compare preconditions evaluated atomically
with the batch (etcd's native txn compares; sqlite/memory check under the
same txn/lock that applies the ops). A failed guard applies NOTHING and
raises the typed :class:`errors.GuardFailed`. This is the primitive the HA
control plane rides: leader-lease CAS (service/leader.py) and epoch fencing
of a deposed leader's writes are both one guarded apply.

The read-scaling half is **watch**: every mutation is stamped with a
monotonic revision and emitted as a ``(rev, op, key, value)`` event, so a
standby replica can list once and then tail changes instead of re-reading
the store per request (the client-go informer pattern, state/informer.py).
``MemoryKV`` notifies in-process subscribers under the same lock hold that
applies the mutation; ``SqliteKV`` appends to a changelog table INSIDE the
same transaction as the data write (watchers — including ones in other
processes sharing the file — tail it by indexed rev); ``EtcdKV`` rides the
native ``/v3/watch`` stream. ``delete_prefix`` expands to one delete event
per existing key, so a watch-fed cache never needs a relist on the happy
path; a gap (compaction, slow-consumer overflow, canceled stream) surfaces
as the typed :class:`errors.WatchLost`, whose only correct recovery is
relist-then-rewatch.
"""

from __future__ import annotations

import abc
import base64
import bisect
import collections
import contextlib
import sqlite3
import threading
import time
from typing import NamedTuple

from tpu_docker_api import errors

#: op kinds KV.apply accepts: ("put", key, value) | ("delete", key) |
#: ("delete_prefix", prefix)
_APPLY_OPS = {"put": 3, "delete": 2, "delete_prefix": 2}

#: events retained for watch replay/buffering on the hermetic backends
#: (MemoryKV global log + per-watch queues; SqliteKV changelog rows). A
#: watcher that falls further behind than this loses the gapless contract
#: and gets a typed WatchLost instead of a silent gap.
WATCH_LOG_RETAIN = 4096


class WatchEvent(NamedTuple):
    """One mutation, as a watcher sees it. ``rev`` is the store's monotonic
    revision: non-decreasing across events, strictly greater than any
    earlier mutation's rev (etcd stamps every key changed by one txn with
    the same rev; memory/sqlite stamp per key). ``op`` is ``"put"`` or
    ``"delete"``; ``value`` is None for deletes. A ``delete_prefix`` is
    always expanded to one event per key that actually existed — deleting
    an absent key emits nothing, matching etcd."""

    rev: int
    op: str
    key: str
    value: str | None


class Watch(abc.ABC):
    """Handle on an event stream from :meth:`KV.watch`. Pull-based so every
    backend (push-notified memory, poll-tailed sqlite, streamed etcd) looks
    identical to the informer loop that consumes it."""

    @abc.abstractmethod
    def poll(self, timeout_s: float = 0.0) -> list[WatchEvent]:
        """Events since the last poll, in rev order; blocks up to
        ``timeout_s`` when none are pending ([] on timeout). Raises
        :class:`errors.WatchLost` when the gapless contract is broken
        (compaction past our rev, buffer overflow, canceled stream) and
        :class:`errors.StoreUnavailable` when the path to the store died —
        both mean relist-then-rewatch."""

    def close(self) -> None:  # noqa: B027
        pass


def _check_guards(guards: list[tuple] | None) -> list[tuple]:
    """Validate guard shapes: ``("value", key, expected)`` with expected a
    str (current value must equal it) or None (key must be absent)."""
    guards = list(guards or [])
    for g in guards:
        if (len(g) != 3 or g[0] != "value" or not isinstance(g[1], str)
                or not (g[2] is None or isinstance(g[2], str))):
            raise ValueError(f"malformed guard {g!r}")
    return guards


def _guard_mismatch(key: str, expected: str | None,
                    actual: str | None) -> "errors.GuardFailed":
    def short(v):
        if v is None:
            return "<absent>"
        return v if len(v) <= 64 else v[:61] + "..."

    return errors.GuardFailed(
        f"guard on {key}: expected {short(expected)}, found {short(actual)}")


class KV(abc.ABC):
    """Minimal KV surface (reference etcd.Put/Get/Del, common.go:45-73)."""

    @abc.abstractmethod
    def put(self, key: str, value: str) -> None: ...

    @abc.abstractmethod
    def get(self, key: str) -> str:
        """Return the value; raise errors.NotExistInStore if absent."""

    @abc.abstractmethod
    def delete(self, key: str) -> None:
        """Delete the key (no error if absent, matching etcd semantics)."""

    @abc.abstractmethod
    def range_prefix(self, prefix: str) -> dict[str, str]:
        """All key→value pairs whose key starts with ``prefix``, key-sorted."""

    def keys_prefix(self, prefix: str, limit: int = 0,
                    start_after: str = "") -> list[str]:
        """Sorted keys under ``prefix`` — no value fetch, no deserialize
        (etcd ``keys_only``, sqlite ``SELECT k``). ``start_after`` is
        exclusive; ``limit`` ≤ 0 means unbounded. The cheap primitive for
        callers that only inspect key names (latest-version derivation,
        marker sweeps): at O(100k) objects, hauling every value over the
        wire to throw it away was pure waste. Base fallback rides
        ``range_prefix`` so wrapper/test KVs keep working; real backends
        override with a values-free scan."""
        ks = [k for k in self.range_prefix(prefix) if k > start_after]
        return ks[:limit] if limit > 0 else ks

    def range_prefix_page(self, prefix: str, limit: int,
                          start_after: str = "",
                          at_rev: int = 0) -> tuple[dict[str, str], int]:
        """One bounded, rev-anchored page: up to ``limit`` key→value pairs
        with key > ``start_after`` under ``prefix`` (key order), plus the
        revision the page reflects. ``at_rev = 0`` serves the current
        state and returns its revision — the first page of a walk;
        ``at_rev > 0`` must serve the SAME snapshot that revision did, or
        raise the typed :class:`errors.ContinueExpired` — so a page
        sequence is a consistent snapshot or a loud 410, never a silent
        dup/skip. etcd serves old revisions natively (MVCC); memory and
        sqlite prove no event touched the prefix since ``at_rev`` via
        their watch logs (a trimmed log ⇒ ContinueExpired, same stance as
        WatchLost). The base fallback (wrapper/test KVs) pages the full
        range and can only anchor to the current revision."""
        if limit <= 0:
            raise ValueError("range_prefix_page requires limit > 0")
        cur = self.current_rev()
        if at_rev > 0 and at_rev != cur:
            raise errors.ContinueExpired(
                f"page anchored at rev {at_rev}, store at {cur}")
        items = {}
        for k, v in self.range_prefix(prefix).items():
            if k <= start_after:
                continue
            items[k] = v
            if len(items) >= limit:
                break
        return items, cur

    def delete_prefix(self, prefix: str) -> None:
        for k in self.range_prefix(prefix):
            self.delete(k)

    def apply(self, ops: list[tuple], guards: list[tuple] | None = None) -> None:
        """Atomically apply a batch of ``("put", k, v)`` / ``("delete", k)``
        / ``("delete_prefix", p)`` ops — all land or none do. The two
        ``txn.*`` crash points bracket the commit so the chaos suite can
        prove both halves of the contract: a crash BEFORE the txn leaves
        nothing applied, a crash AFTER leaves everything applied (and the
        reconciler finishes the flow forward).

        ``guards`` are compare preconditions — ``("value", key, expected)``
        where ``expected`` is the exact current value (str) or None for
        "key must be absent" — evaluated atomically WITH the batch: a
        mismatch applies nothing and raises the typed
        :class:`errors.GuardFailed` (the contention loser's signal; never
        blind-retried at this layer). Subclasses override ``_apply`` with a
        genuinely atomic implementation; the base fallback (check, then
        sequential ops) keeps wrapper/test KVs working but is NOT atomic."""
        from tpu_docker_api.service.crashpoints import crash_point
        from tpu_docker_api.telemetry import trace

        guards = _check_guards(guards)
        if not ops and not guards:
            return
        for op in ops:
            want = _APPLY_OPS.get(op[0])
            if want is None or len(op) != want:
                raise ValueError(f"malformed apply op {op!r}")
        # the crash points sit INSIDE the span, so a simulated kill at
        # either txn boundary closes it as status="lost" — the trace shows
        # exactly which commit the daemon died around
        with trace.child("kv.apply", ops=len(ops), guards=len(guards)):
            crash_point("txn.before_apply")
            self._apply(ops, guards)
            crash_point("txn.after_apply")

    def cas(self, key: str, expected: str | None, new: str) -> None:
        """Compare-and-swap convenience: write ``new`` iff the key's current
        value is exactly ``expected`` (None = create-if-absent). Raises
        :class:`errors.GuardFailed` when the compare loses."""
        self.apply([("put", key, new)], guards=[("value", key, expected)])

    def _apply(self, ops: list[tuple], guards: list[tuple] | None = None) -> None:
        for _, key, expected in guards or []:
            actual = self.get_or(key)
            if actual != expected:
                raise _guard_mismatch(key, expected, actual)
        for op in ops:
            if op[0] == "put":
                self.put(op[1], op[2])
            elif op[0] == "delete":
                self.delete(op[1])
            else:
                self.delete_prefix(op[1])

    def get_or(self, key: str, default: str | None = None) -> str | None:
        try:
            return self.get(key)
        except errors.NotExistInStore:
            return default

    # -- watch ---------------------------------------------------------------

    def watch(self, prefix: str, start_rev: int = 0) -> Watch:
        """Tail mutations under ``prefix``: events with rev **strictly
        greater than** ``start_rev``, in order, with no gaps. Pair with
        :meth:`range_prefix_with_rev` for the list-then-watch handshake:
        snapshot at rev R, watch from R, and nothing is missed or doubled.
        Backends implement this; plain wrapper KVs delegate."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support watch")

    def current_rev(self) -> int:
        """The store's latest revision (0 = no mutation ever observed).
        Backends with real revision tracking override; the base returns 0
        so simple test doubles keep working (watch from 0 = everything)."""
        return 0

    def range_prefix_with_rev(self, prefix: str) -> tuple[dict[str, str], int]:
        """Atomic (snapshot, revision) pair — the list half of the informer
        handshake. The revision is taken with the snapshot (same lock hold
        / read transaction / response header), so ``watch(prefix, rev)``
        delivers exactly the mutations the snapshot does not contain."""
        return self.range_prefix(prefix), self.current_rev()

    def close(self) -> None:  # noqa: B027
        pass


class _MemoryWatch(Watch):
    """Per-subscriber bounded queue. The emitting thread offers events
    under the store lock; poll drains under the watch's own condition (the
    kv-lock → watch-lock order is one-way, so no deadlock)."""

    def __init__(self, kv: "MemoryKV", prefix: str, maxlen: int) -> None:
        self._kv = kv
        self.prefix = prefix
        self._maxlen = maxlen
        self._cv = threading.Condition()
        self._q: collections.deque[WatchEvent] = collections.deque()
        self._lost: str | None = None

    def _offer(self, events: list[WatchEvent]) -> None:
        """Called by the mutator with kv._mu held."""
        with self._cv:
            for ev in events:
                if not ev.key.startswith(self.prefix):
                    continue
                if self._q and len(self._q) >= self._maxlen:
                    # a slow consumer must lose LOUDLY, not drop silently
                    self._lost = (f"watch buffer overflow at "
                                  f"{self._maxlen} events")
                    break
                self._q.append(ev)
            self._cv.notify_all()

    def _mark_lost(self, why: str) -> None:
        with self._cv:
            self._lost = why
            self._cv.notify_all()

    def poll(self, timeout_s: float = 0.0) -> list[WatchEvent]:
        with self._cv:
            if not self._q and self._lost is None and timeout_s > 0:
                self._cv.wait(timeout_s)
            if self._lost is not None:
                raise errors.WatchLost(self._lost)
            out = list(self._q)
            self._q.clear()
            return out

    def close(self) -> None:
        self._kv._unsubscribe(self)


class MemoryKV(KV):
    """In-process dict store for hermetic tests (and the shared-object
    store multi-``Program`` harnesses inject). Every mutation funnels
    through :meth:`_apply`, which stamps revisions and notifies watch
    subscribers under the SAME lock hold that applies the ops — an
    in-process watcher can never observe a gap or a reordering."""

    def __init__(self, log_retain: int = WATCH_LOG_RETAIN) -> None:
        self._d: dict[str, str] = {}
        #: sorted slice of the live keys, maintained incrementally (bisect
        #: insert/remove) so prefix windows and bounded pages are
        #: O(log N + result) instead of a full sort per call — at O(100k)
        #: keys, sorting per list request is what made lists O(N log N)
        self._keys: list[str] = []
        self._mu = threading.Lock()
        self._rev = 0
        self._log_retain = log_retain
        self._log: collections.deque[WatchEvent] = collections.deque()
        self._trimmed_below = 0  # revs <= this are gone from the log
        self._watches: list[_MemoryWatch] = []

    def put(self, key: str, value: str) -> None:
        self._apply([("put", key, value)])

    def get(self, key: str) -> str:
        with self._mu:
            if key not in self._d:
                raise errors.NotExistInStore(key)
            return self._d[key]

    def delete(self, key: str) -> None:
        self._apply([("delete", key)])

    def _window_locked(self, prefix: str, start_after: str = "") -> tuple[int, int]:
        """[lo, hi) indices of self._keys inside ``prefix``, past
        ``start_after`` (exclusive). Caller holds the lock."""
        lo = bisect.bisect_right(self._keys, max(prefix, start_after)) \
            if start_after >= prefix else bisect.bisect_left(self._keys, prefix)
        if not prefix:
            return lo, len(self._keys)
        end = _prefix_end(prefix)
        hi = len(self._keys) if end == "\0" \
            else bisect.bisect_left(self._keys, end)
        return lo, hi

    def range_prefix(self, prefix: str) -> dict[str, str]:
        with self._mu:
            lo, hi = self._window_locked(prefix)
            return {k: self._d[k] for k in self._keys[lo:hi]}

    def keys_prefix(self, prefix: str, limit: int = 0,
                    start_after: str = "") -> list[str]:
        with self._mu:
            lo, hi = self._window_locked(prefix, start_after)
            if limit > 0:
                hi = min(hi, lo + limit)
            return self._keys[lo:hi]

    def range_prefix_page(self, prefix: str, limit: int,
                          start_after: str = "",
                          at_rev: int = 0) -> tuple[dict[str, str], int]:
        if limit <= 0:
            raise ValueError("range_prefix_page requires limit > 0")
        with self._mu:
            if at_rev > 0:
                # serve at_rev iff we can PROVE the prefix is untouched
                # since then: every event after at_rev is still in the log
                # (else the proof is gone — same stance as WatchLost) and
                # none of them landed under the prefix
                if at_rev < self._trimmed_below:
                    raise errors.ContinueExpired(
                        f"page anchored at rev {at_rev}, log trimmed "
                        f"through {self._trimmed_below}")
                for ev in self._log:
                    if ev.rev > at_rev and ev.key.startswith(prefix):
                        raise errors.ContinueExpired(
                            f"prefix {prefix!r} mutated at rev {ev.rev} "
                            f"past the page anchor {at_rev}")
            lo, hi = self._window_locked(prefix, start_after)
            hi = min(hi, lo + limit)
            return ({k: self._d[k] for k in self._keys[lo:hi]},
                    at_rev or self._rev)

    def delete_prefix(self, prefix: str) -> None:
        # one lock hold, not one delete per key — the purge paths submit a
        # single op and the backend must honor that shape
        self._apply([("delete_prefix", prefix)])

    def current_rev(self) -> int:
        with self._mu:
            return self._rev

    def range_prefix_with_rev(self, prefix: str) -> tuple[dict[str, str], int]:
        with self._mu:
            lo, hi = self._window_locked(prefix)
            return {k: self._d[k] for k in self._keys[lo:hi]}, self._rev

    def watch(self, prefix: str, start_rev: int = 0) -> Watch:
        w = _MemoryWatch(self, prefix, maxlen=self._log_retain)
        with self._mu:
            if start_rev < self._trimmed_below:
                # replay would have a hole: fail at first poll, like etcd's
                # compacted-revision cancel
                w._mark_lost(f"start rev {start_rev} compacted (log "
                             f"trimmed through rev {self._trimmed_below})")
            else:
                w._offer([ev for ev in self._log if ev.rev > start_rev])
            self._watches.append(w)
        return w

    def _unsubscribe(self, w: _MemoryWatch) -> None:
        with self._mu:
            if w in self._watches:
                self._watches.remove(w)

    def _apply(self, ops: list[tuple], guards: list[tuple] | None = None) -> None:
        with self._mu:
            # guards evaluate under the SAME lock hold that applies the ops:
            # no other writer can slip between the compare and the commit
            for _, key, expected in guards or []:
                actual = self._d.get(key)
                if actual != expected:
                    raise _guard_mismatch(key, expected, actual)
            events: list[WatchEvent] = []

            def emit(op: str, key: str, value: str | None) -> None:
                self._rev += 1
                events.append(WatchEvent(self._rev, op, key, value))

            for op in ops:
                if op[0] == "put":
                    if op[1] not in self._d:
                        bisect.insort(self._keys, op[1])
                    self._d[op[1]] = op[2]
                    emit("put", op[1], op[2])
                elif op[0] == "delete":
                    if self._d.pop(op[1], None) is not None:
                        self._keys.pop(bisect.bisect_left(self._keys, op[1]))
                        emit("delete", op[1], None)
                else:
                    lo, hi = self._window_locked(op[1])
                    for k in self._keys[lo:hi]:
                        del self._d[k]
                        emit("delete", k, None)
                    del self._keys[lo:hi]
            for ev in events:
                if len(self._log) >= self._log_retain:
                    self._trimmed_below = self._log.popleft().rev
                self._log.append(ev)
            for w in self._watches:
                w._offer(events)


class _SqliteWatch(Watch):
    """Tail of the ``kv_log`` changelog table by indexed rev. Works across
    PROCESSES: any SqliteKV instance over the same file sees rows the
    moment the writer's transaction commits (this is what makes two real
    daemons over shared sqlite — the HA verification setup — watchable).
    Poll is a bounded-cadence scan; staleness is one poll interval."""

    SCAN_SLEEP_S = 0.02

    def __init__(self, kv: "SqliteKV", prefix: str, start_rev: int) -> None:
        self._kv = kv
        self.prefix = prefix
        self._last_rev = start_rev

    def poll(self, timeout_s: float = 0.0) -> list[WatchEvent]:
        deadline = time.monotonic() + timeout_s
        while True:
            events, scanned = self._kv._read_log_since(
                self._last_rev, self.prefix)
            # advance past non-matching rows too, or every poll re-scans them
            self._last_rev = max(self._last_rev, scanned)
            if events:
                return events
            if time.monotonic() >= deadline:
                return []
            time.sleep(min(self.SCAN_SLEEP_S,
                           max(deadline - time.monotonic(), 0.001)))


class SqliteKV(KV):
    """Durable store on sqlite (WAL). One data table, one changelog table,
    synchronous writes.

    Unlike the reference — which flushes scheduler/version state only on
    graceful Stop (SURVEY.md §3.1) — every ``put`` here commits, so a hard
    crash loses nothing. A busy timeout bounds lock waits: a foreign
    process holding the database (backup tooling, a second daemon by
    mistake) makes ops block up to ``busy_timeout_s`` and then fail,
    instead of raising ``database is locked`` instantly or hanging.

    Every mutation routes through :meth:`_apply`, which appends one
    ``kv_log`` row per changed key INSIDE the same transaction as the data
    write: a committed mutation and its watch event are indivisible (a
    crash can never persist one without the other), and the AUTOINCREMENT
    rev is monotonic across every process sharing the file. The log is
    trimmed to ``log_retain`` rows (watermark in ``kv_meta``); a watcher
    behind the watermark gets a typed WatchLost.
    """

    BUSY_TIMEOUT_S = 5.0
    TRIM_EVERY = 64

    def __init__(self, path: str, busy_timeout_s: float = BUSY_TIMEOUT_S,
                 log_retain: int = WATCH_LOG_RETAIN,
                 trim_every: int = TRIM_EVERY) -> None:
        self._conn = sqlite3.connect(
            path, timeout=busy_timeout_s, check_same_thread=False
        )
        self._busy_timeout_s = busy_timeout_s
        self._mu = threading.Lock()
        self._log_retain = log_retain
        self._trim_every = max(1, trim_every)
        self._applies_since_trim = 0
        with self._mu:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(f"PRAGMA busy_timeout={int(busy_timeout_s * 1000)}")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (k TEXT PRIMARY KEY, v TEXT NOT NULL)"
            )
            # AUTOINCREMENT (not bare rowid): revs must never be reused
            # after a trim, or a watcher could silently resume across a gap
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv_log ("
                "rev INTEGER PRIMARY KEY AUTOINCREMENT, "
                "op TEXT NOT NULL, k TEXT NOT NULL, v TEXT)"
            )
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv_meta (k TEXT PRIMARY KEY, "
                "v TEXT NOT NULL)"
            )
            self._conn.execute(
                "INSERT OR IGNORE INTO kv_meta(k, v) VALUES('trim_rev', '0')"
            )
            self._conn.commit()

    @contextlib.contextmanager
    def _busy_guard(self):
        """Normalize a busy/locked exhaustion (a foreign writer held the
        database past ``busy_timeout_s``) to the typed
        :class:`errors.StoreUnavailable` — the sqlite analog of EtcdKV's
        connection-class normalization, so callers classify store-path
        failures with ONE except clause instead of matching sqlite3
        internals. Other OperationalErrors (corruption, disk I/O) still
        surface raw: they are not an availability condition."""
        try:
            yield
        except sqlite3.OperationalError as e:
            msg = str(e).lower()
            if "locked" in msg or "busy" in msg:
                raise errors.StoreUnavailable(
                    f"sqlite busy past the {self._busy_timeout_s}s bounded "
                    f"wait: {e}") from e
            raise

    def put(self, key: str, value: str) -> None:
        self._apply([("put", key, value)])

    def get(self, key: str) -> str:
        with self._busy_guard(), self._mu:
            row = self._conn.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        if row is None:
            raise errors.NotExistInStore(key)
        return row[0]

    def delete(self, key: str) -> None:
        self._apply([("delete", key)])

    @staticmethod
    def _prefix_where(prefix: str) -> tuple[str, tuple]:
        """One index-friendly range predicate (``k >= prefix AND k <
        end``) selecting exactly the prefix's subtree — the per-key GLOB
        scan this replaces walked the whole table. Falls back to GLOB for
        prefixes whose incremented end is not valid TEXT (raw-0xff keys —
        an etcd-wire artifact sqlite deployments never store)."""
        if not prefix:
            return "1=1", ()
        end = _prefix_end(prefix)
        try:
            end.encode()
        except UnicodeEncodeError:  # pragma: no cover — non-TEXT end
            return "k GLOB ?", (prefix.replace("[", "[[]") + "*",)
        if end == "\0":  # all-0xff prefix: no upper bound
            return "k >= ?", (prefix,)
        return "k >= ? AND k < ?", (prefix, end)

    def range_prefix(self, prefix: str) -> dict[str, str]:
        where, params = self._prefix_where(prefix)
        with self._busy_guard(), self._mu:
            rows = self._conn.execute(
                f"SELECT k, v FROM kv WHERE {where} ORDER BY k", params,
            ).fetchall()
        return dict(rows)

    def keys_prefix(self, prefix: str, limit: int = 0,
                    start_after: str = "") -> list[str]:
        """Keys only — never deserializes a value row (``SELECT k`` rides
        the primary-key index end to end)."""
        where, params = self._prefix_where(prefix)
        if start_after:
            where += " AND k > ?"
            params = params + (start_after,)
        sql = f"SELECT k FROM kv WHERE {where} ORDER BY k"
        if limit > 0:
            sql += " LIMIT ?"
            params = params + (limit,)
        with self._busy_guard(), self._mu:
            rows = self._conn.execute(sql, params).fetchall()
        return [k for (k,) in rows]

    def range_prefix_page(self, prefix: str, limit: int,
                          start_after: str = "",
                          at_rev: int = 0) -> tuple[dict[str, str], int]:
        """One bounded SELECT (``k > ? AND k < ? ORDER BY k LIMIT ?``)
        inside one read transaction with the rev-anchor proof: the page is
        served at ``at_rev`` only if the changelog still covers every
        event past it AND none of those events touched the prefix — the
        same one-WAL-snapshot discipline as ``_read_log_since``."""
        if limit <= 0:
            raise ValueError("range_prefix_page requires limit > 0")
        where, params = self._prefix_where(prefix)
        page_where, page_params = where, params
        if start_after:
            page_where += " AND k > ?"
            page_params = page_params + (start_after,)
        with self._busy_guard(), self._mu:
            try:
                self._conn.execute("BEGIN")
                if at_rev > 0:
                    trim_rev = int(self._conn.execute(
                        "SELECT v FROM kv_meta WHERE k = 'trim_rev'"
                    ).fetchone()[0])
                    if at_rev < trim_rev:
                        raise errors.ContinueExpired(
                            f"page anchored at rev {at_rev}, changelog "
                            f"trimmed through {trim_rev}")
                    touched = self._conn.execute(
                        f"SELECT rev FROM kv_log WHERE rev > ? AND {where} "
                        f"LIMIT 1", (at_rev,) + params).fetchone()
                    if touched is not None:
                        raise errors.ContinueExpired(
                            f"prefix {prefix!r} mutated at rev {touched[0]} "
                            f"past the page anchor {at_rev}")
                rows = self._conn.execute(
                    f"SELECT k, v FROM kv WHERE {page_where} ORDER BY k "
                    f"LIMIT ?", page_params + (limit,)).fetchall()
                rev = at_rev or self._current_rev_locked()
                self._conn.commit()
            except BaseException:
                self._conn.rollback()
                raise
        return dict(rows), rev

    def delete_prefix(self, prefix: str) -> None:
        """One transaction: a single bounded DELETE statement for the data
        rows (a purge of an N-key family is not N round trips) plus the
        per-key changelog expansion, so a crash mid-purge can never leave
        half a family behind — or a family gone but unobservable."""
        self._apply([("delete_prefix", prefix)])

    def current_rev(self) -> int:
        with self._busy_guard(), self._mu:
            return self._current_rev_locked()

    def _current_rev_locked(self) -> int:
        # sqlite_sequence survives log trims; MAX(rev) alone would regress
        # after a full trim of a quiet store
        row = self._conn.execute(
            "SELECT seq FROM sqlite_sequence WHERE name = 'kv_log'"
        ).fetchone()
        return int(row[0]) if row else 0

    def range_prefix_with_rev(self, prefix: str) -> tuple[dict[str, str], int]:
        where, params = self._prefix_where(prefix)
        with self._busy_guard(), self._mu:
            try:
                # explicit txn: the snapshot and its rev are one consistent
                # read even with a foreign process writing concurrently
                self._conn.execute("BEGIN")
                rows = self._conn.execute(
                    f"SELECT k, v FROM kv WHERE {where} ORDER BY k", params,
                ).fetchall()
                rev = self._current_rev_locked()
                self._conn.commit()
            except BaseException:
                self._conn.rollback()
                raise
        return dict(rows), rev

    def watch(self, prefix: str, start_rev: int = 0) -> Watch:
        return _SqliteWatch(self, prefix, start_rev)

    def _read_log_since(self, last_rev: int,
                        prefix: str) -> tuple[list[WatchEvent], int]:
        """(matching events with rev > last_rev, highest rev scanned).
        Raises WatchLost when the trim watermark passed last_rev — the
        changelog no longer proves there is no gap. Watermark and rows are
        read in ONE explicit transaction (one WAL snapshot): two
        autocommit statements would let a FOREIGN process's trim land
        between them, passing the staleness check against the old
        watermark while the row scan already reflects the post-trim log —
        a silent, permanently undetected gap."""
        with self._busy_guard(), self._mu:
            try:
                self._conn.execute("BEGIN")
                trim_rev = int(self._conn.execute(
                    "SELECT v FROM kv_meta WHERE k = 'trim_rev'"
                ).fetchone()[0])
                rows = self._conn.execute(
                    "SELECT rev, op, k, v FROM kv_log WHERE rev > ? "
                    "ORDER BY rev LIMIT 1000", (last_rev,),
                ).fetchall()
                self._conn.commit()
            except BaseException:
                self._conn.rollback()
                raise
        if last_rev < trim_rev:
            raise errors.WatchLost(
                f"changelog compacted to rev {trim_rev}, watcher at "
                f"{last_rev}")
        events = [WatchEvent(int(r), op, k, v) for r, op, k, v in rows
                  if k.startswith(prefix)]
        scanned = int(rows[-1][0]) if rows else last_rev
        return events, scanned

    def _apply(self, ops: list[tuple], guards: list[tuple] | None = None) -> None:
        """All ops — data rows AND their changelog rows — in ONE sqlite
        transaction: a mid-batch failure (or a crash before the commit)
        rolls everything back, so a mutation and its watch event are
        indivisible. Guards SELECT and compare inside that transaction —
        BEGIN IMMEDIATE takes the write lock up front, so even a foreign
        process (second daemon, backup tooling) cannot change a guarded
        key between the compare and the commit."""
        with self._busy_guard(), self._mu:
            try:
                self._conn.execute("BEGIN IMMEDIATE")
                for _, key, expected in guards or []:
                    row = self._conn.execute(
                        "SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
                    actual = None if row is None else row[0]
                    if actual != expected:
                        raise _guard_mismatch(key, expected, actual)
                log_rows: list[tuple[str, str, str | None]] = []
                for op in ops:
                    if op[0] == "put":
                        self._conn.execute(
                            "INSERT INTO kv(k, v) VALUES(?, ?) "
                            "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                            (op[1], op[2]),
                        )
                        log_rows.append(("put", op[1], op[2]))
                    elif op[0] == "delete":
                        cur = self._conn.execute(
                            "DELETE FROM kv WHERE k = ?", (op[1],))
                        if cur.rowcount > 0:
                            log_rows.append(("delete", op[1], None))
                    else:
                        where, params = self._prefix_where(op[1])
                        doomed = self._conn.execute(
                            f"SELECT k FROM kv WHERE {where} ORDER BY k",
                            params).fetchall()
                        self._conn.execute(
                            f"DELETE FROM kv WHERE {where}", params)
                        log_rows.extend(("delete", k, None) for (k,) in doomed)
                self._conn.executemany(
                    "INSERT INTO kv_log(op, k, v) VALUES(?, ?, ?)", log_rows)
                self._applies_since_trim += 1
                if self._applies_since_trim >= self._trim_every:
                    self._applies_since_trim = 0
                    self._trim_log_locked()
                self._conn.commit()
            except BaseException:
                self._conn.rollback()
                raise

    def _trim_log_locked(self) -> None:
        """Bound the changelog (inside the caller's transaction): drop rows
        below ``max_rev - log_retain`` and advance the watermark watchers
        compare against."""
        max_rev = self._current_rev_locked()
        floor = max_rev - self._log_retain
        if floor <= 0:
            return
        self._conn.execute("DELETE FROM kv_log WHERE rev <= ?", (floor,))
        self._conn.execute(
            "UPDATE kv_meta SET v = ? WHERE k = 'trim_rev' "
            "AND CAST(v AS INTEGER) < ?", (str(floor), floor))

    def close(self) -> None:
        with self._mu:
            self._conn.close()


class EtcdKV(KV):
    """etcd v3 over its grpc-gateway JSON API.

    The reference dials etcd gRPC with a 2 s blocking connect and 1 s per-op
    timeout (etcd/client.go:14-23, common.go:31); we keep the same budgets on
    HTTP. Keys/values are base64 on the wire per the gateway contract.

    Store-outage tolerance (docs/robustness.md "Durable work queue"): every
    connection-class failure (refused/reset/timeout) is normalized to
    :class:`errors.StoreUnavailable` — the KV analog of the host layer's
    ``HostUnreachable`` — so callers classify store-path failures with one
    except clause instead of matching ``requests`` internals. Idempotent
    READS (``get``/``range_prefix``) additionally retry up to
    ``retry_attempts`` times with capped exponential backoff before giving
    up; writes are normalized but never retried here (the work queue owns
    write retry policy, and a blind double-put hides real outages).
    """

    DIAL_TIMEOUT_S = 2.0
    OP_TIMEOUT_S = 1.0
    RETRY_ATTEMPTS = 3
    RETRY_BASE_S = 0.05
    RETRY_MAX_S = 1.0

    def __init__(self, addr: str, retry_attempts: int = RETRY_ATTEMPTS,
                 retry_base_s: float = RETRY_BASE_S,
                 retry_max_s: float = RETRY_MAX_S,
                 op_deadline_s: float = 0.0) -> None:
        import requests  # lazy: hermetic paths never import it

        self._requests = requests
        self._addr = addr.rstrip("/")
        self._session = requests.Session()
        self._retry_attempts = max(1, retry_attempts)
        self._retry_base_s = retry_base_s
        self._retry_max_s = retry_max_s
        # per-op deadline (config store_op_deadline_s): the socket timeout
        # every request rides, so a hung store surfaces as a typed
        # StoreUnavailable in bounded time instead of wedging an API
        # thread that holds a family lock. <= 0 keeps the reference's 1 s
        # OP_TIMEOUT_S — the default path byte-for-byte
        self._op_timeout_s = op_deadline_s if op_deadline_s > 0 else self.OP_TIMEOUT_S
        # fail fast if unreachable, like the reference's blocking dial
        # (no retry: a daemon pointed at a dead store must error at boot,
        # not spin through a backoff schedule before reporting it)
        self._post("/v3/kv/range", {"key": _b64("probe"), "limit": 1},
                   timeout=self.DIAL_TIMEOUT_S)

    def _post(self, path: str, body: dict, timeout: float | None = None,
              idempotent: bool = False) -> dict:
        from tpu_docker_api.utils.backoff import backoff_delay_s

        attempts = self._retry_attempts if idempotent else 1
        last: Exception | None = None
        for attempt in range(attempts):
            try:
                r = self._session.post(
                    self._addr + path, json=body,
                    timeout=timeout or self._op_timeout_s,
                )
                r.raise_for_status()
                return r.json()
            except (self._requests.ConnectionError,
                    self._requests.Timeout) as e:
                last = e
                if attempt + 1 < attempts:
                    time.sleep(backoff_delay_s(
                        attempt, self._retry_base_s, self._retry_max_s))
        raise errors.StoreUnavailable(
            f"etcd {self._addr}{path}: {type(last).__name__}: {last}"
        ) from last

    def put(self, key: str, value: str) -> None:
        self._post("/v3/kv/put", {"key": _b64(key), "value": _b64(value)})

    def get(self, key: str) -> str:
        resp = self._post("/v3/kv/range", {"key": _b64(key)}, idempotent=True)
        kvs = resp.get("kvs", [])
        if not kvs:
            raise errors.NotExistInStore(key)
        return _unb64(kvs[0]["value"])

    def delete(self, key: str) -> None:
        self._post("/v3/kv/deleterange", {"key": _b64(key)})

    def range_prefix(self, prefix: str) -> dict[str, str]:
        resp = self._post(
            "/v3/kv/range",
            {"key": _b64(prefix), "range_end": _b64(_prefix_end(prefix))},
            idempotent=True,
        )
        out = {_unb64_key(kv["key"]): _unb64(kv["value"])
               for kv in resp.get("kvs", [])}
        return dict(sorted(out.items()))

    def keys_prefix(self, prefix: str, limit: int = 0,
                    start_after: str = "") -> list[str]:
        """Native ``keys_only`` range: the server never ships a value byte."""
        body = {"key": _b64(max(prefix, start_after + "\0")),
                "range_end": _b64(_prefix_end(prefix)), "keys_only": True}
        if limit > 0:
            body["limit"] = str(limit)
        resp = self._post("/v3/kv/range", body, idempotent=True)
        return sorted(_unb64_key(kv["key"]) for kv in resp.get("kvs", []))

    def range_prefix_page(self, prefix: str, limit: int,
                          start_after: str = "",
                          at_rev: int = 0) -> tuple[dict[str, str], int]:
        """Native MVCC page: ``limit`` + ``key`` (start_after + one NUL =
        the smallest strictly-greater key) + ``revision`` on
        ``/v3/kv/range``, so etcd itself serves every page of a walk at
        the first page's revision. A compacted revision comes back as the
        gateway's 400 ``...required revision has been compacted`` —
        mapped to the typed ContinueExpired, exactly like Kubernetes'
        410 Gone."""
        if limit <= 0:
            raise ValueError("range_prefix_page requires limit > 0")
        body = {"key": _b64(max(prefix, start_after + "\0")),
                "range_end": _b64(_prefix_end(prefix)),
                "limit": str(limit)}
        if at_rev > 0:
            body["revision"] = str(at_rev)
        try:
            resp = self._post("/v3/kv/range", body, idempotent=True)
        except self._requests.HTTPError as e:
            detail = ""
            try:
                detail = e.response.json().get("error", "")
            except Exception:  # noqa: BLE001 — non-JSON error body
                detail = getattr(e.response, "text", "")[:200]
            if "compacted" in detail:
                raise errors.ContinueExpired(
                    f"revision {at_rev} compacted: {detail}") from e
            raise
        out = {_unb64_key(kv["key"]): _unb64(kv["value"])
               for kv in resp.get("kvs", [])}
        return (dict(sorted(out.items())),
                at_rev or int(resp.get("header", {}).get("revision", 0)))

    def current_rev(self) -> int:
        resp = self._post("/v3/kv/range", {"key": _b64("\0"), "limit": 1},
                          idempotent=True)
        return int(resp.get("header", {}).get("revision", 0))

    def range_prefix_with_rev(self, prefix: str) -> tuple[dict[str, str], int]:
        """One range call: the response header's revision IS the snapshot's
        revision (etcd's own list-then-watch handshake)."""
        resp = self._post(
            "/v3/kv/range",
            {"key": _b64(prefix), "range_end": _b64(_prefix_end(prefix))},
            idempotent=True,
        )
        out = {_unb64_key(kv["key"]): _unb64(kv["value"])
               for kv in resp.get("kvs", [])}
        return dict(sorted(out.items())), int(
            resp.get("header", {}).get("revision", 0))

    def watch(self, prefix: str, start_rev: int = 0) -> Watch:
        """Native ``/v3/watch`` stream on the gateway: the server pushes
        events (deletes already expanded per key by etcd itself); a
        compacted start revision comes back as a cancel response carrying
        ``compact_revision``, surfaced as the typed WatchLost."""
        return _EtcdWatch(self, prefix, start_rev)

    def delete_prefix(self, prefix: str) -> None:
        self._post(
            "/v3/kv/deleterange",
            {"key": _b64(prefix), "range_end": _b64(_prefix_end(prefix))},
        )

    def _apply(self, ops: list[tuple], guards: list[tuple] | None = None) -> None:
        """Native etcd transaction (``/v3/kv/txn``): guards map to the txn's
        ``compare`` list — a value guard is a VALUE compare, an absence
        guard (expected None) is ``VERSION == 0``, etcd's "key was never
        put" sentinel — so the compare-and-commit is ONE server-side atomic
        round trip, with no failure branch (a lost compare changes
        nothing). A txn is a WRITE, so it rides the normalize-but-never-
        retry path — a blind re-apply after an ambiguous timeout could
        double-commit a batch whose first attempt landed
        (``idempotent=False`` is load-bearing, not a default)."""
        compare = []
        for _, key, expected in guards or []:
            if expected is None:
                compare.append({"key": _b64(key), "result": "EQUAL",
                                "target": "VERSION", "version": "0"})
            else:
                compare.append({"key": _b64(key), "result": "EQUAL",
                                "target": "VALUE", "value": _b64(expected)})
        success = []
        for op in ops:
            if op[0] == "put":
                success.append({"requestPut": {
                    "key": _b64(op[1]), "value": _b64(op[2])}})
            elif op[0] == "delete":
                success.append({"requestDeleteRange": {"key": _b64(op[1])}})
            else:
                success.append({"requestDeleteRange": {
                    "key": _b64(op[1]),
                    "range_end": _b64(_prefix_end(op[1]))}})
        body: dict = {"success": success}
        if compare:
            body["compare"] = compare
        resp = self._post("/v3/kv/txn", body, idempotent=False)
        # proto3 JSON omits false booleans: an absent ``succeeded`` on a
        # guarded txn IS the failed compare
        if compare and not resp.get("succeeded"):
            raise errors.GuardFailed(
                f"etcd txn compare failed on "
                f"{[g[1] for g in guards or []]}")

    def close(self) -> None:
        self._session.close()


class _EtcdWatch(Watch):
    """One ``/v3/watch`` stream. A dedicated reader thread blocks on the
    chunked HTTP response and feeds a queue; poll drains it — the informer
    loop never blocks on a socket it cannot time-bound. The stream dying
    (connection reset, gateway restart) is a StoreUnavailable at the next
    poll; a cancel/compaction response is a WatchLost. Either way the
    consumer relists."""

    def __init__(self, kv: "EtcdKV", prefix: str, start_rev: int) -> None:
        import json as _json

        self._json = _json
        self._kv = kv
        self.prefix = prefix
        self._cv = threading.Condition()
        self._q: collections.deque[WatchEvent] = collections.deque()
        self._error: Exception | None = None
        self._closed = False
        body = {"create_request": {
            "key": _b64(prefix),
            "range_end": _b64(_prefix_end(prefix)),
            # etcd's start_revision is INCLUSIVE; our contract is
            # "events with rev > start_rev"
            "start_revision": str(start_rev + 1),
        }}
        try:
            self._resp = kv._session.post(
                kv._addr + "/v3/watch", json=body, stream=True,
                timeout=(kv.DIAL_TIMEOUT_S, None))
            self._resp.raise_for_status()
        except (kv._requests.ConnectionError, kv._requests.Timeout,
                kv._requests.HTTPError) as e:
            raise errors.StoreUnavailable(
                f"etcd watch {kv._addr}: {type(e).__name__}: {e}") from e
        self._thread = threading.Thread(
            target=self._read_loop, name="etcd-watch", daemon=True)
        self._thread.start()

    def _read_loop(self) -> None:
        try:
            self._read_stream()
        finally:
            # the reader OWNS the response: closing it from another thread
            # would deadlock on the buffered-reader lock this thread holds
            # while blocked in iter_lines (close() unblocks us by shutting
            # the socket down instead)
            try:
                self._resp.close()
            except Exception:  # noqa: BLE001
                pass

    def _read_stream(self) -> None:
        try:
            for line in self._resp.iter_lines():
                if not line:
                    continue
                result = self._json.loads(line).get("result", {})
                if result.get("compact_revision") or result.get("canceled"):
                    self._fail(errors.WatchLost(
                        f"watch canceled (compacted at "
                        f"{result.get('compact_revision')})"))
                    return
                if result.get("created"):
                    continue
                header_rev = int(result.get("header", {}).get("revision", 0))
                events = []
                for ev in result.get("events", []):
                    kv_ = ev.get("kv", {})
                    # proto3 JSON omits default enum values: no "type" IS PUT
                    is_put = ev.get("type", "PUT") == "PUT"
                    events.append(WatchEvent(
                        int(kv_.get("mod_revision", header_rev)),
                        "put" if is_put else "delete",
                        _unb64_key(kv_["key"]),
                        _unb64(kv_.get("value", "")) if is_put else None))
                if events:
                    with self._cv:
                        self._q.extend(events)
                        self._cv.notify_all()
        except Exception as e:  # noqa: BLE001 — stream death
            if not self._closed:
                self._fail(errors.StoreUnavailable(
                    f"etcd watch stream died: {type(e).__name__}: {e}"))

    def _fail(self, err: Exception) -> None:
        with self._cv:
            self._error = err
            self._cv.notify_all()

    def poll(self, timeout_s: float = 0.0) -> list[WatchEvent]:
        with self._cv:
            if not self._q and self._error is None and not self._closed \
                    and timeout_s > 0:
                self._cv.wait(timeout_s)
            if self._q:
                out = list(self._q)
                self._q.clear()
                return out
            if self._error is not None and not self._closed:
                raise self._error
            return []

    def close(self) -> None:
        self._closed = True
        # shut the SOCKET down rather than closing the response: a close
        # here would contend for the buffered-reader lock the reader
        # thread holds while blocked mid-recv (observed deadlock); a
        # shutdown makes that recv return EOF, the stream iterator end,
        # and the reader close the response itself
        import socket as socket_mod

        raw = getattr(self._resp, "raw", None)
        conn = (getattr(raw, "_connection", None)
                or getattr(raw, "connection", None))
        sock = getattr(conn, "sock", None)
        try:
            if sock is not None:
                sock.shutdown(socket_mod.SHUT_RDWR)
            else:  # pragma: no cover — urllib3 layout drift fallback
                self._resp.close()
        except OSError:
            pass
        except Exception:  # noqa: BLE001 — already dead is fine
            pass
        with self._cv:
            self._cv.notify_all()
        self._thread.join(timeout=5)


class CountingKV(KV):
    """Instrumentation wrapper: counts store round trips per KV method.

    The churn benchmark (bench.py ``--cp-family churn``) wraps the daemon's
    store in one of these to report **round trips per control-plane flow**
    — the regression gate that keeps "batched" an invariant instead of an
    adjective. Each counted unit is one network round trip on etcd: an
    ``apply`` of 40 ops counts once, which is the whole point."""

    def __init__(self, inner: KV) -> None:
        self.inner = inner
        self._mu = threading.Lock()
        self.counts: dict[str, int] = {}

    def _count(self, method: str) -> None:
        with self._mu:
            self.counts[method] = self.counts.get(method, 0) + 1

    def snapshot(self) -> dict[str, int]:
        with self._mu:
            return dict(self.counts)

    @staticmethod
    def delta(before: dict[str, int], after: dict[str, int]) -> dict[str, int]:
        """Per-method round trips between two snapshots (zeroes dropped)."""
        out = {k: after[k] - before.get(k, 0) for k in after}
        return {k: v for k, v in out.items() if v}

    def put(self, key: str, value: str) -> None:
        self._count("put")
        self.inner.put(key, value)

    def get(self, key: str) -> str:
        self._count("get")
        return self.inner.get(key)

    def delete(self, key: str) -> None:
        self._count("delete")
        self.inner.delete(key)

    def range_prefix(self, prefix: str) -> dict[str, str]:
        self._count("range_prefix")
        return self.inner.range_prefix(prefix)

    def range_prefix_with_rev(self, prefix: str) -> tuple[dict[str, str], int]:
        self._count("range_prefix")
        return self.inner.range_prefix_with_rev(prefix)

    def keys_prefix(self, prefix: str, limit: int = 0,
                    start_after: str = "") -> list[str]:
        self._count("keys_prefix")
        return self.inner.keys_prefix(prefix, limit=limit,
                                      start_after=start_after)

    def range_prefix_page(self, prefix: str, limit: int,
                          start_after: str = "",
                          at_rev: int = 0) -> tuple[dict[str, str], int]:
        self._count("range_prefix_page")
        return self.inner.range_prefix_page(prefix, limit,
                                            start_after=start_after,
                                            at_rev=at_rev)

    READ_METHODS = ("get", "range_prefix", "keys_prefix",
                    "range_prefix_page")

    def reads(self) -> int:
        """Total store read round trips so far (the scale family's gated
        quantity; watch streams are amortized and deliberately excluded)."""
        with self._mu:
            return sum(self.counts.get(m, 0) for m in self.READ_METHODS)

    def current_rev(self) -> int:
        return self.inner.current_rev()

    def watch(self, prefix: str, start_rev: int = 0) -> Watch:
        # counted once per stream OPEN — the whole point of watch is that
        # the events themselves are not per-request round trips
        self._count("watch")
        return self.inner.watch(prefix, start_rev)

    def delete_prefix(self, prefix: str) -> None:
        self._count("delete_prefix")
        self.inner.delete_prefix(prefix)

    def _apply(self, ops: list[tuple], guards: list[tuple] | None = None) -> None:
        # delegate to the inner BACKEND's atomic _apply (not its public
        # apply: the base template already validated and fired the crash
        # points once — they must not fire twice per batch)
        self._count("apply")
        self.inner._apply(ops, guards)

    def close(self) -> None:
        self.inner.close()


def _b64(s: str) -> str:
    # surrogateescape: _prefix_end may produce lone surrogates for non-ascii
    # prefix ends; they round-trip to the intended raw bytes on the wire
    # (identical to strict encoding for any valid-unicode input)
    return base64.b64encode(s.encode("utf-8", "surrogateescape")).decode()


def _unb64_key(s: str) -> str:
    """Keys decode leniently: an incremented range-end byte can make a key
    non-UTF-8, and it must round-trip back through ``_b64``."""
    return base64.b64decode(s).decode("utf-8", "surrogateescape")


def _unb64(s: str) -> str:
    """Values decode strictly: this store only ever writes UTF-8 (JSON), so
    a non-UTF-8 value is corruption by a foreign writer and must fail loudly
    at the read site, not surface as lone surrogates downstream."""
    return base64.b64decode(s).decode()


def _prefix_end(prefix: str) -> str:
    """etcd range_end for a prefix scan: prefix with last byte incremented.
    Operates on the key's raw utf-8 bytes (etcd compares bytes); raw bytes
    that aren't valid utf-8 ride in/out as surrogateescape characters."""
    b = bytearray(prefix.encode("utf-8", "surrogateescape"))
    for i in reversed(range(len(b))):
        if b[i] < 0xFF:
            b[i] += 1
            return bytes(b[: i + 1]).decode(errors="surrogateescape")
        b.pop()
    return "\0"  # prefix was all 0xff: scan everything


def open_store(backend: str, *, etcd_addr: str = "", sqlite_path: str = "",
               retry_attempts: int = EtcdKV.RETRY_ATTEMPTS,
               retry_base_s: float = EtcdKV.RETRY_BASE_S,
               retry_max_s: float = EtcdKV.RETRY_MAX_S,
               op_deadline_s: float = 0.0) -> KV:
    """Open a KV backend by name (config.store_backend); ``retry_*`` maps
    from the ``store_retry_*`` config keys (etcd idempotent-read retry).
    ``op_deadline_s`` (config ``store_op_deadline_s``) bounds every op: the
    etcd socket timeout and the sqlite busy wait. <= 0 keeps each backend's
    historical budget (1 s etcd ops, 5 s sqlite busy) byte-for-byte."""
    if backend == "memory":
        return MemoryKV()
    if backend == "sqlite":
        return SqliteKV(sqlite_path,
                        busy_timeout_s=(op_deadline_s if op_deadline_s > 0
                                        else SqliteKV.BUSY_TIMEOUT_S))
    if backend == "etcd":
        return EtcdKV(etcd_addr, retry_attempts=retry_attempts,
                      retry_base_s=retry_base_s, retry_max_s=retry_max_s,
                      op_deadline_s=op_deadline_s)
    raise ValueError(f"unknown store backend {backend!r}")
