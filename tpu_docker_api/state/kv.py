"""Pluggable key-value backends.

Parity: reference ``internal/etcd/{client,common}.go`` — a clientv3 wrapper with
``Put/Get/Del``. Here the surface is an abstract ``KV`` with three backends:

- ``MemoryKV`` — hermetic tests (the seam SURVEY.md §4 calls for),
- ``SqliteKV`` — durable single-host deployments without an etcd cluster,
- ``EtcdKV``  — etcd v3 via its grpc-gateway JSON API (``/v3/kv/*``), keeping
  the reference's deployment shape without a grpc/protobuf dependency.

All backends add ``range_prefix``/``delete_prefix``, which the reference lacks
and which per-version key layout (state/keys.py) needs.
"""

from __future__ import annotations

import abc
import base64
import sqlite3
import threading

from tpu_docker_api import errors


class KV(abc.ABC):
    """Minimal KV surface (reference etcd.Put/Get/Del, common.go:45-73)."""

    @abc.abstractmethod
    def put(self, key: str, value: str) -> None: ...

    @abc.abstractmethod
    def get(self, key: str) -> str:
        """Return the value; raise errors.NotExistInStore if absent."""

    @abc.abstractmethod
    def delete(self, key: str) -> None:
        """Delete the key (no error if absent, matching etcd semantics)."""

    @abc.abstractmethod
    def range_prefix(self, prefix: str) -> dict[str, str]:
        """All key→value pairs whose key starts with ``prefix``, key-sorted."""

    def delete_prefix(self, prefix: str) -> None:
        for k in self.range_prefix(prefix):
            self.delete(k)

    def get_or(self, key: str, default: str | None = None) -> str | None:
        try:
            return self.get(key)
        except errors.NotExistInStore:
            return default

    def close(self) -> None:  # noqa: B027
        pass


class MemoryKV(KV):
    """In-process dict store for hermetic tests."""

    def __init__(self) -> None:
        self._d: dict[str, str] = {}
        self._mu = threading.Lock()

    def put(self, key: str, value: str) -> None:
        with self._mu:
            self._d[key] = value

    def get(self, key: str) -> str:
        with self._mu:
            if key not in self._d:
                raise errors.NotExistInStore(key)
            return self._d[key]

    def delete(self, key: str) -> None:
        with self._mu:
            self._d.pop(key, None)

    def range_prefix(self, prefix: str) -> dict[str, str]:
        with self._mu:
            return {k: v for k, v in sorted(self._d.items()) if k.startswith(prefix)}


class SqliteKV(KV):
    """Durable store on sqlite (WAL). One table, synchronous writes.

    Unlike the reference — which flushes scheduler/version state only on
    graceful Stop (SURVEY.md §3.1) — every ``put`` here commits, so a hard
    crash loses nothing.
    """

    def __init__(self, path: str) -> None:
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._mu = threading.Lock()
        with self._mu:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (k TEXT PRIMARY KEY, v TEXT NOT NULL)"
            )
            self._conn.commit()

    def put(self, key: str, value: str) -> None:
        with self._mu:
            self._conn.execute(
                "INSERT INTO kv(k, v) VALUES(?, ?) "
                "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                (key, value),
            )
            self._conn.commit()

    def get(self, key: str) -> str:
        with self._mu:
            row = self._conn.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        if row is None:
            raise errors.NotExistInStore(key)
        return row[0]

    def delete(self, key: str) -> None:
        with self._mu:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (key,))
            self._conn.commit()

    def range_prefix(self, prefix: str) -> dict[str, str]:
        with self._mu:
            rows = self._conn.execute(
                "SELECT k, v FROM kv WHERE k GLOB ? ORDER BY k",
                (prefix.replace("[", "[[]") + "*",),
            ).fetchall()
        return dict(rows)

    def close(self) -> None:
        with self._mu:
            self._conn.close()


class EtcdKV(KV):
    """etcd v3 over its grpc-gateway JSON API.

    The reference dials etcd gRPC with a 2 s blocking connect and 1 s per-op
    timeout (etcd/client.go:14-23, common.go:31); we keep the same budgets on
    HTTP. Keys/values are base64 on the wire per the gateway contract.
    """

    DIAL_TIMEOUT_S = 2.0
    OP_TIMEOUT_S = 1.0

    def __init__(self, addr: str) -> None:
        import requests  # lazy: hermetic paths never import it

        self._addr = addr.rstrip("/")
        self._session = requests.Session()
        # fail fast if unreachable, like the reference's blocking dial
        self._post("/v3/kv/range", {"key": _b64("probe"), "limit": 1},
                   timeout=self.DIAL_TIMEOUT_S)

    def _post(self, path: str, body: dict, timeout: float | None = None) -> dict:
        r = self._session.post(
            self._addr + path, json=body, timeout=timeout or self.OP_TIMEOUT_S
        )
        r.raise_for_status()
        return r.json()

    def put(self, key: str, value: str) -> None:
        self._post("/v3/kv/put", {"key": _b64(key), "value": _b64(value)})

    def get(self, key: str) -> str:
        resp = self._post("/v3/kv/range", {"key": _b64(key)})
        kvs = resp.get("kvs", [])
        if not kvs:
            raise errors.NotExistInStore(key)
        return _unb64(kvs[0]["value"])

    def delete(self, key: str) -> None:
        self._post("/v3/kv/deleterange", {"key": _b64(key)})

    def range_prefix(self, prefix: str) -> dict[str, str]:
        resp = self._post(
            "/v3/kv/range",
            {"key": _b64(prefix), "range_end": _b64(_prefix_end(prefix))},
        )
        out = {_unb64_key(kv["key"]): _unb64(kv["value"])
               for kv in resp.get("kvs", [])}
        return dict(sorted(out.items()))

    def delete_prefix(self, prefix: str) -> None:
        self._post(
            "/v3/kv/deleterange",
            {"key": _b64(prefix), "range_end": _b64(_prefix_end(prefix))},
        )

    def close(self) -> None:
        self._session.close()


def _b64(s: str) -> str:
    # surrogateescape: _prefix_end may produce lone surrogates for non-ascii
    # prefix ends; they round-trip to the intended raw bytes on the wire
    # (identical to strict encoding for any valid-unicode input)
    return base64.b64encode(s.encode("utf-8", "surrogateescape")).decode()


def _unb64_key(s: str) -> str:
    """Keys decode leniently: an incremented range-end byte can make a key
    non-UTF-8, and it must round-trip back through ``_b64``."""
    return base64.b64decode(s).decode("utf-8", "surrogateescape")


def _unb64(s: str) -> str:
    """Values decode strictly: this store only ever writes UTF-8 (JSON), so
    a non-UTF-8 value is corruption by a foreign writer and must fail loudly
    at the read site, not surface as lone surrogates downstream."""
    return base64.b64decode(s).decode()


def _prefix_end(prefix: str) -> str:
    """etcd range_end for a prefix scan: prefix with last byte incremented.
    Operates on the key's raw utf-8 bytes (etcd compares bytes); raw bytes
    that aren't valid utf-8 ride in/out as surrogateescape characters."""
    b = bytearray(prefix.encode("utf-8", "surrogateescape"))
    for i in reversed(range(len(b))):
        if b[i] < 0xFF:
            b[i] += 1
            return bytes(b[: i + 1]).decode(errors="surrogateescape")
        b.pop()
    return "\0"  # prefix was all 0xff: scan everything


def open_store(backend: str, *, etcd_addr: str = "", sqlite_path: str = "") -> KV:
    """Open a KV backend by name (config.store_backend)."""
    if backend == "memory":
        return MemoryKV()
    if backend == "sqlite":
        return SqliteKV(sqlite_path)
    if backend == "etcd":
        return EtcdKV(etcd_addr)
    raise ValueError(f"unknown store backend {backend!r}")
