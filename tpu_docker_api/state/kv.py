"""Pluggable key-value backends.

Parity: reference ``internal/etcd/{client,common}.go`` — a clientv3 wrapper with
``Put/Get/Del``. Here the surface is an abstract ``KV`` with three backends:

- ``MemoryKV`` — hermetic tests (the seam SURVEY.md §4 calls for),
- ``SqliteKV`` — durable single-host deployments without an etcd cluster,
- ``EtcdKV``  — etcd v3 via its grpc-gateway JSON API (``/v3/kv/*``), keeping
  the reference's deployment shape without a grpc/protobuf dependency.

All backends add ``range_prefix``/``delete_prefix``, which the reference lacks
and which per-version key layout (state/keys.py) needs, and ``apply`` — an
atomic multi-key put/delete batch (the etcd txn / Kubernetes-apiserver write
pattern) so a version transition is ONE store round trip instead of a
sequence of windows a crash can land between.

``apply`` also takes **guards** — compare preconditions evaluated atomically
with the batch (etcd's native txn compares; sqlite/memory check under the
same txn/lock that applies the ops). A failed guard applies NOTHING and
raises the typed :class:`errors.GuardFailed`. This is the primitive the HA
control plane rides: leader-lease CAS (service/leader.py) and epoch fencing
of a deposed leader's writes are both one guarded apply.
"""

from __future__ import annotations

import abc
import base64
import sqlite3
import threading
import time

from tpu_docker_api import errors

#: op kinds KV.apply accepts: ("put", key, value) | ("delete", key) |
#: ("delete_prefix", prefix)
_APPLY_OPS = {"put": 3, "delete": 2, "delete_prefix": 2}


def _check_guards(guards: list[tuple] | None) -> list[tuple]:
    """Validate guard shapes: ``("value", key, expected)`` with expected a
    str (current value must equal it) or None (key must be absent)."""
    guards = list(guards or [])
    for g in guards:
        if (len(g) != 3 or g[0] != "value" or not isinstance(g[1], str)
                or not (g[2] is None or isinstance(g[2], str))):
            raise ValueError(f"malformed guard {g!r}")
    return guards


def _guard_mismatch(key: str, expected: str | None,
                    actual: str | None) -> "errors.GuardFailed":
    def short(v):
        if v is None:
            return "<absent>"
        return v if len(v) <= 64 else v[:61] + "..."

    return errors.GuardFailed(
        f"guard on {key}: expected {short(expected)}, found {short(actual)}")


class KV(abc.ABC):
    """Minimal KV surface (reference etcd.Put/Get/Del, common.go:45-73)."""

    @abc.abstractmethod
    def put(self, key: str, value: str) -> None: ...

    @abc.abstractmethod
    def get(self, key: str) -> str:
        """Return the value; raise errors.NotExistInStore if absent."""

    @abc.abstractmethod
    def delete(self, key: str) -> None:
        """Delete the key (no error if absent, matching etcd semantics)."""

    @abc.abstractmethod
    def range_prefix(self, prefix: str) -> dict[str, str]:
        """All key→value pairs whose key starts with ``prefix``, key-sorted."""

    def delete_prefix(self, prefix: str) -> None:
        for k in self.range_prefix(prefix):
            self.delete(k)

    def apply(self, ops: list[tuple], guards: list[tuple] | None = None) -> None:
        """Atomically apply a batch of ``("put", k, v)`` / ``("delete", k)``
        / ``("delete_prefix", p)`` ops — all land or none do. The two
        ``txn.*`` crash points bracket the commit so the chaos suite can
        prove both halves of the contract: a crash BEFORE the txn leaves
        nothing applied, a crash AFTER leaves everything applied (and the
        reconciler finishes the flow forward).

        ``guards`` are compare preconditions — ``("value", key, expected)``
        where ``expected`` is the exact current value (str) or None for
        "key must be absent" — evaluated atomically WITH the batch: a
        mismatch applies nothing and raises the typed
        :class:`errors.GuardFailed` (the contention loser's signal; never
        blind-retried at this layer). Subclasses override ``_apply`` with a
        genuinely atomic implementation; the base fallback (check, then
        sequential ops) keeps wrapper/test KVs working but is NOT atomic."""
        from tpu_docker_api.service.crashpoints import crash_point

        guards = _check_guards(guards)
        if not ops and not guards:
            return
        for op in ops:
            want = _APPLY_OPS.get(op[0])
            if want is None or len(op) != want:
                raise ValueError(f"malformed apply op {op!r}")
        crash_point("txn.before_apply")
        self._apply(ops, guards)
        crash_point("txn.after_apply")

    def cas(self, key: str, expected: str | None, new: str) -> None:
        """Compare-and-swap convenience: write ``new`` iff the key's current
        value is exactly ``expected`` (None = create-if-absent). Raises
        :class:`errors.GuardFailed` when the compare loses."""
        self.apply([("put", key, new)], guards=[("value", key, expected)])

    def _apply(self, ops: list[tuple], guards: list[tuple] | None = None) -> None:
        for _, key, expected in guards or []:
            actual = self.get_or(key)
            if actual != expected:
                raise _guard_mismatch(key, expected, actual)
        for op in ops:
            if op[0] == "put":
                self.put(op[1], op[2])
            elif op[0] == "delete":
                self.delete(op[1])
            else:
                self.delete_prefix(op[1])

    def get_or(self, key: str, default: str | None = None) -> str | None:
        try:
            return self.get(key)
        except errors.NotExistInStore:
            return default

    def close(self) -> None:  # noqa: B027
        pass


class MemoryKV(KV):
    """In-process dict store for hermetic tests."""

    def __init__(self) -> None:
        self._d: dict[str, str] = {}
        self._mu = threading.Lock()

    def put(self, key: str, value: str) -> None:
        with self._mu:
            self._d[key] = value

    def get(self, key: str) -> str:
        with self._mu:
            if key not in self._d:
                raise errors.NotExistInStore(key)
            return self._d[key]

    def delete(self, key: str) -> None:
        with self._mu:
            self._d.pop(key, None)

    def range_prefix(self, prefix: str) -> dict[str, str]:
        with self._mu:
            return {k: v for k, v in sorted(self._d.items()) if k.startswith(prefix)}

    def delete_prefix(self, prefix: str) -> None:
        # one lock hold, not one delete per key — the purge paths submit a
        # single op and the backend must honor that shape
        with self._mu:
            for k in [k for k in self._d if k.startswith(prefix)]:
                del self._d[k]

    def _apply(self, ops: list[tuple], guards: list[tuple] | None = None) -> None:
        with self._mu:
            # guards evaluate under the SAME lock hold that applies the ops:
            # no other writer can slip between the compare and the commit
            for _, key, expected in guards or []:
                actual = self._d.get(key)
                if actual != expected:
                    raise _guard_mismatch(key, expected, actual)
            for op in ops:
                if op[0] == "put":
                    self._d[op[1]] = op[2]
                elif op[0] == "delete":
                    self._d.pop(op[1], None)
                else:
                    for k in [k for k in self._d if k.startswith(op[1])]:
                        del self._d[k]


class SqliteKV(KV):
    """Durable store on sqlite (WAL). One table, synchronous writes.

    Unlike the reference — which flushes scheduler/version state only on
    graceful Stop (SURVEY.md §3.1) — every ``put`` here commits, so a hard
    crash loses nothing. A busy timeout bounds lock waits: a foreign
    process holding the database (backup tooling, a second daemon by
    mistake) makes ops block up to ``busy_timeout_s`` and then fail,
    instead of raising ``database is locked`` instantly or hanging.
    """

    BUSY_TIMEOUT_S = 5.0

    def __init__(self, path: str, busy_timeout_s: float = BUSY_TIMEOUT_S) -> None:
        self._conn = sqlite3.connect(
            path, timeout=busy_timeout_s, check_same_thread=False
        )
        self._mu = threading.Lock()
        with self._mu:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(f"PRAGMA busy_timeout={int(busy_timeout_s * 1000)}")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (k TEXT PRIMARY KEY, v TEXT NOT NULL)"
            )
            self._conn.commit()

    def put(self, key: str, value: str) -> None:
        with self._mu:
            self._conn.execute(
                "INSERT INTO kv(k, v) VALUES(?, ?) "
                "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                (key, value),
            )
            self._conn.commit()

    def get(self, key: str) -> str:
        with self._mu:
            row = self._conn.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        if row is None:
            raise errors.NotExistInStore(key)
        return row[0]

    def delete(self, key: str) -> None:
        with self._mu:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (key,))
            self._conn.commit()

    @staticmethod
    def _prefix_where(prefix: str) -> tuple[str, tuple]:
        """One index-friendly range predicate (``k >= prefix AND k <
        end``) selecting exactly the prefix's subtree — the per-key GLOB
        scan this replaces walked the whole table. Falls back to GLOB for
        prefixes whose incremented end is not valid TEXT (raw-0xff keys —
        an etcd-wire artifact sqlite deployments never store)."""
        if not prefix:
            return "1=1", ()
        end = _prefix_end(prefix)
        try:
            end.encode()
        except UnicodeEncodeError:  # pragma: no cover — non-TEXT end
            return "k GLOB ?", (prefix.replace("[", "[[]") + "*",)
        if end == "\0":  # all-0xff prefix: no upper bound
            return "k >= ?", (prefix,)
        return "k >= ? AND k < ?", (prefix, end)

    def range_prefix(self, prefix: str) -> dict[str, str]:
        where, params = self._prefix_where(prefix)
        with self._mu:
            rows = self._conn.execute(
                f"SELECT k, v FROM kv WHERE {where} ORDER BY k", params,
            ).fetchall()
        return dict(rows)

    def delete_prefix(self, prefix: str) -> None:
        """One bounded DELETE in one transaction — a purge of an N-key
        family is a single statement, not N round trips, and a crash
        mid-purge can never leave half a family behind."""
        where, params = self._prefix_where(prefix)
        with self._mu:
            try:
                self._conn.execute(f"DELETE FROM kv WHERE {where}", params)
                self._conn.commit()
            except BaseException:
                self._conn.rollback()
                raise

    def _apply(self, ops: list[tuple], guards: list[tuple] | None = None) -> None:
        """All ops in ONE sqlite transaction: a mid-batch failure (or a
        crash before the commit) rolls everything back. Guards SELECT and
        compare inside that transaction — BEGIN IMMEDIATE takes the write
        lock up front, so even a foreign process (second daemon, backup
        tooling) cannot change a guarded key between the compare and the
        commit."""
        with self._mu:
            try:
                if guards:
                    self._conn.execute("BEGIN IMMEDIATE")
                    for _, key, expected in guards:
                        row = self._conn.execute(
                            "SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
                        actual = None if row is None else row[0]
                        if actual != expected:
                            raise _guard_mismatch(key, expected, actual)
                for op in ops:
                    if op[0] == "put":
                        self._conn.execute(
                            "INSERT INTO kv(k, v) VALUES(?, ?) "
                            "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                            (op[1], op[2]),
                        )
                    elif op[0] == "delete":
                        self._conn.execute(
                            "DELETE FROM kv WHERE k = ?", (op[1],))
                    else:
                        where, params = self._prefix_where(op[1])
                        self._conn.execute(
                            f"DELETE FROM kv WHERE {where}", params)
                self._conn.commit()
            except BaseException:
                self._conn.rollback()
                raise

    def close(self) -> None:
        with self._mu:
            self._conn.close()


class EtcdKV(KV):
    """etcd v3 over its grpc-gateway JSON API.

    The reference dials etcd gRPC with a 2 s blocking connect and 1 s per-op
    timeout (etcd/client.go:14-23, common.go:31); we keep the same budgets on
    HTTP. Keys/values are base64 on the wire per the gateway contract.

    Store-outage tolerance (docs/robustness.md "Durable work queue"): every
    connection-class failure (refused/reset/timeout) is normalized to
    :class:`errors.StoreUnavailable` — the KV analog of the host layer's
    ``HostUnreachable`` — so callers classify store-path failures with one
    except clause instead of matching ``requests`` internals. Idempotent
    READS (``get``/``range_prefix``) additionally retry up to
    ``retry_attempts`` times with capped exponential backoff before giving
    up; writes are normalized but never retried here (the work queue owns
    write retry policy, and a blind double-put hides real outages).
    """

    DIAL_TIMEOUT_S = 2.0
    OP_TIMEOUT_S = 1.0
    RETRY_ATTEMPTS = 3
    RETRY_BASE_S = 0.05
    RETRY_MAX_S = 1.0

    def __init__(self, addr: str, retry_attempts: int = RETRY_ATTEMPTS,
                 retry_base_s: float = RETRY_BASE_S,
                 retry_max_s: float = RETRY_MAX_S) -> None:
        import requests  # lazy: hermetic paths never import it

        self._requests = requests
        self._addr = addr.rstrip("/")
        self._session = requests.Session()
        self._retry_attempts = max(1, retry_attempts)
        self._retry_base_s = retry_base_s
        self._retry_max_s = retry_max_s
        # fail fast if unreachable, like the reference's blocking dial
        # (no retry: a daemon pointed at a dead store must error at boot,
        # not spin through a backoff schedule before reporting it)
        self._post("/v3/kv/range", {"key": _b64("probe"), "limit": 1},
                   timeout=self.DIAL_TIMEOUT_S)

    def _post(self, path: str, body: dict, timeout: float | None = None,
              idempotent: bool = False) -> dict:
        from tpu_docker_api.utils.backoff import backoff_delay_s

        attempts = self._retry_attempts if idempotent else 1
        last: Exception | None = None
        for attempt in range(attempts):
            try:
                r = self._session.post(
                    self._addr + path, json=body,
                    timeout=timeout or self.OP_TIMEOUT_S,
                )
                r.raise_for_status()
                return r.json()
            except (self._requests.ConnectionError,
                    self._requests.Timeout) as e:
                last = e
                if attempt + 1 < attempts:
                    time.sleep(backoff_delay_s(
                        attempt, self._retry_base_s, self._retry_max_s))
        raise errors.StoreUnavailable(
            f"etcd {self._addr}{path}: {type(last).__name__}: {last}"
        ) from last

    def put(self, key: str, value: str) -> None:
        self._post("/v3/kv/put", {"key": _b64(key), "value": _b64(value)})

    def get(self, key: str) -> str:
        resp = self._post("/v3/kv/range", {"key": _b64(key)}, idempotent=True)
        kvs = resp.get("kvs", [])
        if not kvs:
            raise errors.NotExistInStore(key)
        return _unb64(kvs[0]["value"])

    def delete(self, key: str) -> None:
        self._post("/v3/kv/deleterange", {"key": _b64(key)})

    def range_prefix(self, prefix: str) -> dict[str, str]:
        resp = self._post(
            "/v3/kv/range",
            {"key": _b64(prefix), "range_end": _b64(_prefix_end(prefix))},
            idempotent=True,
        )
        out = {_unb64_key(kv["key"]): _unb64(kv["value"])
               for kv in resp.get("kvs", [])}
        return dict(sorted(out.items()))

    def delete_prefix(self, prefix: str) -> None:
        self._post(
            "/v3/kv/deleterange",
            {"key": _b64(prefix), "range_end": _b64(_prefix_end(prefix))},
        )

    def _apply(self, ops: list[tuple], guards: list[tuple] | None = None) -> None:
        """Native etcd transaction (``/v3/kv/txn``): guards map to the txn's
        ``compare`` list — a value guard is a VALUE compare, an absence
        guard (expected None) is ``VERSION == 0``, etcd's "key was never
        put" sentinel — so the compare-and-commit is ONE server-side atomic
        round trip, with no failure branch (a lost compare changes
        nothing). A txn is a WRITE, so it rides the normalize-but-never-
        retry path — a blind re-apply after an ambiguous timeout could
        double-commit a batch whose first attempt landed
        (``idempotent=False`` is load-bearing, not a default)."""
        compare = []
        for _, key, expected in guards or []:
            if expected is None:
                compare.append({"key": _b64(key), "result": "EQUAL",
                                "target": "VERSION", "version": "0"})
            else:
                compare.append({"key": _b64(key), "result": "EQUAL",
                                "target": "VALUE", "value": _b64(expected)})
        success = []
        for op in ops:
            if op[0] == "put":
                success.append({"requestPut": {
                    "key": _b64(op[1]), "value": _b64(op[2])}})
            elif op[0] == "delete":
                success.append({"requestDeleteRange": {"key": _b64(op[1])}})
            else:
                success.append({"requestDeleteRange": {
                    "key": _b64(op[1]),
                    "range_end": _b64(_prefix_end(op[1]))}})
        body: dict = {"success": success}
        if compare:
            body["compare"] = compare
        resp = self._post("/v3/kv/txn", body, idempotent=False)
        # proto3 JSON omits false booleans: an absent ``succeeded`` on a
        # guarded txn IS the failed compare
        if compare and not resp.get("succeeded"):
            raise errors.GuardFailed(
                f"etcd txn compare failed on "
                f"{[g[1] for g in guards or []]}")

    def close(self) -> None:
        self._session.close()


class CountingKV(KV):
    """Instrumentation wrapper: counts store round trips per KV method.

    The churn benchmark (bench.py ``--cp-family churn``) wraps the daemon's
    store in one of these to report **round trips per control-plane flow**
    — the regression gate that keeps "batched" an invariant instead of an
    adjective. Each counted unit is one network round trip on etcd: an
    ``apply`` of 40 ops counts once, which is the whole point."""

    def __init__(self, inner: KV) -> None:
        self.inner = inner
        self._mu = threading.Lock()
        self.counts: dict[str, int] = {}

    def _count(self, method: str) -> None:
        with self._mu:
            self.counts[method] = self.counts.get(method, 0) + 1

    def snapshot(self) -> dict[str, int]:
        with self._mu:
            return dict(self.counts)

    @staticmethod
    def delta(before: dict[str, int], after: dict[str, int]) -> dict[str, int]:
        """Per-method round trips between two snapshots (zeroes dropped)."""
        out = {k: after[k] - before.get(k, 0) for k in after}
        return {k: v for k, v in out.items() if v}

    def put(self, key: str, value: str) -> None:
        self._count("put")
        self.inner.put(key, value)

    def get(self, key: str) -> str:
        self._count("get")
        return self.inner.get(key)

    def delete(self, key: str) -> None:
        self._count("delete")
        self.inner.delete(key)

    def range_prefix(self, prefix: str) -> dict[str, str]:
        self._count("range_prefix")
        return self.inner.range_prefix(prefix)

    def delete_prefix(self, prefix: str) -> None:
        self._count("delete_prefix")
        self.inner.delete_prefix(prefix)

    def _apply(self, ops: list[tuple], guards: list[tuple] | None = None) -> None:
        # delegate to the inner BACKEND's atomic _apply (not its public
        # apply: the base template already validated and fired the crash
        # points once — they must not fire twice per batch)
        self._count("apply")
        self.inner._apply(ops, guards)

    def close(self) -> None:
        self.inner.close()


def _b64(s: str) -> str:
    # surrogateescape: _prefix_end may produce lone surrogates for non-ascii
    # prefix ends; they round-trip to the intended raw bytes on the wire
    # (identical to strict encoding for any valid-unicode input)
    return base64.b64encode(s.encode("utf-8", "surrogateescape")).decode()


def _unb64_key(s: str) -> str:
    """Keys decode leniently: an incremented range-end byte can make a key
    non-UTF-8, and it must round-trip back through ``_b64``."""
    return base64.b64decode(s).decode("utf-8", "surrogateescape")


def _unb64(s: str) -> str:
    """Values decode strictly: this store only ever writes UTF-8 (JSON), so
    a non-UTF-8 value is corruption by a foreign writer and must fail loudly
    at the read site, not surface as lone surrogates downstream."""
    return base64.b64decode(s).decode()


def _prefix_end(prefix: str) -> str:
    """etcd range_end for a prefix scan: prefix with last byte incremented.
    Operates on the key's raw utf-8 bytes (etcd compares bytes); raw bytes
    that aren't valid utf-8 ride in/out as surrogateescape characters."""
    b = bytearray(prefix.encode("utf-8", "surrogateescape"))
    for i in reversed(range(len(b))):
        if b[i] < 0xFF:
            b[i] += 1
            return bytes(b[: i + 1]).decode(errors="surrogateescape")
        b.pop()
    return "\0"  # prefix was all 0xff: scan everything


def open_store(backend: str, *, etcd_addr: str = "", sqlite_path: str = "",
               retry_attempts: int = EtcdKV.RETRY_ATTEMPTS,
               retry_base_s: float = EtcdKV.RETRY_BASE_S,
               retry_max_s: float = EtcdKV.RETRY_MAX_S) -> KV:
    """Open a KV backend by name (config.store_backend); ``retry_*`` maps
    from the ``store_retry_*`` config keys (etcd idempotent-read retry)."""
    if backend == "memory":
        return MemoryKV()
    if backend == "sqlite":
        return SqliteKV(sqlite_path)
    if backend == "etcd":
        return EtcdKV(etcd_addr, retry_attempts=retry_attempts,
                      retry_base_s=retry_base_s, retry_max_s=retry_max_s)
    raise ValueError(f"unknown store backend {backend!r}")
