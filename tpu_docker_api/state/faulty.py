"""Deterministic fault injection for the state store: ``FaultyKV``.

The store-side half of the chaos tier (docs/robustness.md "Store
brownouts"), mirroring :class:`~tpu_docker_api.runtime.faulty.FaultyRuntime`
exactly: where crash points kill the control plane and FaultyRuntime makes
the *engine* misbehave, FaultyKV makes the *store* misbehave — on a
schedule, so every brownout a test provokes is reproducible. It replaces
the ad-hoc ``_OutageKV`` helpers that used to be copy-pasted across test
files, and is the substrate ``bench-brownout`` churns against.

Fault surface:

- **Scripted rules** — the same :class:`FaultRule`/:class:`FaultPlan`
  machinery as the runtime side (re-exported here), targeting KV op names
  (``"get"``, ``"apply"``, ``"range_prefix_with_rev"``, ...) with the same
  four modes: ``fail`` (raise before the op), ``ambiguous`` (the op LANDS,
  then an error is returned — the classic timeout-after-commit), ``latency``
  (sleep, then run) and ``unreachable``. KV-side rules raise the typed
  :class:`errors.StoreUnavailable` so production code classifies injected
  faults exactly like real ones.
- **Hard outage** — :meth:`set_outage` flips a persistent every-op-fails
  switch (the store process died / the network to it is gone), including
  the watch stream: an open watch's ``poll`` raises ``StoreUnavailable``
  so the informer degrades loudly and relists on heal.
- **Per-prefix partition** — :meth:`set_partition` fails only ops touching
  keys under a prefix (one keyspace shard behind a broken route), the
  generalization of the old workqueue ``_OutageKV``'s journal-only gate.
- **Latency window** — :meth:`set_latency` sleeps every op by a fixed
  amount (a slow, not dead, store — the brownout half of the bench).

``calls`` journals ``(op, key, outcome)`` with outcome ∈ {"ok", "fail",
"ambiguous", "latency", "unreachable"} under one lock, like
FaultyRuntime's; probabilistic rules draw from ``random.Random(plan.seed)``
so a plan replays identically.
"""

from __future__ import annotations

import threading
import time

from tpu_docker_api import errors
from tpu_docker_api.runtime.faulty import (  # noqa: F401 — re-exported: the
    FaultPlan,  # KV chaos surface is one vocabulary with the runtime side
    FaultRule,
)
from tpu_docker_api.state.kv import KV, Watch, WatchEvent  # noqa: F401


def _store_error(op: str) -> Exception:
    return errors.StoreUnavailable(f"injected outage on {op}")


class _FaultyWatch(Watch):
    """Watch wrapper: while the outage/partition covers the watched
    prefix, ``poll`` raises ``StoreUnavailable`` — a dead store cannot
    stream events, and an informer that kept draining a live watch through
    an "outage" would never degrade, making the chaos vacuous."""

    def __init__(self, kv: "FaultyKV", inner: Watch, prefix: str) -> None:
        self._kv = kv
        self._inner = inner
        self._prefix = prefix

    def poll(self, timeout_s: float) -> list[WatchEvent]:
        self._kv._check_reachable("watch.poll", self._prefix)
        return self._inner.poll(timeout_s)

    def close(self) -> None:
        self._inner.close()


class FaultyKV(KV):
    """Delegates every op to ``inner``, consulting the fault state first.

    Thread safety mirrors FaultyRuntime: the (count, rule, journal entry)
    triple is taken under one lock; the inner op — and a latency sleep —
    runs outside it so concurrency stays real.
    """

    def __init__(self, inner: KV, plan: FaultPlan | None = None) -> None:
        self.inner = inner
        self.plan = plan or FaultPlan()
        self.calls: list[tuple[str, str, str]] = []
        self._mu = threading.Lock()
        self._counts: dict[str, int] = {}
        self._outage = False
        self._partitions: set[str] = set()
        self._latency_s = 0.0

    # -- fault control surface ---------------------------------------------------

    def set_outage(self, down: bool = True) -> None:
        """Hard outage: every op — reads, writes, watch polls — raises
        ``StoreUnavailable`` until cleared. The store process died."""
        self._outage = down

    def set_partition(self, prefix: str, active: bool = True) -> None:
        """Partition one keyspace subtree: ops touching a key (or a range
        overlapping) under ``prefix`` fail; everything else is healthy."""
        if active:
            self._partitions.add(prefix)
        else:
            self._partitions.discard(prefix)

    def set_latency(self, seconds: float) -> None:
        """Slow-store window: every op sleeps ``seconds`` first (0 = off).
        The brownout's first act — latency, not death."""
        self._latency_s = max(0.0, seconds)

    def fail_nth(self, op: str, n: int, mode: str = "fail",
                 times: int = 1) -> None:
        """Script call numbers ``n .. n+times-1`` of ``op`` to fail with the
        typed ``StoreUnavailable`` (``mode="ambiguous"`` lands the op
        first) — the flake-N-then-heal shape the informer recovery tests
        drive."""
        self.plan.rules.append(FaultRule(
            op=op, on_calls=frozenset(range(n, n + times)), mode=mode,
            times=times, error=_store_error))

    def add_rules(self, rules) -> None:
        self.plan.rules.extend(rules)

    def clear_rules(self) -> None:
        self.plan.rules.clear()

    def op_count(self, op: str) -> int:
        return self._counts.get(op, 0)

    # -- interception ------------------------------------------------------------

    def _partitioned(self, key: str) -> bool:
        # single keys match by prefix; range ops pass their prefix as the
        # key, so overlap in EITHER direction hits the partition (a scan
        # of /apis/v1/ must fail when /apis/v1/queue/ is unroutable — the
        # result would silently exclude the partitioned subtree)
        return any(key.startswith(p) or p.startswith(key)
                   for p in self._partitions)

    def _check_reachable(self, op: str, key: str) -> None:
        if self._outage:
            with self._mu:
                self.calls.append((op, key, "unreachable"))
            raise errors.StoreUnavailable(
                f"injected store outage: connection refused on {op}")
        if self._partitions and self._partitioned(key):
            with self._mu:
                self.calls.append((op, key, "unreachable"))
            raise errors.StoreUnavailable(
                f"injected partition: {key!r} unroutable on {op}")

    def _invoke(self, op: str, key: str, fn):
        self._check_reachable(op, key)
        with self._mu:
            self._counts[op] = self._counts.get(op, 0) + 1
            rule = self.plan.decide(op, self._counts[op])
            if rule is None or rule.mode == "latency":
                self.calls.append((op, key, "ok" if rule is None else "latency"))
            elif rule.mode == "fail":
                self.calls.append((op, key, "fail"))
                raise rule.error(op)
            elif rule.mode == "unreachable":
                self.calls.append((op, key, "unreachable"))
                raise errors.StoreUnavailable(
                    f"injected store outage: connection refused on {op}")
        if self._latency_s > 0:
            time.sleep(self._latency_s)
        if rule is None:
            return fn()
        if rule.mode == "latency":
            time.sleep(rule.latency_s)
            return fn()
        # ambiguous: the op takes effect AND the caller sees an error —
        # journaled only once the effect actually landed
        result = fn()
        del result
        with self._mu:
            self.calls.append((op, key, "ambiguous"))
        raise rule.error(op)

    # -- the KV surface ----------------------------------------------------------

    def put(self, key: str, value: str) -> None:
        return self._invoke("put", key, lambda: self.inner.put(key, value))

    def get(self, key: str) -> str:
        return self._invoke("get", key, lambda: self.inner.get(key))

    def delete(self, key: str) -> None:
        return self._invoke("delete", key, lambda: self.inner.delete(key))

    def range_prefix(self, prefix: str) -> dict[str, str]:
        return self._invoke("range_prefix", prefix,
                            lambda: self.inner.range_prefix(prefix))

    def keys_prefix(self, prefix: str, limit: int = 0,
                    start_after: str = "") -> list[str]:
        return self._invoke(
            "keys_prefix", prefix,
            lambda: self.inner.keys_prefix(prefix, limit=limit,
                                           start_after=start_after))

    def range_prefix_page(self, prefix: str, limit: int,
                          start_after: str = "",
                          at_rev: int = 0) -> tuple[dict[str, str], int]:
        return self._invoke(
            "range_prefix_page", prefix,
            lambda: self.inner.range_prefix_page(prefix, limit,
                                                 start_after=start_after,
                                                 at_rev=at_rev))

    def range_prefix_with_rev(self, prefix: str) -> tuple[dict[str, str], int]:
        return self._invoke(
            "range_prefix_with_rev", prefix,
            lambda: self.inner.range_prefix_with_rev(prefix))

    def delete_prefix(self, prefix: str) -> None:
        return self._invoke("delete_prefix", prefix,
                            lambda: self.inner.delete_prefix(prefix))

    def current_rev(self) -> int:
        return self._invoke("current_rev", "*",
                            lambda: self.inner.current_rev())

    def _apply(self, ops: list[tuple], guards: list[tuple] | None = None) -> None:
        # the base template (our public ``apply``) already validated and
        # fired the txn crash points — delegate to the inner backend's
        # atomic ``_apply`` so they never fire twice per batch. The first
        # op's key names the batch in the journal/partition check (every
        # production batch touches one family subtree).
        key = ops[0][1] if ops else (guards[0][1] if guards else "*")
        return self._invoke("apply", key,
                            lambda: self.inner._apply(ops, guards))

    def watch(self, prefix: str, start_rev: int = 0) -> Watch:
        self._check_reachable("watch", prefix)
        return _FaultyWatch(self, self.inner.watch(prefix, start_rev), prefix)

    def close(self) -> None:
        self.inner.close()

    def __getattr__(self, name: str):
        # backend-specific helpers pass through un-faulted — they model
        # the test harness reaching around the fault, not store traffic
        return getattr(self.inner, name)
