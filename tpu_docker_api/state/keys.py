"""State-store key layout.

Parity + fix: the reference keeps ONE key per resource family —
``/apis/v1/<resource>/<basename>`` with the ``-version`` suffix stripped
(etcd/common.go:75-81) — so each new version overwrites the last and the
documented rollback (README.md:142-144) is impossible. Here every version gets
its own key plus a ``latest`` pointer:

    /apis/v1/containers/<base>/v/<NNNNNNNNNN>   (zero-padded ⇒ key-sorted)
    /apis/v1/containers/<base>/latest            → version number
    /apis/v1/volumes/<base>/v/<NNNNNNNNNN>
    /apis/v1/volumes/<base>/latest

Scheduler / version-map state lives under the same tree as in the reference
(``gpus/gpuStatusMapKey`` → ``/apis/v1/scheduler/*``, ``versions/*`` →
``/apis/v1/versions/*``).
"""

from __future__ import annotations

import enum
import re

PREFIX = "/apis/v1"

#: the one source of the base-name rule: names become KV key segments and
#: container names, so no '-' (version separator), no '/' (key nesting)
BASE_NAME_RE = re.compile(r"^[a-zA-Z0-9_.]+$")


class Resource(str, enum.Enum):
    """Resource kinds (reference etcd/common.go:24-29 enums, plus the
    distributed-job and replicated-service kinds the TPU control plane
    adds)."""
    CONTAINERS = "containers"
    VOLUMES = "volumes"
    JOBS = "jobs"
    SERVICES = "services"
    WORKFLOWS = "workflows"


def split_versioned_name(name: str) -> tuple[str, int | None]:
    """``"train-3"`` → ("train", 3); ``"train"`` → ("train", None).

    The reference requires versioned names on every op but create
    (api/container.go:102-106); base names must not contain '-'
    (api/container.go:66-70) so the split is unambiguous.
    """
    base, sep, tail = name.rpartition("-")
    if sep and tail.isdigit():
        return base, int(tail)
    return name, None


def versioned_name(base: str, version: int) -> str:
    return f"{base}-{version}"


def job_owner_base(owner: str) -> str:
    """Map a job scheduler-owner back to its family base. Job claims are
    keyed by VERSIONED name, optionally with a multislice suffix
    ("train-1", "train-1#s0") — version maps key by base, so ownership
    checks must strip both before judging. Non-job owners pass through."""
    stem = owner.split("#", 1)[0]
    base, version = split_versioned_name(stem)
    return base if version is not None else owner


def family_prefix(resource: Resource, base: str) -> str:
    return f"{PREFIX}/{resource.value}/{base}/"


def version_key(resource: Resource, base: str, version: int) -> str:
    return f"{PREFIX}/{resource.value}/{base}/v/{version:010d}"


def latest_key(resource: Resource, base: str) -> str:
    return f"{PREFIX}/{resource.value}/{base}/latest"


def family_key(resource: Resource, name: str) -> str:
    """Key for a possibly-versioned name's family latest pointer."""
    base, _ = split_versioned_name(name)
    return latest_key(resource, base)


# cross-cutting singletons
SCHEDULER_CHIPS_KEY = f"{PREFIX}/scheduler/chips"
SCHEDULER_PORTS_KEY = f"{PREFIX}/scheduler/ports"
SCHEDULER_SLICES_KEY = f"{PREFIX}/scheduler/slices"
VERSIONS_CONTAINER_KEY = f"{PREFIX}/versions/containers"
VERSIONS_VOLUME_KEY = f"{PREFIX}/versions/volumes"
VERSIONS_JOB_KEY = f"{PREFIX}/versions/jobs"
VERSIONS_SERVICE_KEY = f"{PREFIX}/versions/services"
VERSIONS_WORKFLOW_KEY = f"{PREFIX}/versions/workflows"


# -- leader election (service/leader.py) ---------------------------------------
#: the TTL lease record: JSON {holderId, epoch, deadline, ttlS, advertise}.
#: Written ONLY via CAS on its previous exact value (create-if-absent on an
#: empty store), renewed by the holder's heartbeat, stolen after expiry.
LEADER_LEASE_KEY = f"{PREFIX}/leader/lease"
#: the fencing token: the epoch number alone, bumped atomically with every
#: leadership change and NEVER deleted (a graceful release drops the lease
#: but keeps the epoch, so epochs are monotonic across the store's whole
#: life). Every write a leader issues is guarded on this key still holding
#: the epoch it acquired — a deposed leader's in-flight write loses the
#: compare instead of corrupting state the new leader owns.
LEADER_EPOCH_KEY = f"{PREFIX}/leader/epoch"


# -- sharded writer plane (service/shard.py) -----------------------------------
#: shard 0 maps to the LEGACY singleton keys above, so a ``shard_count=1``
#: deployment is byte-for-byte identical to the unsharded layout (and a
#: later ``shard_count`` bump adopts the existing store as shard 0's
#: keyspace without migration). Shards i>0 get their own lease/epoch pair
#: under ``/leader/shards/<i>/`` with the exact same CAS + fencing
#: semantics — one epoch per shard, never deleted, monotonic forever.


def shard_lease_key(shard: int) -> str:
    if shard == 0:
        return LEADER_LEASE_KEY
    return f"{PREFIX}/leader/shards/{shard}/lease"


def shard_epoch_key(shard: int) -> str:
    if shard == 0:
        return LEADER_EPOCH_KEY
    return f"{PREFIX}/leader/shards/{shard}/epoch"


#: cross-shard coordination record: JSON ``{"seq": N}``, CAS-bumped by any
#: transaction whose invariants span shards (pod capacity, cross-shard
#: admission precedence, service fleets whose replicas hash apart). Two
#: shard leaders racing on a cross-shard invariant serialize here — the
#: CAS loser gets a typed GuardFailed and re-reads, exactly the lease
#: protocol's shape applied to data instead of leadership.
SHARD_COORD_KEY = f"{PREFIX}/leader/coord"


#: operator cordon set (service/host_health.py + scheduler/pod.py): JSON
#: list of host ids that must receive no new placements; persisted so a
#: cordon survives daemon restarts (uncordon is the only way out)
HOSTS_CORDONED_KEY = f"{PREFIX}/scheduler/hosts/cordoned"


# -- durable work-queue journal (state/workqueue.py) ---------------------------
#: every async task is journaled here as a declarative record (kind + JSON
#: params) keyed by a zero-padded submit sequence, so replay after a crash
#: preserves submit order. Lifecycle rides the record's ``state`` field
#: (pending → inflight → dead); successful tasks delete their key (done).
QUEUE_PREFIX = f"{PREFIX}/queue"
QUEUE_TASKS_PREFIX = f"{PREFIX}/queue/tasks/"
#: per-task side-effect markers (e.g. copy-complete): written BEFORE the
#: follow-up action so a replayed task can prove its non-idempotent step
#: already ran and must not re-apply; deleted together with the record
QUEUE_MARKERS_PREFIX = f"{PREFIX}/queue/markers/"


# -- durable admission queue (service/admission.py) ----------------------------
#: capacity-market admission records: one JSON record per job waiting for
#: capacity (state "queued") or parked after a preemption (state
#: "preempted"), keyed by a zero-padded submit sequence so a prefix scan
#: yields submit order. Written atomically WITH the job's ``JobState``
#: phase flip (one KV.apply), so queued/preempted intent and the admission
#: record can never disagree; deleted when the job places (or is stopped/
#: deleted), so queued intent survives restarts and leader failover
ADMISSION_PREFIX = f"{PREFIX}/admission/"


def admission_prefix(shard: int = 0) -> str:
    """Shard 0 owns the legacy flat prefix; shards i>0 nest under an
    ``s<i>/`` segment, so each shard leader scans (and replays) only its
    own records and a one-shard deployment keeps today's exact keys."""
    if shard == 0:
        return ADMISSION_PREFIX
    return f"{ADMISSION_PREFIX}s{shard}/"


def admission_record_key(seq: int, shard: int = 0) -> str:
    return f"{admission_prefix(shard)}{seq:012d}"


def queue_tasks_prefix(shard: int = 0) -> str:
    if shard == 0:
        return QUEUE_TASKS_PREFIX
    return f"{QUEUE_TASKS_PREFIX}s{shard}/"


def queue_task_key(seq: int, shard: int = 0) -> str:
    return f"{queue_tasks_prefix(shard)}{seq:012d}"


def queue_markers_prefix(shard: int = 0) -> str:
    if shard == 0:
        return QUEUE_MARKERS_PREFIX
    return f"{QUEUE_MARKERS_PREFIX}s{shard}/"


def queue_marker_key(task_id: str, shard: int = 0) -> str:
    return f"{queue_markers_prefix(shard)}{task_id}"


# -- serving-gateway drain handshake (service/gateway.py) ----------------------
#: live gateway-instance registry: each stateless gateway heartbeats a
#: JSON record {"id", "ts", "advertise"} under its own key. A record is
#: LIVE while its ts is within 3x the heartbeat interval — a killed
#: gateway simply stops renewing, and the control plane's drain wait
#: ignores stale entries (bounded by the drain deadline either way)
GATEWAY_INSTANCES_PREFIX = f"{PREFIX}/gateway/instances/"
#: per-family drain acks: a gateway that has (a) observed the family's
#: durable ``draining`` marker in its routing table and (b) finished every
#: in-flight request it was proxying to that family writes
#: ``{prefix}{family}/{gateway_id}``. The control plane's quiesce waits
#: until every live instance acked (or the deadline passes), then deletes
#: the family's ack prefix — zero live gateways ⇒ vacuously drained
GATEWAY_ACKS_PREFIX = f"{PREFIX}/gateway/acks/"


def gateway_instance_key(gateway_id: str) -> str:
    return f"{GATEWAY_INSTANCES_PREFIX}{gateway_id}"


def gateway_acks_prefix(base: str) -> str:
    """Every ack for one replica family, prefix-scannable and
    prefix-deletable as a unit."""
    return f"{GATEWAY_ACKS_PREFIX}{base}/"


def gateway_ack_key(base: str, gateway_id: str) -> str:
    return f"{gateway_acks_prefix(base)}{gateway_id}"


def versions_shard_key(resource: Resource, shard: int) -> str:
    """Per-shard version-map snapshot key. Shard 0 keeps the legacy
    singleton key so the existing store needs no migration."""
    if shard == 0:
        return f"{PREFIX}/versions/{resource.value}"
    return f"{PREFIX}/versions/shards/{shard}/{resource.value}"


def shard_root(base: str) -> str:
    """The shard-assignment unit for a family base name: its first
    dot-segment. Replicated-service replica gangs are named
    ``<service>.r<i>`` (service/serving.py), so hashing the root keeps a
    service and every one of its replicas on ONE shard — the autoscaler
    and fleet sweeps never straddle a shard boundary for a single fleet."""
    return base.split(".", 1)[0]


def host_chips_key(host_id: str) -> str:
    """Per-host chip-scheduler state for multi-host pods (each host's
    ChipScheduler persists independently)."""
    return f"{PREFIX}/scheduler/chips/{host_id}"


def host_ports_key(host_id: str) -> str:
    return f"{PREFIX}/scheduler/ports/{host_id}"
