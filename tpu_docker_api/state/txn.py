"""Batched store transactions over ``KV.apply``.

Schedulers and version maps persist their state as one full-snapshot JSON
key each, synchronously under their own lock — correct, but a control-plane
flow that touches several of them (a gang create claims N host chip maps,
M host port maps and the pod slice registry) pays one store round trip per
mutation. :class:`StoreTxn` collapses that: participants defer their
persist into the txn, and ``commit()`` writes every enlisted snapshot in
ONE atomic ``KV.apply``.

Correctness of the deferred snapshot: each participant's ops are built at
COMMIT time, under that participant's own lock, and the locks are held
ACROSS the apply. Any concurrent mutation of a participant either happens
before our snapshot (and is included — full-snapshot keys make a superset
write harmless) or blocks until our write is durable (and its own persist
then lands after, carrying both states). Without the lock-across-apply a
stale snapshot could overwrite a neighbour's committed mutation.

Deadlock safety: commit acquires participant locks in (rank, key) order.
Ranks encode the nesting the live code paths already use — the pod
scheduler takes its own lock and then a host chip lock (``apply_slice``),
so POD < HOST keeps commit compatible with that ordering; no code path
nests the other way. Non-batched mutators hold a single lock only, so they
can never complete a cycle.
"""

from __future__ import annotations

import threading
from typing import Callable

from tpu_docker_api.state.kv import KV
from tpu_docker_api.telemetry import trace

#: lock-acquisition ranks (see module docstring): outer locks first
RANK_POD = 0      # PodScheduler (nests into host chip locks in apply_slice)
RANK_HOST = 1     # ChipScheduler / PortScheduler (leaf locks)
RANK_VERSIONS = 2  # VersionMap (never nests with scheduler locks)


class StoreTxn:
    """Collects deferred persists + explicit ops; commits once atomically.

    A txn is flow-local (single-threaded) and single-shot: mutate
    participants with ``txn=self``, then ``commit()`` exactly once. A txn
    that is never committed persists nothing — in-memory state dies with
    the failed flow (or the process), which is exactly the pre-txn crash
    contract the chaos suite pins.
    """

    def __init__(self, kv: KV) -> None:
        self._kv = kv
        #: store_key → (rank, lock, ops_fn); deduped by key so a gang that
        #: claims twice from one host still writes that host's map once
        self._parts: dict[str, tuple[int, threading.Lock,
                                     Callable[[], list[tuple]]]] = {}
        self._ops: list[tuple] = []
        self._committed = False

    def enlist(self, rank: int, store_key: str, lock: threading.Lock,
               ops_fn: Callable[[], list[tuple]]) -> None:
        """Register a participant: ``ops_fn`` is called at commit time,
        under ``lock``, and must return the ops persisting the
        participant's CURRENT state."""
        self._parts[store_key] = (rank, lock, ops_fn)

    def add_op(self, op: tuple) -> None:
        """Append an explicit op (e.g. a spec put) to the batch."""
        self._ops.append(op)

    @property
    def pending(self) -> bool:
        return bool(self._parts or self._ops)

    def commit(self) -> None:
        """One atomic ``KV.apply`` of every participant snapshot plus the
        explicit ops. Raises whatever the store raises — the caller's
        unwind path then restores in-memory state (nothing was persisted)."""
        if self._committed:
            raise RuntimeError("StoreTxn.commit called twice")
        self._committed = True
        parts = sorted(self._parts.items(),
                       key=lambda kv_: (kv_[1][0], kv_[0]))
        held: list[threading.Lock] = []
        with trace.child("store.txn", participants=len(parts)) as span:
            try:
                for _, (_, lock, _) in parts:
                    lock.acquire()
                    held.append(lock)
                ops: list[tuple] = []
                for _, (_, _, ops_fn) in parts:
                    ops.extend(ops_fn())
                ops.extend(self._ops)
                if span is not None:
                    span.attrs["ops"] = len(ops)
                if ops:
                    self._kv.apply(ops)
            finally:
                for lock in reversed(held):
                    lock.release()
