"""Rev-anchored pagination for the list endpoints.

A list at O(100k) objects must not haul the whole keyspace per request:
every list endpoint takes ``limit`` + an opaque ``continue`` token and
walks the store in bounded pages through ``KV.range_prefix_page``
(state/kv.py). The token pins the walk to the FIRST page's store
revision, so the page sequence is one consistent snapshot — a concurrent
insert/delete under the prefix makes the next page fail with the typed
:class:`errors.ContinueExpired` (HTTP 410, the Kubernetes list contract)
instead of silently duplicating or skipping keys.

Family listing folds the raw key page into one entry per resource family
(the ``.../<base>/latest`` pointer row): the pointer's VALUE is the
latest version number, so a page of families costs zero extra reads and
zero spec deserialization. ``limit`` therefore bounds RAW KEYS SCANNED
(pointer rows + their version records interleave under one prefix); with
the retention compactor bounding history (service/compactor.py), a page
yields at least ``limit / (retention + 1)`` families.
"""

from __future__ import annotations

import base64
import binascii
import json

from tpu_docker_api import errors
from tpu_docker_api.state import keys
from tpu_docker_api.state.keys import Resource
from tpu_docker_api.state.kv import KV


def encode_token(resource: Resource, rev: int, last: str) -> str:
    """Opaque continue token: the anchor revision + the last RAW key the
    previous page consumed (resource included so a token cannot be
    replayed against a different endpoint)."""
    raw = json.dumps({"res": resource.value, "rev": rev, "last": last},
                     sort_keys=True)
    return base64.urlsafe_b64encode(raw.encode()).decode().rstrip("=")


def decode_token(token: str, resource: Resource) -> tuple[int, str]:
    """(anchor rev, last raw key). Garbage ⇒ BadRequest (the client
    corrupted it); a well-formed token for another resource ⇒ BadRequest
    too — neither is the 410 retry-from-scratch signal."""
    try:
        pad = "=" * (-len(token) % 4)
        d = json.loads(base64.urlsafe_b64decode(token + pad))
        rev, last, res = int(d["rev"]), str(d["last"]), str(d["res"])
    except (ValueError, KeyError, TypeError, binascii.Error) as e:
        raise errors.BadRequest(f"malformed continue token: "
                                f"{type(e).__name__}") from None
    if res != resource.value:
        raise errors.BadRequest(
            f"continue token is for {res!r}, not {resource.value!r}")
    if rev <= 0:
        raise errors.BadRequest("malformed continue token: bad rev")
    return rev, last


def _fold_families(resource: Resource, page: dict[str, str]) -> list[dict]:
    """One entry per ``/latest`` pointer row in the raw page; version
    records ride along unparsed (their values are never JSON-decoded)."""
    prefix = f"{keys.PREFIX}/{resource.value}/"
    items = []
    for k, v in page.items():
        rest = k[len(prefix):].split("/")
        if len(rest) == 2 and rest[1] == "latest":
            try:
                items.append({"name": rest[0], "version": int(v)})
            except ValueError:  # foreign junk under the prefix: skip, not 500
                continue
    return items


def list_families(kv: KV, resource: Resource, limit: int = 0,
                  token: str = "") -> dict:
    """One list page: ``{"items": [{name, version}], "continue": str|None,
    "rev": int}``. ``limit <= 0`` without a token is the legacy full scan
    (one consistent ``range_prefix_with_rev`` snapshot, no token)."""
    prefix = f"{keys.PREFIX}/{resource.value}/"
    if limit <= 0 and not token:
        snap, rev = kv.range_prefix_with_rev(prefix)
        return {"items": _fold_families(resource, snap),
                "continue": None, "rev": rev}
    if limit <= 0:
        raise errors.BadRequest("continue requires a positive limit")
    at_rev, last = decode_token(token, resource) if token else (0, "")
    page, rev = kv.range_prefix_page(prefix, limit, start_after=last,
                                     at_rev=at_rev)
    nxt = None
    if len(page) == limit:
        nxt = encode_token(resource, rev, max(page))
    return {"items": _fold_families(resource, page),
            "continue": nxt, "rev": rev}
