"""Typed state store over the KV backends.

The service layer's one stop for persisted specs. Wraps `state.kv.KV` with the
per-version key layout from `state.keys`, giving the rollback-capable history
the reference advertises but cannot deliver (SURVEY.md appendix, etcd quirk).
"""

from __future__ import annotations

import json

from tpu_docker_api import errors
from tpu_docker_api.schemas.state import ContainerState, VolumeState
from tpu_docker_api.state import keys
from tpu_docker_api.state.keys import Resource
from tpu_docker_api.state.kv import KV
from tpu_docker_api.telemetry import trace


class StateStore:
    def __init__(self, kv: KV) -> None:
        self.kv = kv

    # -- generic ----------------------------------------------------------------

    def _put(self, resource: Resource, base: str, version: int, payload: dict,
             pointer: bool = True) -> None:
        # one atomic apply, not two puts: the version record and the family's
        # latest pointer land together — no crash window where a pointer
        # names a spec that was never written (and one store round trip per
        # version transition instead of two). ``pointer=False`` updates a
        # RETIRED version's record (the quiesce bookkeeping a swap/migrate/
        # resize writes after the new version took the pointer) without
        # rewinding the family's latest back onto it.
        with trace.child("store.put", resource=resource.value, base=base,
                         version=version):
            self.kv.apply(self._put_ops(resource, base, version, payload,
                                        pointer=pointer))

    @staticmethod
    def _put_ops(resource: Resource, base: str, version: int,
                 payload: dict, pointer: bool = True) -> list[tuple]:
        ops = [
            ("put", keys.version_key(resource, base, version),
             json.dumps(payload)),
        ]
        if pointer:
            ops.append(("put", keys.latest_key(resource, base), str(version)))
        return ops

    def _get(self, resource: Resource, name: str) -> dict:
        """Fetch by versioned name, or by base name (⇒ latest version)."""
        with trace.child("store.get", resource=resource.value, target=name):
            base, version = keys.split_versioned_name(name)
            if version is None:
                latest = self.kv.get_or(keys.latest_key(resource, base))
                if latest is None:
                    raise errors.NotExistInStore(name)
                version = int(latest)
            raw = self.kv.get_or(keys.version_key(resource, base, version))
            if raw is None:
                raise errors.NotExistInStore(name)
            return json.loads(raw)

    def latest_version(self, resource: Resource, base: str) -> int | None:
        raw = self.kv.get_or(keys.latest_key(resource, base))
        return None if raw is None else int(raw)

    def history(self, resource: Resource, base: str) -> list[int]:
        """Stored versions, oldest first — sorted numerically (zero-padded
        keys are already key-sorted, but parse-and-sort keeps this robust
        to hand-written keys). Keys-only scan: deriving which versions
        exist must not haul every version's full JSON over the wire."""
        prefix = f"{keys.PREFIX}/{resource.value}/{base}/v/"
        return sorted(
            int(k.rsplit("/", 1)[1]) for k in self.kv.keys_prefix(prefix))

    def delete_family(self, resource: Resource, name: str) -> None:
        """Drop every version + the latest pointer (delEtcdInfo semantics)."""
        base, _ = keys.split_versioned_name(name)
        self.kv.delete_prefix(keys.family_prefix(resource, base))

    def delete_version(self, resource: Resource, name: str) -> None:
        base, version = keys.split_versioned_name(name)
        if version is not None:
            self.kv.delete(keys.version_key(resource, base, version))

    # -- containers -------------------------------------------------------------

    def put_container(self, st: ContainerState) -> None:
        base, _ = keys.split_versioned_name(st.container_name)
        self._put(Resource.CONTAINERS, base, st.version, st.to_dict())

    def get_container(self, name: str) -> ContainerState:
        return ContainerState.from_dict(self._get(Resource.CONTAINERS, name))

    # -- jobs -------------------------------------------------------------------

    def put_job(self, st, pointer: bool = True) -> None:
        """``pointer=False`` rewrites a retired version's record (e.g. the
        old gang marked stopped after a swap) without rewinding the
        family's latest pointer onto it — a bare-name ``GET`` must keep
        serving the version that actually superseded it."""
        base, _ = keys.split_versioned_name(st.job_name)
        self._put(Resource.JOBS, base, st.version, st.to_dict(),
                  pointer=pointer)

    def get_job(self, name: str):
        from tpu_docker_api.schemas.job import JobState

        return JobState.from_dict(self._get(Resource.JOBS, name))

    # -- services ---------------------------------------------------------------

    def put_service(self, st) -> None:
        base, _ = keys.split_versioned_name(st.service_name)
        self._put(Resource.SERVICES, base, st.version, st.to_dict())

    def get_service(self, name: str):
        from tpu_docker_api.schemas.service import ServiceState

        return ServiceState.from_dict(self._get(Resource.SERVICES, name))

    # -- workflows --------------------------------------------------------------

    def put_workflow(self, st) -> None:
        base, _ = keys.split_versioned_name(st.workflow_name)
        self._put(Resource.WORKFLOWS, base, st.version, st.to_dict())

    def get_workflow(self, name: str):
        from tpu_docker_api.schemas.workflow import WorkflowState

        return WorkflowState.from_dict(self._get(Resource.WORKFLOWS, name))

    # -- volumes ----------------------------------------------------------------

    def put_volume(self, st: VolumeState) -> None:
        base, _ = keys.split_versioned_name(st.volume_name)
        self._put(Resource.VOLUMES, base, st.version, st.to_dict())

    def get_volume(self, name: str) -> VolumeState:
        return VolumeState.from_dict(self._get(Resource.VOLUMES, name))
