"""State & versioning layer (parity: reference L4 — ``internal/etcd/``,
``internal/version/``, ``internal/workQueue/``)."""

from tpu_docker_api.state.kv import KV, MemoryKV, SqliteKV, open_store  # noqa: F401
from tpu_docker_api.state.keys import Resource, family_key, version_key  # noqa: F401
from tpu_docker_api.state.store import StateStore  # noqa: F401
from tpu_docker_api.state.version import VersionMap  # noqa: F401
from tpu_docker_api.state.workqueue import (  # noqa: F401
    CopyTask,
    DelKeyTask,
    FnTask,
    PutKVTask,
    TaskRecord,
    WorkQueue,
)
