"""Version maps: name → latest version per resource family.

Parity: reference ``internal/version/version.go`` (two concurrent maps wrapping
orcaman/concurrent-map + atomics). Fix applied: the reference restores from the
store on Init but persists only in Close (version.go:40-63), so a crash loses
every bump since boot; here every mutation persists synchronously.
"""

from __future__ import annotations

import json
import threading

from tpu_docker_api.state.kv import KV


class VersionMap:
    def __init__(self, kv: KV, store_key: str,
                 read_through=False) -> None:
        self._kv = kv
        self._key = store_key
        #: HA fleets pass a callable here (daemon wiring: "am I a
        #: standby right now?"): while it returns True every read re-seeds
        #: from the store first, because the leader rolls, creates and
        #: deletes families behind this replica's back — a hit is no more
        #: trustworthy than a miss (staleness must be bounded by one read,
        #: not by this replica's lifetime). A leader (callable False) and
        #: single-process deployments (the bool default) keep the pure
        #: in-memory map: every write is local, zero extra reads.
        #: With an informer attached (attach_informer), the standby read
        #: path upgrades again: watch-fed shadow, zero reads AND zero
        #: JSON re-parses per request.
        self._read_through = (read_through if callable(read_through)
                              else (lambda: read_through))
        self._mu = threading.Lock()
        raw = kv.get_or(store_key)
        self._m: dict[str, int] = json.loads(raw) if raw else {}
        self._informer = None
        #: standby-read shadow, replaced wholesale on every watch event for
        #: our key. READ-only: writers (next_version/set/rollback) never
        #: consult it, so a transiently-lagging event stream can at worst
        #: serve a bounded-stale read — it can never roll the authoritative
        #: map backwards and re-issue an old version number.
        self._shadow: dict[str, int] = {}

    def attach_informer(self, informer) -> None:
        """Standby mode: replace per-read store re-seeding with watch-fed
        updates (state/informer.py). Reads served from the shadow while the
        informer is synced; any degradation falls back to the per-read
        read-through path, so staleness is NEVER worse than before."""
        with self._mu:
            self._shadow = dict(self._m)
        self._informer = informer
        informer.register(self._key, self._on_informer_event)

    def _on_informer_event(self, ev) -> None:
        if ev.key != self._key:
            return  # a longer key sharing our key as its prefix
        m = json.loads(ev.value) if (ev.op == "put" and ev.value) else {}
        with self._mu:
            self._shadow = m

    def _shadow_live(self) -> bool:
        return self._informer is not None and self._informer.synced

    def _persist_locked(self) -> None:
        self._kv.put(self._key, json.dumps(self._m, sort_keys=True))

    def reload_from_store(self) -> None:
        """Replace the in-memory mirror with the store's truth — the
        leadership-handoff cache refresh (a promoted standby may have
        booted long before the old leader's last write)."""
        raw = self._kv.get_or(self._key)
        with self._mu:
            self._m = json.loads(raw) if raw else {}

    def get(self, name: str) -> int | None:
        if self._read_through():
            if self._shadow_live():
                with self._mu:
                    return self._shadow.get(name)
            self.reload_from_store()
        with self._mu:
            return self._m.get(name)

    def contains(self, name: str) -> bool:
        return self.get(name) is not None

    def next_version(self, name: str) -> int:
        """Atomically bump-and-get: first call for a name returns 0.

        The reference starts families at version 0 and names them
        ``"%s-%d"`` (service/container.go:468-486).
        """
        with self._mu:
            v = self._m.get(name)
            v = 0 if v is None else v + 1
            self._m[name] = v
            self._persist_locked()
            return v

    def set(self, name: str, version: int) -> None:
        with self._mu:
            self._m[name] = version
            self._persist_locked()

    def rollback(self, name: str, to_version: int | None) -> None:
        """Undo a failed bump (reference: deferred decrement,
        service/container.go:475-483 — done transactionally here)."""
        with self._mu:
            if to_version is None:
                self._m.pop(name, None)
            else:
                self._m[name] = to_version
            self._persist_locked()

    def remove(self, name: str) -> None:
        with self._mu:
            self._m.pop(name, None)
            self._persist_locked()

    def snapshot(self) -> dict[str, int]:
        if self._read_through():
            if self._shadow_live():
                with self._mu:
                    return dict(self._shadow)
            self.reload_from_store()
        with self._mu:
            return dict(self._m)


class ShardedVersionMap:
    """Version map partitioned across the sharded writer plane: one inner
    :class:`VersionMap` per shard (shard 0 at the LEGACY singleton key, so
    a ``shard_count`` bump adopts the existing snapshot as shard 0's
    without migration; shards i>0 at ``keys.versions_shard_key``).

    Writes delegate to the owning shard's map — and because each inner
    map persists to a key that :class:`~tpu_docker_api.service.shard.ShardMap`
    classifies back to that shard, every persist rides the shard's epoch
    fence through ``ShardedKV``: a deposed shard leader's version bump
    loses its compare instead of clobbering the new leader's snapshot.
    Reads on shards this process does NOT lead go read-through (the
    leader of that shard bumps versions behind our back), while led
    shards keep the pure in-memory map — per-shard, the exact PR 7
    leader/standby read contract.

    Legacy adoption: a single-leader store's version snapshot lists EVERY
    family in the shard-0 singleton, including families that hash to
    other shards after a ``shard_count`` bump. Reads therefore fall back
    to the shard-0 map on an owning-map miss, and the first write
    re-homes the family: the owning shard's map adopts the legacy
    version, then mutates its own copy (which shadows the stale legacy
    entry from then on). ``remove`` also clears a surviving legacy entry
    — that write rides shard 0's fence, so deleting a never-re-homed
    legacy family from a leader that does not hold shard 0 surfaces a
    typed GuardFailed rather than silently resurrecting the family.
    """

    def __init__(self, kv, shard_map, resource, leading) -> None:
        """``leading(shard) -> bool`` is the per-shard read-through
        inverter (typically ``plane.is_leader``)."""
        from tpu_docker_api.state import keys as _keys
        self._shard_map = shard_map
        self._maps = [
            VersionMap(kv, _keys.versions_shard_key(resource, i),
                       read_through=(lambda i=i: not leading(i)))
            for i in range(shard_map.count)
        ]

    def _of(self, name: str) -> VersionMap:
        return self._maps[self._shard_map.shard_of(name)]

    def _lookup(self, name: str) -> tuple[VersionMap, int | None]:
        """Owning map first, then the legacy (shard 0) adoption home."""
        owner = self._of(name)
        v = owner.get(name)
        if v is None and owner is not self._maps[0]:
            v = self._maps[0].get(name)
        return owner, v

    def _rehome(self, name: str) -> VersionMap:
        """Ensure ``name``'s owning map carries its current version before
        a mutation — the first write after a shard_count bump adopts the
        legacy entry into the owning shard's keyspace."""
        owner, v = self._lookup(name)
        if v is not None and owner.get(name) is None:
            owner.set(name, v)
        return owner

    def reload_from_store(self) -> None:
        for m in self._maps:
            m.reload_from_store()

    def reload_shard(self, shard: int) -> None:
        """Takeover cache refresh for ONE shard (daemon on-acquire hook) —
        the other shards' maps are not ours to reseed."""
        self._maps[shard].reload_from_store()

    def get(self, name: str) -> int | None:
        return self._lookup(name)[1]

    def contains(self, name: str) -> bool:
        return self._lookup(name)[1] is not None

    def next_version(self, name: str) -> int:
        return self._rehome(name).next_version(name)

    def set(self, name: str, version: int) -> None:
        self._of(name).set(name, version)

    def rollback(self, name: str, to_version: int | None) -> None:
        self._rehome(name).rollback(name, to_version)

    def remove(self, name: str) -> None:
        owner = self._of(name)
        owner.remove(name)
        legacy = self._maps[0]
        if owner is not legacy and legacy.get(name) is not None:
            legacy.remove(name)

    def snapshot(self) -> dict[str, int]:
        merged: dict[str, int] = {}
        for m in self._maps:
            merged.update(m.snapshot())
        return merged
