"""Version maps: name → latest version per resource family.

Parity: reference ``internal/version/version.go`` (two concurrent maps wrapping
orcaman/concurrent-map + atomics). Fix applied: the reference restores from the
store on Init but persists only in Close (version.go:40-63), so a crash loses
every bump since boot; here every mutation persists synchronously.
"""

from __future__ import annotations

import json
import threading

from tpu_docker_api.state.kv import KV


class VersionMap:
    def __init__(self, kv: KV, store_key: str,
                 read_through=False) -> None:
        self._kv = kv
        self._key = store_key
        #: HA fleets pass a callable here (daemon wiring: "am I a
        #: standby right now?"): while it returns True every read re-seeds
        #: from the store first, because the leader rolls, creates and
        #: deletes families behind this replica's back — a hit is no more
        #: trustworthy than a miss (staleness must be bounded by one read,
        #: not by this replica's lifetime). A leader (callable False) and
        #: single-process deployments (the bool default) keep the pure
        #: in-memory map: every write is local, zero extra reads.
        #: With an informer attached (attach_informer), the standby read
        #: path upgrades again: watch-fed shadow, zero reads AND zero
        #: JSON re-parses per request.
        self._read_through = (read_through if callable(read_through)
                              else (lambda: read_through))
        self._mu = threading.Lock()
        raw = kv.get_or(store_key)
        self._m: dict[str, int] = json.loads(raw) if raw else {}
        self._informer = None
        #: standby-read shadow, replaced wholesale on every watch event for
        #: our key. READ-only: writers (next_version/set/rollback) never
        #: consult it, so a transiently-lagging event stream can at worst
        #: serve a bounded-stale read — it can never roll the authoritative
        #: map backwards and re-issue an old version number.
        self._shadow: dict[str, int] = {}

    def attach_informer(self, informer) -> None:
        """Standby mode: replace per-read store re-seeding with watch-fed
        updates (state/informer.py). Reads served from the shadow while the
        informer is synced; any degradation falls back to the per-read
        read-through path, so staleness is NEVER worse than before."""
        with self._mu:
            self._shadow = dict(self._m)
        self._informer = informer
        informer.register(self._key, self._on_informer_event)

    def _on_informer_event(self, ev) -> None:
        if ev.key != self._key:
            return  # a longer key sharing our key as its prefix
        m = json.loads(ev.value) if (ev.op == "put" and ev.value) else {}
        with self._mu:
            self._shadow = m

    def _shadow_live(self) -> bool:
        return self._informer is not None and self._informer.synced

    def _persist_locked(self) -> None:
        self._kv.put(self._key, json.dumps(self._m, sort_keys=True))

    def reload_from_store(self) -> None:
        """Replace the in-memory mirror with the store's truth — the
        leadership-handoff cache refresh (a promoted standby may have
        booted long before the old leader's last write)."""
        raw = self._kv.get_or(self._key)
        with self._mu:
            self._m = json.loads(raw) if raw else {}

    def get(self, name: str) -> int | None:
        if self._read_through():
            if self._shadow_live():
                with self._mu:
                    return self._shadow.get(name)
            self.reload_from_store()
        with self._mu:
            return self._m.get(name)

    def contains(self, name: str) -> bool:
        return self.get(name) is not None

    def next_version(self, name: str) -> int:
        """Atomically bump-and-get: first call for a name returns 0.

        The reference starts families at version 0 and names them
        ``"%s-%d"`` (service/container.go:468-486).
        """
        with self._mu:
            v = self._m.get(name)
            v = 0 if v is None else v + 1
            self._m[name] = v
            self._persist_locked()
            return v

    def set(self, name: str, version: int) -> None:
        with self._mu:
            self._m[name] = version
            self._persist_locked()

    def rollback(self, name: str, to_version: int | None) -> None:
        """Undo a failed bump (reference: deferred decrement,
        service/container.go:475-483 — done transactionally here)."""
        with self._mu:
            if to_version is None:
                self._m.pop(name, None)
            else:
                self._m[name] = to_version
            self._persist_locked()

    def remove(self, name: str) -> None:
        with self._mu:
            self._m.pop(name, None)
            self._persist_locked()

    def snapshot(self) -> dict[str, int]:
        if self._read_through():
            if self._shadow_live():
                with self._mu:
                    return dict(self._shadow)
            self.reload_from_store()
        with self._mu:
            return dict(self._m)
