"""Workflow DTOs — declarative crash-proof DAG orchestration.

Jobs run to completion and Services serve forever; a **Workflow** is the
multi-step lifecycle between them (ROADMAP item 4): a DAG of job steps —
fine-tune, then eval — finished by a ``promote`` step that rolls a
Service to the produced artifact through ``replace_job_spec``, plus cron
schedules for recurring runs. Workflows persist exactly like jobs and
services — immutable spec versions plus a ``latest`` pointer committed in
one atomic ``KV.apply`` — with the DAG's control half (per-step status,
run ordinal, cron bookkeeping) rewritten in place on the latest version.

Step gangs are real jobs (family ``<workflow>.s<run>_<index>``) admitted
at the workflow's priority class, so a pipeline burst backfills and
preempts through the capacity market like everything else. Artifact
hand-off rides volume binds: the workflow's shared ``binds`` mount into
every job step, so a training step's output volume is the eval step's
input without any copy step.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from tpu_docker_api import errors

#: workflow lifecycle. ``running`` = the engine owns the DAG; the
#: terminals are ``succeeded`` / ``failed`` (a cron re-fire resets a
#: terminal workflow back to ``running`` with a fresh run ordinal);
#: ``deleting`` = teardown intent is durable — a crash mid-delete leaves
#: this phase behind and the reconciler finishes the sweep.
WORKFLOW_PHASES = ("running", "succeeded", "failed", "deleting")

#: per-step state machine. ``pending`` → (deps met, backoff elapsed) →
#: ``launching`` (launch TaskRecord journaled, gang not proven yet) →
#: ``running`` (gang exists) → ``succeeded`` | back to ``pending`` with
#: ``attempts`` bumped (retry) | ``failed`` (budget burned ⇒ the whole
#: workflow settles terminal-failed and frees everything).
STEP_STATES = ("pending", "launching", "running", "succeeded", "failed")

STEP_KINDS = ("job", "promote")

#: missed-tick catch-up policy (docs/robustness.md "Workflows"): with
#: k > 1 schedule boundaries elapsed since the last fire (daemon down, or
#: the previous run still in flight), ``skip`` realigns the schedule to
#: the next future boundary firing nothing, ``fire_once`` fires exactly
#: ONE run covering all k missed ticks. k == 1 is the ordinary on-time
#: fire under both policies.
CRON_CATCHUP_POLICIES = ("skip", "fire_once")

#: env marker rendered into every step gang's JobState: maps the gang
#: back to its owning workflow DURABLY, so reconcile/invariants can
#: garbage-collect orphan step gangs after the workflow family is gone
#: (a name-shape match alone would misjudge a user job named "x.s0_1")
WORKFLOW_OWNER_ENV = "TPU_DOCKER_API_WORKFLOW"
#: companion marker: which run ordinal the gang belongs to — a cron
#: re-fire bumps the run, and gangs of superseded runs are GC'd by it
WORKFLOW_RUN_ENV = "TPU_DOCKER_API_WORKFLOW_RUN"


def owner_from_env(env: list[str]) -> str | None:
    """The owning workflow recorded in a step gang's stored env, or None.
    THE one implementation of the marker lookup — workflow.py and the
    invariants oracle must agree on what ownership means."""
    want = f"{WORKFLOW_OWNER_ENV}="
    for e in env:
        if e.startswith(want):
            return e[len(want):]
    return None


def run_from_env(env: list[str]) -> int | None:
    want = f"{WORKFLOW_RUN_ENV}="
    for e in env:
        if e.startswith(want) and e[len(want):].isdigit():
            return int(e[len(want):])
    return None


@dataclasses.dataclass
class WorkflowStep:
    """One DAG node (immutable spec half). ``kind == "job"`` runs a gang
    to completion; ``kind == "promote"`` rolls ``service`` to ``image``
    through the Service rolling-update machinery."""
    name: str
    kind: str = "job"
    deps: list[str] = dataclasses.field(default_factory=list)
    image: str = ""
    cmd: list[str] = dataclasses.field(default_factory=list)
    env: list[str] = dataclasses.field(default_factory=list)
    binds: list[str] = dataclasses.field(default_factory=list)
    chip_count: int = 0
    accelerator_type: str = ""
    #: promote target (kind == "promote"): the Service to roll to `image`
    service: str = ""
    #: per-step retry budget; -1 ⇒ config ``workflow_max_step_retries``
    max_retries: int = -1

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "WorkflowStep":
        return WorkflowStep(
            name=d.get("name", ""),
            kind=d.get("kind", "job"),
            deps=list(d.get("deps", [])),
            image=d.get("image", d.get("imageName", "")),
            cmd=list(d.get("cmd", [])),
            env=list(d.get("env", [])),
            binds=list(d.get("binds", [])),
            chip_count=errors.as_int(d.get("chipCount",
                                           d.get("chip_count", 0)),
                                     "chipCount"),
            accelerator_type=d.get("acceleratorType",
                                   d.get("accelerator_type", "")),
            service=d.get("service", ""),
            max_retries=errors.as_int(d.get("maxRetries",
                                            d.get("max_retries", -1)),
                                      "maxRetries"),
        )

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def fresh_step_status() -> dict[str, Any]:
    """A step's control record at the start of a run (and after a retry
    reset, which carries ``attempts``/``error`` forward explicitly)."""
    return {"state": "pending", "attempts": 0, "job": "", "error": "",
            "notBefore": 0.0}


@dataclasses.dataclass
class WorkflowCreate:
    """POST /workflows body."""
    workflow_name: str
    steps: list[WorkflowStep] = dataclasses.field(default_factory=list)
    priority_class: str = ""      # "" ⇒ config workflow_default_class
    #: shared artifact binds mounted into EVERY job step (the hand-off
    #: volume), on top of each step's own binds
    binds: list[str] = dataclasses.field(default_factory=list)
    #: recurring schedule: fire a fresh run every interval (0 ⇒ one-shot)
    cron_interval_s: float = 0.0
    cron_catchup: str = "skip"
    cron_enabled: bool = True

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "WorkflowCreate":
        return WorkflowCreate(
            workflow_name=d.get("workflowName", ""),
            steps=[WorkflowStep.from_dict(s) for s in d.get("steps", [])],
            priority_class=d.get("priorityClass", ""),
            binds=list(d.get("binds", [])),
            cron_interval_s=errors.as_float(
                d.get("cronIntervalS", 0.0), "cronIntervalS"),
            cron_catchup=d.get("cronCatchup", "skip"),
            cron_enabled=bool(d.get("cronEnabled", True)),
        )


@dataclasses.dataclass
class WorkflowPatch:
    """PATCH /workflows/{name} body: cron control only — the DAG spec is
    immutable (delete + recreate to change it). Disabling cron mid-flight
    lets the current run finish; no further runs fire."""
    cron_enabled: bool | None = None
    cron_interval_s: float | None = None
    cron_catchup: str | None = None

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "WorkflowPatch":
        return WorkflowPatch(
            cron_enabled=(bool(d["cronEnabled"])
                          if "cronEnabled" in d else None),
            cron_interval_s=(errors.as_float(d["cronIntervalS"],
                                             "cronIntervalS")
                             if "cronIntervalS" in d else None),
            cron_catchup=d.get("cronCatchup"),
        )


@dataclasses.dataclass
class WorkflowState:
    """Persisted per workflow version — the spec half is immutable
    (steps/binds/class/cron spec; a change makes version n+1), the
    control half (phase, run, stepStatus, cron bookkeeping) is rewritten
    in place on the latest version like a job's lifecycle phase."""
    workflow_name: str         # versioned, e.g. "pipe-1"
    version: int
    steps: list[dict]          # WorkflowStep dicts (spec order = DAG order)
    priority_class: str = "production"
    binds: list[str] = dataclasses.field(default_factory=list)
    cron_interval_s: float = 0.0
    cron_catchup: str = "skip"
    # -- control half (rewritten in place on the latest version) --------------
    phase: str = "running"
    #: run ordinal: 0 at create, bumped by every cron fire — step gang
    #: families embed it, so runs never collide on job names
    run: int = 0
    #: step name → {"state", "attempts", "job", "error", "notBefore"}
    step_status: dict = dataclasses.field(default_factory=dict)
    cron_enabled: bool = True
    #: wall-clock anchor of the schedule (the engine's injected clock);
    #: fires advance it by whole intervals so boundaries never drift
    last_fire_ts: float = 0.0
    fired_runs: int = 0
    #: ticks that found the previous run still in flight (overlap
    #: suppression) or were skipped by the catch-up policy
    suppressed_ticks: int = 0
    skipped_ticks: int = 0
    #: audit record of the last phase transition: {"ts", "from", "to",
    #: "reason"} — the operator's answer to "why is this failed"
    last_transition: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "WorkflowState":
        return WorkflowState(
            workflow_name=d["workflow_name"],
            version=int(d["version"]),
            steps=[dict(s) for s in d.get("steps", [])],
            priority_class=d.get("priority_class", "production"),
            binds=list(d.get("binds", [])),
            cron_interval_s=float(d.get("cron_interval_s", 0.0)),
            cron_catchup=d.get("cron_catchup", "skip"),
            phase=d.get("phase", "running"),
            run=int(d.get("run", 0)),
            step_status={k: dict(v)
                         for k, v in d.get("step_status", {}).items()},
            cron_enabled=bool(d.get("cron_enabled", True)),
            last_fire_ts=float(d.get("last_fire_ts", 0.0)),
            fired_runs=int(d.get("fired_runs", 0)),
            suppressed_ticks=int(d.get("suppressed_ticks", 0)),
            skipped_ticks=int(d.get("skipped_ticks", 0)),
            last_transition=dict(d.get("last_transition", {})),
        )

    def spec_steps(self) -> list[WorkflowStep]:
        return [WorkflowStep.from_dict(
            {**s, "chipCount": s.get("chip_count", 0),
             "acceleratorType": s.get("accelerator_type", ""),
             "maxRetries": s.get("max_retries", -1)})
            for s in self.steps]


def validate_dag(steps: list[WorkflowStep]) -> None:
    """Reject empty DAGs, duplicate/unknown names, bad kinds, underspecified
    steps, and cycles — at POST time, with typed errors, so a workflow the
    engine cannot drive is never persisted."""
    if not steps:
        raise errors.BadRequest("a workflow needs at least one step")
    names = [s.name for s in steps]
    if len(set(names)) != len(names):
        raise errors.BadRequest(f"duplicate step names in {names}")
    known = set(names)
    for s in steps:
        if not s.name or not s.name.replace("_", "").isalnum():
            raise errors.BadRequest(
                f"invalid step name {s.name!r}: must be nonempty, "
                "[a-zA-Z0-9_] only")
        if s.kind not in STEP_KINDS:
            raise errors.BadRequest(
                f"step {s.name}: unknown kind {s.kind!r} "
                f"(known: {STEP_KINDS})")
        unknown = set(s.deps) - known
        if unknown:
            raise errors.BadRequest(
                f"step {s.name}: unknown deps {sorted(unknown)}")
        if s.name in s.deps:
            raise errors.BadRequest(f"step {s.name} depends on itself")
        if not s.image:
            raise errors.BadRequest(f"step {s.name}: image required")
        if s.kind == "job" and s.chip_count <= 0 and not s.accelerator_type:
            raise errors.BadRequest(
                f"step {s.name}: chipCount or acceleratorType required")
        if s.kind == "promote" and not s.service:
            raise errors.BadRequest(
                f"step {s.name}: promote needs a target service")
    # Kahn's algorithm: anything left after peeling roots is a cycle
    deps = {s.name: set(s.deps) for s in steps}
    while True:
        roots = [n for n, d in deps.items() if not d]
        if not roots:
            break
        for n in roots:
            del deps[n]
        for d in deps.values():
            d.difference_update(roots)
    if deps:
        raise errors.BadRequest(
            f"dependency cycle among steps {sorted(deps)}")
