"""State-store payloads.

Parity: reference ``internal/model/etcd.go:12-36`` — the full, runtime-validated
container/volume spec is persisted so any flow can rebuild an identical
resource (the control plane's checkpoint, SURVEY.md §5.4). Unlike the
reference (which stores raw docker SDK structs), we persist our own
runtime-neutral spec (`tpu_docker_api.runtime.spec.ContainerSpec`) as a dict,
so the payload survives a runtime-backend swap.
"""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class ContainerState:
    """Persisted per container family version (model/etcd.go EtcdContainerInfo)."""
    container_name: str  # versioned name, e.g. "train-3"
    version: int
    spec: dict[str, Any]  # runtime.spec.ContainerSpec.to_dict()
    # declarative liveness: False after a deliberate stop. The health
    # watcher's crash recovery only resurrects containers whose latest
    # version wants to run (SURVEY.md §5.3)
    desired_running: bool = True

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "ContainerState":
        return ContainerState(
            container_name=d["container_name"],
            version=int(d["version"]),
            spec=d["spec"],
            desired_running=bool(d.get("desired_running", True)),
        )


@dataclasses.dataclass
class VolumeState:
    """Persisted per volume family version (model/etcd.go EtcdVolumeInfo)."""
    volume_name: str  # versioned name, e.g. "data-2"
    version: int
    size: str  # e.g. "10GB"; "" ⇒ unsized
    driver_opts: dict[str, str] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "VolumeState":
        return VolumeState(
            volume_name=d["volume_name"],
            version=int(d["version"]),
            size=d.get("size", ""),
            driver_opts=d.get("driver_opts", {}),
        )
