"""Volume request DTOs (parity: reference ``internal/model/volume.go:7-35``)."""

from __future__ import annotations

import dataclasses

#: allowed size units → bytes multiplier (model/volume.go VolumeSizeMap)
VOLUME_SIZE_UNITS: dict[str, int] = {
    "KB": 1024,
    "MB": 1024**2,
    "GB": 1024**3,
    "TB": 1024**4,
}


@dataclasses.dataclass
class VolumeCreate:
    """POST /volumes body (model/volume.go VolumeCreate)."""
    volume_name: str
    size: str = ""  # e.g. "10GB"; empty ⇒ unsized


@dataclasses.dataclass
class VolumeSize:
    """PATCH /volumes/{name}/size body (model/volume.go VolumeSize)."""
    size: str = ""


@dataclasses.dataclass
class VolumeDelete:
    """DELETE /volumes/{name} body."""
    del_etcd_info_and_version_record: bool = False


def parse_size(size: str) -> int:
    """``"10GB"`` → bytes. Raises ValueError on unknown unit or bad number.

    Parity: utils/file.go:21-45 ``ToBytes`` + the unit validation at
    api/volume.go:118-124.
    """
    s = size.strip().upper()
    for unit, mult in VOLUME_SIZE_UNITS.items():
        if s.endswith(unit):
            # multiply before int() so fractional sizes ("1.5GB") keep precision
            return int(float(s[: -len(unit)]) * mult)
    raise ValueError(f"size {size!r} must end with one of {list(VOLUME_SIZE_UNITS)}")


@dataclasses.dataclass
class VolumeRollback:
    """PATCH /volumes/{name}/rollback body (see ContainerRollback)."""
    version: int
    data_from: str = "latest"
