"""Request / state DTOs (parity: reference ``internal/model/``)."""

from tpu_docker_api.schemas.container import (  # noqa: F401
    Bind,
    ContainerCommit,
    ContainerDelete,
    ContainerExecute,
    ContainerPatchChips,
    ContainerPatchVolume,
    ContainerPort,
    ContainerRun,
    ContainerStop,
)
from tpu_docker_api.schemas.state import ContainerState, VolumeState  # noqa: F401
from tpu_docker_api.schemas.tpu import ChipInfo, HostTopologyInfo  # noqa: F401
from tpu_docker_api.schemas.volume import (  # noqa: F401
    VOLUME_SIZE_UNITS,
    VolumeCreate,
    VolumeDelete,
    VolumeSize,
)
