"""TPU telemetry wire format.

Parity: reference ``internal/model/gpu.go:3-28`` — the NVML-shaped
``GpuInfo/Memory/ProcessInfo`` structs returned by the detect-gpu sidecar.
The TPU equivalents carry what libtpu / the accel sysfs expose: chip id,
mesh coordinates, ICI neighbours, HBM, duty cycle, and the host topology
summary the scheduler seeds from (SURVEY.md §2.2 row 1).
"""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class ChipInfo:
    """One TPU chip as reported by the telemetry sidecar (NVML GpuInfo analog)."""
    chip_id: int                      # host-local index (the /dev/accel<N> number)
    device_path: str                  # e.g. "/dev/accel0"
    coords: tuple[int, int, int]      # (x, y, z) in the slice mesh
    cores_per_chip: int = 1
    hbm_total_bytes: int = 0
    hbm_used_bytes: int = 0
    duty_cycle_pct: float = 0.0       # TensorCore duty cycle (power/util analog)
    pid: int = 0                      # owning process if attached, else 0

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["coords"] = list(self.coords)
        return d

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "ChipInfo":
        return ChipInfo(
            chip_id=int(d["chip_id"]),
            device_path=d.get("device_path", f"/dev/accel{d['chip_id']}"),
            coords=tuple(d.get("coords", (0, 0, 0))),  # type: ignore[arg-type]
            cores_per_chip=int(d.get("cores_per_chip", 1)),
            hbm_total_bytes=int(d.get("hbm_total_bytes", 0)),
            hbm_used_bytes=int(d.get("hbm_used_bytes", 0)),
            duty_cycle_pct=float(d.get("duty_cycle_pct", 0.0)),
            pid=int(d.get("pid", 0)),
        )


@dataclasses.dataclass
class HostTopologyInfo:
    """The sidecar's host summary: what `GET /api/v1/detect/tpu` returns.

    The scheduler seeds from this on first boot, the way the reference seeds
    its GPU map from detect-gpu (gpuscheduler/scheduler.go:142-158).
    """
    accelerator_type: str             # e.g. "v5e-8", "v5p-16"
    generation: str                   # "v5e", "v5p", ...
    chips: list[ChipInfo] = dataclasses.field(default_factory=list)
    mesh_shape: tuple[int, int, int] = (0, 0, 0)   # host-local physical mesh
    libtpu_version: str = ""

    def to_dict(self) -> dict[str, Any]:
        return {
            "accelerator_type": self.accelerator_type,
            "generation": self.generation,
            "chips": [c.to_dict() for c in self.chips],
            "mesh_shape": list(self.mesh_shape),
            "libtpu_version": self.libtpu_version,
        }

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "HostTopologyInfo":
        return HostTopologyInfo(
            accelerator_type=d["accelerator_type"],
            generation=d.get("generation", d["accelerator_type"].split("-")[0]),
            chips=[ChipInfo.from_dict(c) for c in d.get("chips", [])],
            mesh_shape=tuple(d.get("mesh_shape", (0, 0, 0))),  # type: ignore[arg-type]
            libtpu_version=d.get("libtpu_version", ""),
        )
