"""Container request DTOs.

Parity: reference ``internal/model/container.go:7-44``. ``GpuCount`` becomes
``chip_count`` (TPU chips are exclusively scheduled, like the reference's GPU
UUIDs), and the run request grows ``slice_shape`` so callers may ask for an
ICI-contiguous sub-slice (e.g. "2x2") instead of a bare count — the shape a
bare GPU control plane cannot express.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from tpu_docker_api import errors


@dataclasses.dataclass
class ContainerPort:
    """One container→host port mapping; host side is scheduler-assigned."""
    container_port: int
    host_port: int = 0  # 0 ⇒ allocate from the port scheduler
    protocol: str = "tcp"


@dataclasses.dataclass
class Bind:
    """Volume bind ``src:dest`` (model/volume.go Bind{Src,Dest})."""
    src: str
    dest: str

    def render(self) -> str:
        return f"{self.src}:{self.dest}"


@dataclasses.dataclass
class ContainerRun:
    """POST /containers body (model/container.go:7-15, ContainerRun)."""
    image_name: str
    container_name: str
    chip_count: int = 0
    slice_shape: str = ""  # optional, e.g. "2x2": ask for an ICI-contiguous block
    binds: list[Bind] = dataclasses.field(default_factory=list)
    env: list[str] = dataclasses.field(default_factory=list)
    cmd: list[str] = dataclasses.field(default_factory=list)
    container_ports: list[ContainerPort] = dataclasses.field(default_factory=list)

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "ContainerRun":
        return ContainerRun(
            image_name=d.get("imageName", ""),
            container_name=d.get("containerName", ""),
            chip_count=errors.as_int(
                d.get("chipCount", d.get("gpuCount", 0)), "chipCount"),
            slice_shape=d.get("sliceShape", ""),
            binds=[Bind(b["src"], b["dest"]) for b in d.get("binds", [])],
            env=list(d.get("env", [])),
            cmd=list(d.get("cmd", [])),
            container_ports=[
                ContainerPort(
                    container_port=errors.as_int(p["containerPort"],
                                                 "containerPort"),
                    host_port=errors.as_int(p.get("hostPort", 0), "hostPort"),
                    protocol=p.get("protocol", "tcp"),
                )
                for p in d.get("containerPorts", [])
            ],
        )


@dataclasses.dataclass
class ContainerDelete:
    """DELETE /containers/{name} body (model/container.go ContainerDelete)."""
    force: bool = False
    del_etcd_info_and_version_record: bool = False


@dataclasses.dataclass
class ContainerExecute:
    """POST /containers/{name}/execute body (model/container.go ContainerExecute)."""
    work_dir: str = ""
    cmd: list[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ContainerPatchChips:
    """PATCH /containers/{name}/gpu body (model/container.go ContainerGpuPatch)."""
    chip_count: int = 0


@dataclasses.dataclass
class ContainerPatchVolume:
    """PATCH /containers/{name}/volume body (model/container.go ContainerVolumePatch)."""
    old_bind: Bind | None = None
    new_bind: Bind | None = None


@dataclasses.dataclass
class ContainerStop:
    """Internal stop options (model/container.go ContainerStop / service use)."""
    restore_chips: bool = False
    restore_ports: bool = False


@dataclasses.dataclass
class ContainerCommit:
    """POST /containers/{name}/commit body (model/container.go ContainerCommit)."""
    new_image_name: str = ""


@dataclasses.dataclass
class ContainerRollback:
    """PATCH /containers/{name}/rollback body. No reference analog — its
    README advertises version rollback (README.md:142-144) but the
    latest-wins etcd layout cannot deliver it (SURVEY.md appendix)."""
    version: int
    data_from: str = "latest"  # "latest" (keep newest data) | "target" (snapshot restore)
