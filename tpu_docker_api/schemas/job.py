"""Distributed-job DTOs.

The reference has no job concept — its unit is one container on one host. A
TPU control plane's headline object is a **distributed JAX job**: N containers
(one per host) over one ICI-contiguous slice, bootstrapped into a single JAX
runtime (BASELINE.json configs #3-#5). Jobs carry the same immutable-versioned
rolling-replacement semantics as containers: patching a job's chip count
creates ``job-(n+1)`` on a fresh slice and retires ``job-n``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from tpu_docker_api import errors


@dataclasses.dataclass
class JobRun:
    """POST /jobs body."""
    image_name: str
    job_name: str
    chip_count: int = 0          # total chips; whole-host multiples span hosts
    accelerator_type: str = ""   # alternative ask: "v5p-64" ⇒ chip count
    binds: list[str] = dataclasses.field(default_factory=list)   # "src:dest"
    env: list[str] = dataclasses.field(default_factory=list)
    cmd: list[str] = dataclasses.field(default_factory=list)
    # >1 ⇒ multislice: chipCount splits into numSlices separate ICI slices
    # stitched over DCN with MEGASCALE_* env (workload/jaxenv.py)
    num_slices: int = 1
    # capacity-market priority class (service/admission.py): one of the
    # configured ``priority_class_weights`` names; "" ⇒ the configured
    # default. Higher-weight jobs may preempt strictly-lower-weight gangs
    # when the pool is full and admission is enabled
    priority_class: str = ""
    # elastic data-parallel gang (docs/robustness.md "Elastic gangs"):
    # when true, a host loss or a partial preemption SHRINKS the gang to
    # its surviving hosts (never below minMembers) instead of killing it,
    # and a durable grow-back record re-admits the lost members through
    # the capacity market once pressure lifts. Requires a single-slice
    # whole-host gang spanning >= 2 hosts.
    elastic: bool = False
    # the smallest member (host) count an elastic gang may shrink to;
    # 0 ⇒ 1 (elastic jobs only)
    min_members: int = 0

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "JobRun":
        return JobRun(
            image_name=d.get("imageName", ""),
            job_name=d.get("jobName", ""),
            chip_count=errors.as_int(d.get("chipCount", 0), "chipCount"),
            accelerator_type=d.get("acceleratorType", ""),
            binds=list(d.get("binds", [])),
            env=list(d.get("env", [])),
            cmd=list(d.get("cmd", [])),
            num_slices=errors.as_int(d.get("numSlices", 1), "numSlices"),
            priority_class=d.get("priorityClass", ""),
            elastic=bool(d.get("elastic", False)),
            min_members=errors.as_int(d.get("minMembers", 0), "minMembers"),
        )


@dataclasses.dataclass
class JobPatchChips:
    """PATCH /jobs/{name}/tpu body — rolling rescale onto a new slice."""
    chip_count: int = 0
    accelerator_type: str = ""

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "JobPatchChips":
        return JobPatchChips(
            chip_count=errors.as_int(d.get("chipCount", 0), "chipCount"),
            accelerator_type=d.get("acceleratorType", ""),
        )


@dataclasses.dataclass
class JobDelete:
    force: bool = False
    del_state_and_version_record: bool = False

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "JobDelete":
        return JobDelete(
            force=bool(d.get("force", False)),
            del_state_and_version_record=bool(
                d.get("delStateAndVersionRecord", d.get("delEtcdInfoAndVersionRecord", False))
            ),
        )


#: gang lifecycle phases (service/job_supervisor.py): a job is ``running``
#: until a member dies; the supervisor moves it through ``restarting``
#: (whole-gang stop→start in flight) back to ``running``, or — once the
#: restart budget is burned — to terminal ``failed`` (slices/ports freed).
#: ``migrating`` is the host-fault analog of ``restarting``: the gang is
#: being re-placed onto healthy hosts (whole-gang stop → release slice →
#: re-apply excluding unhealthy hosts → start), charged to its own
#: ``job_max_migrations`` budget. ``stopped`` is the user-requested
#: quiesce (resources retained for resume).
#:
#: The capacity market (service/admission.py) adds two phases: ``queued``
#: — admitted into the durable admission queue instead of hard-failing a
#: full pool (no members exist yet, no resources held) — and
#: ``preempted`` — the gang was quiesced and its slices/ports released to
#: make room for a higher-priority job; it re-admits automatically, ahead
#: of equal-priority queued jobs. Both are DORMANT: no member may run and
#: the job owns zero slices/ports (invariants.py enforces it; supervisor
#: and reconciler leave dormant members alone except to finish a
#: half-quiesced preemption).
#:
#: Elastic gangs add two in-flight phases: ``scaling_down`` — the gang is
#: being shrunk to its surviving hosts (host loss) or donating spare
#: members (partial preemption); ``scaling_up`` — a grow-back admitted
#: through the capacity market is restoring lost members. Both are
#: persisted FIRST (like ``restarting``/``migrating``) so a daemon death
#: mid-resize is adoptable: the reconciler/supervisor finish the resize
#: forward without re-counting it, and at rest neither phase may survive
#: (invariants.py flags a scaling phase at rest as a violation).
JOB_PHASES = ("running", "restarting", "migrating", "failed", "stopped",
              "queued", "preempted", "scaling_down", "scaling_up")

#: in-flight resize phases (service/job.py ``resize_gang``)
SCALING_PHASES = ("scaling_down", "scaling_up")

#: phases with no runtime footprint: members must not run, and — except
#: ``stopped``, which retains its grant for resume — the job owns nothing.
#: Supervision, gang recovery and liveness classification all skip them.
DORMANT_PHASES = ("failed", "stopped", "queued", "preempted")


@dataclasses.dataclass
class JobState:
    """Persisted per job version — everything needed to rebuild or rescale."""
    job_name: str            # versioned, e.g. "train-2"
    version: int
    image: str
    cmd: list[str]
    env: list[str]
    binds: list[str]
    chip_count: int
    coordinator_port: int
    # [(host_id, container_name, process_id, [chip_ids], tpu_port), ...]
    # ordered slice-major with equal process counts per slice, so
    # slice_id(pid) = pid // (len(placements) // num_slices)
    placements: list[list[Any]]
    desired_running: bool = True
    num_slices: int = 1
    # megascale DCN port (multislice only), allocated on process 0's host
    megascale_port: int = 0
    # gang lifecycle (JOB_PHASES); persisted so a daemon crash mid-recovery
    # is recognizable (phase == "restarting") and terminal failure survives
    phase: str = "running"
    # whole-gang restarts consumed against the supervisor's budget
    restarts: int = 0
    # host-fault migrations consumed against job_max_migrations — a
    # SEPARATE budget on purpose: a dead host must not eat the
    # crash-restart budget (no restart can fix it), and a crash-looping
    # workload must not eat the migration budget
    migrations: int = 0
    # why the job went terminal (phase == "failed"), surfaced in the API
    failure_reason: str = ""
    # capacity market (service/admission.py): the job's priority class
    # name (weights resolve through config at decision time, so operators
    # can retune without rewriting stored state)
    priority_class: str = "batch"
    # admission-order seniority: monotonically increasing submit sequence.
    # Victim selection is lowest-priority-first then YOUNGEST-first
    # (largest submitted_seq) — the paged.py seniority rule that makes
    # preemption terminate (juniors can never displace seniors)
    submitted_seq: int = 0
    # times this job was preempted (observability; not a budget — a
    # preempted job always re-admits when capacity returns)
    preemptions: int = 0
    # elastic gang contract (docs/robustness.md "Elastic gangs"): when
    # true, host loss / partial preemption shrink the gang (never below
    # min_members) instead of killing it, and members_desired records the
    # FULL member count the gang grows back to through the admission
    # queue. Non-elastic jobs keep all three at their zero defaults.
    elastic: bool = False
    min_members: int = 0
    members_desired: int = 0
    # lifetime resizes executed (observability — grows without bound on a
    # healthy long-lived elastic gang; shrinks and grow-backs both count)
    resizes: int = 0
    # the last (or in-flight, while phase is scaling_*) resize:
    # {"direction": "down"|"up", "reason", "ts", "fromMembers",
    #  "toMembers", "excludeHosts": [host ids], "attempts": n} —
    # persisted BEFORE the resize acts so adoption knows the target;
    # "attempts" counts retries of THIS resize and is what
    # ``job_resize_max`` bounds (never the lifetime counter); {} = never
    # resized
    last_resize: dict = dataclasses.field(default_factory=dict)
    # serving-gateway drain handshake (service/gateway.py): persisted
    # BEFORE the first member stop of a service-owned replica quiesce so
    # the gateway (and GET /services/{name}) see the replica leave the
    # routing table while it still serves in-flight streams. Durable stop
    # intent: reconcile adopts a draining non-dormant job by finishing
    # the stop, and invariants.py flags draining at rest (like the
    # scaling phases). Cleared by the same write that lands the job in a
    # dormant phase.
    draining: bool = False

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "JobState":
        return JobState(
            job_name=d["job_name"],
            version=int(d["version"]),
            image=d["image"],
            cmd=list(d.get("cmd", [])),
            env=list(d.get("env", [])),
            binds=list(d.get("binds", [])),
            chip_count=int(d.get("chip_count", 0)),
            coordinator_port=int(d.get("coordinator_port", 0)),
            placements=[list(p) for p in d.get("placements", [])],
            desired_running=bool(d.get("desired_running", True)),
            num_slices=int(d.get("num_slices", 1)),
            megascale_port=int(d.get("megascale_port", 0)),
            phase=d.get("phase", "running"),
            restarts=int(d.get("restarts", 0)),
            migrations=int(d.get("migrations", 0)),
            failure_reason=d.get("failure_reason", ""),
            priority_class=d.get("priority_class", "batch"),
            submitted_seq=int(d.get("submitted_seq", 0)),
            preemptions=int(d.get("preemptions", 0)),
            elastic=bool(d.get("elastic", False)),
            min_members=int(d.get("min_members", 0)),
            members_desired=int(d.get("members_desired", 0)),
            resizes=int(d.get("resizes", 0)),
            last_resize=dict(d.get("last_resize") or {}),
            draining=bool(d.get("draining", False)),
        )
