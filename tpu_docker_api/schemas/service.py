"""Service DTOs — declarative replicated serving.

The reference (and every job-shaped resource here so far) models *run to
completion* work. A **Service** is the traffic-facing dual (ROADMAP item
3): N identical replica gangs behind one name, each replica a distributed
job created through the existing gang machinery, with the replica count
owned by an SLO-driven autoscaler instead of an operator. Services are
persisted exactly like jobs — immutable spec versions plus a ``latest``
pointer, committed in one atomic ``KV.apply`` — so a rolling weight/spec
update is a new service version rolled replica-by-replica through the
same immutable-version replace sequencing jobs use.

Replica gangs are real jobs (family ``<service>.r<index>``) admitted at
the service's priority class — default ``production``, so a traffic-driven
scale-up enters the capacity market above ``batch``/``preemptible``
training and may preempt it (docs/robustness.md "Capacity market").
"""

from __future__ import annotations

import dataclasses
from typing import Any

from tpu_docker_api import errors

#: service lifecycle. ``active`` = the autoscaler owns the replica count;
#: ``deleting`` = teardown intent is durable — a crash mid-delete leaves
#: this phase behind and the reconciler finishes the sweep (every replica
#: gang removed, then the family dropped). There is no "stopped": a
#: service with zero traffic scales to ``min_replicas``, and deleting it
#: is the way to free them.
SERVICE_PHASES = ("active", "deleting")

#: env marker rendered into every replica gang's JobState: maps the gang
#: back to its owning service DURABLY, so the reconciler can garbage-
#: collect orphan replica fleets after the service family itself is gone
#: (a name-shape match alone would misjudge a user job named "x.r1")
SERVICE_OWNER_ENV = "TPU_DOCKER_API_SERVICE"


def owner_from_env(env: list[str]) -> str | None:
    """The owning service recorded in a replica gang's stored env, or
    None. THE one implementation of the marker lookup — serving.py and
    the invariants oracle must agree on what ownership means."""
    want = f"{SERVICE_OWNER_ENV}="
    for e in env:
        if e.startswith(want):
            return e[len(want):]
    return None


@dataclasses.dataclass
class ServiceCreate:
    """POST /services body."""
    service_name: str
    image_name: str
    chips_per_replica: int = 0
    accelerator_type: str = ""    # alternative per-replica ask, e.g. "v5e-8"
    replicas: int = 1             # initial replica count
    min_replicas: int = 1
    max_replicas: int = 4
    priority_class: str = ""      # "" ⇒ config service_default_class
    binds: list[str] = dataclasses.field(default_factory=list)
    env: list[str] = dataclasses.field(default_factory=list)
    cmd: list[str] = dataclasses.field(default_factory=list)
    # SLO policy: breach of either target triggers a scale-up
    ttft_p95_target_ms: float = 200.0
    queue_depth_target: int = 4
    # synthetic-load model capacity (fake-runtime replicas): requests/s
    # one replica absorbs before its TTFT/queue signals breach the target
    replica_capacity_rps: float = 100.0
    # the replica-reported metrics endpoint (real path): GET
    # http://<host>:<coordinatorPort><metricsPath> must return the paged
    # engine's SLO export ({"ttftP95Ms", "itlP95Ms", "queueDepth"}).
    # "" ⇒ no scrape; signals come from the synthetic load model only
    metrics_path: str = ""

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "ServiceCreate":
        return ServiceCreate(
            service_name=d.get("serviceName", ""),
            image_name=d.get("imageName", ""),
            chips_per_replica=errors.as_int(
                d.get("chipsPerReplica", 0), "chipsPerReplica"),
            accelerator_type=d.get("acceleratorType", ""),
            replicas=errors.as_int(d.get("replicas", 1), "replicas"),
            min_replicas=errors.as_int(d.get("minReplicas", 1),
                                       "minReplicas"),
            max_replicas=errors.as_int(d.get("maxReplicas", 4),
                                       "maxReplicas"),
            priority_class=d.get("priorityClass", ""),
            binds=list(d.get("binds", [])),
            env=list(d.get("env", [])),
            cmd=list(d.get("cmd", [])),
            ttft_p95_target_ms=errors.as_float(
                d.get("ttftP95TargetMs", 200.0), "ttftP95TargetMs"),
            queue_depth_target=errors.as_int(
                d.get("queueDepthTarget", 4), "queueDepthTarget"),
            replica_capacity_rps=errors.as_float(
                d.get("replicaCapacityRps", 100.0), "replicaCapacityRps"),
            metrics_path=d.get("metricsPath", ""),
        )


@dataclasses.dataclass
class ServicePatch:
    """PATCH /services/{name} body. ``replicas`` is a MANUAL scale (counted
    against the zero-manual-ops bench gate; the autoscaler keeps ruling
    afterwards). ``image_name`` is a weight/spec update: a new immutable
    service version, rolled replica-by-replica."""
    replicas: int | None = None
    min_replicas: int | None = None
    max_replicas: int | None = None
    image_name: str = ""
    ttft_p95_target_ms: float | None = None
    queue_depth_target: int | None = None

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "ServicePatch":
        def opt_int(key):
            return (errors.as_int(d[key], key) if key in d else None)

        return ServicePatch(
            replicas=opt_int("replicas"),
            min_replicas=opt_int("minReplicas"),
            max_replicas=opt_int("maxReplicas"),
            image_name=d.get("imageName", ""),
            ttft_p95_target_ms=(
                errors.as_float(d["ttftP95TargetMs"], "ttftP95TargetMs")
                if "ttftP95TargetMs" in d else None),
            queue_depth_target=opt_int("queueDepthTarget"),
        )


@dataclasses.dataclass
class ServiceState:
    """Persisted per service version — the spec half is immutable (image/
    cmd/env/binds/chips; a change makes version n+1), the control half
    (replicas, phase, lastScale) is rewritten in place on the latest
    version like a job's lifecycle phase."""
    service_name: str          # versioned, e.g. "web-1"
    version: int
    image: str
    cmd: list[str]
    env: list[str]
    binds: list[str]
    chips_per_replica: int
    accelerator_type: str = ""
    replicas: int = 1
    min_replicas: int = 1
    max_replicas: int = 4
    priority_class: str = "production"
    phase: str = "active"
    ttft_p95_target_ms: float = 200.0
    queue_depth_target: int = 4
    replica_capacity_rps: float = 100.0
    metrics_path: str = ""
    #: audit record of the last replica-count change: {"ts", "direction",
    #: "from", "to", "reason", "trigger" ("autoscale" | "manual")} — the
    #: operator's answer to "why did this scale" without reading logs
    last_scale: dict = dataclasses.field(default_factory=dict)
    #: per-incarnation scale counts, persisted WITH the decision (same
    #: apply). The /metrics counters are process-lifetime and survive a
    #: delete+recreate of the same name; these die with the family, so
    #: the zero-manual-ops audit judges THIS service, not its namesake
    manual_scales: int = 0
    auto_scales: int = 0

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "ServiceState":
        return ServiceState(
            service_name=d["service_name"],
            version=int(d["version"]),
            image=d["image"],
            cmd=list(d.get("cmd", [])),
            env=list(d.get("env", [])),
            binds=list(d.get("binds", [])),
            chips_per_replica=int(d.get("chips_per_replica", 0)),
            accelerator_type=d.get("accelerator_type", ""),
            replicas=int(d.get("replicas", 1)),
            min_replicas=int(d.get("min_replicas", 1)),
            max_replicas=int(d.get("max_replicas", 4)),
            priority_class=d.get("priority_class", "production"),
            phase=d.get("phase", "active"),
            ttft_p95_target_ms=float(d.get("ttft_p95_target_ms", 200.0)),
            queue_depth_target=int(d.get("queue_depth_target", 4)),
            replica_capacity_rps=float(d.get("replica_capacity_rps", 100.0)),
            metrics_path=d.get("metrics_path", ""),
            last_scale=dict(d.get("last_scale", {})),
            manual_scales=int(d.get("manual_scales", 0)),
            auto_scales=int(d.get("auto_scales", 0)),
        )
