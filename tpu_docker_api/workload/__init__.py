"""Workload layer: what the control plane injects into containers so the JAX
job inside finds its slice, its peers, and its mesh (SURVEY.md §5.8)."""

from tpu_docker_api.workload.jaxenv import (  # noqa: F401
    DistributedJob,
    render_distributed_env,
    render_job_specs,
)
