"""JAX distributed-environment rendering.

The reference's only cross-container duty is port mapping
(service/container.go:489-501); a TPU control plane must also render the
distributed bootstrap so N containers initialize one JAX job over ICI/DCN
(SURVEY.md §2.3 "Communication backend" row):

- ``JAX_COORDINATOR_ADDRESS`` + ``JAX_NUM_PROCESSES`` + ``JAX_PROCESS_ID`` —
  consumed by ``jax.distributed.initialize`` inside the container;
- ``TPU_PROCESS_BOUNDS`` / ``TPU_CHIPS_PER_PROCESS_BOUNDS`` /
  ``TPU_PROCESS_ADDRESSES`` / ``TPU_PROCESS_PORT`` / ``CLOUD_TPU_TASK_ID`` —
  consumed by libtpu to assemble the slice mesh from per-process chip subsets.

Within one host ICI does the transport; across hosts the coordinator address
rides DCN. The coordinator's host port comes from the port scheduler — the
TPU analog of the reference's host-port rendering.
"""

from __future__ import annotations

import dataclasses

from tpu_docker_api.runtime.spec import ContainerSpec
from tpu_docker_api.scheduler.topology import HostTopology


@dataclasses.dataclass
class ProcessPlacement:
    """One JAX process (= one container) in a distributed job."""
    process_id: int
    host: str                 # routable address of the host running it
    chip_ids: list[int]       # host-local chips handed to this process
    tpu_process_port: int     # libtpu mesh port (host side)
    # per-host topology for multi-host pods (hosts may differ from the
    # control-plane host); None ⇒ use the topology passed to render_job_specs
    topology: HostTopology | None = None
    # which ICI domain this process belongs to in a multislice job
    slice_id: int = 0


@dataclasses.dataclass
class DistributedJob:
    """A placement of N processes forming one JAX job."""
    name: str
    placements: list[ProcessPlacement]
    coordinator_port: int
    # "gx,gy,gz" DCN process grid (the pod scheduler's host-block shape);
    # "" ⇒ safe 1D default from _process_bounds
    process_bounds: str = ""
    # multislice (SURVEY.md §2.3 "megascale flags"): >1 ⇒ the job spans
    # num_slices ICI domains stitched over DCN; per-process slice ids live
    # on the placements and every process gets MEGASCALE_* env
    num_slices: int = 1
    # megascale transport port on the slice-0 coordinator host; 0 ⇒ reuse
    # coordinator_port + 1 (must be distinct from the JAX coordination port)
    megascale_port: int = 0

    @property
    def coordinator_address(self) -> str:
        # process 0 publishes the coordinator PortBinding (render_job_specs),
        # so the address must name ITS host — placements order is not assumed
        coord = next(p for p in self.placements if p.process_id == 0)
        return f"{coord.host}:{self.coordinator_port}"

    @property
    def resolved_megascale_port(self) -> int:
        """Megascale transport port. NB: callers that build multislice jobs
        must reserve this port with the host port scheduler exactly like
        ``coordinator_port`` — the +1 default is a convention, not a
        reservation."""
        return self.megascale_port or self.coordinator_port + 1

    @property
    def megascale_address(self) -> str:
        """libtpu's megascale rendezvous expects the coordinator on slice 0
        worker 0 — anchored to slice 0's lowest process id, NOT to global
        process 0 (which may live on another slice)."""
        coord = min(
            (p for p in self.placements if p.slice_id == 0),
            key=lambda p: p.process_id,
        )
        return f"{coord.host}:{self.resolved_megascale_port}"


def bootstrap_jax(platform: str = "", virtual_devices: int = 0) -> None:
    """Shared entrypoint bootstrap (train/serve __main__s): optional virtual
    CPU devices + platform override, then ``jax.distributed.initialize`` from
    the env this module renders when the control plane launched a
    multi-process job. Must run before any backend use."""
    import os

    if virtual_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{virtual_devices}").strip()
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    n_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if n_processes > 1:
        if platform == "cpu" or os.environ.get("JAX_PLATFORMS", "") == "cpu":
            # cross-process collectives on the CPU backend go through gloo;
            # explicit so multi-process CPU jobs (tests, the dryrun analog
            # of a real pod) don't depend on the default — whether the
            # platform came from the argument or from container env
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except (AttributeError, ValueError) as e:  # older/newer jax
                import logging

                logging.getLogger(__name__).warning(
                    "could not select gloo CPU collectives (%s); "
                    "multi-process CPU collectives depend on jax default", e)
        jax.distributed.initialize(
            coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
            num_processes=n_processes,
            process_id=int(os.environ["JAX_PROCESS_ID"]),
        )


def _process_bounds(n_processes: int) -> str:
    """Arrange processes on a 1D DCN axis: "n,1,1" — the safe default that
    matches any chips-per-process shape; topology-shaped bounds are an
    optimization the scheduler can layer on later."""
    return f"{n_processes},1,1"


def render_distributed_env(job: DistributedJob, placement: ProcessPlacement) -> list[str]:
    """The JAX-side (DCN bootstrap) env for ONE process of the job; the
    libtpu-side TPU_* vars come from runtime.spec.render_tpu_attachment.

    Multislice jobs (num_slices > 1) additionally get the MEGASCALE_* vars
    libtpu's DCN transport reads — the stitching the reference's NCCL/MPI
    jobs would have configured by hand (SURVEY.md §2.3, comm-backend row).
    """
    env = [
        f"JAX_COORDINATOR_ADDRESS={job.coordinator_address}",
        f"JAX_NUM_PROCESSES={len(job.placements)}",
        f"JAX_PROCESS_ID={placement.process_id}",
    ]
    if job.num_slices > 1:
        env += [
            f"MEGASCALE_COORDINATOR_ADDRESS={job.megascale_address}",
            f"MEGASCALE_NUM_SLICES={job.num_slices}",
            f"MEGASCALE_SLICE_ID={placement.slice_id}",
            f"MEGASCALE_PORT={job.resolved_megascale_port}",
        ]
    return env


def render_job_specs(
    job: DistributedJob,
    topology: HostTopology,
    image: str,
    cmd: list[str],
    base_env: list[str] | None = None,
    libtpu_path: str = "",
) -> list[ContainerSpec]:
    """ContainerSpecs for every process of a distributed job — what the
    service layer submits to the runtime, one container per process
    (BASELINE.json config #4: scheduler places GSPMD DP ranks).

    Device mounts + TPU_* env come from the one renderer the container flows
    already use (runtime.spec.render_tpu_attachment), so patches stay
    idempotent; the coordinator and libtpu mesh ports are published as real
    PortBindings so bridge-networked containers are reachable.
    """
    from tpu_docker_api.runtime.spec import PortBinding, render_tpu_attachment

    # the libtpu ICI mesh (TPU_PROCESS_ADDRESSES / bounds / task id) is
    # per-SLICE: an ICI domain only spans one slice, and libtpu must not try
    # to assemble a mesh across hosts it has no ICI path to. MEGASCALE_*
    # (render_distributed_env) does the cross-slice stitching over DCN.
    by_slice: dict[int, list[ProcessPlacement]] = {}
    for p in job.placements:
        by_slice.setdefault(p.slice_id, []).append(p)
    for members in by_slice.values():
        members.sort(key=lambda p: p.process_id)

    specs = []
    for p in job.placements:
        slice_members = by_slice[p.slice_id]
        peers = [f"{m.host}:{m.tpu_process_port}" for m in slice_members]
        spec = ContainerSpec(
            name=f"{job.name}-p{p.process_id}",
            image=image,
            cmd=list(cmd),
            env=list(base_env or []) + render_distributed_env(job, p),
            port_bindings=[
                PortBinding(p.tpu_process_port, p.tpu_process_port)
            ],
        )
        if p.process_id == 0:
            spec.port_bindings.append(
                PortBinding(job.coordinator_port, job.coordinator_port)
            )
        if (job.num_slices > 1
                and p.slice_id == 0 and p is slice_members[0]):
            ms_port = job.resolved_megascale_port
            spec.port_bindings.append(PortBinding(ms_port, ms_port))
        render_tpu_attachment(
            spec, sorted(p.chip_ids), p.topology or topology,
            libtpu_path=libtpu_path,
            process_bounds=job.process_bounds
            or _process_bounds(len(slice_members)),
            task_id=slice_members.index(p),
            process_addresses=peers,
            process_port=p.tpu_process_port,
        )
        specs.append(spec)
    return specs
