"""JAX distributed-environment rendering.

The reference's only cross-container duty is port mapping
(service/container.go:489-501); a TPU control plane must also render the
distributed bootstrap so N containers initialize one JAX job over ICI/DCN
(SURVEY.md §2.3 "Communication backend" row):

- ``JAX_COORDINATOR_ADDRESS`` + ``JAX_NUM_PROCESSES`` + ``JAX_PROCESS_ID`` —
  consumed by ``jax.distributed.initialize`` inside the container;
- ``TPU_PROCESS_BOUNDS`` / ``TPU_CHIPS_PER_PROCESS_BOUNDS`` /
  ``TPU_PROCESS_ADDRESSES`` / ``TPU_PROCESS_PORT`` / ``CLOUD_TPU_TASK_ID`` —
  consumed by libtpu to assemble the slice mesh from per-process chip subsets.

Within one host ICI does the transport; across hosts the coordinator address
rides DCN. The coordinator's host port comes from the port scheduler — the
TPU analog of the reference's host-port rendering.
"""

from __future__ import annotations

import dataclasses

from tpu_docker_api.runtime.spec import ContainerSpec
from tpu_docker_api.scheduler.topology import HostTopology


@dataclasses.dataclass
class ProcessPlacement:
    """One JAX process (= one container) in a distributed job."""
    process_id: int
    host: str                 # routable address of the host running it
    chip_ids: list[int]       # host-local chips handed to this process
    tpu_process_port: int     # libtpu mesh port (host side)
    # per-host topology for multi-host pods (hosts may differ from the
    # control-plane host); None ⇒ use the topology passed to render_job_specs
    topology: HostTopology | None = None


@dataclasses.dataclass
class DistributedJob:
    """A placement of N processes forming one JAX job."""
    name: str
    placements: list[ProcessPlacement]
    coordinator_port: int
    # "gx,gy,gz" DCN process grid (the pod scheduler's host-block shape);
    # "" ⇒ safe 1D default from _process_bounds
    process_bounds: str = ""

    @property
    def coordinator_address(self) -> str:
        # process 0 publishes the coordinator PortBinding (render_job_specs),
        # so the address must name ITS host — placements order is not assumed
        coord = next(p for p in self.placements if p.process_id == 0)
        return f"{coord.host}:{self.coordinator_port}"


def _process_bounds(n_processes: int) -> str:
    """Arrange processes on a 1D DCN axis: "n,1,1" — the safe default that
    matches any chips-per-process shape; topology-shaped bounds are an
    optimization the scheduler can layer on later."""
    return f"{n_processes},1,1"


def render_distributed_env(job: DistributedJob, placement: ProcessPlacement) -> list[str]:
    """The JAX-side (DCN bootstrap) env for ONE process of the job; the
    libtpu-side TPU_* vars come from runtime.spec.render_tpu_attachment."""
    return [
        f"JAX_COORDINATOR_ADDRESS={job.coordinator_address}",
        f"JAX_NUM_PROCESSES={len(job.placements)}",
        f"JAX_PROCESS_ID={placement.process_id}",
    ]


def render_job_specs(
    job: DistributedJob,
    topology: HostTopology,
    image: str,
    cmd: list[str],
    base_env: list[str] | None = None,
    libtpu_path: str = "",
) -> list[ContainerSpec]:
    """ContainerSpecs for every process of a distributed job — what the
    service layer submits to the runtime, one container per process
    (BASELINE.json config #4: scheduler places GSPMD DP ranks).

    Device mounts + TPU_* env come from the one renderer the container flows
    already use (runtime.spec.render_tpu_attachment), so patches stay
    idempotent; the coordinator and libtpu mesh ports are published as real
    PortBindings so bridge-networked containers are reachable.
    """
    from tpu_docker_api.runtime.spec import PortBinding, render_tpu_attachment

    peers = [f"{p.host}:{p.tpu_process_port}" for p in job.placements]
    specs = []
    for p in job.placements:
        spec = ContainerSpec(
            name=f"{job.name}-p{p.process_id}",
            image=image,
            cmd=list(cmd),
            env=list(base_env or []) + render_distributed_env(job, p),
            port_bindings=[
                PortBinding(p.tpu_process_port, p.tpu_process_port)
            ],
        )
        if p.process_id == 0:
            spec.port_bindings.append(
                PortBinding(job.coordinator_port, job.coordinator_port)
            )
        render_tpu_attachment(
            spec, sorted(p.chip_ids), p.topology or topology,
            libtpu_path=libtpu_path,
            process_bounds=job.process_bounds or _process_bounds(len(job.placements)),
            task_id=p.process_id,
            process_addresses=peers,
            process_port=p.tpu_process_port,
        )
        specs.append(spec)
    return specs
