"""Process bootstrap / lifecycle.

Parity: reference ``cmd/gpu-docker-api/main.go`` — the go-svc ``Init/Start/
Stop`` triple. Init wires config → runtime → store → workQueue → schedulers →
versions in the same order (main.go:50-86); Start launches the HTTP server and
the work-queue sync loop (main.go:88-115); Stop drains and closes every
subsystem (main.go:117-130). Unlike the reference, scheduler/version state is
already durably persisted on every mutation, so Stop is not load-bearing for
correctness.

CLI: ``python -m tpu_docker_api -c etc/config.toml``.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from tpu_docker_api import config as config_mod
from tpu_docker_api.buildinfo import build_info
from tpu_docker_api.api.app import ApiServer, build_router
from tpu_docker_api.runtime import open_runtime
from tpu_docker_api.scheduler.pod import Pod, PodHost, PodScheduler
from tpu_docker_api.scheduler.ports import PortScheduler
from tpu_docker_api.scheduler.slices import ChipScheduler
from tpu_docker_api.scheduler.topology import HostTopology
from tpu_docker_api.service.container import ContainerService
from tpu_docker_api.service.job import JobService
from tpu_docker_api.service.volume import VolumeService
from tpu_docker_api.state import keys
from tpu_docker_api.state.kv import open_store
from tpu_docker_api.state.store import StateStore
from tpu_docker_api.state.version import VersionMap
from tpu_docker_api.state.workqueue import WorkQueue

log = logging.getLogger(__name__)


class Program:
    def __init__(self, cfg: config_mod.Config, host: str = "0.0.0.0",
                 kv=None, runtime=None, pod_runtimes=None) -> None:
        self.cfg = cfg
        self.host = host
        self.api_server: ApiServer | None = None
        # injection seam for the crash-consistency harness: a "restarted"
        # Program must boot over the SAME KV + runtime the dead one used
        # (with the default memory backend, open_store would hand each
        # Program a fresh empty store and hide every crash bug).
        # ``pod_runtimes`` extends the seam to multi-host pods: host_id →
        # runtime for non-local [[pod_hosts]] entries, so a "restarted"
        # daemon sees the same remote engines the dead one drove
        self._injected_kv = kv
        self._injected_runtime = runtime
        self._injected_pod_runtimes = pod_runtimes or {}

    def init(self) -> None:
        cfg = self.cfg
        from tpu_docker_api.telemetry.metrics import MetricsRegistry

        # metrics first: the work queue's degradation counters need a home
        # before any durable submit can happen
        self.metrics = MetricsRegistry()
        self.kv = self._injected_kv or open_store(
            cfg.store_backend, etcd_addr=cfg.etcd_addr,
            sqlite_path=cfg.sqlite_path,
            retry_attempts=cfg.store_retry_attempts,
            retry_base_s=cfg.store_retry_base_s,
            retry_max_s=cfg.store_retry_max_s,
        )
        self.store = StateStore(self.kv)
        self.runtime = self._injected_runtime or (
            open_runtime("docker", docker_host=cfg.docker_host)
            if cfg.runtime_backend == "docker"
            else open_runtime("fake", allow_exec=True)
        )
        self.wq = WorkQueue(
            self.kv,
            submit_timeout_s=cfg.queue_submit_timeout_s,
            close_deadline_s=cfg.queue_close_deadline_s,
            metrics=self.metrics,
        )
        topology = self._discover_topology()
        self.chip_scheduler = ChipScheduler(topology, self.kv)
        self.port_scheduler = PortScheduler(
            self.kv, cfg.start_port, cfg.end_port
        )
        self.container_versions = VersionMap(self.kv, keys.VERSIONS_CONTAINER_KEY)
        self.volume_versions = VersionMap(self.kv, keys.VERSIONS_VOLUME_KEY)
        self.container_svc = ContainerService(
            self.runtime, self.store, self.chip_scheduler, self.port_scheduler,
            self.container_versions, self.wq, libtpu_path=cfg.libtpu_path,
        )
        self.volume_svc = VolumeService(
            self.runtime, self.store, self.volume_versions, self.wq
        )
        self.pod = self._build_pod(topology)
        self.pod_scheduler = PodScheduler(self.pod, self.kv)
        self.job_versions = VersionMap(self.kv, keys.VERSIONS_JOB_KEY)
        self.job_svc = JobService(
            self.pod, self.pod_scheduler, self.store, self.job_versions,
            libtpu_path=cfg.libtpu_path,
        )
        from tpu_docker_api.service.host_health import HostMonitor
        from tpu_docker_api.service.job_supervisor import JobSupervisor
        from tpu_docker_api.service.reconcile import Reconciler

        # host failure domains: engine probing + healthy→suspect→down per
        # host; built before the supervisor so its down-verdicts gate the
        # supervisor's migrate-vs-hands-off decision from the first poll
        self.host_monitor = None
        if cfg.host_probe_interval_s > 0:
            self.host_monitor = HostMonitor(
                self.pod, self.pod_scheduler,
                interval_s=cfg.host_probe_interval_s,
                down_grace_s=cfg.host_down_grace_s,
                job_svc=self.job_svc, job_versions=self.job_versions,
                work_queue=self.wq,
                registry=self.metrics,
                # late-bound: the supervisor is constructed just below —
                # a confirmed-down host must wake it immediately, not
                # wait out the poll interval
                on_down=lambda hid: self.job_supervisor.wake(hid),
            )
        # gang supervision (whole-gang restart with backoff, crash-loop →
        # terminal failed; host-down → migration): built in init so the
        # startup reconcile and the watcher's delegation hook can use it
        # before start()
        self.job_supervisor = JobSupervisor(
            self.pod, self.job_svc, self.store, self.job_versions,
            interval_s=cfg.job_supervise_interval,
            max_restarts=cfg.job_max_restarts,
            max_migrations=cfg.job_max_migrations,
            backoff_base_s=cfg.job_backoff_base_s,
            backoff_max_s=cfg.job_backoff_max_s,
            backoff_jitter=cfg.job_backoff_jitter,
            registry=self.metrics,
            host_monitor=self.host_monitor,
        )
        # job families allocate from the same local chip/port pools, so
        # their claims must be off-limits to the reconciler's leak sweep
        self.reconciler = Reconciler(
            self.runtime, self.store, self.chip_scheduler,
            self.port_scheduler, self.container_versions,
            container_svc=self.container_svc,
            shared_version_maps=[self.job_versions],
            job_svc=self.job_svc, job_versions=self.job_versions,
            job_max_restarts=cfg.job_max_restarts,
            job_max_migrations=cfg.job_max_migrations,
            registry=self.metrics,
            # durable-queue adoption: the startup sweep replays the journal
            # a dead daemon left (pending/in-flight records) before judging
            # family state
            work_queue=self.wq,
        )

    def _build_pod(self, local_topology: HostTopology) -> Pod:
        """Multi-host pod from [[pod_hosts]] config, else a single-host pod
        wrapping this host's runtime + schedulers (SURVEY.md hard part #3 —
        the reference is locked to one docker socket)."""
        cfg = self.cfg
        if not cfg.pod_hosts:
            return Pod.single_host(PodHost(
                host_id="local", address="127.0.0.1", grid_coord=(0, 0, 0),
                topology=local_topology, runtime=self.runtime,
                chips=self.chip_scheduler, ports=self.port_scheduler,
            ))
        hosts = []
        for entry in cfg.pod_hosts:
            host_id = entry["host_id"]
            if entry.get("local", False):
                # THIS machine: share the container service's runtime and
                # schedulers so local chips have exactly one accounting
                # (otherwise POST /containers and POST /jobs would both hand
                # out the same physical chips from separate pools)
                hosts.append(PodHost(
                    host_id=host_id,
                    address=entry["address"],
                    grid_coord=tuple(entry.get("grid_coord", [0, 0, 0])),
                    topology=local_topology,
                    runtime=self.runtime,
                    chips=self.chip_scheduler,
                    ports=self.port_scheduler,
                ))
                continue
            runtime = self._injected_pod_runtimes.get(host_id) or (
                open_runtime("docker", docker_host=entry.get(
                    "docker_host", cfg.docker_host))
                if entry.get("runtime_backend", cfg.runtime_backend) == "docker"
                else open_runtime("fake", allow_exec=True)
            )
            if cfg.breaker_threshold > 0:
                # circuit breaker per REMOTE engine: a dead socket must
                # cost one timeout, not one per caller per poll. The local
                # host's runtime stays unwrapped — it is shared with the
                # container service, and a local dockerd outage takes the
                # daemon with it anyway
                from tpu_docker_api.service.host_health import BreakerRuntime

                runtime = BreakerRuntime(
                    runtime, host_id=host_id,
                    threshold=cfg.breaker_threshold,
                    # cooldown tied to the probe interval so every monitor
                    # tick past it doubles as the half-open recovery probe
                    cooldown_s=cfg.host_probe_interval_s or 5.0,
                )
            topo = HostTopology.build(
                entry.get("accelerator_type", cfg.accelerator_type))
            hosts.append(PodHost(
                host_id=host_id,
                address=entry["address"],
                grid_coord=tuple(entry.get("grid_coord", [0, 0, 0])),
                topology=topo,
                runtime=runtime,
                chips=ChipScheduler(topo, self.kv, keys.host_chips_key(host_id)),
                ports=PortScheduler(self.kv, cfg.start_port, cfg.end_port,
                                    store_key=keys.host_ports_key(host_id)),
            ))
        grid = tuple(
            max(h.grid_coord[d] for h in hosts) + 1 for d in range(3)
        )
        gen = hosts[0].topology.generation
        return Pod(gen, grid, hosts)  # type: ignore[arg-type]

    def _discover_topology(self) -> HostTopology:
        """Topology from the telemetry sidecar if configured (the reference's
        first-boot detect-gpu fetch, gpuscheduler/scheduler.go:142-158), else
        from local probe, else synthesized from config accelerator_type."""
        cfg = self.cfg
        if cfg.detect_tpu_addr:
            import requests

            resp = requests.get(
                cfg.detect_tpu_addr.rstrip("/") + "/api/v1/detect/tpu", timeout=5
            )
            resp.raise_for_status()
            from tpu_docker_api.schemas.tpu import HostTopologyInfo
            from tpu_docker_api.telemetry.probe import topology_from_info

            return topology_from_info(HostTopologyInfo.from_dict(resp.json()["data"]))
        from tpu_docker_api.telemetry.probe import probe_local_topology

        local = probe_local_topology()
        if local is not None:
            log.info("using locally probed topology: %d chips", local.n_chips)
            return local
        log.info("no TPU hardware detected; topology from config %s",
                 cfg.accelerator_type)
        return HostTopology.build(cfg.accelerator_type)

    def start(self) -> None:
        self.wq.start()
        if self.cfg.reconcile_on_start:
            # repair whatever a previous incarnation left half-done BEFORE
            # serving traffic (an interrupted rolling replace must not be
            # visible as two live versions). A failed sweep must not block
            # boot — a recovery feature that crash-loops the daemon is worse
            # than the drift it would repair
            try:
                report = self.reconciler.reconcile()
                if report["actions"]:
                    log.warning("startup reconcile repaired %d drift(s): %s",
                                report["driftCount"],
                                [a["action"] for a in report["actions"]])
            except Exception:  # noqa: BLE001
                log.exception("startup reconcile failed; serving anyway "
                              "(rerun via /api/v1/reconcile)")
        if self.cfg.reconcile_interval > 0:
            self.reconciler.start_periodic(self.cfg.reconcile_interval)
        if self.cfg.job_supervise_interval > 0:
            self.job_supervisor.start()
        if self.host_monitor is not None:
            self.host_monitor.start()
        self.health_watcher = None
        if self.cfg.health_watch_interval > 0:
            from tpu_docker_api.service.watch import HealthWatcher

            self.health_watcher = HealthWatcher(
                self.runtime,
                interval_s=self.cfg.health_watch_interval,
                restart_policy=self.cfg.restart_policy,
                crash_handler=self.container_svc.handle_crash,
                # gang members are the supervisor's: the container path
                # declines them (never restart one member in isolation).
                # Only wired when the supervisor loop actually runs —
                # delegating to a stopped supervisor would strand crashed
                # members with no recovery path at all
                job_crash_handler=(
                    self.job_supervisor.handle_member_death
                    if self.cfg.job_supervise_interval > 0 else None),
                restart_backoff_s=self.cfg.restart_backoff_s,
                restart_backoff_max_s=self.cfg.restart_backoff_max_s,
                registry=self.metrics,
            )
            self.health_watcher.start()
        router = build_router(
            self.container_svc, self.volume_svc,
            self.chip_scheduler, self.port_scheduler, work_queue=self.wq,
            health_watcher=self.health_watcher, metrics=self.metrics,
            job_svc=self.job_svc, pod_scheduler=self.pod_scheduler,
            reconciler=self.reconciler, job_supervisor=self.job_supervisor,
            host_monitor=self.host_monitor,
        )
        bi = build_info()  # warm the git probe BEFORE serving /healthz
        self.api_server = ApiServer(router, host=self.host, port=self.cfg.port)
        self.api_server.start()
        log.info("tpu-docker-api %s (%s@%s) serving on %s:%d "
                 "(%d chips, ports %d-%d)",
                 bi["version"], bi["branch"], bi["commit"],
                 self.host, self.api_server.port,
                 self.chip_scheduler.topology.n_chips,
                 self.cfg.start_port, self.cfg.end_port)

    def stop(self) -> None:
        if self.api_server:
            self.api_server.close()
        if getattr(self, "health_watcher", None) is not None:
            self.health_watcher.close()
        if getattr(self, "host_monitor", None) is not None:
            self.host_monitor.close()
        if getattr(self, "job_supervisor", None) is not None:
            self.job_supervisor.close()
        if getattr(self, "reconciler", None) is not None:
            self.reconciler.close()
        self.wq.close()
        for host in self.pod.hosts.values():
            if host.runtime is not self.runtime:
                host.runtime.close()
        self.runtime.close()
        self.kv.close()
        log.info("tpu-docker-api stopped")


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="tpu-docker-api")
    parser.add_argument("-c", "--config", default=None, help="TOML config path")
    parser.add_argument("--host", default="0.0.0.0")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    prg = Program(config_mod.load(args.config), host=args.host)
    prg.init()
    prg.start()

    done = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    prg.stop()


if __name__ == "__main__":
    main()
