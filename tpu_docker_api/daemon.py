"""Process bootstrap / lifecycle.

Parity: reference ``cmd/gpu-docker-api/main.go`` — the go-svc ``Init/Start/
Stop`` triple. Init wires config → runtime → store → workQueue → schedulers →
versions in the same order (main.go:50-86); Start launches the HTTP server and
the work-queue sync loop (main.go:88-115); Stop drains and closes every
subsystem (main.go:117-130). Unlike the reference, scheduler/version state is
already durably persisted on every mutation, so Stop is not load-bearing for
correctness.

CLI: ``python -m tpu_docker_api -c etc/config.toml``.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from tpu_docker_api import config as config_mod
from tpu_docker_api.buildinfo import build_info
from tpu_docker_api.api.app import ApiServer, build_router
from tpu_docker_api.runtime import open_runtime
from tpu_docker_api.scheduler.pod import Pod, PodHost, PodScheduler
from tpu_docker_api.scheduler.ports import PortScheduler
from tpu_docker_api.scheduler.slices import ChipScheduler
from tpu_docker_api.scheduler.topology import HostTopology
from tpu_docker_api.service.container import ContainerService
from tpu_docker_api.service.job import JobService
from tpu_docker_api.service.volume import VolumeService
from tpu_docker_api.state import keys
from tpu_docker_api.state.kv import open_store
from tpu_docker_api.state.store import StateStore
from tpu_docker_api.state.version import VersionMap
from tpu_docker_api.state.workqueue import WorkQueue

log = logging.getLogger(__name__)


class Program:
    def __init__(self, cfg: config_mod.Config, host: str = "0.0.0.0",
                 kv=None, runtime=None, pod_runtimes=None,
                 leader_clock=None) -> None:
        self.cfg = cfg
        self.host = host
        self.api_server: ApiServer | None = None
        # injection seam for the crash-consistency harness: a "restarted"
        # Program must boot over the SAME KV + runtime the dead one used
        # (with the default memory backend, open_store would hand each
        # Program a fresh empty store and hide every crash bug).
        # ``pod_runtimes`` extends the seam to multi-host pods: host_id →
        # runtime for non-local [[pod_hosts]] entries, so a "restarted"
        # daemon sees the same remote engines the dead one drove.
        # ``leader_clock`` extends it to the leader lease: the failover
        # chaos harness drives TTL expiry with a virtual clock
        self._injected_kv = kv
        self._injected_runtime = runtime
        self._injected_pod_runtimes = pod_runtimes or {}
        self._injected_leader_clock = leader_clock

    def init(self) -> None:
        cfg = self.cfg
        from tpu_docker_api.telemetry.metrics import MetricsRegistry

        # metrics first: the work queue's degradation counters need a home
        # before any durable submit can happen
        self.metrics = MetricsRegistry()
        # tracer second: every subsystem below records spans into this one
        # sink (one per Program — multi-daemon test processes must not
        # cross-contaminate buffers); tracing_enabled=false makes every
        # span site a no-op
        from tpu_docker_api.telemetry.trace import Tracer

        self.tracer = Tracer(buffer_size=cfg.trace_buffer_size,
                             enabled=cfg.tracing_enabled,
                             registry=self.metrics,
                             slow_ms=cfg.trace_slow_ms)
        raw_kv = self._injected_kv or open_store(
            cfg.store_backend, etcd_addr=cfg.etcd_addr,
            sqlite_path=cfg.sqlite_path,
            retry_attempts=cfg.store_retry_attempts,
            retry_base_s=cfg.store_retry_base_s,
            retry_max_s=cfg.store_retry_max_s,
            # per-op deadline: a hung store surfaces as a typed
            # StoreUnavailable in bounded time instead of a wedged thread
            # (0 = each backend's historical timeout, byte-for-byte)
            op_deadline_s=cfg.store_op_deadline_s,
        )
        # store failure domain (service/store_health.py, docs/robustness.md
        # "Store brownouts"): every op through this daemon is measured and
        # classified — purely observational on the healthy path (zero extra
        # round trips) — feeding the healthy→degraded→outage machine that
        # gates mutations, writer loops and the stale-read contract below.
        # Installed UNDER every other wrapper (fencing, sharding, informer),
        # so leader renewals and informer relists double as outage probes.
        from tpu_docker_api.service.store_health import (StoreHealth,
                                                         StoreHealthKV)

        self.store_health = StoreHealth(
            fail_threshold=cfg.store_health_fail_threshold,
            outage_grace_s=cfg.store_health_outage_grace_s,
            probe_interval_s=cfg.store_health_probe_interval_s,
            registry=self.metrics,
        )
        raw_kv = StoreHealthKV(raw_kv, self.store_health)
        #: the writer-loop hold: observe, don't act, while the store is out
        store_gate = self.store_health.allows_writes
        self._raw_kv = raw_kv
        self.leader_elector = None
        self.shard_plane = None
        self.shard_map = None
        #: serializes shard acquire/loss callbacks (each shard's elector
        #: heartbeats on its own thread) against the shared writer loops
        self._shard_mu = threading.Lock()
        self._shard_writers_on = False
        if cfg.leader_election and cfg.shard_count > 1:
            # sharded writer plane (service/shard.py, docs/robustness.md
            # "Sharded writer plane"): N leases instead of one. Every
            # write batch is fenced on the epochs of exactly the shards
            # it touches, cross-shard batches serialize through the
            # coordination record, and the acquire/loss callbacks below
            # start/stop the writer loops per shard-portfolio instead of
            # per-lease. shard_count=1 never reaches this branch — the
            # PR 7 single-elector path below stays byte-for-byte.
            import os
            import socket

            from tpu_docker_api.service.shard import (ShardMap, ShardPlane,
                                                      ShardedKV)

            holder = cfg.leader_id or f"{socket.gethostname()}:{os.getpid()}"
            plane_kwargs = {}
            if self._injected_leader_clock is not None:
                plane_kwargs["clock"] = self._injected_leader_clock
            self.shard_map = ShardMap(cfg.shard_count)
            # the plane rides the RAW store (lease writes carry their own
            # CAS guards); callbacks resolve the subsystems built below
            # lazily — electors only start in start()/step()
            self.shard_plane = ShardPlane(
                raw_kv, self.shard_map, holder,
                ttl_s=cfg.leader_ttl_s,
                renew_interval_s=cfg.leader_renew_interval_s or None,
                advertise=f"{self.host}:{cfg.port}",
                on_acquire=self._on_shard_acquire,
                on_loss=self._on_shard_loss,
                preferred=frozenset(cfg.shard_preferred),
                defer_vacant_s=cfg.shard_standby_delay_s,
                **plane_kwargs,
            )
            self.kv = ShardedKV(raw_kv, self.shard_plane)
        elif cfg.leader_election:
            # HA fleet member: EVERY write this process issues — StoreTxn
            # commits, journal claim/ack, scheduler persists — carries an
            # epoch-fencing guard once the elector has held leadership, so
            # a deposed leader's in-flight writes fail typed instead of
            # corrupting state the new leader owns. The elector itself is
            # constructed at the end of init (its callbacks start/stop the
            # writer subsystems built below); the fence closure reads it
            # late. leader_election = false skips the wrapper entirely:
            # single-process deployments keep today's store byte-for-byte
            from tpu_docker_api.service.leader import FencedKV

            self.kv = FencedKV(raw_kv, self._fence_guards)
        else:
            self.kv = raw_kv
        # watch-fed standby read path (state/informer.py, ROADMAP item 2's
        # "stateless API replicas serving reads from watch-fed caches"):
        # one informer mirrors the whole /apis/v1 tree off the RAW store
        # (watch is a read; fencing never applies), and read_kv routes
        # get/range_prefix to that mirror while this replica stands by —
        # zero store round trips per GET, staleness bounded by watch lag.
        # Writes, leader reads, degraded-informer reads and the whole
        # read_cache="read-through" / leader_election=false configuration
        # pass through to self.kv byte-for-byte.
        self.informer = None
        read_kv = self.kv
        if (cfg.leader_election and cfg.read_cache == "informer"
                and self.shard_plane is None):
            from tpu_docker_api.state.informer import Informer, InformerReadKV

            self.informer = Informer(raw_kv, keys.PREFIX + "/",
                                     registry=self.metrics)
            # store_health hookup: during a store OUTAGE reads ride the
            # (possibly stale) mirror with explicit staleness, instead of
            # burning a deadline-bounded store failure per GET
            read_kv = InformerReadKV(self.kv, self.informer,
                                     active=self._standby_reads_active,
                                     store_health=self.store_health)
        self.read_kv = read_kv
        self.store = StateStore(read_kv)
        # runtime fan-out: ONE bounded pool for the whole process (job
        # service, supervisor, host monitor, reconciler), so total engine
        # concurrency is capped by fanout_workers rather than multiplied
        # across subsystems. workers=1 (default) is the serial singleton
        # behavior with per-op telemetry
        from tpu_docker_api.runtime.fanout import Fanout

        self.fanout = Fanout(cfg.fanout_workers, registry=self.metrics,
                             name="pod")
        self.metrics.gauge_fn(
            "fanout_inflight", self.fanout.inflight,
            help="Engine calls currently submitted to the fan-out pool")
        self.metrics.gauge_fn(
            "fanout_workers", lambda: self.fanout.workers,
            help="Fan-out pool size (config fanout_workers)")
        self.runtime = self._injected_runtime or (
            open_runtime("docker", docker_host=cfg.docker_host,
                         pool_size=cfg.engine_pool_size)
            if cfg.runtime_backend == "docker"
            else open_runtime("fake", allow_exec=True)
        )
        wq_shard_kwargs = {}
        if self.shard_plane is not None:
            # journal records land in the owning shard's sub-prefix and
            # replay/sweep only over shards this process leads
            wq_shard_kwargs = {
                "shard_fn": self._task_shard,
                "owned_shards": lambda: self.shard_plane.held,
            }
        self.wq = WorkQueue(
            self.kv,
            submit_timeout_s=cfg.queue_submit_timeout_s,
            close_deadline_s=cfg.queue_close_deadline_s,
            dead_letter_retry_budget=cfg.queue_dead_letter_retry_budget,
            metrics=self.metrics,
            tracer=self.tracer,
            store_gate=store_gate,
            **wq_shard_kwargs,
        )
        topology = self._discover_topology()
        self.chip_scheduler = ChipScheduler(topology, self.kv)
        self.port_scheduler = PortScheduler(
            self.kv, cfg.start_port, cfg.end_port
        )
        # read-through while STANDING BY: the leader creates, rolls and
        # deletes families behind this replica's back, so a standby's
        # version reads must re-seed from the store every time (staleness
        # bounded by one read). The callable resolves the role live —
        # once this replica leads, its own map is authoritative again and
        # the extra reads stop
        standby_read_through = (
            (lambda: self.leader_elector is not None
             and not self.leader_elector.is_leader)
            if cfg.leader_election else False)
        if self.shard_plane is not None:
            # per-shard version maps: each shard's snapshot persists at its
            # own key (riding that shard's epoch fence), and reads on
            # shards this process does NOT lead go read-through — the
            # PR 7 leader/standby read contract applied per shard
            from tpu_docker_api.state.version import ShardedVersionMap

            def _svm(resource):
                return ShardedVersionMap(read_kv, self.shard_map, resource,
                                         self.shard_plane.is_leader)
            self._make_versions = _svm
        else:
            _legacy_keys = {
                keys.Resource.CONTAINERS: keys.VERSIONS_CONTAINER_KEY,
                keys.Resource.VOLUMES: keys.VERSIONS_VOLUME_KEY,
                keys.Resource.JOBS: keys.VERSIONS_JOB_KEY,
                keys.Resource.SERVICES: keys.VERSIONS_SERVICE_KEY,
                keys.Resource.WORKFLOWS: keys.VERSIONS_WORKFLOW_KEY,
            }

            def _vm(resource):
                return VersionMap(read_kv, _legacy_keys[resource],
                                  read_through=standby_read_through)
            self._make_versions = _vm
        self.container_versions = self._make_versions(keys.Resource.CONTAINERS)
        self.volume_versions = self._make_versions(keys.Resource.VOLUMES)
        self.container_svc = ContainerService(
            self.runtime, self.store, self.chip_scheduler, self.port_scheduler,
            self.container_versions, self.wq, libtpu_path=cfg.libtpu_path,
        )
        self.volume_svc = VolumeService(
            self.runtime, self.store, self.volume_versions, self.wq
        )
        self.pod = self._build_pod(topology)
        self.pod_scheduler = PodScheduler(self.pod, self.kv)
        self.job_versions = self._make_versions(keys.Resource.JOBS)
        if self.informer is not None:
            # standby version reads go fully watch-fed: zero store reads
            # AND zero JSON re-parses per request (the shadow updates on
            # events, not on reads); the informer-degraded fallback inside
            # VersionMap keeps the old read-through staleness bound
            for vm in (self.container_versions, self.volume_versions,
                       self.job_versions):
                vm.attach_informer(self.informer)
        self.job_svc = JobService(
            self.pod, self.pod_scheduler, self.store, self.job_versions,
            libtpu_path=cfg.libtpu_path, fanout=self.fanout,
            registry=self.metrics,
            # elastic gangs (docs/robustness.md "Elastic gangs"): one gate
            # + one loop bound, consulted by every resize decision site
            # (supervisor, drain, admission) through the job service
            resize_enabled=cfg.job_resize_enabled,
            resize_max=cfg.job_resize_max,
        )
        # capacity market (service/admission.py): constructed
        # unconditionally — priority-class validation and submit-seq
        # seniority stamping apply even without the market — while
        # admission_enabled gates the policy itself (queue/preempt/
        # backfill); disabled keeps the legacy hard refusal byte-for-byte
        from tpu_docker_api.service.admission import AdmissionController

        adm_shard_kwargs = {}
        if self.shard_plane is not None:
            adm_shard_kwargs = {
                "shard_fn": self.shard_map.shard_of,
                "owned_shards": lambda: self.shard_plane.held,
            }
        self.admission = AdmissionController(
            self.job_svc, self.store, self.job_versions,
            self.pod_scheduler, self.kv,
            enabled=cfg.admission_enabled,
            classes=cfg.priority_class_weights,
            default_class=cfg.priority_class_default,
            max_skips=cfg.admission_max_skips,
            interval_s=cfg.admission_interval_s,
            registry=self.metrics,
            tracer=self.tracer,
            store_gate=store_gate,
            **adm_shard_kwargs,
        )
        self.job_svc.admission = self.admission
        # Service resource (service/serving.py): declarative replicated
        # serving over replica gangs, scaled by the SLO-driven autoscaler
        # through the capacity market at the service's priority class
        from tpu_docker_api.service.serving import ServingService

        self.service_versions = self._make_versions(keys.Resource.SERVICES)
        if self.informer is not None:
            self.service_versions.attach_informer(self.informer)
        self.serving = ServingService(
            self.job_svc, self.store, self.service_versions,
            self.job_versions, admission=self.admission,
            default_class=cfg.service_default_class,
            interval_s=cfg.autoscale_interval_s,
            up_cooldown_s=cfg.autoscale_up_cooldown_s,
            down_cooldown_s=cfg.autoscale_down_cooldown_s,
            down_watermark=cfg.autoscale_down_watermark,
            registry=self.metrics,
            tracer=self.tracer,
            owns=self._owns_or_none(),
            store_gate=store_gate,
        )
        # Workflow resource (service/workflow.py): durable DAG orchestration
        # over job steps — every step transition a journaled task record
        # (exactly-once across crashes), promote steps rolling Services,
        # cron re-fires with explicit catch-up semantics
        from tpu_docker_api.service.workflow import WorkflowService

        self.workflow_versions = self._make_versions(keys.Resource.WORKFLOWS)
        if self.informer is not None:
            self.workflow_versions.attach_informer(self.informer)
        self.workflow = WorkflowService(
            self.job_svc, self.store, self.workflow_versions,
            self.job_versions, work_queue=self.wq, serving=self.serving,
            admission=self.admission,
            default_class=cfg.workflow_default_class,
            max_step_retries=cfg.workflow_max_step_retries,
            backoff_base_s=cfg.workflow_backoff_base_s,
            backoff_max_s=cfg.workflow_backoff_max_s,
            interval_s=cfg.workflow_interval_s,
            registry=self.metrics,
            tracer=self.tracer,
            owns=self._owns_or_none(),
            store_gate=store_gate,
        )
        # engine-pool saturation gauges: one labeled sample per DISTINCT
        # engine behind this pod (the local runtime is shared by several
        # PodHost entries; BreakerRuntime/FaultyRuntime delegate pool_view
        # to the transport underneath). The endpoint label is the engine's
        # host set — cardinality bounded by pod size, and a removed host's
        # series disappears at the next scrape (pull-time rendering)
        self.metrics.gauge_series_fn(
            "engine_pool_in_use",
            lambda: self._engine_pool_series("inUse"),
            help="Engine keep-alive connections currently in use, "
                 "per engine endpoint")
        self.metrics.gauge_series_fn(
            "engine_pool_idle",
            lambda: self._engine_pool_series("idle"),
            help="Idle engine keep-alive connections retained, "
                 "per engine endpoint")
        from tpu_docker_api.service.host_health import HostMonitor
        from tpu_docker_api.service.job_supervisor import JobSupervisor
        from tpu_docker_api.service.reconcile import Reconciler

        # host failure domains: engine probing + healthy→suspect→down per
        # host; built before the supervisor so its down-verdicts gate the
        # supervisor's migrate-vs-hands-off decision from the first poll
        self.host_monitor = None
        if cfg.host_probe_interval_s > 0:
            self.host_monitor = HostMonitor(
                self.pod, self.pod_scheduler,
                interval_s=cfg.host_probe_interval_s,
                down_grace_s=cfg.host_down_grace_s,
                job_svc=self.job_svc, job_versions=self.job_versions,
                work_queue=self.wq,
                registry=self.metrics,
                fanout=self.fanout,
                # late-bound: the supervisor is constructed just below —
                # a confirmed-down host must wake it immediately, not
                # wait out the poll interval
                on_down=lambda hid: self.job_supervisor.wake(hid),
                store_gate=store_gate,
            )
        # gang supervision (whole-gang restart with backoff, crash-loop →
        # terminal failed; host-down → migration): built in init so the
        # startup reconcile and the watcher's delegation hook can use it
        # before start()
        self.job_supervisor = JobSupervisor(
            self.pod, self.job_svc, self.store, self.job_versions,
            interval_s=cfg.job_supervise_interval,
            max_restarts=cfg.job_max_restarts,
            max_migrations=cfg.job_max_migrations,
            backoff_base_s=cfg.job_backoff_base_s,
            backoff_max_s=cfg.job_backoff_max_s,
            backoff_jitter=cfg.job_backoff_jitter,
            registry=self.metrics,
            host_monitor=self.host_monitor,
            fanout=self.fanout,
            owns=self._owns_or_none(),
            store_gate=store_gate,
        )
        # job families allocate from the same local chip/port pools, so
        # their claims must be off-limits to the reconciler's leak sweep
        self.reconciler = Reconciler(
            self.runtime, self.store, self.chip_scheduler,
            self.port_scheduler, self.container_versions,
            container_svc=self.container_svc,
            shared_version_maps=[self.job_versions],
            job_svc=self.job_svc, job_versions=self.job_versions,
            job_max_restarts=cfg.job_max_restarts,
            job_max_migrations=cfg.job_max_migrations,
            registry=self.metrics,
            # durable-queue adoption: the startup sweep replays the journal
            # a dead daemon left (pending/in-flight records) before judging
            # family state
            work_queue=self.wq,
            fanout=self.fanout,
            # admission-journal adoption (enabled deployments only): purge/
            # settle/re-journal records after the family passes repaired
            # any half-preempted gang
            admission=self.admission if cfg.admission_enabled else None,
            # Service adoption: converge every service to one fully-owned
            # replica set after a crash (missing/surplus/orphan replicas,
            # interrupted deletes and spec rolls)
            serving=self.serving,
            # Workflow adoption: finish interrupted step transitions, GC
            # finished/orphan step gangs, settle terminal workflows
            workflow=self.workflow,
            full_interval_s=cfg.reconcile_full_interval_s,
            tracer=self.tracer,
            owns=self._owns_or_none(),
            owned_shards=(None if self.shard_plane is None
                          else (lambda: self.shard_plane.held)),
            store_gate=store_gate,
        )
        # loss-free recovery: the instant the store heals, treat EVERYTHING
        # as changed (an outage swallows an unknown set of events) and wake
        # the supervisor — the next reconcile pass relists, replays the
        # journal and repairs whatever drifted while the writers held
        self.store_health.on_recover(self._on_store_recover)
        # event-driven reconcile (ROADMAP item 4): feed the reconciler's
        # dirty-set from the store's watch stream so periodic passes are
        # O(changes). Reuses the read-path informer when one exists;
        # otherwise a dedicated reflector over the RAW store (watch is a
        # read — fencing never applies). reconcile_full_interval_s = 0
        # (default) skips all of this: every pass stays a full scan.
        self.reconcile_informer = None
        if cfg.reconcile_full_interval_s > 0:
            feed = self.informer
            if feed is None:
                from tpu_docker_api.state.informer import Informer

                feed = Informer(raw_kv, keys.PREFIX + "/",
                                registry=self.metrics)
                self.reconcile_informer = feed
            self.reconciler.attach_dirty_feed(feed)
        # L7 serving gateway (service/gateway.py, api/gateway_app.py): a
        # stateless ingress on its own listener — drain-aware zero-drop
        # routing, retry/hedge budgets, breakers, outlier ejection, typed
        # load shedding. The routing table registers on the informer feed
        # HERE (before start() lists) so the initial snapshot seeds it;
        # the DrainCoordinator hooks the quiesce paths so rolls, scale-
        # downs and preemptions wait for gateway drain-acks.
        self.gateway = None
        self.gateway_server = None
        self.gateway_informer = None
        if cfg.gateway_enabled:
            from tpu_docker_api.api.gateway_app import GatewayServer
            from tpu_docker_api.service.gateway import (DrainCoordinator,
                                                        Gateway)

            self.gateway = Gateway(
                raw_kv,
                resolve_addr=lambda hid: (
                    self.pod.hosts[hid].address
                    if hid in self.pod.hosts else None),
                registry=self.metrics,
                tracer=self.tracer,
                signals=self.serving.replica_signal,
                request_timeout_s=cfg.gateway_request_timeout_s,
                connect_timeout_s=cfg.gateway_connect_timeout_s,
                retry_limit=cfg.gateway_retry_limit,
                retry_budget_ratio=cfg.gateway_retry_budget_ratio,
                hedge_ms=cfg.gateway_hedge_ms,
                breaker_threshold=cfg.gateway_breaker_threshold,
                breaker_cooldown_s=cfg.gateway_breaker_cooldown_s,
                outlier_latency_factor=cfg.gateway_outlier_latency_factor,
                max_inflight=cfg.gateway_max_inflight,
                max_inflight_per_endpoint=(
                    cfg.gateway_max_inflight_per_endpoint),
                pool_size=cfg.gateway_pool_size,
                heartbeat_s=cfg.gateway_heartbeat_s,
            )
            feed = self.informer or self.reconcile_informer
            if feed is None:
                from tpu_docker_api.state.informer import Informer

                feed = Informer(raw_kv, keys.PREFIX + "/",
                                registry=self.metrics)
                self.gateway_informer = feed
            self.gateway.table.attach(feed)
            self.gateway_server = GatewayServer(
                self.gateway, host=self.host, port=cfg.gateway_port)
            # control-plane half of the drain handshake: quiesce/preempt
            # paths wait (deadline-bounded) for every live gateway's ack
            # before the first member stop. The coordinator rides the RAW
            # store: instance heartbeats/acks are gateway-owned liveness
            # records, not fenced control-plane state
            self.job_svc.drain_coordinator = DrainCoordinator(
                raw_kv, heartbeat_s=cfg.gateway_heartbeat_s)
            self.job_svc.drain_deadline_s = cfg.gateway_drain_deadline_s
        # bounded history (service/compactor.py): a writer loop — started
        # leader-only in _start_writers — trimming version records past
        # history_retention_versions plus settled admission/marker garbage
        self.compactor = None
        if cfg.history_retention_versions > 0:
            from tpu_docker_api.service.compactor import HistoryCompactor

            self.compactor = HistoryCompactor(
                self.kv, self.store,
                maps=[(keys.Resource.CONTAINERS, self.container_versions),
                      (keys.Resource.VOLUMES, self.volume_versions),
                      (keys.Resource.JOBS, self.job_versions),
                      (keys.Resource.SERVICES, self.service_versions),
                      (keys.Resource.WORKFLOWS, self.workflow_versions)],
                retention=cfg.history_retention_versions,
                runtime=self.runtime, pod=self.pod, work_queue=self.wq,
                interval_s=cfg.history_compact_interval_s,
                registry=self.metrics,
                # trim under the same family locks the API flows hold, so
                # GC can never race a rollback/replace mid-read
                locks={keys.Resource.CONTAINERS:
                       self.container_svc.family_lock,
                       keys.Resource.JOBS: self.job_svc.family_lock},
                tracer=self.tracer,
                owns=self._owns_or_none(),
                store_gate=store_gate,
            )
        # constructed here (not in start) so the router always has the
        # instance regardless of role: on an HA standby the watcher exists
        # but only STARTS when the lease is acquired
        self.health_watcher = None
        if cfg.health_watch_interval > 0:
            from tpu_docker_api.service.watch import HealthWatcher

            self.health_watcher = HealthWatcher(
                self.runtime,
                interval_s=cfg.health_watch_interval,
                restart_policy=cfg.restart_policy,
                crash_handler=self.container_svc.handle_crash,
                # gang members are the supervisor's: the container path
                # declines them (never restart one member in isolation).
                # Only wired when the supervisor loop actually runs —
                # delegating to a stopped supervisor would strand crashed
                # members with no recovery path at all
                job_crash_handler=(
                    self.job_supervisor.handle_member_death
                    if cfg.job_supervise_interval > 0 else None),
                restart_backoff_s=cfg.restart_backoff_s,
                restart_backoff_max_s=cfg.restart_backoff_max_s,
                registry=self.metrics,
            )
        if cfg.leader_election and self.shard_plane is None:
            import os
            import socket

            from tpu_docker_api.service.leader import LeaderElector

            holder = cfg.leader_id or f"{socket.gethostname()}:{os.getpid()}"
            elector_kwargs = {}
            if self._injected_leader_clock is not None:
                elector_kwargs["clock"] = self._injected_leader_clock
            # the elector rides the RAW store: its lease writes carry their
            # own CAS guards (fencing the epoch bump on the epoch it
            # replaces would be circular)
            self.leader_elector = LeaderElector(
                raw_kv, holder_id=holder, ttl_s=cfg.leader_ttl_s,
                renew_interval_s=cfg.leader_renew_interval_s or None,
                on_acquire=lambda epoch: self._start_writers(),
                on_loss=lambda reason: self._stop_writers(),
                advertise=f"{self.host}:{cfg.port}",
                **elector_kwargs,
            )

    def _reload_caches(self) -> None:
        """Re-read every stateful mirror (version maps, slice registry +
        cordons, per-host chip/port maps — the local host's schedulers are
        shared with the pod, so the host walk covers them)."""
        for vm in (self.container_versions, self.volume_versions,
                   self.job_versions, self.service_versions,
                   self.workflow_versions):
            vm.reload_from_store()
        self.pod_scheduler.reload_from_store()
        for host in self.pod.hosts.values():
            host.chips.reload_from_store()
            host.ports.reload_from_store()

    def _on_store_recover(self) -> None:
        """StoreHealth outage→healthy hook (fires on the thread whose op
        proved the heal — must stay cheap and non-blocking): mark every
        family dirty and cut the writer loops' intervals short. The actual
        repair work — informer relist, journal replay, drift sweep — rides
        the loops' own threads."""
        self.reconciler.mark_all_dirty("store-recovered")
        self.job_supervisor.wake()

    def _owns_or_none(self):
        """Family-ownership filter handed to the writer loops: None in
        unsharded mode (loops visit everything, today's behavior), else
        the plane's lock-free owns() check."""
        return None if self.shard_plane is None else self.shard_plane.owns

    def _task_shard(self, kind: str, params: dict) -> int:
        """WorkQueue shard classifier: journal a task under the shard
        owning the family it mutates. Family-less tasks (raw put_kv) are
        classified by their target key; anything global lands on shard 0,
        the singleton-of-last-resort."""
        base = params.get("base")
        if base:
            return self.shard_map.shard_of(base)
        key = params.get("key")
        if key:
            shard = self.shard_map.shard_of_key(key)
            return 0 if shard is None else shard
        return 0

    def _on_shard_acquire(self, shard: int, epoch: int) -> None:
        """Shard-portfolio takeover. Per shard: reseed that shard's
        version maps, drop its journal seq cache, then adopt + replay its
        journal via a reconcile pass (exactly-once: markers + CAS claims,
        same machinery as single-leader failover). Process-wide: the
        writer loops start once, on the FIRST shard acquired — each loop
        filters its families through plane.owns, so one set of threads
        serves however many shards this process holds. Shard 0 is the
        singleton-of-last-resort: its holder also runs the host monitor
        and health watcher."""
        with self._shard_mu:
            for vm in (self.container_versions, self.volume_versions,
                       self.job_versions, self.service_versions,
                       self.workflow_versions):
                vm.reload_shard(shard)
            self.wq.reset_shard_cache(shard)
            self.admission.reset_seq_cache()
            # global singletons (schedulers, cordons) may have moved under
            # other shard leaders — or an earlier deployment — while we
            # did not hold this slice; every acquire adopts keyspace we
            # may never have observed, so reseed on every acquire
            self.pod_scheduler.reload_from_store()
            for host in self.pod.hosts.values():
                host.chips.reload_from_store()
                host.ports.reload_from_store()
            if not self._shard_writers_on:
                self.wq.start()
                if self.cfg.reconcile_interval > 0:
                    self.reconciler.start_periodic(self.cfg.reconcile_interval)
                if self.cfg.job_supervise_interval > 0:
                    self.job_supervisor.start()
                if (self.cfg.admission_enabled
                        and self.cfg.admission_interval_s > 0):
                    self.admission.start()
                if self.cfg.autoscale_interval_s > 0:
                    self.serving.start()
                if self.cfg.workflow_interval_s > 0:
                    self.workflow.start()
                if self.compactor is not None:
                    self.compactor.start()
                self._shard_writers_on = True
            if shard == 0:
                if self.host_monitor is not None:
                    self.host_monitor.start()
                if self.health_watcher is not None:
                    self.health_watcher.start()
        if self.cfg.reconcile_on_start:
            # journal-ownership handoff for THIS shard: the reconcile pass
            # replays the dead leader's pending records (owns-filtered, so
            # it touches only families of shards we now hold). Outside the
            # mutex — a long repair must not block another shard's
            # elector callback
            try:
                report = self.reconciler.reconcile()
                if report["actions"]:
                    log.warning(
                        "shard %d takeover reconcile repaired %d drift(s): %s",
                        shard, report["driftCount"],
                        [a["action"] for a in report["actions"]])
            except Exception:  # noqa: BLE001
                log.exception("shard %d takeover reconcile failed; serving "
                              "anyway (rerun via /api/v1/reconcile)", shard)

    def _on_shard_loss(self, shard: int, reason: str) -> None:
        """Blast-radius containment, the loss side: losing ONE shard's
        lease only narrows plane.owns — the loops keep running for the
        shards still held. Only losing the LAST shard stops the writer
        role (and losing shard 0 stops the singletons it carries)."""
        with self._shard_mu:
            if shard == 0:
                if self.host_monitor is not None:
                    self.host_monitor.close()
                if self.health_watcher is not None:
                    self.health_watcher.close()
            still = self.shard_plane.held - {shard}
            if self._shard_writers_on and not still:
                self._shard_writers_on = False
                if self.compactor is not None:
                    self.compactor.close()
                self.workflow.close()
                self.serving.close()
                self.admission.close()
                self.job_supervisor.close()
                self.reconciler.close()
                self.wq.close()

    def _engine_pool_stat(self, key: str) -> float:
        """Sum one connection-pool stat over the DISTINCT engines behind
        the pod (the local runtime backs several PodHost entries once —
        dedupe by identity; engines without a pool contribute 0)."""
        return sum(v for _, v in self._engine_pool_series(key))

    def _engine_pool_series(self, key: str) -> list[tuple[dict, float]]:
        """Per-engine connection-pool stat series for /metrics: one
        ``{endpoint=...}`` sample per DISTINCT engine (dedupe by runtime
        identity — the local dockerd backs several PodHost entries once).
        The endpoint label value is the sorted host-id set the engine
        serves, so cardinality is bounded by pod size and a shared
        engine renders as ONE series, never double-counted."""
        by_engine: dict[int, tuple] = {}
        for host_id in sorted(self.pod.hosts):
            rt = self.pod.hosts[host_id].runtime
            by_engine.setdefault(id(rt), (rt, []))[1].append(host_id)
        out = []
        for rt, host_ids in by_engine.values():
            try:
                v = rt.pool_view().get(key, 0)
            except AttributeError:
                continue
            out.append(({"endpoint": ",".join(host_ids)}, float(v)))
        return out

    def _fence_guards(self) -> list:
        """Fence closure for the FencedKV wrapper (leader_election only):
        empty until the elector first acquires, then the acquired epoch."""
        elector = getattr(self, "leader_elector", None)
        return [] if elector is None else elector.fence_guards()

    def _standby_reads_active(self) -> bool:
        """InformerReadKV's role predicate: serve reads from the mirror
        only while STANDING BY. The leader's own maps are authoritative
        (every write is local), and the leadership-handoff cache reload in
        _start_writers must read the real store — is_leader flips True
        before on_acquire fires, so those reloads pass through here."""
        elector = getattr(self, "leader_elector", None)
        return elector is not None and not elector.is_leader

    def _build_pod(self, local_topology: HostTopology) -> Pod:
        """Multi-host pod from [[pod_hosts]] config, else a single-host pod
        wrapping this host's runtime + schedulers (SURVEY.md hard part #3 —
        the reference is locked to one docker socket)."""
        cfg = self.cfg
        if not cfg.pod_hosts:
            return Pod.single_host(PodHost(
                host_id="local", address="127.0.0.1", grid_coord=(0, 0, 0),
                topology=local_topology, runtime=self.runtime,
                chips=self.chip_scheduler, ports=self.port_scheduler,
            ))
        hosts = []
        for entry in cfg.pod_hosts:
            host_id = entry["host_id"]
            if entry.get("local", False):
                # THIS machine: share the container service's runtime and
                # schedulers so local chips have exactly one accounting
                # (otherwise POST /containers and POST /jobs would both hand
                # out the same physical chips from separate pools)
                hosts.append(PodHost(
                    host_id=host_id,
                    address=entry["address"],
                    grid_coord=tuple(entry.get("grid_coord", [0, 0, 0])),
                    topology=local_topology,
                    runtime=self.runtime,
                    chips=self.chip_scheduler,
                    ports=self.port_scheduler,
                ))
                continue
            runtime = self._injected_pod_runtimes.get(host_id) or (
                open_runtime("docker", docker_host=entry.get(
                    "docker_host", cfg.docker_host),
                    pool_size=cfg.engine_pool_size)
                if entry.get("runtime_backend", cfg.runtime_backend) == "docker"
                else open_runtime("fake", allow_exec=True)
            )
            if cfg.breaker_threshold > 0:
                # circuit breaker per REMOTE engine: a dead socket must
                # cost one timeout, not one per caller per poll. The local
                # host's runtime stays unwrapped — it is shared with the
                # container service, and a local dockerd outage takes the
                # daemon with it anyway
                from tpu_docker_api.service.host_health import BreakerRuntime

                runtime = BreakerRuntime(
                    runtime, host_id=host_id,
                    threshold=cfg.breaker_threshold,
                    # cooldown tied to the probe interval so every monitor
                    # tick past it doubles as the half-open recovery probe
                    cooldown_s=cfg.host_probe_interval_s or 5.0,
                )
            topo = HostTopology.build(
                entry.get("accelerator_type", cfg.accelerator_type))
            hosts.append(PodHost(
                host_id=host_id,
                address=entry["address"],
                grid_coord=tuple(entry.get("grid_coord", [0, 0, 0])),
                topology=topo,
                runtime=runtime,
                chips=ChipScheduler(topo, self.kv, keys.host_chips_key(host_id)),
                ports=PortScheduler(self.kv, cfg.start_port, cfg.end_port,
                                    store_key=keys.host_ports_key(host_id)),
            ))
        grid = tuple(
            max(h.grid_coord[d] for h in hosts) + 1 for d in range(3)
        )
        gen = hosts[0].topology.generation
        return Pod(gen, grid, hosts)  # type: ignore[arg-type]

    def _discover_topology(self) -> HostTopology:
        """Topology from the telemetry sidecar if configured (the reference's
        first-boot detect-gpu fetch, gpuscheduler/scheduler.go:142-158), else
        from local probe, else synthesized from config accelerator_type."""
        cfg = self.cfg
        if cfg.detect_tpu_addr:
            import requests

            resp = requests.get(
                cfg.detect_tpu_addr.rstrip("/") + "/api/v1/detect/tpu", timeout=5
            )
            resp.raise_for_status()
            from tpu_docker_api.schemas.tpu import HostTopologyInfo
            from tpu_docker_api.telemetry.probe import topology_from_info

            return topology_from_info(HostTopologyInfo.from_dict(resp.json()["data"]))
        from tpu_docker_api.telemetry.probe import probe_local_topology

        local = probe_local_topology()
        if local is not None:
            log.info("using locally probed topology: %d chips", local.n_chips)
            return local
        log.info("no TPU hardware detected; topology from config %s",
                 cfg.accelerator_type)
        return HostTopology.build(cfg.accelerator_type)

    def _start_writers(self) -> None:
        """The writer role: every subsystem that MUTATES shared state.
        Single-process deployments run this unconditionally in start();
        in an HA fleet (leader_election = true) exactly one replica runs
        it at a time — on lease acquire — and halts it on loss, so the
        invariants the chaos suite proves survive N daemons sharing one
        store."""
        if self.leader_elector is not None:
            # leadership handoff, step one: re-seed every in-memory KV
            # mirror from the store. This replica may have booted long
            # before the dead leader's last write — supervising gangs or
            # sweeping leaks against boot-time scheduler/version snapshots
            # would re-allocate claimed chips and "repair" healthy state
            self._reload_caches()
        self.wq.start()
        if self.cfg.reconcile_on_start:
            # repair whatever a previous incarnation left half-done BEFORE
            # serving traffic (an interrupted rolling replace must not be
            # visible as two live versions) — under leader election this is
            # also the journal-ownership handoff: the new leader adopts and
            # replays the dead one's pending records here. A failed sweep
            # must not block boot — a recovery feature that crash-loops the
            # daemon is worse than the drift it would repair
            try:
                report = self.reconciler.reconcile()
                if report["actions"]:
                    log.warning("startup reconcile repaired %d drift(s): %s",
                                report["driftCount"],
                                [a["action"] for a in report["actions"]])
            except Exception:  # noqa: BLE001
                log.exception("startup reconcile failed; serving anyway "
                              "(rerun via /api/v1/reconcile)")
        if self.cfg.reconcile_interval > 0:
            self.reconciler.start_periodic(self.cfg.reconcile_interval)
        if self.cfg.job_supervise_interval > 0:
            self.job_supervisor.start()
        if self.host_monitor is not None:
            self.host_monitor.start()
        if self.health_watcher is not None:
            self.health_watcher.start()
        if self.cfg.admission_enabled and self.cfg.admission_interval_s > 0:
            # the admission loop mutates shared state (preemption, gang
            # placement) — a writer like the supervisor, leader-only in
            # an HA fleet
            self.admission.start()
        if self.cfg.autoscale_interval_s > 0:
            # the autoscaler mutates shared state (replica gangs, service
            # records) — a writer like the admission loop, leader-only in
            # an HA fleet
            self.serving.start()
        if self.cfg.workflow_interval_s > 0:
            # the DAG engine mutates shared state (step gangs, workflow
            # records) — a writer like the autoscaler, leader-only in an
            # HA fleet
            self.workflow.start()
        if self.compactor is not None:
            # history compaction deletes shared state — a writer like the
            # loops above, leader-only in an HA fleet
            self.compactor.start()

    def _stop_writers(self) -> None:
        """Halt the writer role (lease loss, shutdown). Every close is
        guarded and restartable: a later re-acquire calls _start_writers
        again on the same instances."""
        if getattr(self, "compactor", None) is not None:
            self.compactor.close()
        if getattr(self, "workflow", None) is not None:
            self.workflow.close()
        if getattr(self, "serving", None) is not None:
            self.serving.close()
        if getattr(self, "admission", None) is not None:
            self.admission.close()
        if getattr(self, "health_watcher", None) is not None:
            self.health_watcher.close()
        if getattr(self, "host_monitor", None) is not None:
            self.host_monitor.close()
        if getattr(self, "job_supervisor", None) is not None:
            self.job_supervisor.close()
        if getattr(self, "reconciler", None) is not None:
            self.reconciler.close()
        if getattr(self, "wq", None) is not None:
            self.wq.close()

    def start(self) -> None:
        if self.informer is not None:
            # the mirror warms on BOTH roles (a demoted leader must serve
            # cached reads immediately, not after a cold list) and before
            # the elector, so a standby's first GETs can already hit it;
            # until the initial list lands, reads fall through to the store
            self.informer.start()
        if self.reconcile_informer is not None:
            # the dirty-feed reflector warms on both roles too: a standby
            # promoted later must not start its first dirty passes from a
            # cold, everything-is-dirty state
            self.reconcile_informer.start()
        if self.gateway_informer is not None:
            # dedicated routing-table feed (only when no shared informer
            # exists): the gateway serves traffic on every role, so its
            # table warms unconditionally
            self.gateway_informer.start()
        if self.leader_elector is None and self.shard_plane is None:
            # single-process: writers start unconditionally, as always
            self._start_writers()
        router = build_router(
            self.container_svc, self.volume_svc,
            self.chip_scheduler, self.port_scheduler, work_queue=self.wq,
            health_watcher=self.health_watcher, metrics=self.metrics,
            job_svc=self.job_svc, pod_scheduler=self.pod_scheduler,
            reconciler=self.reconciler, job_supervisor=self.job_supervisor,
            host_monitor=self.host_monitor,
            leader_elector=self.leader_elector,
            shard_plane=self.shard_plane,
            informer=self.informer,
            fanout=self.fanout,
            admission=self.admission,
            serving=self.serving,
            workflow_svc=self.workflow,
            compactor=self.compactor,
            gateway=self.gateway,
            store_health=self.store_health,
            list_default_limit=self.cfg.list_default_limit,
            list_max_limit=self.cfg.list_max_limit,
            tracer=self.tracer,
        )
        bi = build_info()  # warm the git probe BEFORE serving /healthz
        self.api_server = ApiServer(router, host=self.host, port=self.cfg.port)
        self.api_server.start()
        if self.gateway_server is not None:
            # serving ingress on its own listener — starts after the
            # control-plane API so /healthz can already name the gateway
            self.gateway_server.start()
            log.info("gateway %s serving on %s:%d",
                     self.gateway.instance_id, self.host,
                     self.gateway_server.port)
        if self.leader_elector is not None:
            # serving is up (reads + 503-with-hint on mutations) BEFORE the
            # election begins: a standby is useful from its first second
            self.leader_elector.start()
        if self.shard_plane is not None:
            # same contract per shard: the process answers reads and
            # wrong-shard 503s before contesting any lease
            self.shard_plane.start()
        log.info("tpu-docker-api %s (%s@%s) serving on %s:%d "
                 "(%d chips, ports %d-%d)%s",
                 bi["version"], bi["branch"], bi["commit"],
                 self.host, self.api_server.port,
                 self.chip_scheduler.topology.n_chips,
                 self.cfg.start_port, self.cfg.end_port,
                 " [leader election enabled]"
                 if self.leader_elector is not None else "")

    def stop(self) -> None:
        """Shutdown — tolerant of a partially-completed init (every subsystem
        access is guarded), so a failed boot reports its root cause instead
        of masking it with an AttributeError during cleanup."""
        if getattr(self, "gateway_server", None) is not None:
            # the ingress goes first: stop accepting serving traffic (and
            # deregister this instance's heartbeat so drains stop waiting
            # on it) before the control plane dismantles anything
            self.gateway_server.close()
        if getattr(self, "gateway_informer", None) is not None:
            self.gateway_informer.close()
        if getattr(self, "api_server", None) is not None:
            self.api_server.close()
        if getattr(self, "leader_elector", None) is not None:
            # graceful: release the lease so the standby takes over NOW
            # instead of waiting out the TTL (the epoch key stays put —
            # fencing monotonicity)
            self.leader_elector.close(release=True)
        if getattr(self, "shard_plane", None) is not None:
            # same, per shard: every held lease is released so the
            # survivors take over immediately
            self.shard_plane.close(release=True)
        if getattr(self, "informer", None) is not None:
            self.informer.close()
        if getattr(self, "reconcile_informer", None) is not None:
            self.reconcile_informer.close()
        self._stop_writers()
        if getattr(self, "fanout", None) is not None:
            self.fanout.close()
        if getattr(self, "pod", None) is not None:
            for host in self.pod.hosts.values():
                if host.runtime is not self.runtime:
                    host.runtime.close()
        if getattr(self, "runtime", None) is not None:
            self.runtime.close()
        if getattr(self, "kv", None) is not None:
            self.kv.close()
        if getattr(self, "tracer", None) is not None:
            # reboot contract: no daemon ends with open spans — whatever a
            # dying flow left open closes as status="lost"
            self.tracer.close()
        log.info("tpu-docker-api stopped")


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="tpu-docker-api")
    parser.add_argument("-c", "--config", default=None, help="TOML config path")
    parser.add_argument("--host", default="0.0.0.0")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    prg = Program(config_mod.load(args.config), host=args.host)
    prg.init()
    prg.start()

    done = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    prg.stop()


if __name__ == "__main__":
    main()
