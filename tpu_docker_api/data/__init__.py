from tpu_docker_api.data.loader import (  # noqa: F401
    TokenSource,
    make_batch_fn,
    open_token_files,
)
