"""Token-stream data layer: deterministic, shardable, resumable.

The reference provisions opaque containers and has no data path at all
(SURVEY.md §0); the workloads this control plane launches are MaxText-class
pretraining jobs, so the framework ships the loader those jobs need. The
design is TPU-first in the same sense as the trainer:

- **Stateless step→batch mapping.** A batch is a pure function of
  ``(seed, step)``: window indices come from an affine permutation of the
  window space, so resuming at step N reproduces exactly the batch the
  pre-quiesce job would have seen at step N — no iterator state in
  checkpoints, nothing to migrate on rescale. This is the data-layer half of
  the quiesce→resume contract (train/__main__.py).
- **Process-sharded rows.** In a multi-host job every process owns a
  disjoint row range of the global batch (``rows_for_process``) — the
  data-parallel analog of how the job service shards chips (workload/
  jaxenv.py renders ``JAX_PROCESS_ID``; the loader consumes it).
- **Zero-copy reads.** Token files are memory-mapped (np.memmap); a batch
  gathers windows without materializing the corpus. Host RAM stays O(batch).

File format: flat little-endian token ids, ``.bin`` (uint16 when
vocab < 65536, else int32) or ``.npy``. Multiple files concatenate in sorted
order into one logical stream diced into non-overlapping (seq+1)-token
windows (+1: the trainer shifts tokens/targets off one array).
"""

from __future__ import annotations

import dataclasses
import pathlib
from typing import Callable, Sequence

import numpy as np


def _coprime_stride(n: int, seed: int) -> int:
    """Deterministic multiplier coprime to n (an affine permutation of
    Z_n needs gcd(a, n) == 1); scans odd offsets from a seed-mixed start."""
    if n == 1:
        return 1
    a = (0x9E3779B1 * (seed + 1)) % n
    a = a | 1  # odd helps for even n
    while np.gcd(int(a), int(n)) != 1:
        a = (a + 2) % n or 1
    return int(a)


@dataclasses.dataclass(frozen=True)
class TokenSource:
    """A logical token stream diced into fixed windows."""

    arrays: tuple[np.ndarray, ...]  # memory-mapped, 1-D
    window: int                     # tokens per window (seq + 1)

    def __post_init__(self):
        if self.window < 2:
            raise ValueError(f"window must be >= 2, got {self.window}")
        if not self.arrays or sum(a.size for a in self.arrays) < self.window:
            total = sum(a.size for a in self.arrays) if self.arrays else 0
            raise ValueError(
                f"need at least {self.window} tokens, have {total}")

    @property
    def n_tokens(self) -> int:
        return sum(a.size for a in self.arrays)

    @property
    def n_windows(self) -> int:
        return self.n_tokens // self.window

    def read_window(self, index: int) -> np.ndarray:
        """Window ``index`` (mod n_windows ⇒ infinite epochs) as int32."""
        index = int(index) % self.n_windows
        start = index * self.window
        out = np.empty(self.window, np.int32)
        filled = 0
        for arr in self.arrays:
            if start >= arr.size:
                start -= arr.size
                continue
            take = min(arr.size - start, self.window - filled)
            out[filled:filled + take] = arr[start:start + take]
            filled += take
            start = 0
            if filled == self.window:
                return out
        raise AssertionError("unreachable: n_windows bounds the index")


def open_token_files(
    paths: Sequence[str | pathlib.Path] | str | pathlib.Path,
    window: int,
    bin_dtype: str = "uint16",
) -> TokenSource:
    """Memory-map token files into a TokenSource. ``paths`` may be a single
    file, a directory (all ``*.bin``/``*.npy`` inside, sorted), or a list."""
    if isinstance(paths, (str, pathlib.Path)):
        p = pathlib.Path(paths)
        if p.is_dir():
            paths = sorted(
                q for q in p.iterdir() if q.suffix in (".bin", ".npy"))
        else:
            paths = [p]
    arrays = []
    for p in map(pathlib.Path, paths):
        if p.suffix == ".npy":
            arr = np.load(p, mmap_mode="r")
            if arr.ndim != 1:
                raise ValueError(f"{p}: token arrays must be 1-D, got {arr.shape}")
        elif p.suffix == ".bin":
            arr = np.memmap(p, dtype=np.dtype(bin_dtype), mode="r")
        else:
            raise ValueError(f"{p}: expected .bin or .npy")
        arrays.append(arr)
    return TokenSource(arrays=tuple(arrays), window=window)


def rows_for_process(
    global_batch: int, process_index: int, process_count: int
) -> range:
    """The contiguous row range of the global batch a process owns."""
    if global_batch % process_count:
        raise ValueError(
            f"global batch {global_batch} must divide by process count "
            f"{process_count}")
    per = global_batch // process_count
    return range(process_index * per, (process_index + 1) * per)


def make_batch_fn(
    source: TokenSource,
    global_batch: int,
    *,
    seed: int = 0,
    process_index: int = 0,
    process_count: int = 1,
) -> Callable[[int], np.ndarray]:
    """``fn(step) -> (local_batch, window) int32``, a pure function.

    Window selection for (step, row): position ``p = step·B + row`` in the
    visitation order, mapped through the affine permutation
    ``w = (a·p + b) mod n_windows`` — a full-period shuffle that changes
    per epoch (b advances by the epoch index, so revisits interleave
    differently) while staying O(1) stateless.
    """
    n = source.n_windows
    a = _coprime_stride(n, seed)
    rows = rows_for_process(global_batch, process_index, process_count)

    def batch_at(step: int) -> np.ndarray:
        out = np.empty((len(rows), source.window), np.int32)
        for i, row in enumerate(rows):
            p = step * global_batch + row
            epoch, pos = divmod(p, n)
            w = (a * pos + seed + epoch) % n
            out[i] = source.read_window(w)
        return out

    return batch_at


def write_token_file(
    tokens: np.ndarray, path: str | pathlib.Path, bin_dtype: str = "uint16"
) -> pathlib.Path:
    """Write a 1-D token array in the loader's ``.bin`` format (tooling for
    tests and corpus prep)."""
    path = pathlib.Path(path)
    arr = np.asarray(tokens)
    if arr.ndim != 1:
        raise ValueError(f"tokens must be 1-D, got {arr.shape}")
    info = np.iinfo(np.dtype(bin_dtype))
    if arr.min() < info.min or arr.max() > info.max:
        raise ValueError(f"token ids do not fit {bin_dtype}")
    arr.astype(np.dtype(bin_dtype)).tofile(path)
    return path
