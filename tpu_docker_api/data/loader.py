"""Token-stream data layer: deterministic, shardable, resumable.

The reference provisions opaque containers and has no data path at all
(SURVEY.md §0); the workloads this control plane launches are MaxText-class
pretraining jobs, so the framework ships the loader those jobs need. The
design is TPU-first in the same sense as the trainer:

- **Stateless step→batch mapping.** A batch is a pure function of
  ``(seed, step)``: window indices come from an affine permutation of the
  window space, so resuming at step N reproduces exactly the batch the
  pre-quiesce job would have seen at step N — no iterator state in
  checkpoints, nothing to migrate on rescale. This is the data-layer half of
  the quiesce→resume contract (train/__main__.py).
- **Process-sharded rows.** In a multi-host job every process owns a
  disjoint row range of the global batch (``rows_for_process``) — the
  data-parallel analog of how the job service shards chips (workload/
  jaxenv.py renders ``JAX_PROCESS_ID``; the loader consumes it).
- **Zero-copy reads.** Token files are memory-mapped (np.memmap); a batch
  gathers windows without materializing the corpus. Host RAM stays O(batch).
- **Native fast path.** When every source file is a plain ``.bin`` and
  the C++ loader (tpu_native/dataloader.cc, ``make -C tpu_native``) is
  built, ``make_batch_fn`` transparently routes batch assembly through
  it: mmap + tight widen loop, plus a background worker that precomputes
  step+1 for the same row range (the trainer's sequential access hits
  it, overlapping host data work with device compute). Bit-identical to
  the numpy path by construction AND by test (tests/test_data.py); unset
  builds or ``.npy`` sources silently use the numpy path, and
  ``TPU_DOCKER_API_NATIVE_DATA=0`` disables it outright.

File format: flat little-endian token ids, ``.bin`` (uint16 when
vocab < 65536, else int32) or ``.npy``. Multiple files concatenate in sorted
order into one logical stream diced into non-overlapping (seq+1)-token
windows (+1: the trainer shifts tokens/targets off one array).
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
import pathlib
from typing import Callable, Sequence

import numpy as np


def _coprime_stride(n: int, seed: int) -> int:
    """Deterministic multiplier coprime to n (an affine permutation of
    Z_n needs gcd(a, n) == 1); scans odd offsets from a seed-mixed start."""
    if n == 1:
        return 1
    a = (0x9E3779B1 * (seed + 1)) % n
    a = a | 1  # odd helps for even n
    while np.gcd(int(a), int(n)) != 1:
        a = (a + 2) % n or 1
    return int(a)


@dataclasses.dataclass(frozen=True)
class TokenSource:
    """A logical token stream diced into fixed windows."""

    arrays: tuple[np.ndarray, ...]  # memory-mapped, 1-D
    window: int                     # tokens per window (seq + 1)
    #: set by open_token_files when EVERY file is a plain .bin — the
    #: precondition for the native fast path (raw little-endian tokens,
    #: no npy headers to skip)
    bin_paths: tuple[str, ...] | None = None
    bin_dtype: str = "uint16"

    def __post_init__(self):
        if self.window < 2:
            raise ValueError(f"window must be >= 2, got {self.window}")
        if not self.arrays or sum(a.size for a in self.arrays) < self.window:
            total = sum(a.size for a in self.arrays) if self.arrays else 0
            raise ValueError(
                f"need at least {self.window} tokens, have {total}")

    @property
    def n_tokens(self) -> int:
        return sum(a.size for a in self.arrays)

    @property
    def n_windows(self) -> int:
        return self.n_tokens // self.window

    def read_window(self, index: int) -> np.ndarray:
        """Window ``index`` (mod n_windows ⇒ infinite epochs) as int32."""
        index = int(index) % self.n_windows
        start = index * self.window
        out = np.empty(self.window, np.int32)
        filled = 0
        for arr in self.arrays:
            if start >= arr.size:
                start -= arr.size
                continue
            take = min(arr.size - start, self.window - filled)
            out[filled:filled + take] = arr[start:start + take]
            filled += take
            start = 0
            if filled == self.window:
                return out
        raise AssertionError("unreachable: n_windows bounds the index")


def open_token_files(
    paths: Sequence[str | pathlib.Path] | str | pathlib.Path,
    window: int,
    bin_dtype: str = "uint16",
) -> TokenSource:
    """Memory-map token files into a TokenSource. ``paths`` may be a single
    file, a directory (all ``*.bin``/``*.npy`` inside, sorted), or a list."""
    if isinstance(paths, (str, pathlib.Path)):
        p = pathlib.Path(paths)
        if p.is_dir():
            paths = sorted(
                q for q in p.iterdir() if q.suffix in (".bin", ".npy"))
        else:
            paths = [p]
    arrays = []
    all_bin: list[str] | None = []
    for p in map(pathlib.Path, paths):
        if p.suffix == ".npy":
            arr = np.load(p, mmap_mode="r")
            if arr.ndim != 1:
                raise ValueError(f"{p}: token arrays must be 1-D, got {arr.shape}")
            all_bin = None
        elif p.suffix == ".bin":
            arr = np.memmap(p, dtype=np.dtype(bin_dtype), mode="r")
            if all_bin is not None:
                all_bin.append(str(p))
        else:
            raise ValueError(f"{p}: expected .bin or .npy")
        arrays.append(arr)
    return TokenSource(arrays=tuple(arrays), window=window,
                       bin_paths=tuple(all_bin) if all_bin else None,
                       bin_dtype=bin_dtype)


def rows_for_process(
    global_batch: int, process_index: int, process_count: int
) -> range:
    """The contiguous row range of the global batch a process owns."""
    if global_batch % process_count:
        raise ValueError(
            f"global batch {global_batch} must divide by process count "
            f"{process_count}")
    per = global_batch // process_count
    return range(process_index * per, (process_index + 1) * per)


# ---- native fast path (tpu_native/dataloader.cc) --------------------------

# absolute candidates only: a bare "libtpudata.so" would dlopen from the
# default search path (LD_LIBRARY_PATH etc.), where a stale or planted
# same-named library could shadow the real one (ADVICE r3)
_NATIVE_PATHS = (
    os.path.join(os.path.dirname(__file__), "..", "..", "tpu_native",
                 "libtpudata.so"),
    "/usr/local/lib/libtpudata.so",
)
_native_cache: list = []  # [lib-or-None], memoized


def _native_lib():
    """The C++ loader library, or None (unbuilt / disabled). Memoized —
    one dlopen per process."""
    if _native_cache:
        return _native_cache[0]
    lib = None
    if os.environ.get("TPU_DOCKER_API_NATIVE_DATA", "1") != "0":
        for path in _NATIVE_PATHS:
            try:
                cand = ctypes.CDLL(path)
                cand.tpudata_abi_version.restype = ctypes.c_int32
                if cand.tpudata_abi_version() != 1:
                    continue
            except (OSError, AttributeError):
                # unbuilt, unloadable, or a foreign .so without our
                # symbols — the documented contract is numpy fallback
                continue
            cand.tpudata_open.restype = ctypes.c_int64
            cand.tpudata_open.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_int32,
                ctypes.c_int64, ctypes.c_int32]
            cand.tpudata_n_windows.restype = ctypes.c_int64
            cand.tpudata_n_windows.argtypes = [ctypes.c_int64]
            cand.tpudata_n_tokens.restype = ctypes.c_int64
            cand.tpudata_n_tokens.argtypes = [ctypes.c_int64]
            cand.tpudata_batch.restype = ctypes.c_int32
            cand.tpudata_batch.argtypes = [
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32)]
            cand.tpudata_close.argtypes = [ctypes.c_int64]
            lib = cand
            break
    _native_cache.append(lib)
    return lib


class _NativeBatcher:
    """Owns one native source handle; ``__call__(step)`` fills this
    process's rows. The handle is closed on GC (the worker thread joins
    there), so the object must outlive the returned batch fn — it IS the
    batch fn."""

    def __init__(self, lib, paths: tuple[str, ...], window: int,
                 bin_dtype: str, global_batch: int, rows: range,
                 seed: int):
        self._lib = lib
        arr = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths])
        self._handle = lib.tpudata_open(
            arr, len(paths), window, np.dtype(bin_dtype).itemsize)
        if self._handle < 0:
            raise OSError(f"tpudata_open failed for {paths}")
        self._window = window
        self._global_batch = global_batch
        self._rows = rows
        self._seed = seed

    def __call__(self, step: int) -> np.ndarray:
        out = np.empty((len(self._rows), self._window), np.int32)
        rc = self._lib.tpudata_batch(
            self._handle, int(step), self._global_batch,
            self._rows.start, self._rows.stop, self._seed,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc != 0:
            raise RuntimeError(f"tpudata_batch failed rc={rc}")
        return out

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None and getattr(self, "_handle", -1) >= 0:
            lib.tpudata_close(self._handle)
            self._handle = -1


def make_batch_fn(
    source: TokenSource,
    global_batch: int,
    *,
    seed: int = 0,
    process_index: int = 0,
    process_count: int = 1,
) -> Callable[[int], np.ndarray]:
    """``fn(step) -> (local_batch, window) int32``, a pure function.

    Window selection for (step, row): position ``p = step·B + row`` in the
    visitation order, mapped through the affine permutation
    ``w = (a·p + b) mod n_windows`` — a full-period shuffle that changes
    per epoch (b advances by the epoch index, so revisits interleave
    differently) while staying O(1) stateless.
    """
    n = source.n_windows
    a = _coprime_stride(n, seed)
    rows = rows_for_process(global_batch, process_index, process_count)

    # the native decode loop knows exactly uint16/int32 — any other
    # dtype (int16 shares uint16's itemsize!) must stay on numpy or a
    # sign-blind widen would silently corrupt the stream
    if (source.bin_paths and seed >= 0
            and source.bin_dtype in ("uint16", "int32")):
        lib = _native_lib()
        if lib is not None:
            try:
                return _NativeBatcher(lib, source.bin_paths, source.window,
                                      source.bin_dtype, global_batch, rows,
                                      seed)
            except OSError:
                pass  # fall through to the numpy path

    def batch_at(step: int) -> np.ndarray:
        out = np.empty((len(rows), source.window), np.int32)
        for i, row in enumerate(rows):
            p = step * global_batch + row
            epoch, pos = divmod(p, n)
            w = (a * pos + seed + epoch) % n
            out[i] = source.read_window(w)
        return out

    return batch_at


def write_token_file(
    tokens: np.ndarray, path: str | pathlib.Path, bin_dtype: str = "uint16"
) -> pathlib.Path:
    """Write a 1-D token array in the loader's ``.bin`` format (tooling for
    tests and corpus prep)."""
    path = pathlib.Path(path)
    arr = np.asarray(tokens)
    if arr.ndim != 1:
        raise ValueError(f"tokens must be 1-D, got {arr.shape}")
    info = np.iinfo(np.dtype(bin_dtype))
    if arr.min() < info.min or arr.max() > info.max:
        raise ValueError(f"token ids do not fit {bin_dtype}")
    arr.astype(np.dtype(bin_dtype)).tofile(path)
    return path
