"""Pallas TPU flash attention (causal, GQA-aware).

Online-softmax attention tiled for the MXU: the q block lives in VMEM, k/v are
walked block-by-block with running (max, sum, acc) statistics in f32, so the
S×S score matrix never materializes in HBM — the op that XLA's automatic
fusion cannot produce on its own (it would re-materialize scores for the
softmax). Layout follows the pallas guide (/opt/skills/guides/pallas_guide.md):
128-aligned tiles, f32 accumulation via ``preferred_element_type``, causal
masking with ``broadcasted_iota``, and a dynamic ``fori_loop`` bound so causal
q blocks skip never-visible k blocks entirely.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int, scale: float,
    causal: bool,
):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale  # (block_q, head_dim)
    head_dim = q.shape[-1]
    num_k_blocks = k_ref.shape[2] // block_k

    # causal: k blocks strictly after this q block's last row are all masked
    if causal:
        k_limit = lax.div((qi + 1) * block_q + block_k - 1, block_k)
    else:
        k_limit = num_k_blocks

    def body(kj, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, 0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        if causal:
            rows = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # (block_q, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = lax.fori_loop(0, k_limit, body, (acc0, m0, l0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_kernel_kvgrid(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
    block_q: int, block_k: int, scale: float, causal: bool,
):
    """kv-blocked variant: the kv axis is the innermost GRID dimension, so
    only (block_k, head_dim) of k/v ever sits in VMEM — unbounded seq.
    Accumulators persist across kv grid steps in VMEM scratch."""
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: skip blocks where every k position is after every q position
    visible = (not causal) or (kj * block_k <= qi * block_q + block_q - 1)

    @pl.when(visible)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            rows = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = m_new

    @pl.when(kj == nk - 1)
    def _finalize():
        o_ref[0, 0] = (
            acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
        ).astype(o_ref.dtype)


#: k+v bf16 VMEM budget under which the fori-loop variant (whole kv resident,
#: causal early-exit) is preferred; above it, the kv-grid variant streams
_KV_VMEM_BUDGET_BYTES = 4 * 1024 * 1024


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret"),
)
def flash_attention(
    q: jnp.ndarray,  # (batch, num_heads, seq, head_dim)
    k: jnp.ndarray,  # (batch, num_kv_heads, seq, head_dim)
    v: jnp.ndarray,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Tiled causal attention. seq must divide by the block sizes (the model
    layer pads to a multiple of 128); head grouping (GQA) is expressed in the
    k/v BlockSpec index maps, so kv heads are never materially repeated."""
    batch, num_heads, seq, head_dim = q.shape
    num_kv_heads = k.shape[1]
    assert num_heads % num_kv_heads == 0
    group = num_heads // num_kv_heads
    block_q = min(block_q, seq)
    block_k = min(block_k, seq)
    assert seq % block_q == 0 and seq % block_k == 0

    scale = 1.0 / (head_dim**0.5)
    kv_bytes = 2 * seq * head_dim * 2  # k + v, bf16
    if kv_bytes <= _KV_VMEM_BUDGET_BYTES:
        # short/medium seq: whole k/v resident, causal rows stop their k loop
        # early (dynamic fori bound) — no wasted grid steps
        kernel = functools.partial(
            _flash_kernel, block_q=block_q, block_k=block_k,
            scale=scale, causal=causal,
        )
        return pl.pallas_call(
            kernel,
            grid=(batch, num_heads, seq // block_q),
            in_specs=[
                pl.BlockSpec((1, 1, block_q, head_dim),
                             lambda b, h, i: (b, h, i, 0)),
                pl.BlockSpec((1, 1, seq, head_dim),
                             lambda b, h, i, g=group: (b, h // g, 0, 0)),
                pl.BlockSpec((1, 1, seq, head_dim),
                             lambda b, h, i, g=group: (b, h // g, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, block_q, head_dim),
                                   lambda b, h, i: (b, h, i, 0)),
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            interpret=interpret,
        )(q, k, v)

    # long seq: kv as innermost grid axis, only one (block_k, head_dim) tile
    # of k/v in VMEM at a time; accumulators live in scratch across kv steps
    kernel = functools.partial(
        _flash_kernel_kvgrid, block_q=block_q, block_k=block_k,
        scale=scale, causal=causal,
    )
    return pl.pallas_call(
        kernel,
        grid=(batch, num_heads, seq // block_q, seq // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j, g=group: (b, h // g, j, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j, g=group: (b, h // g, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, head_dim),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
