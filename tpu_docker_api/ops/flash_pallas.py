"""Pallas TPU flash attention (causal, GQA-aware), forward + backward.

Online-softmax attention tiled for the MXU: the q block lives in VMEM, k/v
stream in block-by-block as the innermost grid axis (Mosaic double-buffers
grid-step loads, overlapping the k/v DMA with compute) with running
(max, sum, acc) statistics in f32 scratch, so the S×S score matrix never
materializes in HBM — the op that XLA's automatic fusion cannot produce on
its own (it would re-materialize scores for the softmax). Layout follows the
pallas guide (/opt/skills/guides/pallas_guide.md): 128-aligned tiles, f32
accumulation via ``preferred_element_type``, causal masking with
``broadcasted_iota`` on diagonal tiles only (never-visible tiles are skipped,
fully-visible tiles skip the mask compute), and the softmax runs in the
base-2 domain (``exp2``; scale·log2(e) folded into q).

Training runs through a ``jax.custom_vjp``: the forward also emits the
per-row logsumexp L = m + log(l), and the backward is the FlashAttention-2
recomputation scheme — probabilities are rebuilt per tile from (q, k, L), so
the backward is O(seq) memory too:

    D_i  = rowsum(dO_i ∘ O_i)
    P_ij = exp(q_i k_j^T · scale − L_i)
    dV_j = Σ_i P_ij^T dO_i
    dS_ij = P_ij ∘ (dO_i V_j^T − D_i)
    dQ_i = scale · Σ_j dS_ij K_j
    dK_j = scale · Σ_i dS_ij^T Q_i

Two backward kernels: one gridded over q blocks (dq), one over kv blocks
(dk/dv) with the GQA group as the innermost grid axis so the group's
contributions accumulate into the kv-head output block while it stays
resident in VMEM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: remat policy fragment: save the flash forward's (out, lse) residuals so a
#: rematerialized backward runs only the backward kernels instead of
#: re-running the forward kernel first (combine with a dots policy via
#: ``jax.checkpoint_policies.save_from_both_policies``)
FLASH_SAVEABLE = jax.checkpoint_policies.save_only_these_names(
    "flash_out", "flash_lse"
)

#: the framework-wide training remat policy, used at EVERY ``jax.checkpoint``
#: site that can reach the flash kernel (llama, moe, pipeline stages): save
#: ONLY the flash residuals, recompute every dot. Profiling the 350m bench
#: on v5e showed the dots-saveable policy spending ~25% of the step moving
#: saved activations through scan-stacked buffers at ~1/6 of HBM peak, while
#: recomputing those dots on the MXU costs less — lean remat measured ~5%
#: faster end-to-end (and frees ~6GB at bench shapes). The flash (out, lse)
#: stay saved: the kernel re-run is the one recompute that is not cheap.
TRAIN_REMAT_POLICY = FLASH_SAVEABLE

_NEG_INF = -1e30
#: scores are kept in the base-2 domain inside every kernel: fold log2(e)
#: into the qk scale (applied to q once, head_dim-wide, instead of per score
#: tile) and use exp2 for the softmax. The emitted lse stays natural-log
#: (lse = ln2·m2 + ln l), so the kernel boundary contract is unchanged.
_LOG2E = 1.4426950408889634
_LN2 = 0.6931471805599453


def _causal_mask(s, qi, kj, block_q, block_k):
    """Mask scores above the diagonal in tile (qi, kj) — the one place the
    mask semantics live for the forward and both backward kernels."""
    rows = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = kj * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(rows >= cols, s, _NEG_INF)


def _causal_dispatch(step, qi, kj, block_q, block_k, causal):
    """Run ``step(masked)`` for tile (qi, kj): diagonal tiles apply the
    causal mask, fully-visible tiles skip the mask compute (these kernels
    are VPU-bound — the iota/compare is real cost), never-visible tiles are
    skipped entirely. Shared by the forward and both backward kernels."""
    if not causal:
        step(False)
        return
    fully = (kj + 1) * block_k <= qi * block_q
    diag = (~fully) & (kj * block_k <= qi * block_q + block_q - 1)
    pl.when(fully)(lambda: step(False))
    pl.when(diag)(lambda: step(True))


def _flash_kernel_kvgrid(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
    block_q: int, block_k: int, scale: float, causal: bool,
):
    """kv-blocked variant: the kv axis is the innermost GRID dimension, so
    only (block_k, head_dim) of k/v ever sits in VMEM — unbounded seq.
    Accumulators persist across kv grid steps in VMEM scratch."""
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _step(masked):
        # bf16 dot operands (full-rate MXU), f32 accumulation + stats.
        # The base-2 softmax scale is folded into q (head_dim-sized multiply)
        # instead of scaling the (block_q, block_k) score tile — one less
        # full-tile VPU op in a VPU-bound kernel.
        q = (q_ref[0, 0].astype(jnp.float32) * (scale * _LOG2E)).astype(
            q_ref.dtype)
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # base-2 domain — see _LOG2E
        if masked:
            s = _causal_mask(s, qi, kj, block_q, block_k)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp2(s - m_new)
        alpha = jnp.exp2(m_prev - m_new)
        l_ref[:] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    _causal_dispatch(_step, qi, kj, block_q, block_k, causal)

    @pl.when(kj == nk - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        # transposed store — see the lse comment in _fwd_impl
        lse_ref[0, 0] = jnp.broadcast_to(
            (m_ref[:] * _LN2 + jnp.log(l_safe)).T, lse_ref.shape[2:])


def _probs_tile(q, k, lse, qi, kj, block_q, block_k, scale, masked):
    """Rebuild the softmax probability tile P_ij = exp(q k^T · scale − L_i)
    from saved logsumexp — the FlashAttention-2 recomputation step shared by
    both backward kernels. Computed in the base-2 domain (see _LOG2E);
    ``masked`` applies the causal mask (diagonal tiles only — fully-visible
    tiles skip it)."""
    s = jax.lax.dot_general(
        (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype), k,
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )
    if masked:
        s = _causal_mask(s, qi, kj, block_q, block_k)
    return jnp.exp2(s - lse * _LOG2E)


def _flash_bwd_dq_kernel(
    k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref, *,
    block_q: int, block_k: int, scale: float, causal: bool,
):
    """dQ for one q block. Grid is (batch, head, q_block, kv_block) with the
    kv axis innermost: only one (block_k, head_dim) tile of k/v is ever in
    VMEM (unbounded seq, mirroring the forward's kv-grid variant), and dQ
    accumulates across kv steps in f32 scratch."""
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _step(masked):
        # bf16 dot operands (full-rate MXU), f32 accumulation + stats
        q = q_ref[0, 0]                               # (block_q, head_dim)
        do = do_ref[0, 0]
        # stats tiles are transposed (8, block_q) — see _fwd_impl
        lse = lse_ref[0, 0, :1, :].T                  # (block_q, 1)
        delta = delta_ref[0, 0, :1, :].T
        k = k_ref[0, 0]                               # (block_k, head_dim)
        v = v_ref[0, 0]
        p = _probs_tile(q, k, lse, qi, kj, block_q, block_k, scale, masked)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta)).astype(k.dtype)
        acc_ref[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    _causal_dispatch(_step, qi, kj, block_q, block_k, causal)

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0, 0] = (acc_ref[:] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, dk_ref, dv_ref,
    dk_acc_ref, dv_acc_ref, *,
    block_q: int, block_k: int, scale: float, causal: bool,
):
    """dK/dV for one kv block. Grid is (batch, kv_head, kv_block, group,
    q_block) — group and q innermost, so every (g, qi) contribution
    accumulates in f32 scratch while the (b, kv_head, kv_block) output block
    stays resident; one cast to the storage dtype at the end (no bf16
    round-off compounding across GQA group members)."""
    kj = pl.program_id(2)
    g = pl.program_id(3)
    qi = pl.program_id(4)
    ng = pl.num_programs(3)
    nq = pl.num_programs(4)

    @pl.when((g == 0) & (qi == 0))
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    def _step(masked):
        # bf16 dot operands (full-rate MXU), f32 accumulation + stats
        k = k_ref[0, 0]                               # (block_k, head_dim)
        v = v_ref[0, 0]
        q = q_ref[0, 0]                               # (block_q, head_dim)
        do = do_ref[0, 0]
        # stats tiles are transposed (8, block_q) — see _fwd_impl
        lse = lse_ref[0, 0, :1, :].T
        delta = delta_ref[0, 0, :1, :].T
        p = _probs_tile(q, k, lse, qi, kj, block_q, block_k, scale, masked)
        dv_acc_ref[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc_ref[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    # causal: q blocks entirely before this k block see none of it
    _causal_dispatch(_step, qi, kj, block_q, block_k, causal)

    @pl.when((g == ng - 1) & (qi == nq - 1))
    def _finalize():
        dk_ref[0, 0] = (dk_acc_ref[:] * scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc_ref[:].astype(dv_ref.dtype)


def _fwd_impl(q, k, v, causal, block_q, block_k, interpret):
    batch, num_heads, seq, head_dim = q.shape
    num_kv_heads = k.shape[1]
    group = num_heads // num_kv_heads
    scale = 1.0 / (head_dim**0.5)
    out_shapes = (
        jax.ShapeDtypeStruct(q.shape, q.dtype),
        # TRANSPOSED row stats, (…, 8, seq): seq on the lane dim keeps the
        # buffer dense; a (…, seq, 8) layout would pad lanes 8→128 (16x
        # HBM on a buffer that remat saves per layer). The 8 sublanes are
        # broadcast copies (min f32 tile height).
        jax.ShapeDtypeStruct((batch, num_heads, 8, seq), jnp.float32),
    )
    # kv as innermost grid axis, only one (block_k, head_dim) tile of k/v in
    # VMEM at a time (unbounded seq); accumulators live in scratch across kv
    # steps. Mosaic double-buffers grid-step block loads, which overlaps the
    # k/v DMA with compute — measured faster than a whole-kv-resident
    # fori-loop variant even at seq 2048 where both fit VMEM (the fori loop
    # serializes its dot→stats dependency chain with no prefetch overlap).
    kernel = functools.partial(
        _flash_kernel_kvgrid, block_q=block_q, block_k=block_k,
        scale=scale, causal=causal,
    )
    return pl.pallas_call(
        kernel,
        grid=(batch, num_heads, seq // block_q, seq // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j, g=group: (b, h // g, j, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j, g=group: (b, h // g, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, 8, block_q),
                         lambda b, h, i, j: (b, h, 0, i)),
        ),
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        # kv axis carries scratch accumulators step-to-step → arbitrary
        compiler_params=pltpu.CompilerParams(dimension_semantics=(
            "parallel", "parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)


def _bwd_impl(causal, block_q, block_k, interpret, residuals, dout):
    q, k, v, out, lse = residuals
    batch, num_heads, seq, head_dim = q.shape
    num_kv_heads = k.shape[1]
    group = num_heads // num_kv_heads
    scale = 1.0 / (head_dim**0.5)
    # D_i = rowsum(dO ∘ O): tiny elementwise pre-pass, XLA fuses it; built
    # in the same transposed (…, 8, seq) layout as lse (dense lanes)
    delta = jnp.broadcast_to(
        jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1)[:, :, None, :],
        (*dout.shape[:2], 8, dout.shape[2]))

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, block_q=block_q, block_k=block_k,
        scale=scale, causal=causal,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(batch, num_heads, seq // block_q, seq // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j, g=group: (b, h // g, j, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j, g=group: (b, h // g, j, 0)),
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, 8, block_q), lambda b, h, i, j: (b, h, 0, i)),
            pl.BlockSpec((1, 1, 8, block_q), lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, head_dim),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        # kv axis accumulates dq in scratch → arbitrary
        compiler_params=pltpu.CompilerParams(dimension_semantics=(
            "parallel", "parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(k, v, q, dout, lse, delta)

    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
        scale=scale, causal=causal,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(batch, num_kv_heads, seq // block_k, group, seq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, hk, j, g, i, G=group: (b, hk * G + g, i, 0)),
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, hk, j, g, i, G=group: (b, hk * G + g, i, 0)),
            pl.BlockSpec((1, 1, 8, block_q),
                         lambda b, hk, j, g, i, G=group: (b, hk * G + g, 0, i)),
            pl.BlockSpec((1, 1, 8, block_q),
                         lambda b, hk, j, g, i, G=group: (b, hk * G + g, 0, i)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, hk, j, g, i: (b, hk, j, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, hk, j, g, i: (b, hk, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, hk, j, g, i: (b, hk, j, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, hk, j, g, i: (b, hk, j, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        # group + q axes accumulate dk/dv in scratch → arbitrary
        compiler_params=pltpu.CompilerParams(dimension_semantics=(
            "parallel", "parallel", "parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(q, dout, lse, delta, k, v)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    # names let a remat policy keep these residuals: a pallas_call is not a
    # dot primitive, so dots-saveable policies would otherwise discard them
    # and re-run the whole forward kernel inside the backward pass (see
    # FLASH_SAVEABLE / llama's remat policy)
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, residuals, dout):
    return _bwd_impl(causal, block_q, block_k, interpret, residuals, dout)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret"),
)
def flash_attention(
    q: jnp.ndarray,  # (batch, num_heads, seq, head_dim)
    k: jnp.ndarray,  # (batch, num_kv_heads, seq, head_dim)
    v: jnp.ndarray,
    causal: bool = True,
    # measured on v5e at (2, 32|8, 2048, 64): under the kv-grid kernel,
    # (1024, 1024) is fastest — fwd 1.43 ms / bwd 2.10 ms vs 1.48/2.38 for
    # (512, 1024) and 1.93/2.52 for (512, 512); wide blocks amortize the
    # per-step lane reductions (max/sum over block_k) that bound these
    # kernels on the VPU, and (2048, *) / (*, 2048) regress or blow VMEM
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: bool = False,
) -> jnp.ndarray:
    """Tiled causal attention, differentiable (custom VJP). seq must be a
    multiple of 128 (the dispatcher's contract; the model layer pads);
    requested block sizes are clamped to seq then halved until they divide
    it — e.g. seq 640 runs with block_q and block_k 640 rather than
    failing. Head grouping (GQA) is expressed in the k/v BlockSpec index
    maps, so kv heads are never materially repeated."""
    batch, num_heads, seq, head_dim = q.shape
    num_kv_heads = k.shape[1]
    assert num_heads % num_kv_heads == 0
    block_q = min(block_q, seq)
    block_k = min(block_k, seq)
    while seq % block_q:
        block_q //= 2
    while seq % block_k:
        block_k //= 2
    assert block_q >= 128 and block_k >= 128, (
        f"seq {seq} must be a multiple of 128"
    )
    return _flash(q, k, v, causal, block_q, block_k, interpret)
