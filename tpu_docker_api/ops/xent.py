"""Chunked softmax cross-entropy fused with the logits projection.

The dense loss path (``models.llama.lm_head`` + ``cross_entropy``) saves the
full f32 logits — (batch, seq, vocab) ≈ 2 GB at bench shapes — as a backward
residual, and the backward materializes an equally large dlogits buffer. This
op never materializes either: rows are processed in chunks under ``lax.scan``;
the forward keeps only the per-row logsumexp (f32, one scalar per row) and the
backward rebuilds each chunk's logits from (h, w) on the MXU:

    fwd:  per chunk   logits = h_c·w;  lse_c = logsumexp(logits)
          residuals = (h, w, targets, lse)            # no (rows, vocab) saved
    bwd:  per chunk   p = exp(h_c·w − lse_c)
          dlogits = (p − onehot(t_c)) · g/N           # never whole-T sized
          dh_c = dlogits·wᵀ ;  dw += h_cᵀ·dlogits

Trade: one extra logits matmul in the backward (~2 TFLOP at bench shapes)
against ~6 GB of HBM residual/transient traffic — roughly time-neutral on a
v5e at batch 2, but it frees the memory that caps the bench batch size (the
actual win; see docs/perf-notes.md).

The reference has no training stack at all (SURVEY.md §0); this op exists for
the workload layer its BASELINE.json north star requires.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def chunked_cross_entropy(
    h: jnp.ndarray,        # (batch, seq, d) — normed final hidden, bf16
    w: jnp.ndarray,        # (d, vocab) lm head
    targets: jnp.ndarray,  # (batch, seq) int32
    row_chunk: int = 512,
) -> jnp.ndarray:
    """Mean next-token cross-entropy over all (batch, seq) positions,
    matching ``cross_entropy(lm_head(h), targets)`` within f32
    reduction-order tolerance: the operands and per-row math are identical
    (bf16 operands / f32 accumulation on the logits matmul), but the mean is
    accumulated as per-chunk masked sums rather than one global mean, so the
    f32 reduction order differs (tests assert rtol 1e-5 on loss, 5e-2 on
    grads). Rows are padded to a multiple of ``row_chunk`` with zero-weight
    rows."""
    b, s, d = h.shape
    t = b * s
    n_rows = -(-t // row_chunk) * row_chunk
    hf = h.reshape(t, d)
    tf = targets.reshape(t)
    # weight of each row in the mean; padding rows weigh 0
    mask = jnp.full((t,), 1.0 / t, jnp.float32)
    if n_rows != t:
        hf = jnp.pad(hf, ((0, n_rows - t), (0, 0)))
        tf = jnp.pad(tf, (0, n_rows - t))
        mask = jnp.pad(mask, (0, n_rows - t))
    n = n_rows // row_chunk
    return _chunked_xent(
        hf.reshape(n, row_chunk, d),
        w,
        tf.reshape(n, row_chunk),
        mask.reshape(n, row_chunk),
    )


@jax.custom_vjp
def _chunked_xent(h, w, t, mask):
    loss, _ = _xent_fwd_scan(h, w, t, mask)
    return loss


def _chunk_logits(hc, w):
    # bf16 operands (full-rate MXU), f32 accumulation
    return lax.dot_general(
        hc, w.astype(hc.dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _xent_fwd_scan(h, w, t, mask):
    def body(acc, xs):
        hc, tc, mc = xs
        logits = _chunk_logits(hc, w)                      # (rows, vocab) f32
        lse = jax.nn.logsumexp(logits, axis=-1)            # (rows,)
        tl = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
        return acc + jnp.sum((lse - tl) * mc), lse

    loss, lses = lax.scan(body, jnp.float32(0.0), (h, t, mask))
    return loss, lses


def _xent_vjp_fwd(h, w, t, mask):
    loss, lses = _xent_fwd_scan(h, w, t, mask)
    return loss, (h, w, t, mask, lses)


def _xent_vjp_bwd(res, g):
    h, w, t, mask, lses = res
    vocab = w.shape[1]

    def body(dw_acc, xs):
        hc, tc, mc, lsec = xs
        logits = _chunk_logits(hc, w)                      # recompute
        p = jnp.exp(logits - lsec[:, None])
        onehot = (jnp.arange(vocab, dtype=tc.dtype)[None, :]
                  == tc[:, None])
        dlogits = ((p - onehot) * (mc * g)[:, None]).astype(hc.dtype)
        dh_c = lax.dot_general(
            dlogits, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(hc.dtype)
        dw_acc = dw_acc + lax.dot_general(
            hc, dlogits, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dw_acc, dh_c

    dw, dh = lax.scan(
        body, jnp.zeros(w.shape, jnp.float32), (h, t, mask, lses))
    return dh, dw.astype(w.dtype), None, None


_chunked_xent.defvjp(_xent_vjp_fwd, _xent_vjp_bwd)
