"""Normalization ops."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray,
               eps: float = 1e-6) -> jnp.ndarray:
    """LayerNorm (ViT/GPT-style: mean subtraction, scale and bias). Same
    f32-compute discipline as rms_norm."""
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    normed = xc * lax.rsqrt(var + eps)
    out = normed * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    return out.astype(orig_dtype)


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """RMSNorm (Llama-style, no mean subtraction, no bias).

    Computed in float32 regardless of input dtype — bf16 accumulation of
    x**2 loses too much precision — then cast back, so XLA fuses the whole
    thing into neighbouring ops as a single VPU pass.
    """
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(orig_dtype)
