"""Paged KV-cache primitives (the vLLM-PagedAttention capability,
TPU-first).

The dense slot cache preallocates ``slots × max_seq`` positions per
layer whether or not any request uses them — at 8B shapes that is
~128 KB of HBM per position, so 32 slots × 2048 capacity would pin 8 GB
next to 8 GB of int8 weights: impossible on one v5e. Paging replaces the
dense buffer with a POOL of fixed-size pages ``(layers, P, page, kv,
head_dim)`` plus a per-slot page table; HBM scales with the pool (sized
to expected LIVE tokens), not slots × capacity.

TPU-first shape of the design (vs the CUDA block-table kernel):

- **Static shapes everywhere**: the page table rides into each compiled
  program as a ``(S, mp)`` int32 OPERAND (mp = a geometric page-count
  bucket), so XLA sees fixed shapes and the host can repage freely
  between dispatches — no device-side allocator, no eager updates (an
  eager ``.at[].set`` costs a ~150 ms tunnel round-trip; a small host
  operand costs ~0.2 ms, engine design rule per infer/slots.py).
- **Reads gather pages back into a contiguous (S, mp·page, kv, hd)
  view and run the SAME ``dense_attention`` as the dense cache.** Page
  j of a slot covers global positions [j·page, (j+1)·page), so the
  gathered view is element-identical to the dense cache prefix — the
  engine's token-exactness contract (tests/test_slots.py) carries over
  verbatim instead of resting on a new online-softmax numerics story.
  The gather costs one extra HBM round-trip of the live bytes per
  layer; the capacity win (serving points the dense cache cannot
  reach) is the point, and the bucketed ``mp`` keeps the gathered view
  at live size, not capacity.
- **Page 0 is the trash page**: unassigned table entries point at it,
  so writes from lanes whose request already completed (the engine
  processes completions at a pipeline lag) land harmlessly; nothing
  ever reads it unmasked — same just-in-time-overwrite argument as the
  dense engine's drop-mode writes.

Capability analog in the reference: none (no serving at all, SURVEY.md
§0); this extends the round-3 slot engine the way the reference's
versioned rolling-replacement extends plain docker run.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class PagedRef:
    """One layer-scan step's view of the paged cache: the full pools,
    this layer's traced index, and the dispatch's page table. Marker
    type that routes models/llama._attention onto the paged write/read
    path; a pytree is NOT needed — it never crosses a jit boundary as a
    leaf (the pools do, separately, as scan carry)."""

    k_pool: Any    # (layers, P, page, n_kv_heads, head_dim)
    v_pool: Any
    layer_idx: Any  # traced int32 scalar
    table: Any     # (S, mp) int32 page ids; 0 = trash page


def paged_write(pool: jnp.ndarray, layer_idx, table: jnp.ndarray,
                pos: jnp.ndarray, new: jnp.ndarray) -> jnp.ndarray:
    """Scatter one new position per slot into the pool:
    ``pool[layer_idx, table[s, pos[s]//page], pos[s]%page] = new[s]``.
    A position BEYOND the table view (``pos // page >= mp``) routes to
    the trash page unconditionally — the paged analog of the dense
    cache's mode="drop" writes: completed lanes decoding at the
    pipeline lag land there via their zeroed rows, and PARKED
    chunked-prefill lanes (decode position pinned at max_seq, r5) land
    there via this bound even though their rows hold live pages."""
    page = pool.shape[2]
    mp = table.shape[1]
    col = pos // page
    pid = jnp.take_along_axis(
        table, jnp.clip(col, 0, mp - 1)[:, None], axis=1)[:, 0]
    pid = jnp.where(col < mp, pid, 0)
    return pool.at[layer_idx, pid, pos % page].set(
        new.astype(pool.dtype))


def gather_pages(pool: jnp.ndarray, layer_idx,
                 table: jnp.ndarray) -> jnp.ndarray:
    """(S, mp·page, kv, hd) contiguous view of each slot's pages for
    this layer — element-identical to the dense cache prefix of length
    mp·page (trash-page content appears only at positions the causal
    q_offset mask excludes)."""
    layer = lax.dynamic_index_in_dim(pool, layer_idx, 0, keepdims=False)
    g = jnp.take(layer, table, axis=0)  # (S, mp, page, kv, hd)
    s, mp, page = g.shape[0], g.shape[1], g.shape[2]
    return g.reshape(s, mp * page, *g.shape[3:])
