"""Multi-head attention dispatcher.

Two implementations behind one call:

- ``dense``: einsum attention with storage-dtype operands and f32
  accumulation/softmax (bf16 products are exact in f32, so this equals
  fully-upcast math) — the XLA-fused baseline and the correctness
  reference (also what runs on CPU test meshes);
- ``flash``: the Pallas TPU kernel (ops/flash_pallas.py) — O(seq) memory via
  online softmax.

``impl="auto"`` picks flash on TPU when shapes are tile-aligned, else dense.
Inputs are (batch, seq, heads, head_dim) — the model's natural layout; the
flash path transposes to (batch, heads, seq, head_dim) which is the layout
the kernel tiles over.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def dense_attention(
    q: jnp.ndarray,  # (batch, q_seq, num_heads, head_dim)
    k: jnp.ndarray,  # (batch, kv_seq, num_kv_heads, head_dim)
    v: jnp.ndarray,
    causal: bool,
    q_offset: jnp.ndarray | int | None = None,
    probs_dtype: jnp.dtype | None = None,
    kv_len: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Einsum attention with GQA folding. ``q_offset`` gives query i the
    absolute position ``q_offset + i`` so KV-cached decode (queries near the
    end of a longer, partially-filled key buffer) uses the same numerics as
    the q_seq == kv_seq training path: key slot j attends iff
    j <= q_offset + i, which also masks not-yet-written cache slots.
    A (batch,) ``q_offset`` gives every row its own absolute position — the
    continuous-batching decode case (infer/slots.py) where each cache slot
    sits at a different sequence length.

    ``kv_len`` ((batch,) int32) masks key positions ``>= kv_len[row]``
    regardless of causality — right-padded variable-length keys (the
    encdec slot engine's bucketed encoder inputs and per-slot cross
    k/v). Masked columns contribute exp(-1e30 - max) == 0.0 exactly, so
    a padded batch equals its unpadded rows bit-for-bit in f32.

    ``probs_dtype``: storage dtype for the (b, h, q, k) probability tensor
    feeding the PV matmul. The f32 default is the serving-correctness
    choice (results independent of cache dtype). Training paths that keep
    everything bf16 pass the storage dtype — the flash/ring kernels already
    round probs there, and at ViT-scale shapes the f32 probs tensor is the
    step's dominant HBM traffic (profiled 2026-07: b=256 ViT-B/16 carries
    805 MB f32 probs through fwd+bwd; bf16 probs lifted MFU 0.386→0.404)."""
    batch, seq, num_heads, head_dim = q.shape
    kv_seq, num_kv = k.shape[1], k.shape[2]
    group = num_heads // num_kv
    if k.dtype != q.dtype:
        # narrow KV-cache dtypes (fp8 serving cache): upcast in-register —
        # XLA fuses the convert into the einsum, so only the narrow bytes
        # cross HBM
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)
    # q/k stay in the storage dtype with f32 accumulation: bf16 products
    # are exact in f32, so this equals the upcast-everything numerics
    # without writing f32 copies of the cache. probs default to f32 (a
    # downcast makes results depend on the cache dtype — wrong for
    # serving); training callers opt into storage-dtype probs via
    # ``probs_dtype`` below. XLA upcasts v in-register inside the fused
    # einsum, not in HBM.
    qg = q.reshape(batch, seq, num_kv, group, head_dim)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                        preferred_element_type=jnp.float32)
    scores = scores * (1.0 / head_dim**0.5)
    if kv_len is not None:
        k_pos = jnp.arange(kv_seq, dtype=jnp.int32)
        lmask = k_pos[None, :] < kv_len[:, None]          # (batch, k)
        scores = jnp.where(lmask[:, None, None, None, :], scores, -1e30)
    if causal:
        q_pos = jnp.arange(seq, dtype=jnp.int32)
        k_pos = jnp.arange(kv_seq, dtype=jnp.int32)
        if q_offset is not None and getattr(q_offset, "ndim", 0) == 1:
            q_pos = q_pos[None, :] + q_offset[:, None]       # (batch, q_seq)
            mask = k_pos[None, None, :] <= q_pos[:, :, None]  # (b, q, k)
            scores = jnp.where(mask[:, None, None], scores, -1e30)
        else:
            if q_offset is not None:
                q_pos = q_pos + q_offset
            mask = k_pos[None, :] <= q_pos[:, None]  # (q_seq, kv_seq)
            scores = jnp.where(mask[None, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    if probs_dtype is not None:
        probs = probs.astype(probs_dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v,
                     preferred_element_type=jnp.float32)
    return out.reshape(batch, seq, num_heads, head_dim).astype(q.dtype)


_dense_attention = dense_attention  # back-compat alias


def multihead_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    impl: str = "auto",
    probs_dtype: jnp.dtype | None = None,
    kv_len: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """(batch, seq, heads, head_dim) attention with GQA support.
    ``probs_dtype`` forwards to ``dense_attention`` (the flash kernel
    already keeps probs in the storage dtype internally). ``kv_len``
    forces the dense path (the kernel has no length-mask plumbing)."""
    if kv_len is not None:
        return dense_attention(q, k, v, causal, probs_dtype=probs_dtype,
                               kv_len=kv_len)
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        # seq must tile by 128; head_dim 64 works too (Mosaic pads lanes),
        # and dense would materialize O(seq^2) scores — far worse than
        # padding. The kernel also assumes ONE shared seq — cross-attention
        # (q_seq != kv_seq) must stay dense
        aligned = (q.shape[1] % 128 == 0 and q.shape[-1] % 64 == 0
                   and q.shape[1] == k.shape[1])
        # short NON-causal sequences run faster through XLA's fused dense
        # einsums than through the kernel (measured on ViT-B/16 @256
        # tokens, v5e: 541 vs 511 img/s) — the flash win comes from
        # causal-block skipping and O(seq) memory, neither of which a
        # 256-token encoder needs. By 512 tokens the kernel wins again
        # (encdec-base encoder: +7% pairs/s), so the boundary sits at 256
        short_encoder = (not causal) and q.shape[1] <= 256
        impl = "flash" if (on_tpu and aligned and not short_encoder) else "dense"
    if impl == "dense":
        return dense_attention(q, k, v, causal, probs_dtype=probs_dtype)
    if impl in ("flash", "flash_interpret"):
        from tpu_docker_api.ops.flash_pallas import flash_attention

        out = flash_attention(
            q.transpose(0, 2, 1, 3),
            k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3),
            causal=causal,
            interpret=(impl == "flash_interpret"),
        )
        return out.transpose(0, 2, 1, 3)
    raise ValueError(f"unknown attention impl {impl!r}")
