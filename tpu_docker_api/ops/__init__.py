"""TPU-first neural net ops.

The reference control plane ships no kernels (SURVEY.md §2.3) — this package
is the compute path its provisioned workloads run: fused-friendly pure-JAX ops
that XLA maps onto the MXU/VPU, plus Pallas TPU kernels for the ops XLA can't
fuse optimally (flash attention's online softmax).
"""

from tpu_docker_api.ops.attention import multihead_attention  # noqa: F401
from tpu_docker_api.ops.norms import rms_norm  # noqa: F401
from tpu_docker_api.ops.rope import apply_rope, rope_frequencies  # noqa: F401
