"""Int8 quantization for inference serving.

TPU-first rationale: decode is weight-HBM-bound (every step reads every
weight once), and the v5e MXU runs int8×int8 at ~2× the bf16 rate with
int32 accumulation. Weight-only storage halves the per-token weight
traffic; quantizing activations dynamically per row lets the dot itself run
in int8 — the AQT recipe, reduced to its serving-time core:

    w_int8[i, o] = round(w[i, o] / s_w[o]),  s_w[o] = absmax_i |w| / 127
    x_int8[r, i] = round(x[r, i] / s_x[r]),  s_x[r] = absmax_i |x| / 127
    y[r, o]      = (x_int8 · w_int8)[int32] · s_x[r] · s_w[o]

Per-output-channel weight scales and per-row activation scales keep the
quantization error at the ~1% level that weight-only serving tolerates.

Training never touches this module: ``linear`` passes raw arrays straight
to ``@``, and only ``quantize_params`` (infer-time, explicit) rewrites a
param tree's projection weights into ``QuantizedLinear`` leaves. Stacked
per-layer weights quantize along their leading layer dim, and because
``QuantizedLinear`` is a registered pytree, ``lax.scan`` slices the int8
tensor and its scales together.

The reference has no quantization (or any compute) in-tree; this is part of
the serving stack the TPU build provides (SURVEY.md §0, §2.3).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class QuantizedLinear:
    """An (…, in, out) weight stored int8 with per-out-channel f32 scales."""

    w_int8: jnp.ndarray  # (…, in, out) int8
    scale: jnp.ndarray   # (…, out) f32

    @property
    def shape(self):
        return self.w_int8.shape

    @property
    def size(self):
        return self.w_int8.size


jax.tree_util.register_pytree_node(
    QuantizedLinear,
    lambda q: ((q.w_int8, q.scale), None),
    lambda _, kids: QuantizedLinear(*kids),
)

_EPS = 1e-12


def quantize_weight(w: jnp.ndarray) -> QuantizedLinear:
    """Quantize an (…, in, out) weight along its in axis (axis -2)."""
    wf = w.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(wf), axis=-2), _EPS) / 127.0
    w_int8 = jnp.clip(jnp.round(wf / scale[..., None, :]), -127, 127)
    return QuantizedLinear(w_int8.astype(jnp.int8), scale)


def dequantize_weight(q: QuantizedLinear, dtype=jnp.float32) -> jnp.ndarray:
    return (q.w_int8.astype(jnp.float32) * q.scale[..., None, :]).astype(dtype)


def int8_linear(x: jnp.ndarray, q: QuantizedLinear,
                out_dtype=None) -> jnp.ndarray:
    """y = x @ dequant(q) computed as an int8×int8 MXU dot with dynamic
    per-row activation quantization. x: (…, in); q: (in, out)."""
    xf = x.astype(jnp.float32)
    x_scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True),
                          _EPS) / 127.0
    x_int8 = jnp.clip(jnp.round(xf / x_scale), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        x_int8, q.w_int8,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    y = acc.astype(jnp.float32) * x_scale * q.scale
    return y.astype(out_dtype or x.dtype)


def linear(x: jnp.ndarray, w, out_dtype=None) -> jnp.ndarray:
    """The one projection entry point: raw arrays take the plain matmul
    path (training — unchanged numerics), QuantizedLinear takes the int8
    path (serving). ``out_dtype`` asks for widened ACCUMULATION, not a
    cast — the raw path runs the dot with that preferred_element_type
    (the lm_head's bf16-operands/f32-out contract)."""
    if isinstance(w, QuantizedLinear):
        return int8_linear(x, w, out_dtype=out_dtype)
    if out_dtype is not None:
        return jax.lax.dot_general(
            x, w, (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=out_dtype,
        )
    return x @ w
