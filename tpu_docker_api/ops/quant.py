"""Int8 quantization for inference serving.

TPU-first rationale: decode is weight-HBM-bound (every step reads every
weight once), and the v5e MXU runs int8×int8 at ~2× the bf16 rate with
int32 accumulation. Weight-only storage halves the per-token weight
traffic; quantizing activations dynamically per row lets the dot itself run
in int8 — the AQT recipe, reduced to its serving-time core:

    w_int8[i, o] = round(w[i, o] / s_w[o]),  s_w[o] = absmax_i |w| / 127
    x_int8[r, i] = round(x[r, i] / s_x[r]),  s_x[r] = absmax_i |x| / 127
    y[r, o]      = (x_int8 · w_int8)[int32] · s_x[r] · s_w[o]

Per-output-channel weight scales and per-row activation scales keep the
quantization error at the ~1% level that weight-only serving tolerates.

Training never touches this module: ``linear`` passes raw arrays straight
to ``@``, and only ``quantize_params`` (infer-time, explicit) rewrites a
param tree's projection weights into ``QuantizedLinear`` leaves. Stacked
per-layer weights quantize along their leading layer dim, and because
``QuantizedLinear`` is a registered pytree, ``lax.scan`` slices the int8
tensor and its scales together.

The reference has no quantization (or any compute) in-tree; this is part of
the serving stack the TPU build provides (SURVEY.md §0, §2.3).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class QuantizedLinear:
    """An (…, in, out) weight stored int8 with per-out-channel f32 scales."""

    w_int8: jnp.ndarray  # (…, in, out) int8
    scale: jnp.ndarray   # (…, out) f32

    @property
    def shape(self):
        return self.w_int8.shape

    @property
    def size(self):
        return self.w_int8.size


jax.tree_util.register_pytree_node(
    QuantizedLinear,
    lambda q: ((q.w_int8, q.scale), None),
    lambda _, kids: QuantizedLinear(*kids),
)

_EPS = 1e-12


def quantize_weight(w: jnp.ndarray) -> QuantizedLinear:
    """Quantize an (…, in, out) weight along its in axis (axis -2)."""
    wf = w.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(wf), axis=-2), _EPS) / 127.0
    w_int8 = jnp.clip(jnp.round(wf / scale[..., None, :]), -127, 127)
    return QuantizedLinear(w_int8.astype(jnp.int8), scale)


def dequantize_weight(q: QuantizedLinear, dtype=jnp.float32) -> jnp.ndarray:
    return (q.w_int8.astype(jnp.float32) * q.scale[..., None, :]).astype(dtype)


def int8_linear(x: jnp.ndarray, q: QuantizedLinear,
                out_dtype=None) -> jnp.ndarray:
    """y = x @ dequant(q) computed as an int8×int8 MXU dot with dynamic
    per-row activation quantization. x: (…, in); q: (in, out).

    custom_vjp (straight-through): the forward's ``round`` on the
    activations has zero gradient almost everywhere, so naive autodiff
    through it returns zero dL/dx and silently kills backprop through
    any layer BELOW an int8 projection — exactly the QLoRA case (frozen
    int8 base, trainable adapters, gradients must flow through the base
    matmuls to reach earlier layers). The STE backward is the exact
    gradient of the DEQUANTIZED matmul: dL/dx = (g · s_w) @ W_int8ᵀ,
    computed as a mixed f32×int8 dot (the int8→f32 convert fuses into
    the dot — no dequantized weight copy materializes). The weights are
    frozen by contract, so their cotangent is symbolically zero."""
    # custom_vjp nondiff args must LEAD the signature; keep the public
    # (x, q, out_dtype) order via this shim
    return _int8_linear(out_dtype, x, q)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _int8_linear(out_dtype, x, q):
    return _int8_linear_fwd_impl(x, q, out_dtype)


def _int8_linear_fwd_impl(x, q, out_dtype):
    xf = x.astype(jnp.float32)
    x_scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True),
                          _EPS) / 127.0
    x_int8 = jnp.clip(jnp.round(xf / x_scale), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        x_int8, q.w_int8,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    y = acc.astype(jnp.float32) * x_scale * q.scale
    return y.astype(out_dtype or x.dtype)


def _int8_linear_fwd(out_dtype, x, q):
    # residuals must be jax values — a 0-sized array carries x's dtype
    return (_int8_linear_fwd_impl(x, q, out_dtype),
            (q, jnp.zeros((0,), x.dtype)))


def _int8_linear_bwd(out_dtype, res, g):
    q, x_proto = res
    x_dtype = x_proto.dtype
    gs = g.astype(jnp.float32) * q.scale  # fold per-channel scales in
    gx = jax.lax.dot_general(
        gs, q.w_int8,
        (((g.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # frozen weights: symbolically-zero cotangents (float0 for the int8
    # tensor — jax's tangent type for integer leaves)
    gq = QuantizedLinear(
        np.zeros(q.w_int8.shape, jax.dtypes.float0),
        jnp.zeros_like(q.scale))
    return gx.astype(x_dtype), gq


_int8_linear.defvjp(_int8_linear_fwd, _int8_linear_bwd)


@dataclasses.dataclass
class LoraLinear:
    """A frozen base projection (raw array OR QuantizedLinear) plus a
    low-rank adapter branch, evaluated UNMERGED:

        y = linear(x, base) + s·(x @ A) @ B,   s = alpha / rank

    The QLoRA leaf (train/lora.py ``attach_lora``): the merged tree
    ``W + s·A@B`` never materializes — at llama3-8b the bf16 merged
    copy is 16 GB, over a v5e's HBM, while base-int8 + adapters is
    ~8 GB. The adapter branch computes in the adapter dtype (f32) and
    casts at the add, so the base path's numerics/dtype are untouched
    and autodiff reaches A/B exactly; the base is frozen by contract
    (int8 bases get symbolically-zero weight cotangents via
    ``int8_linear``'s STE vjp, raw bases just discard theirs)."""

    base: Any            # (…, in, out) array or QuantizedLinear
    a: jnp.ndarray       # (…, in, rank)
    b: jnp.ndarray       # (…, rank, out)
    scale: float         # alpha / rank — static pytree aux

    @property
    def shape(self):
        return self.base.shape


jax.tree_util.register_pytree_node(
    LoraLinear,
    lambda l: ((l.base, l.a, l.b), l.scale),
    lambda scale, kids: LoraLinear(*kids, scale),
)


def linear(x: jnp.ndarray, w, out_dtype=None) -> jnp.ndarray:
    """The one projection entry point: raw arrays take the plain matmul
    path (training — unchanged numerics), QuantizedLinear takes the int8
    path (serving). ``out_dtype`` asks for widened ACCUMULATION, not a
    cast — the raw path runs the dot with that preferred_element_type
    (the lm_head's bf16-operands/f32-out contract)."""
    if isinstance(w, LoraLinear):
        y = linear(x, w.base, out_dtype=out_dtype)
        delta = (x.astype(w.a.dtype) @ w.a) @ w.b
        return y + (w.scale * delta).astype(y.dtype)
    if isinstance(w, QuantizedLinear):
        return int8_linear(x, w, out_dtype)
    if out_dtype is not None:
        return jax.lax.dot_general(
            x, w, (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=out_dtype,
        )
    return x @ w
