"""Rotary position embeddings (RoPE).

Split-halves convention (rotate_half), precomputed cos/sin tables: the tables
are tiny, static-shaped, and XLA folds their application into the surrounding
QK projections — no gather, no dynamic shapes, MXU-friendly.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Llama-3.x ``"rope_type": "llama3"`` frequency band scaling — the
    ``rope_scaling`` block every real Llama-3.1/3.2 ``config.json``
    carries. Frozen (hashable) so it can live on the frozen LlamaConfig
    that keys jit caches.

    The scheme stretches LOW-frequency (long-wavelength) bands by
    ``factor`` to reach the extended context, keeps HIGH-frequency
    (short-wavelength, local-order) bands untouched, and linearly
    interpolates between the two cutoffs. Wavelengths are measured
    against ``original_max_position_embeddings`` (the pre-extension
    training context)."""

    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8192

    def apply(self, inv_freq: jnp.ndarray) -> jnp.ndarray:
        """Scale per-band inverse frequencies (the HF llama3 formula)."""
        wavelen = 2.0 * jnp.pi / inv_freq
        low_wl = self.original_max_position_embeddings / self.low_freq_factor
        high_wl = (self.original_max_position_embeddings
                   / self.high_freq_factor)
        smooth = ((self.original_max_position_embeddings / wavelen
                   - self.low_freq_factor)
                  / (self.high_freq_factor - self.low_freq_factor))
        smoothed = ((1.0 - smooth) * inv_freq / self.factor
                    + smooth * inv_freq)
        out = jnp.where(wavelen > low_wl, inv_freq / self.factor, inv_freq)
        return jnp.where((wavelen >= high_wl) & (wavelen <= low_wl),
                         smoothed, out)


def rope_frequencies(
    head_dim: int, max_seq_len: int, theta: float = 10000.0,
    scaling: RopeScaling | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(cos, sin) tables of shape (max_seq_len, head_dim // 2), float32."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if scaling is not None:
        inv_freq = scaling.apply(inv_freq)
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # (seq, head_dim/2)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(
    x: jnp.ndarray,  # (batch, seq, heads, head_dim)
    cos: jnp.ndarray,  # (max_seq, head_dim/2)
    sin: jnp.ndarray,
    positions: jnp.ndarray | None = None,  # (batch, seq) absolute positions
) -> jnp.ndarray:
    """Rotate q/k by position-dependent phases.

    The phase TABLES are always f32 (angles at position 32k need the
    mantissa). The rotation itself is applied in x's own dtype on the
    TRAINING path (``positions is None``): the inputs are already
    bf16-rounded, so f32 application adds no information while its
    upcast/downcast converts measured ~3% of the llama3-1b train step
    (docs/perf-notes.md). The KV-cached SERVING path (explicit
    ``positions``) keeps f32 application: bf16 intermediates round at
    fusion boundaries, which differ between lowerings of the same model
    (sharded vs single-device), and serving promises bit-identical tokens
    across those (tests/test_infer.py TestShardedGenerate, and the
    speculative verifier's exactness contract)."""
    _, seq, _, head_dim = x.shape
    if positions is None:
        c = cos[:seq][None, :, None, :]  # (1, seq, 1, hd/2)
        s = sin[:seq][None, :, None, :]
        c = c.astype(x.dtype)
        s = s.astype(x.dtype)
        xc = x
    else:
        c = cos[positions][:, :, None, :]  # (batch, seq, 1, hd/2)
        s = sin[positions][:, :, None, :]
        xc = x.astype(jnp.float32)
    x1, x2 = xc[..., : head_dim // 2], xc[..., head_dim // 2:]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)
