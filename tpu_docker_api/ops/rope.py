"""Rotary position embeddings (RoPE).

Split-halves convention (rotate_half), precomputed cos/sin tables: the tables
are tiny, static-shaped, and XLA folds their application into the surrounding
QK projections — no gather, no dynamic shapes, MXU-friendly.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int, max_seq_len: int, theta: float = 10000.0
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(cos, sin) tables of shape (max_seq_len, head_dim // 2), float32."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # (seq, head_dim/2)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(
    x: jnp.ndarray,  # (batch, seq, heads, head_dim)
    cos: jnp.ndarray,  # (max_seq, head_dim/2)
    sin: jnp.ndarray,
    positions: jnp.ndarray | None = None,  # (batch, seq) absolute positions
) -> jnp.ndarray:
    """Rotate q/k by position-dependent phases; computed in f32, cast back."""
    _, seq, _, head_dim = x.shape
    if positions is None:
        c = cos[:seq][None, :, None, :]  # (1, seq, 1, hd/2)
        s = sin[:seq][None, :, None, :]
    else:
        c = cos[positions][:, :, None, :]  # (batch, seq, 1, hd/2)
        s = sin[positions][:, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., : head_dim // 2], xf[..., head_dim // 2:]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)
