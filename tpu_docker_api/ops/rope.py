"""Rotary position embeddings (RoPE).

Split-halves convention (rotate_half), precomputed cos/sin tables: the tables
are tiny, static-shaped, and XLA folds their application into the surrounding
QK projections — no gather, no dynamic shapes, MXU-friendly.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int, max_seq_len: int, theta: float = 10000.0
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(cos, sin) tables of shape (max_seq_len, head_dim // 2), float32."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # (seq, head_dim/2)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(
    x: jnp.ndarray,  # (batch, seq, heads, head_dim)
    cos: jnp.ndarray,  # (max_seq, head_dim/2)
    sin: jnp.ndarray,
    positions: jnp.ndarray | None = None,  # (batch, seq) absolute positions
) -> jnp.ndarray:
    """Rotate q/k by position-dependent phases.

    The phase TABLES are always f32 (angles at position 32k need the
    mantissa). The rotation itself is applied in x's own dtype on the
    TRAINING path (``positions is None``): the inputs are already
    bf16-rounded, so f32 application adds no information while its
    upcast/downcast converts measured ~3% of the llama3-1b train step
    (docs/perf-notes.md). The KV-cached SERVING path (explicit
    ``positions``) keeps f32 application: bf16 intermediates round at
    fusion boundaries, which differ between lowerings of the same model
    (sharded vs single-device), and serving promises bit-identical tokens
    across those (tests/test_infer.py TestShardedGenerate, and the
    speculative verifier's exactness contract)."""
    _, seq, _, head_dim = x.shape
    if positions is None:
        c = cos[:seq][None, :, None, :]  # (1, seq, 1, hd/2)
        s = sin[:seq][None, :, None, :]
        c = c.astype(x.dtype)
        s = s.astype(x.dtype)
        xc = x
    else:
        c = cos[positions][:, :, None, :]  # (batch, seq, 1, hd/2)
        s = sin[positions][:, :, None, :]
        xc = x.astype(jnp.float32)
    x1, x2 = xc[..., : head_dim // 2], xc[..., head_dim // 2:]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)
