"""MNIST MLP — BASELINE.json config #2 (single-chip smoke workload)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def mlp_init(key: jax.Array, sizes=(784, 512, 256, 10), dtype=jnp.float32) -> dict:
    params = {}
    keys = jax.random.split(key, len(sizes) - 1)
    for i, (k, fan_in, fan_out) in enumerate(zip(keys, sizes[:-1], sizes[1:])):
        params[f"dense_{i}"] = {
            "w": (jax.random.normal(k, (fan_in, fan_out)) * fan_in**-0.5).astype(dtype),
            "b": jnp.zeros((fan_out,), dtype),
        }
    return params


def mlp_forward(params: dict, x: jnp.ndarray) -> jnp.ndarray:
    """(batch, 784) → (batch, 10) logits."""
    n = len(params)
    for i in range(n):
        layer = params[f"dense_{i}"]
        x = x @ layer["w"] + layer["b"]
        if i < n - 1:
            x = jax.nn.relu(x)
    return x


def mlp_loss(params: dict, x: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    logits = mlp_forward(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
