"""Encoder-decoder (T5-class) seq2seq transformer — the cross-attention
family.

The fourth in-tree workload family (reference ships none, SURVEY.md §0),
covering the one architecture surface Llama/MoE/ViT do not: a
bidirectional encoder feeding a causal decoder through CROSS-attention.
What it exercises that the others cannot:

- cross-attention: decoder queries against encoder keys/values — kv seq
  length differs from q seq length, no causal mask, no rope on the cross
  path (positions live in the self-attention paths on each side);
- two heterogeneous layer stacks in one model (scan+remat each);
- seq2seq batches: (src_tokens, tgt_tokens) tuples through the generic
  trainer, like ViT's (images, labels).

TPU-first choices follow the house style (models/llama.py): stacked
layers + ``lax.scan``, bf16 storage with f32 norms/softmax/logits,
Megatron column/row sharding rules over (fsdp, tp), rope for positions
(no learned-position or relative-bias tables — rope is free of the
(S, T) bias matmuls T5 pays and rides the same ops/rope.py path the
other families use), shared src/tgt embedding, ``embed_lookup`` for the
tp-sharded vocab gather.

Sequence parallelism (round 3): on meshes with a real ``sp`` axis, both
stacks' SELF-attention rides ring attention (parallel/ring.py —
non-causal contiguous for the bidirectional encoder, causal zigzag for
the decoder; rope is applied globally before the ring, so no
model-side position changes). CROSS-attention keeps the encoder output
gathered over sp (one all-gather of the (b, S, d) activations per
forward — decoder queries stay seq-sharded, encoder k/v are full), the
same trade MaxText-style encoder-decoder sharding makes: the cross k/v
are reused by every decoder layer, so gathering once beats ringing them
per layer.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpu_docker_api.models.common import trunc_normal_init
from tpu_docker_api.models.llama import cross_entropy, embed_lookup
from tpu_docker_api.ops.attention import multihead_attention
from tpu_docker_api.ops.norms import rms_norm
from tpu_docker_api.ops.quant import linear
from tpu_docker_api.ops.rope import apply_rope, rope_frequencies
from tpu_docker_api.parallel.sharding import constrain

#: suffix rules (parallel/sharding.py): both stacks' projections are
#: Megatron column/row over (fsdp, tp); scan axis never sharded
ENCDEC_RULES: list[tuple[str, P]] = [
    ("embed/tokens",            P("tp", "fsdp")),
    ("enc_layers/attn/wo",      P(None, "tp", "fsdp")),
    ("enc_layers/attn/w*",      P(None, "fsdp", "tp")),
    ("enc_layers/mlp/w_down",   P(None, "tp", "fsdp")),
    ("enc_layers/mlp/w*",       P(None, "fsdp", "tp")),
    ("dec_layers/*attn/wo",     P(None, "tp", "fsdp")),
    ("dec_layers/*attn/w*",     P(None, "fsdp", "tp")),
    ("dec_layers/mlp/w_down",   P(None, "tp", "fsdp")),
    ("dec_layers/mlp/w*",       P(None, "fsdp", "tp")),
    ("*norm*",                  P()),
    ("lm_head",                 P("fsdp", "tp")),
    ("*",                       P()),
]


@dataclasses.dataclass(frozen=True)
class EncDecConfig:
    vocab_size: int = 32000
    dim: int = 768
    enc_layers: int = 12
    dec_layers: int = 12
    n_heads: int = 12
    n_kv_heads: int = 12
    ffn_dim: int = 3072
    max_src_len: int = 512
    max_tgt_len: int = 512
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # >0: encdec_loss fuses the 32k-vocab head into
    # ops.xent.chunked_cross_entropy with this row-chunk size — the (b, T,
    # vocab) f32 logits (2.1 GB at bench shapes) and its backward dlogits
    # are never materialized. The round-2 encdec MFU shortfall (0.334 vs
    # 0.40) was diagnosed as exactly this head (docs/perf-notes.md)
    loss_chunk_rows: int = 0

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def flops_per_pair(self, src_len: int, tgt_len: int) -> float:
        """Training FLOPs per (src, tgt) sequence pair (fwd+bwd ≈ 3×).
        Per-row projection costs: q and o act on the query-side rows, k and
        v on the key-side rows — which differ on the cross path (q/o on
        tgt, k/v on src). One MLP per layer on both sides."""
        d, hd = self.dim, self.head_dim
        qo = 2 * 2 * d * (self.n_heads * hd)       # q + o per row
        kv = 2 * 2 * d * (self.n_kv_heads * hd)    # k + v per row
        mlp = 3 * 2 * d * self.ffn_dim
        enc = self.enc_layers * (
            src_len * (qo + kv + mlp)
            + 2 * 2 * src_len * src_len * (self.n_heads * hd))  # full attn
        dec = self.dec_layers * (
            tgt_len * (qo + kv + mlp)              # self-attention + MLP
            + 2 * 2 * tgt_len * tgt_len * (self.n_heads * hd) / 2  # causal
            + tgt_len * qo + src_len * kv          # cross projections
            + 2 * 2 * tgt_len * src_len * (self.n_heads * hd))     # cross
        head = tgt_len * 2 * d * self.vocab_size
        return 3.0 * (enc + dec + head)


def encdec_presets() -> dict[str, EncDecConfig]:
    return {
        # T5-base-class geometry (~250M params), rope positions
        "encdec-base": EncDecConfig(),
        # CPU-fast config for tests / dryrun
        "tiny": EncDecConfig(
            vocab_size=256, dim=64, enc_layers=2, dec_layers=2, n_heads=4,
            n_kv_heads=2, ffn_dim=128, max_src_len=64, max_tgt_len=64,
            remat=False),
    }


def _attn_params(key, d, cfg: EncDecConfig, L):
    hd = cfg.head_dim
    ks = jax.random.split(key, 4)

    def init(k, shape, fan_in):
        return trunc_normal_init(k, shape, fan_in, cfg.dtype)

    return {
        "wq": init(ks[0], (L, d, cfg.n_heads * hd), d),
        "wk": init(ks[1], (L, d, cfg.n_kv_heads * hd), d),
        "wv": init(ks[2], (L, d, cfg.n_kv_heads * hd), d),
        "wo": init(ks[3], (L, cfg.n_heads * hd, d), cfg.n_heads * hd),
    }


def encdec_init(cfg: EncDecConfig, key: jax.Array) -> dict:
    d = cfg.dim
    k_embed, k_enc, k_dec_self, k_dec_cross, k_mlps, k_head = (
        jax.random.split(key, 6))

    def init(k, shape, fan_in):
        return trunc_normal_init(k, shape, fan_in, cfg.dtype)

    def mlp_params(k, L):
        ks = jax.random.split(k, 3)
        return {
            "w_gate": init(ks[0], (L, d, cfg.ffn_dim), d),
            "w_up": init(ks[1], (L, d, cfg.ffn_dim), d),
            "w_down": init(ks[2], (L, cfg.ffn_dim, d), cfg.ffn_dim),
        }

    km_enc, km_dec = jax.random.split(k_mlps)
    Le, Ld = cfg.enc_layers, cfg.dec_layers
    return {
        "embed": {"tokens": init(k_embed, (cfg.vocab_size, d), d)},
        "enc_layers": {
            "attn_norm": jnp.ones((Le, d), cfg.dtype),
            "mlp_norm": jnp.ones((Le, d), cfg.dtype),
            "attn": _attn_params(k_enc, d, cfg, Le),
            "mlp": mlp_params(km_enc, Le),
        },
        "dec_layers": {
            "self_norm": jnp.ones((Ld, d), cfg.dtype),
            "cross_norm": jnp.ones((Ld, d), cfg.dtype),
            "mlp_norm": jnp.ones((Ld, d), cfg.dtype),
            "self_attn": _attn_params(k_dec_self, d, cfg, Ld),
            "cross_attn": _attn_params(k_dec_cross, d, cfg, Ld),
            "mlp": mlp_params(km_dec, Ld),
        },
        "enc_final_norm": jnp.ones((d,), cfg.dtype),
        "final_norm": jnp.ones((d,), cfg.dtype),
        "lm_head": init(k_head, (d, cfg.vocab_size), d),
    }


def _project_qkv(x, weights, cfg: EncDecConfig, kv_from=None):
    """q from ``x``, k/v from ``kv_from`` (defaults to x — self-attention)."""
    b, s, _ = x.shape
    hd = cfg.head_dim
    src = x if kv_from is None else kv_from
    sk = src.shape[1]
    q = linear(x, weights["wq"]).reshape(b, s, cfg.n_heads, hd)
    k = linear(src, weights["wk"]).reshape(b, sk, cfg.n_kv_heads, hd)
    v = linear(src, weights["wv"]).reshape(b, sk, cfg.n_kv_heads, hd)
    return q, k, v


def _mlp(x, mlp):
    gate = jax.nn.silu(linear(x, mlp["w_gate"]))
    up = linear(x, mlp["w_up"])
    return linear(gate * up, mlp["w_down"])


def _has_sp(mesh) -> bool:
    return (mesh is not None and not mesh.empty
            and mesh.shape.get("sp", 1) > 1)


def _enc_block(x, layer, cfg: EncDecConfig, rope_cos, rope_sin, mesh,
               kv_len=None):
    """Bidirectional self-attention + SwiGLU, pre-norm residuals. On an
    sp mesh the attention rides the non-causal ring (contiguous
    placement — no causal skew to fix). ``kv_len`` ((b,) int32) masks
    right-pad positions out of the bidirectional attention — bucketed
    slot-engine admissions must encode EXACTLY like the unpadded source
    (pad keys would otherwise shift every real position's softmax)."""
    b, s, d = x.shape
    y = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q, k, v = _project_qkv(y, layer["attn"], cfg)
    q = apply_rope(q, rope_cos, rope_sin)
    k = apply_rope(k, rope_cos, rope_sin)
    if _has_sp(mesh):
        if kv_len is not None:
            # the ring kernel has no length-mask plumbing; silently
            # dropping the mask would corrupt every real position's
            # bidirectional softmax — the exact bug the mask prevents
            raise NotImplementedError(
                "kv_len masking is not supported on sp-mesh encodes "
                "(ring attention path)")
        from tpu_docker_api.parallel.ring import ring_attention

        out = ring_attention(q, k, v, mesh, causal=False)
    else:
        out = multihead_attention(q, k, v, causal=False,
                                  probs_dtype=cfg.dtype, kv_len=kv_len)
    x = x + linear(out.reshape(b, s, d), layer["attn"]["wo"])
    bspec = P(("dp", "fsdp"), "sp")
    x = constrain(x, mesh, bspec) if mesh is not None else x
    x = x + _mlp(rms_norm(x, layer["mlp_norm"], cfg.norm_eps), layer["mlp"])
    return constrain(x, mesh, bspec) if mesh is not None else x


def _dec_block(x, enc_out, layer, cfg: EncDecConfig, rope_cos, rope_sin,
               mesh):
    """Causal self-attention → cross-attention over ``enc_out`` → SwiGLU.
    Cross-attention applies no rope: relative order information lives in
    each side's self-attention; the cross path is pure content lookup.
    On an sp mesh: self-attention rides the causal zigzag ring; the
    cross path keeps enc_out replicated over sp (module docstring) so
    seq-sharded queries attend full encoder k/v."""
    b, s, d = x.shape
    y = rms_norm(x, layer["self_norm"], cfg.norm_eps)
    q, k, v = _project_qkv(y, layer["self_attn"], cfg)
    q = apply_rope(q, rope_cos, rope_sin)
    k = apply_rope(k, rope_cos, rope_sin)
    if _has_sp(mesh):
        from tpu_docker_api.parallel.ring import ring_attention

        out = ring_attention(q, k, v, mesh, causal=True,
                             placement="zigzag")
    else:
        out = multihead_attention(q, k, v, causal=True)
    x = x + linear(out.reshape(b, s, d), layer["self_attn"]["wo"])

    y = rms_norm(x, layer["cross_norm"], cfg.norm_eps)
    q, k, v = _project_qkv(y, layer["cross_attn"], cfg, kv_from=enc_out)
    # auto dispatch: its q_seq == kv_seq guard keeps differing-length
    # cross shapes on dense; equal-length pairs may take the flash kernel
    out = multihead_attention(q, k, v, causal=False, probs_dtype=cfg.dtype)
    x = x + linear(out.reshape(b, s, d), layer["cross_attn"]["wo"])
    bspec = P(("dp", "fsdp"), "sp")
    x = constrain(x, mesh, bspec) if mesh is not None else x
    x = x + _mlp(rms_norm(x, layer["mlp_norm"], cfg.norm_eps), layer["mlp"])
    return constrain(x, mesh, bspec) if mesh is not None else x


def _maybe_remat(fn, cfg: EncDecConfig):
    if not cfg.remat:
        return fn
    from tpu_docker_api.ops.flash_pallas import TRAIN_REMAT_POLICY

    return jax.checkpoint(fn, policy=TRAIN_REMAT_POLICY)


def encdec_encode(params, src, cfg: EncDecConfig, mesh=None, kv_len=None):
    """(b, S) source tokens → (b, S, d) encoder output (final-normed).
    ``kv_len`` ((b,) int32): treat row b's positions >= kv_len[b] as
    right-padding — excluded from every layer's attention, so the
    output at real positions equals encoding the unpadded source."""
    x = embed_lookup(params["embed"]["tokens"], src, mesh)
    if mesh is not None:
        x = constrain(x, mesh, P(("dp", "fsdp"), "sp"))
    rope_cos, rope_sin = rope_frequencies(
        cfg.head_dim, src.shape[1], cfg.rope_theta)
    block = _maybe_remat(functools.partial(
        _enc_block, cfg=cfg, rope_cos=rope_cos, rope_sin=rope_sin,
        mesh=mesh, kv_len=kv_len), cfg)

    def body(x, layer):
        return block(x, layer), None

    x, _ = lax.scan(body, x, params["enc_layers"])
    out = rms_norm(x, params["enc_final_norm"], cfg.norm_eps).astype(
        cfg.dtype)
    if _has_sp(mesh):
        # gather the encoder output over sp ONCE: every decoder layer's
        # cross-attention reuses it as full-length k/v (module docstring)
        out = constrain(out, mesh, P(("dp", "fsdp"), None))
    return out


def encdec_hidden(params, batch, cfg: EncDecConfig, mesh=None):
    """((b, S) src, (b, T) tgt-input) → final decoder hidden (b, T, d),
    pre-final-norm — shared by the dense-logits tail (``encdec_forward``)
    and the chunked-CE loss (which never materializes full logits)."""
    src, tgt = batch
    enc_out = encdec_encode(params, src, cfg, mesh)
    x = embed_lookup(params["embed"]["tokens"], tgt, mesh)
    if mesh is not None:
        x = constrain(x, mesh, P(("dp", "fsdp"), "sp"))
    rope_cos, rope_sin = rope_frequencies(
        cfg.head_dim, tgt.shape[1], cfg.rope_theta)
    block = _maybe_remat(functools.partial(
        _dec_block, cfg=cfg, rope_cos=rope_cos, rope_sin=rope_sin,
        mesh=mesh), cfg)

    def body(x, layer):
        return block(x, enc_out, layer), None

    x, _ = lax.scan(body, x, params["dec_layers"])
    return x


def encdec_forward(params, batch, cfg: EncDecConfig, mesh=None):
    """((b, S) src, (b, T) tgt-input) → next-token logits (b, T, vocab)."""
    x = encdec_hidden(params, batch, cfg, mesh)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = linear(x.astype(cfg.dtype), params["lm_head"],
                    out_dtype=jnp.float32)
    if mesh is not None:
        logits = constrain(logits, mesh, P(("dp", "fsdp"), "sp", "tp"))
    return logits


def encdec_loss(params, batch, cfg: EncDecConfig, mesh=None):
    """Teacher-forced seq2seq CE: batch = (src (b, S), tgt (b, T+1));
    decoder consumes tgt[:, :-1] and predicts tgt[:, 1:].

    With ``cfg.loss_chunk_rows`` set, the head fuses into
    ``ops.xent.chunked_cross_entropy`` exactly like ``llama_loss``."""
    src, tgt = batch
    if cfg.loss_chunk_rows:
        from tpu_docker_api.ops.xent import chunked_cross_entropy

        x = encdec_hidden(params, (src, tgt[:, :-1]), cfg, mesh)
        h = rms_norm(x, params["final_norm"], cfg.norm_eps).astype(cfg.dtype)
        if mesh is not None:
            h = constrain(h, mesh, P(("dp", "fsdp"), "sp", None))
        return chunked_cross_entropy(
            h, params["lm_head"], tgt[:, 1:], cfg.loss_chunk_rows)
    logits = encdec_forward(params, (src, tgt[:, :-1]), cfg, mesh)
    return cross_entropy(logits, tgt[:, 1:])


def encdec_synthetic_batch(key: jax.Array, batch: int, src_len: int,
                           tgt_len: int, cfg: EncDecConfig,
                           row_offset: int = 0):
    """(src, tgt) synthetic pair with the same per-GLOBAL-row derivation
    contract as vit_synthetic_batch (process-count-invariant rows)."""
    rows = jnp.arange(row_offset, row_offset + batch)
    keys = jax.vmap(lambda r: jax.random.fold_in(key, r))(rows)

    def one(k):
        k1, k2 = jax.random.split(k)
        src = jax.random.randint(k1, (src_len,), 0, cfg.vocab_size,
                                 dtype=jnp.int32)
        tgt = jax.random.randint(k2, (tgt_len + 1,), 0, cfg.vocab_size,
                                 dtype=jnp.int32)
        return src, tgt

    return jax.vmap(one)(keys)


def _cross_kv(params, enc_out, cfg: EncDecConfig):
    """Precompute every decoder layer's cross-attention k/v from the
    encoder output — they are fixed for the whole decode, so they are
    computed once, OUTSIDE the token loop: (Ld, b, S, kvh, hd) each."""
    b, S, _ = enc_out.shape
    hd = cfg.head_dim

    def per_layer(_, w):
        k = linear(enc_out, w["wk"]).reshape(b, S, cfg.n_kv_heads, hd)
        v = linear(enc_out, w["wv"]).reshape(b, S, cfg.n_kv_heads, hd)
        return None, (k, v)

    _, (ks, vs) = lax.scan(per_layer, None,
                           params["dec_layers"]["cross_attn"])
    return ks, vs


def encdec_slot_decode_step(
    params: dict,
    tok: jnp.ndarray,        # (S,) int32 current token per slot
    pos: jnp.ndarray,        # (S,) int32 per-slot decode position
    cfg: EncDecConfig,
    k_cache: jnp.ndarray,    # (Ld, S, max_tgt, kvh, hd) self-attn cache
    v_cache: jnp.ndarray,
    cross_k: jnp.ndarray,    # (Ld, S, src_cap, kvh, hd) per-slot static
    cross_v: jnp.ndarray,
    src_lens: jnp.ndarray,   # (S,) int32 true source length per slot
    rope_cos, rope_sin,
    kv_limit: int | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """ONE decoder position for S independent slot rows — the decode
    body of the encdec slot engine (infer/encdec_slots.py). Math is
    ``encdec_generate``'s dec_step with three slot-engine twists, all
    established by the llama engine (models/llama.py ``_attention``):
    per-row positions (scatter cache writes, ``mode="drop"`` past
    capacity; per-row causal ``q_offset``), a static ``kv_limit``
    read bucket on the self-attn cache, and per-row ``src_lens``
    masking the cross path (each slot's static cross k/v sit
    right-padded in a shared bucket-capacity buffer). Returns
    (logits (S, vocab) f32, k_cache, v_cache)."""
    from tpu_docker_api.ops.attention import dense_attention

    S = tok.shape[0]
    d, hd = cfg.dim, cfg.head_dim
    x = embed_lookup(params["embed"]["tokens"], tok[:, None], None)
    rows = jnp.arange(S, dtype=jnp.int32)[:, None]
    positions = pos[:, None]

    def layer_body(inner, packed):
        x, k_cache, v_cache = inner
        layer, layer_idx, ck, cv = packed
        y = rms_norm(x, layer["self_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(y, layer["self_attn"], cfg)
        q = apply_rope(q, rope_cos, rope_sin, positions)
        k = apply_rope(k, rope_cos, rope_sin, positions)
        k_cache = k_cache.at[layer_idx, rows, positions].set(
            k.astype(k_cache.dtype), mode="drop")
        v_cache = v_cache.at[layer_idx, rows, positions].set(
            v.astype(v_cache.dtype), mode="drop")
        kc = lax.dynamic_index_in_dim(k_cache, layer_idx, 0, False)
        vc = lax.dynamic_index_in_dim(v_cache, layer_idx, 0, False)
        if kv_limit is not None and kv_limit < kc.shape[1]:
            kc = lax.slice_in_dim(kc, 0, kv_limit, axis=1)
            vc = lax.slice_in_dim(vc, 0, kv_limit, axis=1)
        out = dense_attention(q, kc, vc, causal=True, q_offset=pos)
        x = x + linear(out.reshape(S, 1, d), layer["self_attn"]["wo"])

        y = rms_norm(x, layer["cross_norm"], cfg.norm_eps)
        q = linear(y, layer["cross_attn"]["wq"]).reshape(
            S, 1, cfg.n_heads, hd)
        out = dense_attention(q, ck, cv, causal=False, kv_len=src_lens)
        x = x + linear(out.reshape(S, 1, d), layer["cross_attn"]["wo"])
        x = x + _mlp(rms_norm(x, layer["mlp_norm"], cfg.norm_eps),
                     layer["mlp"])
        return (x, k_cache, v_cache), None

    (x, k_cache, v_cache), _ = lax.scan(
        layer_body, (x, k_cache, v_cache),
        (params["dec_layers"], jnp.arange(cfg.dec_layers), cross_k,
         cross_v))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = linear(x.astype(cfg.dtype), params["lm_head"],
                    out_dtype=jnp.float32)
    return logits[:, 0], k_cache, v_cache


def encdec_generate(
    params: dict,
    src: jnp.ndarray,        # (b, S) int32 source tokens
    cfg: EncDecConfig,
    max_new_tokens: int = 32,
    bos_id: int = 0,
    eos_id: int | None = None,
    pad_id: int = 0,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    rng: jax.Array | None = None,
) -> jnp.ndarray | dict:
    """Seq2seq generation: encode once, then a KV-cached decoder
    loop — self-attention against a (Ld, b, T, kvh, hd) cache written one
    position per step, cross-attention against the precomputed encoder
    k/v. Returns (b, max_new_tokens) int32; with ``eos_id`` set, returns
    {"tokens", "lengths"} with the same truncate-at-eos-inclusive
    contract as the llama engine (positions after eos hold ``pad_id``).

    Sampling shares ``infer.sampling.make_sampler`` with the llama
    engine: ``temperature == 0`` is greedy argmax (default);
    ``temperature > 0`` draws from the temperature-scaled, optionally
    top-k/top-p-filtered distribution, one ``rng``-derived key per step.
    Sampler knobs are Python-level (baked into the compiled program);
    ``rng`` is traced. Jit-compatible (one compile per
    (b, S, max_new_tokens, sampler-config) shape)."""
    from tpu_docker_api.infer.sampling import make_sampler
    from tpu_docker_api.ops.attention import dense_attention

    sampler = make_sampler(temperature, top_k=top_k, top_p=top_p)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    b, _ = src.shape
    d, hd = cfg.dim, cfg.head_dim
    Ld, n_kv = cfg.dec_layers, cfg.n_kv_heads
    enc_out = encdec_encode(params, src, cfg)
    cross_k, cross_v = _cross_kv(params, enc_out, cfg)
    rope_cos, rope_sin = rope_frequencies(hd, max_new_tokens, cfg.rope_theta)

    k_cache = jnp.zeros((Ld, b, max_new_tokens, n_kv, hd), cfg.dtype)
    v_cache = jnp.zeros_like(k_cache)

    def dec_step(carry, step_key):
        tok, k_cache, v_cache, step = carry
        x = embed_lookup(params["embed"]["tokens"], tok[:, None], None)

        def layer_body(inner, packed):
            x, k_cache, v_cache = inner
            layer, layer_idx, ck, cv = packed
            y = rms_norm(x, layer["self_norm"], cfg.norm_eps)
            q, k, v = _project_qkv(y, layer["self_attn"], cfg)
            pos = jnp.full((b, 1), step, jnp.int32)
            q = apply_rope(q, rope_cos, rope_sin, pos)
            k = apply_rope(k, rope_cos, rope_sin, pos)
            zero = jnp.int32(0)
            k_cache = lax.dynamic_update_slice(
                k_cache, k.astype(k_cache.dtype)[None],
                (layer_idx, zero, step, zero, zero))
            v_cache = lax.dynamic_update_slice(
                v_cache, v.astype(v_cache.dtype)[None],
                (layer_idx, zero, step, zero, zero))
            kc = lax.dynamic_index_in_dim(k_cache, layer_idx, 0, False)
            vc = lax.dynamic_index_in_dim(v_cache, layer_idx, 0, False)
            out = dense_attention(q, kc, vc, causal=True, q_offset=step)
            x = x + linear(out.reshape(b, 1, d), layer["self_attn"]["wo"])

            y = rms_norm(x, layer["cross_norm"], cfg.norm_eps)
            # q only: the cross k/v were precomputed once by _cross_kv —
            # projecting them again from enc_out here would cost two full
            # (b, S, d) matmuls per layer per generated token
            q = linear(y, layer["cross_attn"]["wq"]).reshape(
                b, 1, cfg.n_heads, hd)
            out = dense_attention(q, ck, cv, causal=False)
            x = x + linear(out.reshape(b, 1, d), layer["cross_attn"]["wo"])
            x = x + _mlp(rms_norm(x, layer["mlp_norm"], cfg.norm_eps),
                         layer["mlp"])
            return (x, k_cache, v_cache), None

        (x, k_cache, v_cache), _ = lax.scan(
            layer_body, (x, k_cache, v_cache),
            (params["dec_layers"], jnp.arange(Ld), cross_k, cross_v))
        x = rms_norm(x, params["final_norm"], cfg.norm_eps)
        logits = linear(x.astype(cfg.dtype), params["lm_head"],
                        out_dtype=jnp.float32)
        nxt = sampler(logits[:, 0], step_key)
        return (nxt, k_cache, v_cache, step + 1), nxt

    start = jnp.full((b,), bos_id, jnp.int32)
    step_keys = jax.random.split(rng, max_new_tokens)
    _, toks = lax.scan(dec_step, (start, k_cache, v_cache, jnp.int32(0)),
                       step_keys)
    toks = toks.transpose(1, 0)  # (b, max_new_tokens)
    if eos_id is None:
        return toks
    # eos contract (same as infer/engine.py): length = first eos + 1,
    # else max_new; positions after eos are pad. Done rows keep decoding
    # inside the scan (their cache writes are their own rows), so this
    # masking is purely cosmetic/post-hoc — outputs before eos are
    # untouched.
    is_eos = toks == eos_id
    any_eos = jnp.any(is_eos, axis=1)
    first_eos = jnp.argmax(is_eos, axis=1)
    lengths = jnp.where(any_eos, first_eos + 1, toks.shape[1])
    past = jnp.arange(toks.shape[1], dtype=jnp.int32)[None, :] >= (
        lengths[:, None])
    return {"tokens": jnp.where(past, jnp.int32(pad_id), toks),
            "lengths": lengths.astype(jnp.int32)}
