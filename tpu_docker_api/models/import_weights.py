"""HF-layout checkpoint import/export: safetensors ↔ the in-tree llama
param tree.

The reference's entire product is running REAL user images/workloads
(/root/reference/README.md:64-92, api/gpu-docker-api-sample-interface.md
:262-321); the TPU-serving analog of that duty is serving an actual
pretrained checkpoint, not random-init geometry. This module is the
bridge: a Hugging-Face-layout Llama checkpoint (config.json +
model.safetensors, optionally sharded with an index) loads into the
stacked-layer param tree of models/llama.py, composing with int8
quantization at load so llama3-8b fits a single 16 GB v5e chip.

Layout mapping (HF name → in-tree path; W is stored (out, in) by
torch's Linear and transposed here to our (in, out)):

    model.embed_tokens.weight            embed/tokens      (vocab, d) as-is
    model.layers.{i}.self_attn.q_proj    layers/attn/wq    stack + .T
    model.layers.{i}.self_attn.k_proj    layers/attn/wk    stack + .T
    model.layers.{i}.self_attn.v_proj    layers/attn/wv    stack + .T
    model.layers.{i}.self_attn.o_proj    layers/attn/wo    stack + .T
    model.layers.{i}.mlp.gate_proj       layers/mlp/w_gate stack + .T
    model.layers.{i}.mlp.up_proj         layers/mlp/w_up   stack + .T
    model.layers.{i}.mlp.down_proj       layers/mlp/w_down stack + .T
    model.layers.{i}.input_layernorm     layers/attn_norm  stack
    model.layers.{i}.post_attention_layernorm  layers/mlp_norm  stack
    model.norm.weight                    final_norm        as-is
    lm_head.weight                       lm_head           .T (absent ⇒
                                         tied: embed_tokens.T)

RoPE needs NO head permutation: HF checkpoints store q/k in the
rotate_half (split-halves) layout, which is exactly ops/rope.py's
convention — both compute [x1·c − x2·s, x2·c + x1·s] over the
(i, i + d/2) dim pairing. GQA likewise imports untouched: both sides
order projection output channels head-major, with n_kv_heads·head_dim
k/v rows.

Int8-at-load streams layer by layer: each (out, in) tensor is read
(zero-copy mmap slice via safetensors), transposed, quantized with
EXACTLY infer/quantize.quantize_weight's math (absmax/127 per out
channel in f32, round-half-even), and written into preallocated stacked
int8/scale buffers — peak host memory is the int8 tree plus ONE layer's
f32 temporaries, and no bf16 copy of the model ever materializes
(~8 GB for llama3-8b instead of 16 GB + 16 GB).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import numpy as np

__all__ = [
    "hf_llama_config", "import_hf_llama", "export_hf_llama",
    "load_tokenizer", "HFCheckpoint",
]

_EPS = 1e-12  # quantize_weight's scale clamp — numerics must match


class HFCheckpoint:
    """Tensor resolver over an HF checkpoint directory (or a bare
    .safetensors file): single ``model.safetensors`` or sharded
    ``model-XXXXX-of-YYYYY.safetensors`` + ``model.safetensors.index
    .json``. Tensors are read lazily per name — at no point is a whole
    shard materialized — so the importer's peak memory stays at the
    output tree, not the checkpoint."""

    def __init__(self, path: str):
        self.path = path
        self._handles: dict[str, Any] = {}
        if os.path.isfile(path):
            self.directory = os.path.dirname(path) or "."
            self._map = {name: os.path.basename(path)
                         for name in self._open(os.path.basename(path))
                         .keys()}
            return
        if not os.path.isdir(path):
            raise FileNotFoundError(f"no checkpoint at {path}")
        self.directory = path
        index = os.path.join(path, "model.safetensors.index.json")
        single = os.path.join(path, "model.safetensors")
        if os.path.exists(index):
            with open(index) as f:
                self._map = dict(json.load(f)["weight_map"])
        elif os.path.exists(single):
            self._map = {name: "model.safetensors"
                         for name in self._open("model.safetensors").keys()}
        else:
            cands = sorted(f for f in os.listdir(path)
                           if f.endswith(".safetensors"))
            if not cands:
                raise FileNotFoundError(
                    f"{path}: no model.safetensors, index, or "
                    f"*.safetensors files")
            self._map = {}
            for fname in cands:
                for name in self._open(fname).keys():
                    self._map[name] = fname

    def _open(self, fname: str):
        h = self._handles.get(fname)
        if h is None:
            from safetensors import safe_open

            h = safe_open(os.path.join(self.directory, fname),
                          framework="numpy")
            self._handles[fname] = h
        return h

    def names(self) -> list[str]:
        return sorted(self._map)

    def __contains__(self, name: str) -> bool:
        return name in self._map

    def tensor(self, name: str) -> np.ndarray:
        fname = self._map.get(name)
        if fname is None:
            raise KeyError(
                f"checkpoint {self.path} has no tensor {name!r}")
        return self._open(fname).get_tensor(name)


def hf_llama_config(path: str, **overrides):
    """LlamaConfig from an HF ``config.json`` (a directory or the file
    itself). Only llama-architecture checkpoints are accepted — the
    geometry keys map 1:1 onto LlamaConfig."""
    from tpu_docker_api.models.llama import LlamaConfig

    cfg_path = (os.path.join(path, "config.json")
                if os.path.isdir(path) else path)
    with open(cfg_path) as f:
        hf = json.load(f)
    archs = hf.get("architectures") or []
    if archs and not any("llama" in a.lower() for a in archs):
        raise ValueError(
            f"{cfg_path}: architectures {archs} is not a llama family "
            f"checkpoint")
    fields = dict(
        vocab_size=hf["vocab_size"],
        dim=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads",
                          hf["num_attention_heads"]),
        ffn_dim=hf["intermediate_size"],
        max_seq_len=hf.get("max_position_embeddings", 8192),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
    )
    rs = hf.get("rope_scaling")
    if rs:
        # every real llama-3.1/3.2 config carries this block; importing
        # while ignoring it would produce silently wrong RoPE
        # frequencies for positions past the original context (VERDICT
        # r4 missing #2) — so: implement llama3, refuse everything else
        rtype = rs.get("rope_type") or rs.get("type")  # old configs: "type"
        if rtype == "llama3":
            from tpu_docker_api.ops.rope import RopeScaling

            fields["rope_scaling"] = RopeScaling(
                factor=float(rs["factor"]),
                low_freq_factor=float(rs.get("low_freq_factor", 1.0)),
                high_freq_factor=float(rs.get("high_freq_factor", 4.0)),
                original_max_position_embeddings=int(
                    rs.get("original_max_position_embeddings", 8192)),
            )
        elif rtype != "default":  # "default" = explicit no-op
            raise ValueError(
                f"{cfg_path}: rope_scaling type {rtype!r} is not "
                f"supported (implemented: 'llama3', 'default') — "
                f"refusing to import with wrong RoPE frequencies")
    head_dim = hf.get("head_dim")
    if head_dim and head_dim * fields["n_heads"] != fields["dim"]:
        raise ValueError(
            f"{cfg_path}: head_dim {head_dim} × heads "
            f"{fields['n_heads']} != hidden_size {fields['dim']} — "
            f"non-uniform head layouts are not supported")
    fields.update(overrides)
    return LlamaConfig(**fields)


def _np_dtype(dtype) -> np.dtype:
    return np.dtype(dtype)  # jnp.bfloat16 → ml_dtypes bfloat16


def _quantize_np(w: np.ndarray):
    """quantize_weight's exact math on host: (in, out) f32 → int8 +
    per-out-channel f32 scale. np.round and jnp.round both round half
    to even, so the result is bit-identical to quantizing on device
    (asserted by tests/test_import_weights.py)."""
    wf = w.astype(np.float32)
    scale = np.maximum(np.max(np.abs(wf), axis=-2), _EPS) / 127.0
    w_int8 = np.clip(np.round(wf / scale[..., None, :]), -127, 127)
    return w_int8.astype(np.int8), scale.astype(np.float32)


def import_hf_llama(path: str, cfg=None, *, quantize: bool = False,
                    to_device: bool = True):
    """(cfg, params) from an HF-layout llama checkpoint.

    ``cfg`` defaults to ``hf_llama_config(path)`` (the checkpoint's own
    geometry); pass one explicitly to assert an expected preset — any
    tensor-shape mismatch raises with the offending name. With
    ``quantize`` every projection loads straight to int8
    (``QuantizedLinear`` leaves, infer/quantize.py) without ever
    materializing the bf16 tree. ``to_device=False`` returns host
    (numpy) leaves — callers placing onto a mesh device_put with their
    own shardings."""
    import jax
    import jax.numpy as jnp

    from tpu_docker_api.ops.quant import QuantizedLinear

    ckpt = path if isinstance(path, HFCheckpoint) else HFCheckpoint(path)
    if cfg is None:
        cfg = hf_llama_config(ckpt.directory)
    dt = _np_dtype(cfg.dtype)
    L, d, hd = cfg.n_layers, cfg.dim, cfg.head_dim

    def get(name: str, shape: tuple[int, ...]) -> np.ndarray:
        t = ckpt.tensor(name)
        if tuple(t.shape) != shape:
            raise ValueError(
                f"{name}: shape {tuple(t.shape)} != expected {shape} "
                f"for config (dim={d}, heads={cfg.n_heads}/"
                f"{cfg.n_kv_heads}, ffn={cfg.ffn_dim}, "
                f"vocab={cfg.vocab_size})")
        return t

    # (in-tree leaf, HF suffix, (in, out)) for the seven stacked
    # projections; norms stack separately below
    projs = [
        (("attn", "wq"), "self_attn.q_proj", (d, cfg.n_heads * hd)),
        (("attn", "wk"), "self_attn.k_proj", (d, cfg.n_kv_heads * hd)),
        (("attn", "wv"), "self_attn.v_proj", (d, cfg.n_kv_heads * hd)),
        (("attn", "wo"), "self_attn.o_proj", (cfg.n_heads * hd, d)),
        (("mlp", "w_gate"), "mlp.gate_proj", (d, cfg.ffn_dim)),
        (("mlp", "w_up"), "mlp.up_proj", (d, cfg.ffn_dim)),
        (("mlp", "w_down"), "mlp.down_proj", (cfg.ffn_dim, d)),
    ]
    stacked: dict[tuple, Any] = {}
    for key, suffix, (fin, fout) in projs:
        if quantize:
            w8 = np.empty((L, fin, fout), np.int8)
            sc = np.empty((L, fout), np.float32)
        else:
            buf = np.empty((L, fin, fout), dt)
        for i in range(L):
            # torch Linear stores (out, in); transpose to our (in, out).
            # The cast to the model dtype happens BEFORE quantization so
            # int8-at-load equals import-bf16-then-quantize bit-exactly.
            w = get(f"model.layers.{i}.{suffix}.weight",
                    (fout, fin)).T.astype(dt)
            if quantize:
                w8[i], sc[i] = _quantize_np(w)
            else:
                buf[i] = w
        stacked[key] = (QuantizedLinear(w8, sc) if quantize else buf)

    attn_norm = np.empty((L, d), dt)
    mlp_norm = np.empty((L, d), dt)
    for i in range(L):
        attn_norm[i] = get(f"model.layers.{i}.input_layernorm.weight",
                           (d,)).astype(dt)
        mlp_norm[i] = get(
            f"model.layers.{i}.post_attention_layernorm.weight",
            (d,)).astype(dt)

    embed = get("model.embed_tokens.weight",
                (cfg.vocab_size, d)).astype(dt)
    if "lm_head.weight" in ckpt:
        head = get("lm_head.weight", (cfg.vocab_size, d)).T.astype(dt)
    else:
        # tied embeddings (llama-3.2 1B/3B): the output projection IS
        # the embedding table transposed
        head = np.ascontiguousarray(embed.T)
    params = {
        "embed": {"tokens": embed},
        "layers": {
            "attn_norm": attn_norm,
            "mlp_norm": mlp_norm,
            "attn": {k[1]: stacked[k] for k in
                     (("attn", "wq"), ("attn", "wk"), ("attn", "wv"),
                      ("attn", "wo"))},
            "mlp": {k[1]: stacked[k] for k in
                    (("mlp", "w_gate"), ("mlp", "w_up"),
                     ("mlp", "w_down"))},
        },
        "final_norm": get("model.norm.weight", (d,)).astype(dt),
        "lm_head": (QuantizedLinear(*_quantize_np(head)) if quantize
                    else head),
    }
    if to_device:
        params = jax.tree_util.tree_map(jnp.asarray, params)
    return cfg, params


def export_hf_llama(params: dict, cfg, out_dir: str,
                    *, tie_embeddings: bool = False) -> str:
    """Write an in-tree (float) param tree as an HF-layout checkpoint:
    ``model.safetensors`` + ``config.json`` under ``out_dir``. The
    inverse of :func:`import_hf_llama` — round-trip is bit-exact
    (tests) — and the path that turns an in-tree orbax training
    checkpoint into a portable artifact any HF-ecosystem tool can read.
    ``tie_embeddings`` omits lm_head (readers reconstruct it from the
    embedding, as import does)."""
    from safetensors.numpy import save_file

    os.makedirs(out_dir, exist_ok=True)
    layers = params["layers"]
    tensors: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embed"]["tokens"]),
        "model.norm.weight": np.asarray(params["final_norm"]),
    }
    if not tie_embeddings:
        tensors["lm_head.weight"] = np.ascontiguousarray(
            np.asarray(params["lm_head"]).T)
    hf_names = {
        ("attn", "wq"): "self_attn.q_proj",
        ("attn", "wk"): "self_attn.k_proj",
        ("attn", "wv"): "self_attn.v_proj",
        ("attn", "wo"): "self_attn.o_proj",
        ("mlp", "w_gate"): "mlp.gate_proj",
        ("mlp", "w_up"): "mlp.up_proj",
        ("mlp", "w_down"): "mlp.down_proj",
    }
    for (group, leaf), suffix in hf_names.items():
        w = np.asarray(layers[group][leaf])  # (L, in, out)
        for i in range(cfg.n_layers):
            tensors[f"model.layers.{i}.{suffix}.weight"] = (
                np.ascontiguousarray(w[i].T))
    for i in range(cfg.n_layers):
        tensors[f"model.layers.{i}.input_layernorm.weight"] = (
            np.asarray(layers["attn_norm"][i]))
        tensors[f"model.layers.{i}.post_attention_layernorm.weight"] = (
            np.asarray(layers["mlp_norm"][i]))
    path = os.path.join(out_dir, "model.safetensors")
    save_file(tensors, path, metadata={"format": "pt"})
    hf_cfg = {
        "architectures": ["LlamaForCausalLM"],
        "model_type": "llama",
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.dim,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "intermediate_size": cfg.ffn_dim,
        "max_position_embeddings": cfg.max_seq_len,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.norm_eps,
        "tie_word_embeddings": tie_embeddings,
        "torch_dtype": "bfloat16",
    }
    rs = getattr(cfg, "rope_scaling", None)
    if rs is not None:
        # round-trip the llama3 scaling block: an exported checkpoint
        # must carry the frequencies it was trained/served with, or an
        # HF reader reconstructs different rope tables
        hf_cfg["rope_scaling"] = {
            "rope_type": "llama3",
            "factor": rs.factor,
            "low_freq_factor": rs.low_freq_factor,
            "high_freq_factor": rs.high_freq_factor,
            "original_max_position_embeddings":
                rs.original_max_position_embeddings,
        }
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=2)
    return path


@dataclasses.dataclass
class Tokenizer:
    """Thin text↔ids adapter over a local HF tokenizer — the hook that
    lets serve accept {"text": ...} alongside raw token IDs. Loading is
    strictly offline (``tokenizer.json`` / tokenizer files on disk; no
    hub traffic)."""

    _tok: Any

    def encode(self, text: str) -> list[int]:
        return list(self._tok.encode(text))

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    @property
    def eos_id(self) -> int | None:
        return self._tok.eos_token_id

    @property
    def bos_id(self) -> int | None:
        return self._tok.bos_token_id


def load_tokenizer(path: str) -> Tokenizer:
    """Tokenizer from a local checkpoint dir or tokenizer.json file.
    Uses the fast (rust) tokenizer directly when a tokenizer.json
    exists — that avoids transformers' config resolution entirely —
    else falls back to AutoTokenizer with local_files_only."""
    from transformers import AutoTokenizer, PreTrainedTokenizerFast

    if os.path.isfile(path) and path.endswith(".json"):
        return Tokenizer(PreTrainedTokenizerFast(tokenizer_file=path))
    tok_json = os.path.join(path, "tokenizer.json")
    if os.path.isfile(tok_json) and not os.path.exists(
            os.path.join(path, "tokenizer_config.json")):
        return Tokenizer(PreTrainedTokenizerFast(tokenizer_file=tok_json))
    return Tokenizer(AutoTokenizer.from_pretrained(
        path, local_files_only=True))


def _main(argv=None) -> None:
    """CLI: turn an in-tree orbax training checkpoint into a portable
    HF-layout artifact (the outbound half of the real-weights duty):

        python -m tpu_docker_api.models.import_weights \
            --ckpt-dir /ckpt --preset llama3-1b --out /export [--tie]
    """
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m tpu_docker_api.models.import_weights")
    p.add_argument("--ckpt-dir", required=True,
                   help="orbax training checkpoint to export")
    p.add_argument("--preset", required=True,
                   help="llama preset the checkpoint was trained at")
    p.add_argument("--out", required=True, help="output directory")
    p.add_argument("--tie", action="store_true",
                   help="omit lm_head (tied-embedding layout)")
    p.add_argument("--platform", default="",
                   help="force a jax platform (tests: cpu)")
    args = p.parse_args(argv)

    from tpu_docker_api.workload.jaxenv import bootstrap_jax

    bootstrap_jax(args.platform, 0)

    from tpu_docker_api.models.llama import llama_presets
    from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
    from tpu_docker_api.train.checkpoint import restore_model_params

    cfg = llama_presets()[args.preset]
    mesh = build_mesh(MeshPlan(dp=-1, fsdp=1, tp=1, sp=1))
    params, step = restore_model_params(args.ckpt_dir, cfg, mesh)
    path = export_hf_llama(params, cfg, args.out, tie_embeddings=args.tie)
    print(json.dumps({"event": "exported", "step": step, "path": path}))


if __name__ == "__main__":
    _main()
