"""Mixtral-family sparse Mixture-of-Experts decoder, TPU-first.

Expert parallelism (SURVEY.md §2.3 — absent in the reference, first-class
here): expert weights and the dispatched token buffers shard over the ``ep``
mesh axis; the dispatch/combine einsums are annotated with sharding
constraints and XLA lowers the token shuffle to ``all_to_all`` collectives on
ICI — the TPU-native equivalent of the NCCL all-to-all a GPU MoE stack would
hand-write.

Routing is GShard/Switch-style with static shapes (XLA needs them): top-k
gating, per-expert capacity ``C``, one-hot dispatch/combine tensors built with
cumsum position assignment, tokens over capacity dropped (residual stream
carries them unchanged). Attention, norms, rope, remat and the layer-stacked
``lax.scan`` are shared with models/llama.py — one source of truth.

Reference parity note: gpu-docker-api has no model zoo at all (SURVEY.md §0);
this module exists to satisfy the EP row of the §2.3 checklist.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpu_docker_api.models.common import trunc_normal_init
from tpu_docker_api.models.llama import (
    _attention, cross_entropy, embed_lookup, lm_head)
from tpu_docker_api.ops.norms import rms_norm
from tpu_docker_api.ops.rope import rope_frequencies
from tpu_docker_api.parallel.sharding import LLAMA_RULES, constrain

#: param-path sharding rules (parallel/sharding.py machinery, first match
#: wins): MoE-specific rows here, everything shared with Llama (embed, attn,
#: norms, lm_head) composed from LLAMA_RULES. Experts shard on ep; within an
#: expert the ffn dims shard on tp, model dim on fsdp — the Megatron layout
#: per expert.
MOE_RULES: list[tuple[str, P]] = [
    ("layers/moe/router",    P(None, "fsdp", None)),
    ("layers/moe/w_gate",    P(None, "ep", "fsdp", "tp")),
    ("layers/moe/w_up",      P(None, "ep", "fsdp", "tp")),
    ("layers/moe/w_down",    P(None, "ep", "tp", "fsdp")),
    *LLAMA_RULES,
]


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01
    max_seq_len: int = 8192
    rope_theta: float = 1e6
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    attention_impl: str = "auto"
    # "auto" | "gather" | "einsum" | "sort" — see _moe_mlp. auto (r5):
    # gather/scatter on a single device, "sort" (dense-packed with
    # explicit ep sharding constraints, no (t, E, C) tensors) on
    # multi-device meshes; "einsum" = the one-hot GSPMD-all-to-all
    # form, kept reachable as the multi-chip escape hatch
    dispatch_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def capacity(self, n_tokens: int) -> int:
        """Per-expert token capacity for a flat batch of ``n_tokens``."""
        c = math.ceil(self.top_k * n_tokens * self.capacity_factor
                      / self.n_experts)
        return max(int(c), 1)

    def flops_per_token(self, seq_len: int | None = None) -> float:
        """Training FLOPs/token — only ``top_k`` experts fire per token."""
        seq = seq_len or self.max_seq_len
        d, h = self.dim, self.head_dim
        per_layer = (
            2 * d * (self.n_heads * h)
            + 2 * 2 * d * (self.n_kv_heads * h)
            + 2 * (self.n_heads * h) * d
            + 2 * d * self.n_experts                       # router
            + self.top_k * 3 * 2 * d * self.ffn_dim        # active experts
        )
        embed = 2 * d * self.vocab_size
        fwd = self.n_layers * per_layer + embed
        attn = self.n_layers * 2 * 2 * seq * (self.n_heads * h) / 2
        return 3.0 * (fwd + attn)


def moe_presets() -> dict[str, MoEConfig]:
    return {
        # parity-scale flagship: Mixtral-8x7B geometry
        "mixtral-8x7b": MoEConfig(
            vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, ffn_dim=14336, n_experts=8, top_k=2,
            max_seq_len=32768, rope_theta=1e6,
        ),
        # single-v5e-chip bench config (~0.5B params with 8 experts;
        # head_dim 128 tiles the flash kernel cleanly)
        "bench-moe": MoEConfig(
            vocab_size=32000, dim=1024, n_layers=8, n_heads=8,
            n_kv_heads=8, ffn_dim=2048, n_experts=8, top_k=2,
            max_seq_len=2048, rope_theta=10000.0,
        ),
        # CPU-fast config for tests / dryrun
        "moe-tiny": MoEConfig(
            vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=128, n_experts=4, top_k=2, max_seq_len=128,
            rope_theta=10000.0, remat=False,
        ),
    }


def moe_init(cfg: MoEConfig, key: jax.Array) -> dict:
    """Parameter pytree; expert weights carry (n_layers, n_experts, ...)."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    d, hd, L, E = cfg.dim, cfg.head_dim, cfg.n_layers, cfg.n_experts

    def init(key, shape, fan_in):
        return trunc_normal_init(key, shape, fan_in, cfg.dtype)

    ks = jax.random.split(k_layers, 8)
    return {
        "embed": {"tokens": init(k_embed, (cfg.vocab_size, d), d)},
        "layers": {
            "attn_norm": jnp.ones((L, d), cfg.dtype),
            "mlp_norm": jnp.ones((L, d), cfg.dtype),
            "attn": {
                "wq": init(ks[0], (L, d, cfg.n_heads * hd), d),
                "wk": init(ks[1], (L, d, cfg.n_kv_heads * hd), d),
                "wv": init(ks[2], (L, d, cfg.n_kv_heads * hd), d),
                "wo": init(ks[3], (L, cfg.n_heads * hd, d), cfg.n_heads * hd),
            },
            "moe": {
                # router in f32 end-to-end: tiny, and routing decisions are
                # precision-sensitive (bf16 logit ties flip top-k picks)
                "router": (jax.random.truncated_normal(
                    ks[4], -2, 2, (L, d, E), jnp.float32) * (d**-0.5)),
                "w_gate": init(ks[5], (L, E, d, cfg.ffn_dim), d),
                "w_up": init(ks[6], (L, E, d, cfg.ffn_dim), d),
                "w_down": init(ks[7], (L, E, cfg.ffn_dim, d), cfg.ffn_dim),
            },
        },
        "final_norm": jnp.ones((d,), cfg.dtype),
        "lm_head": init(k_head, (d, cfg.vocab_size), d),
    }


def _route_topk(x_flat: jnp.ndarray, router: jnp.ndarray, cfg: MoEConfig,
                drop_free: bool = False):
    """Top-k routing decisions: (gate_vals (t,K) f32, gate_idx (t,K),
    pos (t,K) capacity slot, keep (t,K) mask, aux_loss, C).

    Static shapes throughout: cumsum capacity assignment (GShard eq. 2),
    overflow tokens dropped. ``drop_free=True`` sets capacity = t so NO
    token ever drops — the decode-serving mode, where capacity drops would
    couple co-batched requests (a token's expert contribution zeroing out
    depending on what else is in the batch)."""
    t = x_flat.shape[0]
    E, K = cfg.n_experts, cfg.top_k
    C = t if drop_free else cfg.capacity(t)
    logits = x_flat.astype(jnp.float32) @ router          # (t, E)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = lax.top_k(probs, K)             # (t, K)
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    # expert choice one-hots, ranked: k=0 claims capacity slots first
    onehots = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)  # (t, K, E)
    # position of each (token, choice) in its expert's queue: cumsum over the
    # flattened (K, t) order so all k=0 picks rank ahead of k=1 picks
    ranked = onehots.transpose(1, 0, 2).reshape(K * t, E)   # (K*t, E)
    pos_ranked = jnp.cumsum(ranked, axis=0) - ranked        # 0-based slots
    pos = pos_ranked.reshape(K, t, E).transpose(1, 0, 2)    # (t, K, E)
    pos = jnp.sum(pos * onehots, axis=-1)                   # (t, K)
    keep = pos < C                                          # capacity mask

    # load-balance aux loss (Switch eq. 4): E * Σ_e f_e · P_e
    top1 = jax.nn.one_hot(gate_idx[:, 0], E, dtype=jnp.float32)
    frac_tokens = jnp.mean(top1, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return gate_vals, gate_idx, pos, keep, aux, C


def _route(x_flat: jnp.ndarray, router: jnp.ndarray, cfg: MoEConfig,
           drop_free: bool = False):
    """Top-k routing → (dispatch (t,E,C), combine (t,E,C), aux_loss) —
    the einsum-dispatch form (multi-device path; see _moe_mlp)."""
    gate_vals, gate_idx, pos, keep, aux, C = _route_topk(
        x_flat, router, cfg, drop_free)
    E = cfg.n_experts
    onehots = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)  # (t, K, E)

    # dispatch: 0/1 (t, E, C); combine: gate-weighted (t, E, C). Built in
    # the STORAGE dtype: these are the two largest tensors in the step
    # (t·E·C — 2.7 GB each at bench shapes in f32), and f32 here made
    # their backward cotangents f32 too (+a same-size layout copy —
    # profiled ~20% of the MoE step). 0/1 dispatch is exact in bf16;
    # combine carries gate weights, whose bf16 rounding is the same order
    # as the bf16 expert outputs they multiply.
    dt = x_flat.dtype
    slot_onehot = jax.nn.one_hot(pos, C, dtype=dt)           # (t, K, C)
    disp_k = onehots.astype(dt)[..., None] * slot_onehot[:, :, None, :]
    disp_k = disp_k * keep[:, :, None, None].astype(dt)
    dispatch = jnp.sum(disp_k, axis=1)                       # (t, E, C)
    combine = jnp.sum(disp_k * gate_vals.astype(dt)[:, :, None, None], axis=1)
    return dispatch, combine, aux


def _expert_swiglu(xe, layer_moe):
    """(E, C, d) → (E, C, d) batched expert SwiGLU (shared by both
    dispatch implementations)."""
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, layer_moe["w_gate"]))
    up = jnp.einsum("ecd,edf->ecf", xe, layer_moe["w_up"])
    return jnp.einsum("ecf,efd->ecd", gate * up, layer_moe["w_down"])


def _moe_mlp(x, layer_moe, cfg: MoEConfig, mesh: Mesh | None,
             drop_free: bool = False):
    """Sparse FFN: route → dispatch → batched expert SwiGLU → combine.
    Returns (out, aux_loss).

    Three dispatch implementations, same math (the tests assert
    equality):

    - **gather/scatter** (auto's single-device pick): tokens scatter
      into the (E·C, d) expert buffers by flat slot id and expert
      outputs gather back — O(t·K·d) traffic. The einsum form's
      (t, E, C) dispatch/combine tensors are the two LARGEST arrays in
      the whole step (2.7 GB each at bench shapes) and their matmuls
      pure overhead; switching the bench path to gather measured 2.9x
      tokens/s on v5e (20.1k -> 58.6k). Carries no sharding
      constraints, so it is single-device only.
    - **sort** (auto's mesh pick, r5): the same dense-packed dispatch
      plus explicit ep/fsdp sharding constraints, so the expert compute
      shards legally under GSPMD while the (t, E, C) tensors still
      never exist. Scatter/gather endpoints stay replicated over ep —
      linear-size work.
    - **einsum**: one-hot (t, E, C) contractions; under GSPMD the
      dispatch einsum IS the all-to-all (tokens leave their
      data-parallel home shard for their expert's ep shard). Kept as
      the explicit multi-chip escape hatch (--moe-dispatch einsum)
      should real ICI profiling favor it over sort's replicated
      endpoints.
    """
    b, s, d = x.shape
    x_flat = x.reshape(b * s, d)
    impl = cfg.dispatch_impl
    multi_device = mesh is not None and mesh.devices.size > 1
    if impl == "auto":
        # r5 (VERDICT r4 weak #4): auto picks the SORT form on meshes —
        # it shards the expert compute (where the FLOPs are) without
        # ever materializing the einsum form's (t, E, C) tensors, whose
        # single-device proxy measured 2.6x lower MFU. The einsum form
        # stays reachable as dispatch_impl="einsum" (its dispatch
        # contraction IS the GSPMD all-to-all — the honest fallback if
        # multi-chip profiling ever shows sort's replicated
        # scatter/gather endpoints dominating; not measurable in this
        # single-chip environment, dryrun proves compile+run only).
        impl = "sort" if multi_device else "gather"
    elif impl not in ("gather", "einsum", "sort"):
        raise ValueError(f"unknown dispatch impl {impl!r}")
    if impl == "gather" and multi_device:
        # the scatter/gather path carries no sharding constraints — on a
        # mesh GSPMD would replicate the expert buffers and compute;
        # "sort" is the constrained variant that shards legally
        raise ValueError(
            "dispatch_impl='gather' is single-device only; use 'auto', "
            "'einsum', or 'sort' on a multi-device mesh")

    if impl in ("gather", "sort"):
        # dense-packed dispatch: tokens scatter into contiguous (E·C, d)
        # expert buffers by flat slot id (cumsum capacity ranking — the
        # same packing an argsort-by-expert produces, without the sort),
        # expert outputs gather back. O(t·K·d) dispatch traffic; the
        # (t, E, C) one-hot tensors — 2.7 GB each at bench shapes, and
        # the einsum path's measured 2.6x MFU deficit (VERDICT r3 weak
        # #4) — never exist. "sort" adds the ep/fsdp sharding
        # constraints so the EXPERT COMPUTE (where the FLOPs are)
        # shards over the mesh; the scatter/gather endpoints stay
        # replicated over ep — linear-size work, the honest trade vs
        # the einsum form whose dispatch contraction is itself sharded.
        gate_vals, gate_idx, pos, keep, aux, C = _route_topk(
            x_flat, layer_moe["router"], cfg, drop_free=drop_free)
        t = b * s
        E, K = cfg.n_experts, cfg.top_k
        # flat slot per (token, choice); dropped choices get DISTINCT
        # out-of-range ids so unique_indices holds and mode="drop" elides
        flat_slot = jnp.where(
            keep, gate_idx * C + pos,
            E * C + jnp.arange(t * K, dtype=jnp.int32).reshape(t, K))
        src = jnp.broadcast_to(x_flat[:, None, :], (t, K, d))
        xe = jnp.zeros((E * C, d), x.dtype).at[flat_slot.reshape(-1)].set(
            src.reshape(t * K, d), mode="drop", unique_indices=True)
        xe = xe.reshape(E, C, d)
        if impl == "sort" and mesh is not None:
            xe = constrain(xe, mesh, P("ep", None, "fsdp"))
        ye = _expert_swiglu(xe, layer_moe)
        if impl == "sort" and mesh is not None:
            ye = constrain(ye, mesh, P("ep", None, "fsdp"))
        picked = ye.reshape(E * C, d).at[flat_slot.reshape(-1)].get(
            mode="fill", fill_value=0).reshape(t, K, d)
        w = (gate_vals * keep).astype(x.dtype)             # (t, K)
        out = jnp.einsum("tk,tkd->td", w, picked)
        return out.reshape(b, s, d), aux

    dispatch, combine, aux = _route(x_flat, layer_moe["router"], cfg,
                                    drop_free=drop_free)
    # (E, C, d) expert buffers — sharded on ep, so this einsum IS the
    # all-to-all (tokens leave their data-parallel home shard for their
    # expert's shard)
    xe = jnp.einsum("tec,td->ecd", dispatch, x_flat)
    if mesh is not None:
        xe = constrain(xe, mesh, P("ep", None, "fsdp"))
    ye = _expert_swiglu(xe, layer_moe)
    if mesh is not None:
        ye = constrain(ye, mesh, P("ep", None, "fsdp"))
    out = jnp.einsum("tec,ecd->td", combine, ye)
    return out.reshape(b, s, d), aux


def _moe_block(x, layer, cfg: MoEConfig, rope_cos, rope_sin, mesh,
               cache=None, start_pos=None, kv_limit=None):
    """Transformer block: Llama attention (shared code) + sparse FFN.
    Returns (x, aux_loss), or (x, aux_loss, new_cache) on the KV-cached
    path (``cache=(k_all, v_all, layer_idx)`` — llama's _attention
    contract)."""
    bspec = P(("dp", "fsdp"), "sp" if cache is None else None)
    attn_out = _attention(
        rms_norm(x, layer["attn_norm"], cfg.norm_eps), layer, cfg,
        rope_cos, rope_sin, mesh, cache=cache, start_pos=start_pos,
        kv_limit=kv_limit,
    )
    new_cache = None
    if cache is not None:
        attn_out, new_cache = attn_out
    x = x + attn_out
    x = constrain(x, mesh, bspec) if mesh is not None else x
    # decode steps (cached, seq 1) route drop-free: capacity = t is tiny
    # there, and capacity drops would make output depend on co-batched
    # requests. Prefill/training keep the GShard capacity heuristic —
    # drop-free at large t would cost O(t^2 E) dispatch memory.
    moe_out, aux = _moe_mlp(
        rms_norm(x, layer["mlp_norm"], cfg.norm_eps), layer["moe"], cfg,
        mesh, drop_free=(cache is not None and x.shape[1] == 1))
    x = x + moe_out
    x = constrain(x, mesh, bspec) if mesh is not None else x
    return (x, aux) if cache is None else (x, aux, new_cache)


def moe_forward(
    params: dict,
    tokens: jnp.ndarray,  # (batch, seq) int32
    cfg: MoEConfig,
    mesh: Mesh | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(logits (b, s, vocab) f32, mean router aux loss)."""
    seq = tokens.shape[1]
    x = embed_lookup(params["embed"]["tokens"], tokens, mesh)
    if mesh is not None:
        x = constrain(x, mesh, P(("dp", "fsdp"), "sp"))
    rope_cos, rope_sin = rope_frequencies(cfg.head_dim, seq, cfg.rope_theta)

    block = functools.partial(
        _moe_block, cfg=cfg, rope_cos=rope_cos, rope_sin=rope_sin, mesh=mesh
    )
    if cfg.remat:
        from tpu_docker_api.ops.flash_pallas import TRAIN_REMAT_POLICY

        block = jax.checkpoint(block, policy=TRAIN_REMAT_POLICY)

    def scan_body(x, layer):
        x, aux = block(x, layer)
        return x, aux

    x, aux_per_layer = lax.scan(scan_body, x, params["layers"])
    logits = lm_head(params, x, cfg)
    if mesh is not None:
        logits = constrain(logits, mesh, P(("dp", "fsdp"), "sp", "tp"))
    return logits, jnp.mean(aux_per_layer)


def moe_forward_cached(
    params: dict,
    tokens: jnp.ndarray,      # (batch, seq) int32 — the NEW tokens only
    cfg: MoEConfig,
    k_cache: jnp.ndarray,     # (n_layers, batch, max_seq, n_kv_heads, hd)
    v_cache: jnp.ndarray,
    start_pos: jnp.ndarray,
    mesh: Mesh | None = None,
    last_only: bool = False,
    kv_limit: int | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """KV-cached forward for serving — rides the shared decoder skeleton
    (models/llama.py ``decoder_forward_cached``: cache carried through the
    layer scan, new-token slots written in place) with the sparse-FFN block
    body. Router aux loss is an inference no-op and is discarded; decode
    steps route drop-free (see ``_moe_block``)."""
    from tpu_docker_api.models.llama import decoder_forward_cached

    def block_fn(x, layer, cache, rope_cos, rope_sin):
        x, _aux, new_cache = _moe_block(
            x, layer, cfg, rope_cos, rope_sin, mesh,
            cache=cache, start_pos=start_pos, kv_limit=kv_limit,
        )
        return x, new_cache

    return decoder_forward_cached(
        params, tokens, cfg, k_cache, v_cache, mesh, last_only, block_fn)


def moe_loss(
    params: dict, tokens: jnp.ndarray, cfg: MoEConfig,
    mesh: Mesh | None = None,
) -> jnp.ndarray:
    """Causal LM loss + router load-balance penalty."""
    logits, aux = moe_forward(params, tokens[:, :-1], cfg, mesh)
    return cross_entropy(logits, tokens[:, 1:]) + cfg.router_aux_coef * aux
