"""Llama-family decoder transformer, TPU-first.

Design choices (vs a torch-style port):

- **Stacked layers + lax.scan**: all per-layer weights carry a leading
  ``n_layers`` dim and the forward scans over them — compile time is O(1) in
  depth and remat policy applies uniformly (MaxText-style).
- **bf16 params/activations, f32 where it matters**: norms, softmax and the
  final logits run in f32; matmuls feed the MXU in bf16. RoPE phase tables
  are f32 but the rotation applies in the storage dtype on the training
  path (f32 on the KV-cached serving path — see ops/rope.py for why).
- **Sharding by annotation**: ``parallel.sharding.LLAMA_RULES`` map param
  paths to (fsdp, tp) PartitionSpecs; activations are constrained to
  (dp+fsdp, sp) — XLA inserts the collectives.
- **Attention dispatch**: Pallas flash kernel on TPU, dense fallback, ring
  attention (parallel/ring.py) or Ulysses all-to-all (parallel/ulysses.py)
  when the mesh has a real sp axis.
- **Remat**: each scanned block is wrapped in ``jax.checkpoint`` with a
  dots-saveable policy, trading FLOPs for HBM as depth grows.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpu_docker_api.models.common import trunc_normal_init
from tpu_docker_api.ops.attention import dense_attention, multihead_attention
from tpu_docker_api.ops.paged import PagedRef, gather_pages, paged_write
from tpu_docker_api.ops.norms import rms_norm
from tpu_docker_api.ops.quant import linear
from tpu_docker_api.ops.rope import (RopeScaling, apply_rope,
                                     rope_frequencies)
from tpu_docker_api.parallel.sharding import constrain


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    # llama-3.x band scaling (ops.rope.RopeScaling) or None; carried on
    # the config so every rope table — train, serve, pipeline — builds
    # from the same scaled frequencies the checkpoint was trained with
    rope_scaling: Any = None
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    attention_impl: str = "auto"  # ops.attention impls, "ring", or "ulysses"
    # >0: train loss runs ops.xent.chunked_cross_entropy with this row-chunk
    # size instead of materializing (batch, seq, vocab) logits
    loss_chunk_rows: int = 0

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def flops_per_token(self, seq_len: int | None = None) -> float:
        """Training FLOPs/token (fwd+bwd ≈ 3× forward matmul FLOPs) — the
        MFU numerator used by bench.py."""
        seq = seq_len or self.max_seq_len
        d, h = self.dim, self.head_dim
        per_layer = (
            2 * d * (self.n_heads * h)          # wq
            + 2 * 2 * d * (self.n_kv_heads * h)  # wk, wv
            + 2 * (self.n_heads * h) * d        # wo
            + 3 * 2 * d * self.ffn_dim          # gate, up, down
        )
        embed = 2 * d * self.vocab_size         # lm_head matmul
        fwd = self.n_layers * per_layer + embed
        # attention score+value matmuls, causal ⇒ half the k positions
        attn = self.n_layers * 2 * 2 * seq * (self.n_heads * h) / 2
        return 3.0 * (fwd + attn)  # fwd + 2x bwd


def llama_presets() -> dict[str, LlamaConfig]:
    return {
        # parity target: MaxText Llama-3-8B (BASELINE.json north star)
        "llama3-8b": LlamaConfig(
            vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, ffn_dim=14336, max_seq_len=8192,
        ),
        "llama3-1b": LlamaConfig(
            vocab_size=128256, dim=2048, n_layers=16, n_heads=32,
            n_kv_heads=8, ffn_dim=8192, max_seq_len=8192,
        ),
        # llama-3.1 8B: the geometry of llama3-8b plus the llama3
        # rope_scaling block and the 128k context every real 3.1
        # checkpoint carries (r5, ops/rope.py) — the preset to assert
        # against --hf-ckpt imports of Meta-Llama-3.1-8B config.json
        # files, so every field must match what importing one
        # produces. Serving/training pick their own working --max-seq;
        # this field is the model's ADDRESSABLE context, not a cache
        # size.
        "llama31-8b": LlamaConfig(
            vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, ffn_dim=14336, max_seq_len=131072,
            rope_scaling=RopeScaling(
                factor=8.0, low_freq_factor=1.0, high_freq_factor=4.0,
                original_max_position_embeddings=8192),
        ),
        # single-v5e-chip bench config (fits 16GB HBM with optimizer state;
        # head_dim 128 so the Pallas flash path tiles cleanly on the MXU)
        "bench-350m": LlamaConfig(
            vocab_size=32000, dim=1024, n_layers=24, n_heads=8,
            n_kv_heads=8, ffn_dim=2816, max_seq_len=2048,
            rope_theta=10000.0,
        ),
        # CPU-fast configs for tests / dryrun
        "tiny": LlamaConfig(
            vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=128, max_seq_len=128, rope_theta=10000.0, remat=False,
        ),
    }


def llama_init(cfg: LlamaConfig, key: jax.Array) -> dict:
    """Initialize a parameter pytree (truncated-normal fan-in scaling)."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    d, hd = cfg.dim, cfg.head_dim
    L = cfg.n_layers

    def init(key, shape, fan_in):
        return trunc_normal_init(key, shape, fan_in, cfg.dtype)

    ks = jax.random.split(k_layers, 7)
    params = {
        "embed": {"tokens": init(k_embed, (cfg.vocab_size, d), d)},
        "layers": {
            "attn_norm": jnp.ones((L, d), cfg.dtype),
            "mlp_norm": jnp.ones((L, d), cfg.dtype),
            "attn": {
                "wq": init(ks[0], (L, d, cfg.n_heads * hd), d),
                "wk": init(ks[1], (L, d, cfg.n_kv_heads * hd), d),
                "wv": init(ks[2], (L, d, cfg.n_kv_heads * hd), d),
                "wo": init(ks[3], (L, cfg.n_heads * hd, d), cfg.n_heads * hd),
            },
            "mlp": {
                "w_gate": init(ks[4], (L, d, cfg.ffn_dim), d),
                "w_up": init(ks[5], (L, d, cfg.ffn_dim), d),
                "w_down": init(ks[6], (L, cfg.ffn_dim, d), cfg.ffn_dim),
            },
        },
        "final_norm": jnp.ones((d,), cfg.dtype),
        "lm_head": init(k_head, (d, cfg.vocab_size), d),
    }
    return params


def _attention(x, layer, cfg: LlamaConfig, rope_cos, rope_sin, mesh,
               cache=None, start_pos=None, kv_limit=None):
    """Self-attention. With ``cache=(k_all, v_all, layer_idx)`` — the FULL
    (n_layers, batch, max_seq, n_kv_heads, head_dim) cache buffers plus this
    layer's index — runs the KV-cached path: writes the new k/v into this
    layer's slots at ``start_pos`` (a small in-place dynamic_update_slice on
    the scan-carried buffer; rebuilding a per-layer cache as scan ys would
    re-materialize the whole cache every decode step) and attends against
    the layer's buffer via ``dense_attention``'s q_offset mask (which covers
    both in-block causality and not-yet-written slots). Returns
    (out, (k_all, v_all)) instead of out."""
    b, s, d = x.shape
    hd = cfg.head_dim
    if "w_qkv" in layer["attn"]:
        # serving-fused projections (infer/quantize.py
        # fuse_llama_projections): one dispatch + one activation
        # quantization for q|k|v — per-column math identical to the
        # three separate matmuls
        nq, nkv = cfg.n_heads * hd, cfg.n_kv_heads * hd
        qkv = linear(x, layer["attn"]["w_qkv"])
        q = qkv[..., :nq].reshape(b, s, cfg.n_heads, hd)
        k = qkv[..., nq:nq + nkv].reshape(b, s, cfg.n_kv_heads, hd)
        v = qkv[..., nq + nkv:].reshape(b, s, cfg.n_kv_heads, hd)
    else:
        q = linear(x, layer["attn"]["wq"]).reshape(b, s, cfg.n_heads, hd)
        k = linear(x, layer["attn"]["wk"]).reshape(b, s, cfg.n_kv_heads, hd)
        v = linear(x, layer["attn"]["wv"]).reshape(b, s, cfg.n_kv_heads, hd)
    if isinstance(cache, PagedRef):
        # paged decode (ops/paged.py; infer/paged.py drives it): s == 1,
        # per-row positions; the write scatters into the slot's current
        # page, the read gathers its pages into a contiguous view that
        # is element-identical to the dense cache prefix — downstream
        # attention math is shared with the dense path verbatim
        positions = (start_pos[:, None]
                     + jnp.arange(s, dtype=jnp.int32)[None, :])
        q = apply_rope(q, rope_cos, rope_sin, positions)
        k = apply_rope(k, rope_cos, rope_sin, positions)
        k_pool = paged_write(cache.k_pool, cache.layer_idx, cache.table,
                             start_pos, k[:, 0])
        v_pool = paged_write(cache.v_pool, cache.layer_idx, cache.table,
                             start_pos, v[:, 0])
        k_cache = gather_pages(k_pool, cache.layer_idx, cache.table)
        v_cache = gather_pages(v_pool, cache.layer_idx, cache.table)
        out = dense_attention(q, k_cache, v_cache, causal=True,
                              q_offset=start_pos)
        return linear(out.reshape(b, s, cfg.n_heads * hd),
                      layer["attn"]["wo"]), (k_pool, v_pool)
    if cache is not None:
        k_all, v_all, layer_idx = cache
        per_row = getattr(start_pos, "ndim", 0) == 1
        if per_row:
            # continuous batching (infer/slots.py): every cache row sits at
            # its own length, so the write is a scatter at (row, pos[row])
            # instead of one dynamic slice; mode="drop" makes a slot pushed
            # past capacity a silent no-op rather than a clamped corruption
            positions = (start_pos[:, None]
                         + jnp.arange(s, dtype=jnp.int32)[None, :])
        else:
            positions = jnp.broadcast_to(
                start_pos + jnp.arange(s, dtype=jnp.int32)[None, :], (b, s)
            )
        q = apply_rope(q, rope_cos, rope_sin, positions)
        k = apply_rope(k, rope_cos, rope_sin, positions)
        if per_row:
            rows = jnp.arange(b, dtype=jnp.int32)[:, None]
            k_all = k_all.at[layer_idx, rows, positions].set(
                k.astype(k_all.dtype), mode="drop")
            v_all = v_all.at[layer_idx, rows, positions].set(
                v.astype(v_all.dtype), mode="drop")
        else:
            zero = jnp.int32(0)
            k_all = lax.dynamic_update_slice(
                k_all, k.astype(k_all.dtype)[None],
                (layer_idx, zero, start_pos, zero, zero))
            v_all = lax.dynamic_update_slice(
                v_all, v.astype(v_all.dtype)[None],
                (layer_idx, zero, start_pos, zero, zero))
        k_cache = lax.dynamic_index_in_dim(k_all, layer_idx, 0,
                                           keepdims=False)
        v_cache = lax.dynamic_index_in_dim(v_all, layer_idx, 0,
                                           keepdims=False)
        if kv_limit is not None and kv_limit < k_cache.shape[1]:
            # static length bucket: read only the prefix every position
            # in this dispatch can reach — decode is bandwidth-bound and
            # the full-buffer read is pure waste when slots sit far below
            # capacity (infer/slots.py picks the bucket per chunk). The
            # write above still targets the full buffer.
            k_cache = lax.slice_in_dim(k_cache, 0, kv_limit, axis=1)
            v_cache = lax.slice_in_dim(v_cache, 0, kv_limit, axis=1)
        out = dense_attention(q, k_cache, v_cache, causal=True,
                              q_offset=start_pos)
        return linear(out.reshape(b, s, cfg.n_heads * hd),
                      layer["attn"]["wo"]), (k_all, v_all)
    q = apply_rope(q, rope_cos, rope_sin)
    k = apply_rope(k, rope_cos, rope_sin)
    impl = cfg.attention_impl
    if (impl == "auto" and mesh is not None and not mesh.empty
            and mesh.shape.get("sp", 1) > 1):
        # a real sp axis: ring attention is the only impl that keeps the
        # sharded seq axis device-local (dense/flash would force an
        # all-gather of k/v). Zigzag placement by default — per-device
        # causal block counts are uniform (2n+1 half-stripe pairs each)
        # vs the contiguous layout's 1..n skew (parallel/ring.py; the
        # counts are printed into the multichip dryrun artifact)
        impl = "ring-zigzag"
    if impl in ("ring", "ring-zigzag"):
        from tpu_docker_api.parallel.ring import ring_attention

        out = ring_attention(
            q, k, v, mesh, causal=True,
            placement="zigzag" if impl == "ring-zigzag" else "contiguous")
    elif impl == "ulysses":
        from tpu_docker_api.parallel.ulysses import ulysses_attention

        out = ulysses_attention(q, k, v, mesh, causal=True)
    else:
        out = multihead_attention(q, k, v, causal=True, impl=impl)
    return linear(out.reshape(b, s, cfg.n_heads * hd), layer["attn"]["wo"])


def _mlp(x, layer):
    if "w_gu" in layer["mlp"]:
        gu = linear(x, layer["mlp"]["w_gu"])  # serving-fused gate|up
        gate, up = jnp.split(gu, 2, axis=-1)
        return linear(jax.nn.silu(gate) * up, layer["mlp"]["w_down"])
    gate = jax.nn.silu(linear(x, layer["mlp"]["w_gate"]))
    up = linear(x, layer["mlp"]["w_up"])
    return linear(gate * up, layer["mlp"]["w_down"])


def _block(x, layer, cfg: LlamaConfig, rope_cos, rope_sin, mesh,
           cache=None, start_pos=None, kv_limit=None):
    """One transformer block; the single source of truth for the residual /
    norm wiring of BOTH the training forward (cache=None) and the KV-cached
    decode path (returns (x, new_cache)). Decode's seq dim is 1 so it never
    shards on sp."""
    bspec = P(("dp", "fsdp"), "sp" if cache is None else None)
    attn_out = _attention(
        rms_norm(x, layer["attn_norm"], cfg.norm_eps), layer, cfg,
        rope_cos, rope_sin, mesh, cache=cache, start_pos=start_pos,
        kv_limit=kv_limit,
    )
    new_cache = None
    if cache is not None:
        attn_out, new_cache = attn_out
    x = x + attn_out
    x = constrain(x, mesh, bspec) if mesh is not None else x
    x = x + _mlp(rms_norm(x, layer["mlp_norm"], cfg.norm_eps), layer)
    x = constrain(x, mesh, bspec) if mesh is not None else x
    return x if cache is None else (x, new_cache)


def embed_lookup(table: jnp.ndarray, tokens: jnp.ndarray,
                 mesh: Mesh | None) -> jnp.ndarray:
    """Token-embedding lookup that stays efficient under SPMD.

    On a mesh whose ``tp`` axis shards the table's vocab dim
    (LLAMA_RULES "embed/tokens" → P("tp", "fsdp")), a plain ``jnp.take``
    makes the SPMD partitioner replicate the whole table and repartition
    ("Involuntary full rematerialization" — wasted HBM + ICI every step).
    The MXU-friendly fix (MaxText's ``use_iota_embed``): express the lookup
    as a one-hot × table matmul, which GSPMD shards like any row-parallel
    matmul — local partial products over each device's vocab shard, then a
    psum over tp. Off-mesh (single chip) the gather is ideal, so keep it.
    """
    if mesh is not None and not mesh.empty and mesh.shape.get("tp", 1) > 1:
        onehot = jax.nn.one_hot(tokens, table.shape[0], dtype=table.dtype)
        return jnp.einsum("bsv,vd->bsd", onehot, table)
    return jnp.take(table, tokens, axis=0)


def llama_hidden(
    params: dict,
    tokens: jnp.ndarray,  # (batch, seq) int32
    cfg: LlamaConfig,
    mesh: Mesh | None = None,
) -> jnp.ndarray:
    """The trunk: embed → scanned blocks → final hidden (batch, seq, dim),
    pre-final-norm. Shared by ``llama_forward`` (dense logits tail) and the
    chunked-CE training loss (which never materializes full logits)."""
    seq = tokens.shape[1]
    x = embed_lookup(params["embed"]["tokens"], tokens, mesh)
    if mesh is not None:
        x = constrain(x, mesh, P(("dp", "fsdp"), "sp"))
    rope_cos, rope_sin = rope_frequencies(cfg.head_dim, seq, cfg.rope_theta,
                                          getattr(cfg, "rope_scaling", None))

    block = functools.partial(
        _block, cfg=cfg, rope_cos=rope_cos, rope_sin=rope_sin, mesh=mesh
    )
    if cfg.remat:
        from tpu_docker_api.ops.flash_pallas import TRAIN_REMAT_POLICY

        # dots + the flash kernel's (out, lse): without the latter, the
        # backward pass re-runs the whole flash forward per layer before
        # its backward kernels
        block = jax.checkpoint(block, policy=TRAIN_REMAT_POLICY)

    def scan_body(x, layer):
        return block(x, layer), None

    x, _ = lax.scan(scan_body, x, params["layers"])
    return x


def llama_forward(
    params: dict,
    tokens: jnp.ndarray,  # (batch, seq) int32
    cfg: LlamaConfig,
    mesh: Mesh | None = None,
) -> jnp.ndarray:
    """Next-token logits (batch, seq, vocab) in f32."""
    x = llama_hidden(params, tokens, cfg, mesh)
    logits = lm_head(params, x, cfg)
    if mesh is not None:
        logits = constrain(logits, mesh, P(("dp", "fsdp"), "sp", "tp"))
    return logits


def llama_forward_cached(
    params: dict,
    tokens: jnp.ndarray,      # (batch, seq) int32 — the NEW tokens only
    cfg: LlamaConfig,
    k_cache: jnp.ndarray,     # (n_layers, batch, max_seq, n_kv_heads, head_dim)
    v_cache: jnp.ndarray,
    start_pos: jnp.ndarray,   # int32: absolute position of tokens[:, 0] —
    #                           scalar (whole batch) or (batch,) per-row
    mesh: Mesh | None = None,
    last_only: bool | jnp.ndarray = False,  # True: final position; traced
    #                           int: that position (padded-prefill logit)
    kv_limit: int | None = None,  # static: attend cache[:kv_limit] only
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """KV-cached forward: logits for the new tokens + updated caches.

    Block math is ``_block`` itself (cache threaded through it — one source
    of truth with ``llama_forward``); the layer scan CARRIES the full cache
    buffers and each layer writes only its new-token slots in place, so a
    decode step's cache traffic is one small write + one layer-sized read
    per layer — carrying the cache as scan xs/ys instead would stack fresh
    ys and re-materialize the entire cache every step (~4x decode time at
    bench shapes). Compile time stays O(1) in depth. ``start_pos`` is a
    traced scalar — one compiled program serves every decode step.
    ``last_only=True`` applies lm_head to the final position only (prefill
    wants just the next-token logits; skipping the (b, seq, vocab) f32
    intermediate saves prompt_len× the logits memory and FLOPs).
    """
    def block_fn(x, layer, cache, rope_cos, rope_sin):
        return _block(x, layer, cfg, rope_cos, rope_sin, mesh,
                      cache=cache, start_pos=start_pos, kv_limit=kv_limit)

    return decoder_forward_cached(
        params, tokens, cfg, k_cache, v_cache, mesh, last_only, block_fn)


def llama_forward_paged(
    params: dict,
    tokens: jnp.ndarray,      # (S, 1) int32 — one decode token per slot
    cfg: LlamaConfig,
    k_pool: jnp.ndarray,      # (n_layers, P, page, n_kv_heads, head_dim)
    v_pool: jnp.ndarray,
    table: jnp.ndarray,       # (S, mp) int32 page ids; 0 = trash
    pos: jnp.ndarray,         # (S,) int32 per-slot positions
    max_pos: int,             # position capacity (sizes rope tables)
    mesh: Mesh | None = None,  # tp mesh: pool kv-head dim sharded
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Paged-KV decode step: logits (S, 1, vocab) + updated pools. Block
    math is ``_block`` via the shared skeleton — only the cache write
    (page scatter) and read (page gather, ops/paged.py) differ from
    ``llama_forward_cached``. On a tp ``mesh`` the pools arrive with
    their kv-head dim sharded (infer/paged.py _alloc_cache) and the
    page scatter/gather are per-head-elementwise in that dim, so GSPMD
    keeps them local to each shard — same rule as the dense cache."""
    def block_fn(x, layer, cache, rope_cos, rope_sin):
        kc, vc, layer_idx = cache
        ref = PagedRef(k_pool=kc, v_pool=vc, layer_idx=layer_idx,
                       table=table)
        return _block(x, layer, cfg, rope_cos, rope_sin, mesh,
                      cache=ref, start_pos=pos)

    return decoder_forward_cached(
        params, tokens, cfg, k_pool, v_pool, mesh, False, block_fn,
        max_pos=max_pos)


def decoder_forward_cached(params, tokens, cfg, k_cache, v_cache, mesh,
                           last_only, block_fn, max_pos=None):
    """The shared KV-cached decoder skeleton: embed → cache-carrying layer
    scan → lm_head. ``block_fn(x, layer, (kc, vc, layer_idx), rope_cos,
    rope_sin) -> (x, (kc, vc))`` supplies the block body — Llama's
    ``_block``, MoE's aux-discarding wrapper (models/moe.py), or the
    paged closure (``llama_forward_paged``) — so the cache-as-carry
    mechanics live in exactly one place. ``max_pos`` sizes the rope
    tables when the cache shape doesn't imply it (a page pool's dim 2
    is the page size, not the position capacity)."""
    max_seq = max_pos or k_cache.shape[2]
    x = embed_lookup(params["embed"]["tokens"], tokens, mesh)
    if mesh is not None:
        x = constrain(x, mesh, P(("dp", "fsdp"), None))
    rope_cos, rope_sin = rope_frequencies(cfg.head_dim, max_seq, cfg.rope_theta,
                                          getattr(cfg, "rope_scaling", None))

    def scan_body(carry, layer_and_idx):
        x, kc, vc = carry
        layer, layer_idx = layer_and_idx
        x, (kc, vc) = block_fn(x, layer, (kc, vc, layer_idx),
                               rope_cos, rope_sin)
        return (x, kc, vc), None

    (x, new_k, new_v), _ = lax.scan(
        scan_body, (x, k_cache, v_cache),
        (params["layers"], jnp.arange(cfg.n_layers, dtype=jnp.int32)),
    )
    if last_only is True:
        x = x[:, -1:]
    elif last_only is not False and last_only is not None:
        # traced index: logits for position ``last_only`` only — the padded
        # prefill of a right-padded prompt (infer/slots.py) wants the logit
        # at actual_len-1, which is not the bucket's final position. A
        # (batch,) vector gives every row its own position (the batched
        # prefill), skipping the (b, seq, vocab) f32 logits either way
        idx = jnp.asarray(last_only)
        if idx.ndim == 1:
            x = jnp.take_along_axis(x, idx[:, None, None], axis=1)
        else:
            x = lax.dynamic_slice_in_dim(x, last_only, 1, axis=1)
    logits = lm_head(params, x, cfg)
    return logits, new_k, new_v


def cross_entropy(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Mean next-token cross-entropy; the single loss body shared by every
    training path (llama_loss, moe_loss, parallel.pipeline.pipeline_loss).
    logsumexp form: reduces straight off the logits instead of materializing
    the (batch, seq, vocab) log-softmax — at bench shapes that intermediate
    is 2GB of HBM traffic each way."""
    lse = jax.nn.logsumexp(logits, axis=-1)
    target_logit = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - target_logit)


def lm_head(params: dict, h: jnp.ndarray, cfg: LlamaConfig) -> jnp.ndarray:
    """Final norm + logits projection in f32 — shared model tail. Operands
    stay bf16 (full-rate MXU) with f32 accumulation; upcasting both sides
    would run the largest matmul in the model at the f32 rate (~4x slower
    on v5e) for no extra mantissa in the inputs."""
    h = rms_norm(h, params["final_norm"], cfg.norm_eps)
    return linear(h.astype(cfg.dtype), params["lm_head"],
                  out_dtype=jnp.float32)


def llama_loss(
    params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
    mesh: Mesh | None = None,
) -> jnp.ndarray:
    """Causal LM loss: predict tokens[:, 1:] from tokens[:, :-1].

    With ``cfg.loss_chunk_rows`` set, the logits projection and CE fuse into
    ``ops.xent.chunked_cross_entropy``: no (batch, seq, vocab) residual is
    ever materialized (the backward rebuilds logits per row chunk), freeing
    the HBM that otherwise caps the training batch size."""
    if cfg.loss_chunk_rows:
        from tpu_docker_api.ops.quant import QuantizedLinear, \
            dequantize_weight
        from tpu_docker_api.ops.xent import chunked_cross_entropy

        x = llama_hidden(params, tokens[:, :-1], cfg, mesh)
        h = rms_norm(x, params["final_norm"], cfg.norm_eps).astype(cfg.dtype)
        if mesh is not None:
            # same activation sharding the dense tail's logits constraint
            # implies on its input; the chunk scan inherits it from here
            h = constrain(h, mesh, P(("dp", "fsdp"), "sp", None))
        head = params["lm_head"]
        if isinstance(head, QuantizedLinear):
            # QLoRA over an int8 base (train/lora.py): the chunked-CE
            # scan wants a plain matrix; dequantize the FROZEN head
            # once per step (a bf16 transient — ~1 GB at 8B, freed
            # after the scan; the base gets no gradient either way)
            head = dequantize_weight(head, cfg.dtype)
        return chunked_cross_entropy(
            h, head, tokens[:, 1:], cfg.loss_chunk_rows)
    logits = llama_forward(params, tokens[:, :-1], cfg, mesh)
    return cross_entropy(logits, tokens[:, 1:])


def param_count(params: dict) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))
