"""Vision Transformer (ViT) — the non-causal model family.

The reference ships no models at all (SURVEY.md §0); this joins Llama and
MoE as the third in-tree workload family and is deliberately NOT a decoder:
it exercises the framework surfaces a causal LM cannot — non-causal
attention (the flash kernel's ``causal=False`` path), LayerNorm
(``ops.norms.layer_norm``), tuple batches (images, labels) through the
generic trainer, and classification loss.

TPU-first choices:

- **mean-pool head, no CLS token**: token count stays ``(image/patch)²`` —
  a multiple of 128 for the shipped presets, so sequence dims tile cleanly
  onto the flash kernel and the MXU instead of the 197-token ragged shapes
  a CLS token produces;
- **patchify as reshape+matmul**: the patch embedding is a single
  (P²·C, D) matmul on re-laid-out pixels, not a convolution — identical
  math, and it rides the same Megatron column/row sharding rules as every
  other projection;
- **stacked layers + lax.scan + remat**, bf16 storage / f32 norms, exactly
  llama's discipline (models/llama.py docstring).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpu_docker_api.models.common import trunc_normal_init
from tpu_docker_api.ops.attention import multihead_attention
from tpu_docker_api.ops.norms import layer_norm
from tpu_docker_api.ops.quant import linear
from tpu_docker_api.parallel.sharding import constrain

#: suffix rules (parallel/sharding.py): Megatron column/row over (fsdp, tp),
#: scan axis never sharded, vectors replicated
VIT_RULES: list[tuple[str, P]] = [
    ("patch_embed/w",   P("fsdp", "tp")),           # (P²C, d) column
    ("layers/attn/wq",  P(None, "fsdp", "tp")),     # (L, d, d) column
    ("layers/attn/wk",  P(None, "fsdp", "tp")),
    ("layers/attn/wv",  P(None, "fsdp", "tp")),
    ("layers/attn/wo",  P(None, "tp", "fsdp")),     # row
    ("layers/mlp/w1",   P(None, "fsdp", "tp")),     # (L, d, ff) column
    ("layers/mlp/w2",   P(None, "tp", "fsdp")),     # (L, ff, d) row
    ("head",            P("fsdp", None)),           # (d, classes)
    ("pos_emb",         P()),
    ("*",               P()),                       # biases, norms
]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 256
    patch_size: int = 16
    channels: int = 3
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_dim: int = 3072
    n_classes: int = 1000
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    remat: bool = True

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def flops_per_image(self) -> float:
        """Training FLOPs per image (fwd+bwd ≈ 3× forward matmul FLOPs)."""
        n, d = self.n_patches, self.dim
        per_layer = (4 * 2 * d * d            # wq wk wv wo
                     + 2 * 2 * d * self.ffn_dim)
        attn = 2 * 2 * n * d                  # scores + values per token
        patch = 2 * (self.patch_size ** 2 * self.channels) * d
        head = 2 * d * self.n_classes
        return 3.0 * (n * (self.n_layers * (per_layer + attn))
                      + n * patch + head)


def vit_presets() -> dict[str, ViTConfig]:
    return {
        # ViT-Base/16 at 256px → 256 patches (tiles on the flash kernel)
        "vit-b16": ViTConfig(),
        "vit-s16": ViTConfig(dim=384, n_layers=12, n_heads=6, ffn_dim=1536),
        # CPU-fast config for tests / dryrun (64px/16 → 16 patches)
        "tiny": ViTConfig(image_size=64, patch_size=16, dim=64, n_layers=2,
                          n_heads=4, ffn_dim=128, n_classes=10, remat=False),
    }


def vit_init(cfg: ViTConfig, key: jax.Array) -> dict:
    k_patch, k_layers, k_head, k_pos = jax.random.split(key, 4)
    d, pd = cfg.dim, cfg.patch_size ** 2 * cfg.channels
    L = cfg.n_layers

    def init(key, shape, fan_in):
        return trunc_normal_init(key, shape, fan_in, cfg.dtype)

    ks = jax.random.split(k_layers, 6)
    return {
        "patch_embed": {"w": init(k_patch, (pd, d), pd),
                        "b": jnp.zeros((d,), cfg.dtype)},
        "pos_emb": (jax.random.normal(k_pos, (cfg.n_patches, d), jnp.float32)
                    * 0.02).astype(cfg.dtype),
        "layers": {
            "ln1_w": jnp.ones((L, d), cfg.dtype),
            "ln1_b": jnp.zeros((L, d), cfg.dtype),
            "ln2_w": jnp.ones((L, d), cfg.dtype),
            "ln2_b": jnp.zeros((L, d), cfg.dtype),
            "attn": {
                "wq": init(ks[0], (L, d, d), d),
                "wk": init(ks[1], (L, d, d), d),
                "wv": init(ks[2], (L, d, d), d),
                "wo": init(ks[3], (L, d, d), d),
            },
            "mlp": {
                "w1": init(ks[4], (L, d, cfg.ffn_dim), d),
                "b1": jnp.zeros((L, cfg.ffn_dim), cfg.dtype),
                "w2": init(ks[5], (L, cfg.ffn_dim, d), cfg.ffn_dim),
                "b2": jnp.zeros((L, d), cfg.dtype),
            },
        },
        "final_ln_w": jnp.ones((d,), cfg.dtype),
        "final_ln_b": jnp.zeros((d,), cfg.dtype),
        # near-zero head: initial logits ≈ uniform (ViT practice is exact
        # zero, but that blocks trunk gradients at step 0)
        "head": init(k_head, (d, cfg.n_classes), d) * jnp.asarray(
            0.02, cfg.dtype),
    }


def _patchify(images: jnp.ndarray, cfg: ViTConfig) -> jnp.ndarray:
    """(B, H, W, C) → (B, n_patches, P²·C)."""
    b, h, w, c = images.shape
    p = cfg.patch_size
    x = images.reshape(b, h // p, p, w // p, p, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, (h // p) * (w // p), p * p * c)


def _block(x, layer, cfg: ViTConfig, mesh):
    """Pre-LN transformer encoder block (non-causal attention)."""
    b, n, d = x.shape
    hd = cfg.head_dim
    y = layer_norm(x, layer["ln1_w"], layer["ln1_b"], cfg.norm_eps)
    q = linear(y, layer["attn"]["wq"]).reshape(b, n, cfg.n_heads, hd)
    k = linear(y, layer["attn"]["wk"]).reshape(b, n, cfg.n_heads, hd)
    v = linear(y, layer["attn"]["wv"]).reshape(b, n, cfg.n_heads, hd)
    # bf16 probs: at these shapes the f32 (b, h, n, n) probability tensor
    # is the step's dominant HBM traffic (flash/ring round probs the same
    # way). Half of round 2's 0.36 -> 0.404 MFU win; the other half is the
    # dense short-encoder dispatch in ops/attention.py (attribution:
    # docs/perf-notes.md)
    attn = multihead_attention(q, k, v, causal=False,
                               probs_dtype=cfg.dtype)
    x = x + linear(attn.reshape(b, n, d), layer["attn"]["wo"])
    x = constrain(x, mesh, P(("dp", "fsdp"), None)) if mesh is not None else x
    y = layer_norm(x, layer["ln2_w"], layer["ln2_b"], cfg.norm_eps)
    y = jax.nn.gelu(linear(y, layer["mlp"]["w1"]) + layer["mlp"]["b1"])
    x = x + (linear(y, layer["mlp"]["w2"]) + layer["mlp"]["b2"])
    return constrain(x, mesh, P(("dp", "fsdp"), None)) if mesh is not None else x


def vit_forward(
    params: dict,
    images: jnp.ndarray,  # (B, H, W, C), any float dtype
    cfg: ViTConfig,
    mesh: Mesh | None = None,
) -> jnp.ndarray:
    """Class logits (B, n_classes) in f32 (mean-pooled, no CLS token)."""
    x = _patchify(images.astype(cfg.dtype), cfg)
    x = linear(x, params["patch_embed"]["w"]) + params["patch_embed"]["b"]
    x = x + params["pos_emb"][None]
    if mesh is not None:
        x = constrain(x, mesh, P(("dp", "fsdp"), None))

    block = functools.partial(_block, cfg=cfg, mesh=mesh)
    if cfg.remat:
        from tpu_docker_api.ops.flash_pallas import TRAIN_REMAT_POLICY

        block = jax.checkpoint(block, policy=TRAIN_REMAT_POLICY)

    def scan_body(x, layer):
        return block(x, layer), None

    x, _ = lax.scan(scan_body, x, params["layers"])
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"],
                   cfg.norm_eps)
    pooled = jnp.mean(x.astype(jnp.float32), axis=1)   # (B, d)
    return linear(pooled.astype(cfg.dtype), params["head"],
                  out_dtype=jnp.float32)


def vit_loss(
    params: dict,
    batch: tuple[jnp.ndarray, jnp.ndarray],  # (images (B,H,W,C), labels (B,))
    cfg: ViTConfig,
    mesh: Mesh | None = None,
) -> jnp.ndarray:
    """Mean softmax cross-entropy over classes."""
    images, labels = batch
    logits = vit_forward(params, images, cfg, mesh)
    lse = jax.nn.logsumexp(logits, axis=-1)
    target = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(lse - target)


def vit_synthetic_batch(key: jax.Array, batch: int, cfg: ViTConfig,
                        row_offset: int = 0):
    """(images, labels) synthetic pair — the data layer for tests/bench.

    Each GLOBAL row r derives from ``fold_in(key, r)``, so a process
    generating only its local rows (``row_offset`` = its first global row)
    produces exactly the rows any other process layout would — the same
    process-count-invariant resume/rescale contract as the token data
    paths (train/__main__.py), without materializing the global image
    batch everywhere."""
    rows = jnp.arange(row_offset, row_offset + batch)
    keys = jax.vmap(lambda r: jax.random.fold_in(key, r))(rows)

    def one(k):
        k1, k2 = jax.random.split(k)
        img = jax.random.uniform(
            k1, (cfg.image_size, cfg.image_size, cfg.channels), jnp.float32)
        label = jax.random.randint(k2, (), 0, cfg.n_classes, dtype=jnp.int32)
        return img, label

    return jax.vmap(one)(keys)
