"""Shared model-construction helpers (one source of truth across families)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def trunc_normal_init(key, shape, fan_in, dtype):
    """Truncated-normal fan-in initializer every family uses: N(0, 1/fan_in)
    clipped at ±2σ, drawn in f32 and cast to the storage dtype."""
    return (jax.random.truncated_normal(key, -2, 2, shape, jnp.float32)
            * (fan_in**-0.5)).astype(dtype)
