"""Model families the control plane provisions (BASELINE.json configs).

The reference ships no models (SURVEY.md §0) — these are the TPU-native
workloads: the Llama family (pretrain/inference north star) and the MNIST MLP
(single-chip smoke config #2). Pure-functional JAX: params are nested dicts,
forward passes are jit/pjit-compatible functions, sharding comes from
``parallel.sharding`` rules rather than framework metadata.
"""

from tpu_docker_api.models.llama import (  # noqa: F401
    LlamaConfig,
    llama_forward,
    llama_init,
    llama_presets,
)
from tpu_docker_api.models.mlp import mlp_forward, mlp_init  # noqa: F401
