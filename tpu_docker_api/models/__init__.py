"""Model families the control plane provisions (BASELINE.json configs).

The reference ships no models (SURVEY.md §0) — these are the TPU-native
workloads: the Llama family (pretrain/inference north star), the Mixtral-style
sparse MoE family (expert parallelism, SURVEY.md §2.3), the ViT family
(non-causal encoder), the encoder-decoder family (cross-attention,
seq2seq), and the MNIST MLP (single-chip smoke config #2). Pure-functional JAX: params are nested dicts,
forward passes are jit/pjit-compatible functions, sharding comes from
``parallel.sharding`` rules rather than framework metadata.

``model_fns(cfg)`` is the trainer's dispatch seam: any config type maps to its
(init, loss, sharding-rules) triple, so train/trainer.py stays model-agnostic.
"""

from tpu_docker_api.models.llama import (  # noqa: F401
    LlamaConfig,
    llama_forward,
    llama_init,
    llama_presets,
)
from tpu_docker_api.models.mlp import mlp_forward, mlp_init  # noqa: F401
from tpu_docker_api.models.moe import (  # noqa: F401
    MoEConfig,
    moe_forward,
    moe_init,
    moe_presets,
)
from tpu_docker_api.models.vit import (  # noqa: F401
    ViTConfig,
    vit_forward,
    vit_init,
    vit_presets,
)
from tpu_docker_api.models.encdec import (  # noqa: F401
    EncDecConfig,
    encdec_forward,
    encdec_init,
    encdec_presets,
)


def model_fns(cfg):
    """(init_fn(cfg, key), loss_fn(params, batch, cfg, mesh), rules).
    ``batch`` is whatever the family trains on: a token array for the
    decoder families, an (images, labels) tuple for ViT — the trainer
    shards any batch pytree on its leading axis."""
    from tpu_docker_api.models.encdec import ENCDEC_RULES, encdec_loss
    from tpu_docker_api.models.llama import llama_loss
    from tpu_docker_api.models.moe import MOE_RULES, moe_loss
    from tpu_docker_api.models.vit import VIT_RULES, vit_loss
    from tpu_docker_api.parallel.sharding import LLAMA_RULES

    if isinstance(cfg, MoEConfig):
        return moe_init, moe_loss, MOE_RULES
    if isinstance(cfg, LlamaConfig):
        return llama_init, llama_loss, LLAMA_RULES
    if isinstance(cfg, ViTConfig):
        return vit_init, vit_loss, VIT_RULES
    if isinstance(cfg, EncDecConfig):
        return encdec_init, encdec_loss, ENCDEC_RULES
    raise TypeError(f"no model registered for config type {type(cfg)!r}")


def cached_forward_fn(cfg):
    """The serving dispatch seam (infer/engine.py): any decoder config maps
    to its KV-cached forward with the shared signature
    ``(params, tokens, cfg, k_cache, v_cache, start_pos, mesh, last_only)``.
    NB: MoEConfig subclass-checks must come first if it ever inherits."""
    from tpu_docker_api.models.llama import llama_forward_cached
    from tpu_docker_api.models.moe import moe_forward_cached

    if isinstance(cfg, MoEConfig):
        return moe_forward_cached
    if isinstance(cfg, LlamaConfig):
        return llama_forward_cached
    raise TypeError(f"no cached forward for config type {type(cfg)!r}")


def resolve_preset(spec: str):
    """(family, cfg) from a family-prefixed preset spec — the single
    parser behind the trainer and serve CLIs: "NAME" → llama,
    "moe:NAME" / "vit:NAME" / "encdec:NAME" → that family."""
    from tpu_docker_api.models.encdec import encdec_presets
    from tpu_docker_api.models.vit import vit_presets

    if spec.startswith("moe:"):
        return "moe", moe_presets()[spec[4:]]
    if spec.startswith("vit:"):
        return "vit", vit_presets()[spec[4:]]
    if spec.startswith("encdec:"):
        return "encdec", encdec_presets()[spec[7:]]
    return "llama", llama_presets()[spec]
