"""Shared single-chip training-throughput harness.

One timing discipline for every train bench (bench.py riders,
scripts/validate_tpu.py checks): build on ONE device, warmup (first step
compiles), then a timed loop closed by a device→host read —
``block_until_ready`` has been seen returning early on remote-tunneled
platforms, and a host value transfer cannot lie.
"""

from __future__ import annotations

import time


def time_train_steps(cfg, batch_data, steps: int = 8, warmup: int = 2) -> dict:
    """{"steps_per_sec", "loss"} for ``cfg`` trained on ``batch_data``
    (token array or tuple batch) on one device."""
    import jax

    from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
    from tpu_docker_api.train.trainer import create_train_state, make_train_step

    mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=1),
                      devices=jax.devices()[:1])
    state, opt = create_train_state(cfg, mesh, jax.random.PRNGKey(0))
    step = make_train_step(cfg, mesh, opt)
    for _ in range(max(warmup, 1)):
        state, metrics = step(state, batch_data)
    float(metrics["loss"])  # host read: force real completion
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_data)
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    return {"steps_per_sec": steps / dt, "loss": loss}
