"""Training job entrypoint — what a container image launched by the control
plane actually runs (BASELINE.json north star: POST /containers provisions a
MaxText-class JAX pretraining job).

    python -m tpu_docker_api.train --preset tiny --steps 100 \
        --ckpt-dir /ckpt --save-every 20

Contracts with the control plane:

- **Distributed bootstrap**: if ``JAX_NUM_PROCESSES`` > 1 (rendered by the
  job service, workload/jaxenv.py), calls ``jax.distributed.initialize`` with
  the coordinator/process env before touching any backend.
- **Quiesce**: SIGTERM/SIGINT (docker stop — the rescale flow's graceful
  stop) checkpoints the current step before exiting, so ``job-(n+1)`` resumes
  exactly where ``job-n`` stopped. This is the in-container half of the
  quiesce→swap sequencing in service/job.py.
- **Resume**: boots via ``resume_or_init`` — a fresh dir trains from step 0,
  a dir with checkpoints restores onto the CURRENT mesh shape, which may
  differ from the writer's (orbax resharding; tests/test_checkpoint.py).

Emits one JSON line per log interval: {"step", "loss", "tokens_per_sec"}.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import signal
import sys
import time


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(prog="python -m tpu_docker_api.train")
    p.add_argument("--preset", default="tiny",
                   help="model preset (llama: tiny, bench-350m, llama3-8b...; "
                        "moe: and vit: prefixes for the other families)")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=0, help="0 = preset default")
    p.add_argument("--data", default="",
                   help="token file/dir (.bin/.npy, data/loader.py); "
                        "'' trains on synthetic tokens")
    p.add_argument("--data-seed", type=int, default=0)
    p.add_argument("--data-dtype", default="",
                   help=".bin token width; '' picks by vocab size "
                        "(uint16 below 65536, else int32)")
    p.add_argument("--ckpt-dir", default="", help="'' disables checkpointing")
    p.add_argument("--save-every", type=int, default=50)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--dp", type=int, default=-1)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--ep", type=int, default=1)
    p.add_argument("--moe-dispatch", default="auto",
                   choices=["auto", "gather", "einsum", "sort"],
                   help="moe: presets only. auto (default) = gather on "
                        "one device, sort on meshes (r5); einsum = the "
                        "GSPMD all-to-all form, the escape hatch if "
                        "multi-chip profiling favors it")
    p.add_argument("--optim", default="adamw", choices=["adamw", "adamw-int8"],
                   help="adamw-int8 stores both Adam moments as blockwise "
                        "int8 (halves optimizer HBM)")
    p.add_argument("--lora-rank", type=int, default=0,
                   help="> 0: LoRA fine-tuning — freeze the base, train "
                        "rank-R adapters on --lora-targets; --ckpt-dir "
                        "then holds ADAPTER-only checkpoints")
    p.add_argument("--lora-alpha", type=float, default=16.0)
    p.add_argument("--lora-targets", default="wq,wv",
                   help="comma-separated projection leaf names to adapt")
    p.add_argument("--lora-base-ckpt", default="",
                   help="full-train checkpoint dir to load the frozen "
                        "base from ('' = random init, smoke/bench)")
    p.add_argument("--lora-forward", default=None,
                   choices=["merged", "attached"],
                   help="merged: classic per-step merge (transient "
                        "weight-sized copy); attached: unmerged "
                        "Wx + s·B(Ax) forward — no merged tree, "
                        "required at 8B-on-one-chip scale")
    p.add_argument("--qlora", action="store_true",
                   help="int8-quantize the frozen base at load and use "
                        "the attached forward — llama3-8b fine-tuning "
                        "on a single 16 GB chip (llama presets only)")
    p.add_argument("--profile-dir", default="",
                   help="write a jax.profiler trace (TensorBoard/Perfetto "
                        "format) covering post-compile steps")
    p.add_argument("--profile-steps", type=int, default=5,
                   help="how many steps the trace covers")
    p.add_argument("--platform", default="",
                   help="force a jax platform (tests: cpu)")
    p.add_argument("--virtual-devices", type=int, default=0,
                   help="force N virtual CPU devices (tests)")
    args = p.parse_args(argv)

    from tpu_docker_api.workload.jaxenv import bootstrap_jax

    # coordinator/process identity rendered by the control plane
    bootstrap_jax(args.platform, args.virtual_devices)
    import jax

    n_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))

    from tpu_docker_api.models.llama import llama_presets
    from tpu_docker_api.models.moe import moe_presets
    from tpu_docker_api.parallel.mesh import MeshPlan, build_mesh
    from tpu_docker_api.train.checkpoint import resume_or_init
    from tpu_docker_api.train.trainer import (
        create_train_state,
        make_train_step,
        synthetic_batch,
    )

    from tpu_docker_api.models import resolve_preset

    family, cfg = resolve_preset(args.preset)
    is_vit = family == "vit"
    is_encdec = family == "encdec"
    if args.moe_dispatch != "auto":
        if family != "moe":
            raise SystemExit("--moe-dispatch applies to moe: presets only")
        cfg = dataclasses.replace(cfg, dispatch_impl=args.moe_dispatch)
    if is_vit:
        if args.data or args.seq:
            raise SystemExit("--data/--seq do not apply to vit: presets "
                             "(image batches are synthetic)")
        seq = cfg.n_patches  # tokens-per-image, for the throughput metric
    elif is_encdec:
        if args.data:
            raise SystemExit("--data does not apply to encdec: presets "
                             "(seq2seq pairs are synthetic)")
        seq = args.seq or min(cfg.max_tgt_len, 128)  # src_len == tgt_len
    if not (is_vit or is_encdec):
        if args.seq:
            cfg = dataclasses.replace(cfg, max_seq_len=args.seq)
        seq = min(cfg.max_seq_len, 512) if not args.seq else args.seq

    mesh = build_mesh(MeshPlan(dp=args.dp, fsdp=args.fsdp, tp=args.tp,
                               sp=args.sp, pp=args.pp, ep=args.ep))
    key = jax.random.PRNGKey(0)
    opt = None
    if args.optim == "adamw-int8":
        from tpu_docker_api.train.optim import adamw_int8

        opt = adamw_int8()
    if args.lora_rank <= 0 and (
            args.lora_base_ckpt or args.lora_alpha != 16.0
            or args.lora_targets != "wq,wv" or args.qlora
            or args.lora_forward is not None):
        # a lora flag without --lora-rank would otherwise be silently
        # ignored and a FULL random-init pretrain would run with exit 0
        raise SystemExit(
            "--lora-base-ckpt/--lora-alpha/--lora-targets/--qlora/"
            "--lora-forward require --lora-rank > 0")
    if args.qlora and family != "llama":
        raise SystemExit("--qlora supports llama presets only (the "
                         "int8 quantizer is llama-shaped)")
    if args.qlora and args.lora_forward == "merged":
        # contradictory: merging onto an int8 base would quantize the
        # delta away — reject rather than silently run attached
        raise SystemExit("--qlora requires the attached forward; drop "
                         "--lora-forward merged")
    if args.lora_forward is None:
        args.lora_forward = "attached" if args.qlora else "merged"
    mgr = None
    if args.lora_rank > 0:
        from tpu_docker_api.train.lora import (
            create_lora_state,
            init_base_params,
            lora_resume_or_init,
            make_lora_train_step,
        )

        targets = tuple(t.strip() for t in args.lora_targets.split(",")
                        if t.strip())
        if args.lora_base_ckpt:
            # frozen base from a full-train checkpoint: params-only,
            # metadata-driven restore (works whatever optimizer wrote
            # it; a missing/empty dir is an ERROR — fine-tuning against
            # a silently random base would be garbage with exit 0)
            from tpu_docker_api.train.lora import restore_base_params

            base_params = restore_base_params(args.lora_base_ckpt, cfg,
                                              mesh)
        else:
            base_params = init_base_params(cfg, mesh, key)
        if args.qlora:
            # int8 base + unmerged forward: the QLoRA memory shape —
            # exactly the serving quantizer, so adapters train against
            # the numerics `serve --quantize --lora-forward attached`
            # will run
            from tpu_docker_api.train.lora import quantize_base

            base_params = quantize_base(base_params)
        if args.ckpt_dir:
            state, optimizer, mgr = lora_resume_or_init(
                args.ckpt_dir, cfg, mesh, key, args.lora_rank,
                targets=targets, optimizer=opt)
        else:
            state, optimizer = create_lora_state(
                cfg, mesh, key, args.lora_rank, targets=targets,
                optimizer=opt)
        step_fn = make_lora_train_step(cfg, mesh, optimizer, base_params,
                                       alpha=args.lora_alpha,
                                       forward=args.lora_forward)
    elif args.ckpt_dir:
        state, optimizer, mgr = resume_or_init(args.ckpt_dir, cfg, mesh, key,
                                               optimizer=opt)
        step_fn = make_train_step(cfg, mesh, optimizer)
    else:
        state, optimizer = create_train_state(cfg, mesh, key, optimizer=opt)
        step_fn = make_train_step(cfg, mesh, optimizer)
    start_step = int(state.step)

    # quiesce contract: graceful stop ⇒ checkpoint ⇒ exit 0
    stop = {"now": False}

    def _quiesce(signum, _frame):
        stop["now"] = True

    signal.signal(signal.SIGTERM, _quiesce)
    signal.signal(signal.SIGINT, _quiesce)

    def _save(final: bool = False) -> None:
        if mgr is not None:
            mgr.save(state)
            if final:
                mgr.wait()

    # get_batch returns this process's rows of the global batch (the train
    # step's contract — trainer.py assembles the global array from process
    # shards when JAX_NUM_PROCESSES > 1)
    if args.data:
        from tpu_docker_api.data import make_batch_fn, open_token_files

        # stateless (seed, step) -> batch: resume at step N reads exactly
        # the batch job-(n-1) would have seen — the data half of quiesce
        bin_dtype = args.data_dtype or (
            "int32" if cfg.vocab_size > 65535 else "uint16")
        source = open_token_files(args.data, window=seq + 1,
                                  bin_dtype=bin_dtype)
        get_batch = make_batch_fn(
            source, args.batch, seed=args.data_seed,
            process_index=jax.process_index(),
            process_count=n_processes,
        )
    elif is_vit:
        from tpu_docker_api.data.loader import rows_for_process
        from tpu_docker_api.models.vit import vit_synthetic_batch

        rows = rows_for_process(args.batch, jax.process_index(), n_processes)

        def get_batch(i):
            # generate only this process's rows of the GLOBAL batch (full
            # images are ~786KB each); row-keyed generation keeps the
            # process-count-invariant resume/rescale contract (line 141)
            return vit_synthetic_batch(
                jax.random.PRNGKey(i), rows.stop - rows.start, cfg,
                row_offset=rows.start)
    elif is_encdec:
        from tpu_docker_api.data.loader import rows_for_process
        from tpu_docker_api.models.encdec import encdec_synthetic_batch

        rows = rows_for_process(args.batch, jax.process_index(), n_processes)

        def get_batch(i):
            return encdec_synthetic_batch(
                jax.random.PRNGKey(i), rows.stop - rows.start, seq, seq,
                cfg, row_offset=rows.start)
    else:
        from tpu_docker_api.data.loader import rows_for_process

        rows = rows_for_process(args.batch, jax.process_index(), n_processes)

        def get_batch(i):
            full = synthetic_batch(jax.random.PRNGKey(i), args.batch, seq,
                                   cfg.vocab_size)
            return full[rows.start:rows.stop]

    # profiling (SURVEY.md §5.1 — the reference has none): trace a window
    # of post-compile steps; the first step's compile would drown the trace
    # (unless only one step remains, where compile-heavy beats no trace)
    profile_at = min(start_step + 1, args.steps - 1) if args.profile_dir else -1
    profiling = False

    def _stop_profile(metrics) -> None:
        nonlocal profiling
        float(metrics["loss"])  # drain the dispatch queue into the trace
        jax.profiler.stop_trace()
        profiling = False
        print(json.dumps({"event": "profile_written",
                          "dir": args.profile_dir}), flush=True)

    tokens_per_step = args.batch * seq
    t0 = time.monotonic()
    for i in range(start_step, args.steps):
        if i == profile_at:
            jax.profiler.start_trace(args.profile_dir)
            profiling = True
        state, metrics = step_fn(state, get_batch(i))
        if profiling and i >= profile_at + args.profile_steps - 1:
            _stop_profile(metrics)
        # host-side counter: reading metrics["step"] would force a device
        # sync every step and defeat async dispatch on TPU
        done = i + 1
        if stop["now"]:
            if profiling:
                _stop_profile(metrics)
            _save(final=True)
            print(json.dumps({"event": "quiesced", "step": done}), flush=True)
            return
        if done % args.log_every == 0 or done == args.steps:
            dt = time.monotonic() - t0
            steps_done = done - start_step
            print(json.dumps({
                "step": done,
                "loss": round(float(metrics["loss"]), 4),
                "tokens_per_sec": round(steps_done * tokens_per_step / dt, 1),
            }), flush=True)
        if mgr is not None and done % args.save_every == 0:
            _save()
    if profiling:  # profile window outran the step budget
        _stop_profile(metrics)
    _save(final=True)
    print(json.dumps({"event": "done", "step": int(state.step)}), flush=True)


if __name__ == "__main__":
    sys.exit(main())
