"""Checkpoint / resume for training state (SURVEY.md §5.4).

The reference checkpoints only control-plane state (etcd specs) and has no
workload-state concept at all; this module supplies the workload half: orbax
saves of the sharded ``TrainState``, async by default so the train loop
doesn't stall on HBM→disk, restored **directly into the target shardings**
(each host/chip reads only its own shards — no full-model host
materialization, the same property create_train_state has on init).

This is also the quiesce point for the control plane's rolling rescale
(service/container.py): save() → migrate the checkpoint volume → restore on
the new mesh. Restoring onto a *different* mesh shape works by construction:
orbax lays the on-disk array out by global shape and the restore shardings
decide how it is re-split.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np
import optax
import orbax.checkpoint as ocp

from tpu_docker_api.models import model_fns
from tpu_docker_api.parallel.sharding import param_shardings
from tpu_docker_api.train.trainer import TrainState, _opt_shardings


class CheckpointManager:
    """Thin orbax CheckpointManager wrapper bound to one run directory."""

    def __init__(self, directory: str | os.PathLike, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        # explicit handler registry: (a) ``item_metadata`` works on a
        # manager that has not saved/restored yet (params-only restores
        # read the checkpoint's own structure first); (b) PyTreeRestore
        # is admitted against the StandardSave on-disk format — it is
        # the one restore path that honors ocp.PLACEHOLDER, which
        # restore_params uses to SKIP reading optimizer moments
        registry = ocp.handlers.DefaultCheckpointHandlerRegistry()
        std = ocp.StandardCheckpointHandler()
        registry.add("default", ocp.args.StandardSave, std)
        registry.add("default", ocp.args.StandardRestore, std)
        registry.add("default", ocp.args.PyTreeRestore,
                     ocp.PyTreeCheckpointHandler())
        self._mgr = ocp.CheckpointManager(
            os.fspath(os.path.abspath(directory)),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
            handler_registry=registry,
        )

    def save(self, state: TrainState, step: int | None = None) -> bool:
        """Async save; returns whether a save was started (interval gate)."""
        step = int(state.step) if step is None else step
        return self._mgr.save(step, args=ocp.args.StandardSave(state))

    def restore(self, cfg, mesh, optimizer: optax.GradientTransformation,
                step: int | None = None, rules=None) -> TrainState:
        """Restore into the shardings implied by (cfg, mesh, rules) — the
        mesh may differ from the one the checkpoint was written on."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint steps in directory")
        model_init, _, model_rules = model_fns(cfg)
        rules = rules if rules is not None else model_rules
        abstract_params = jax.eval_shape(
            lambda k: model_init(cfg, k), jax.random.PRNGKey(0))
        p_sh = param_shardings(abstract_params, mesh, rules)
        abstract_opt = jax.eval_shape(optimizer.init, abstract_params)
        o_sh = _opt_shardings(optimizer, abstract_params, mesh, rules,
                              param_sh=p_sh, abstract_opt=abstract_opt)

        def as_abstract(tree, shardings):
            return jax.tree_util.tree_map(
                lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
                tree, shardings)

        from jax.sharding import NamedSharding, PartitionSpec as P

        target = TrainState(
            step=jax.ShapeDtypeStruct((), np.int32,
                                      sharding=NamedSharding(mesh, P())),
            params=as_abstract(abstract_params, p_sh),
            opt_state=as_abstract(abstract_opt, o_sh),
        )
        return self.restore_with_target(target, step)

    def restore_with_target(self, target, step: int | None = None):
        """Restore into an arbitrary abstract pytree (ShapeDtypeStruct +
        shardings) — the seam LoRA's adapter-only checkpoints use
        (train/lora.py builds a target the model registry can't derive)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint steps in directory")
        return self._mgr.restore(step, args=ocp.args.StandardRestore(target))

    def restore_params(self, shardings, step: int | None = None) -> dict:
        """Restore ONLY the params tree, no matter which optimizer wrote
        the checkpoint. The target comes from the checkpoint's OWN
        metadata; the step and every optimizer subtree are
        ``ocp.PLACEHOLDER`` so their bytes are never read — at 8B-adamw
        scale the moments are 2 extra f32 copies of every weight, which
        neither fit one serving chip nor deserve the disk reads. This is
        the seam for frozen-base loads (LoRA ``--lora-base-ckpt``) and
        serving, where coupling the restore to the writing run's
        optimizer choice (adamw vs adamw-int8) would be fragile."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint steps in directory")
        raw = self._mgr.item_metadata(step).tree  # [step, params, opt]

        def sds(m, sharding):
            return jax.ShapeDtypeStruct(tuple(m.shape), m.dtype,
                                        sharding=sharding)

        target = [
            ocp.PLACEHOLDER,
            jax.tree_util.tree_map(sds, raw[1], shardings),
            jax.tree_util.tree_map(lambda _: ocp.PLACEHOLDER, raw[2]),
        ]
        # explicit restore_args: without them the handler falls back to
        # the sharding recorded in the checkpoint FILE, which references
        # the writer's devices — a restore on a different topology (the
        # normal serving case) then fails
        restore_args = jax.tree_util.tree_map(
            lambda x: x if x is ocp.PLACEHOLDER else ocp.ArrayRestoreArgs(
                sharding=x.sharding, global_shape=x.shape, dtype=x.dtype),
            target,
            is_leaf=lambda x: (x is ocp.PLACEHOLDER
                               or isinstance(x, jax.ShapeDtypeStruct)))
        restored = self._mgr.restore(
            step, args=ocp.args.PyTreeRestore(item=target,
                                              restore_args=restore_args))
        return restored[1]

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def wait(self) -> None:
        """Block until pending async saves are durable (pre-migration barrier
        for the rescale flow)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def restore_model_params(directory, cfg, mesh, rules=None):
    """(params, step) of the latest checkpoint in ``directory``,
    params-only (optimizer state skipped via PLACEHOLDER — see
    ``CheckpointManager.restore_params``). The one recipe behind
    serving's ``--ckpt-dir``/``--draft-ckpt`` loads and LoRA's frozen
    base; raises FileNotFoundError for a missing/empty directory."""
    model_init, _, model_rules = model_fns(cfg)
    rules = rules if rules is not None else model_rules
    abstract = jax.eval_shape(
        lambda k: model_init(cfg, k), jax.random.PRNGKey(0))
    with CheckpointManager(directory) as mgr:
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint steps in {os.fspath(directory)}")
        return mgr.restore_params(
            param_shardings(abstract, mesh, rules), step), step


def resume_or_init(
    directory: str | os.PathLike,
    cfg,
    mesh,
    key: jax.Array,
    optimizer: optax.GradientTransformation | None = None,
    rules=None,
    max_to_keep: int = 3,
) -> tuple[TrainState, optax.GradientTransformation, CheckpointManager]:
    """The crash-safe entry point: restore the latest step if one exists,
    else fresh-init — the workload analog of the schedulers' restore-from-
    etcd-on-boot (SURVEY.md §3.1)."""
    from tpu_docker_api.train.trainer import create_train_state, default_optimizer

    optimizer = optimizer or default_optimizer()
    mgr = CheckpointManager(directory, max_to_keep=max_to_keep)
    if mgr.latest_step() is not None:
        state = mgr.restore(cfg, mesh, optimizer, rules=rules)
        return state, optimizer, mgr
    state, optimizer = create_train_state(cfg, mesh, key, optimizer,
                                          rules=rules)
    return state, optimizer, mgr
