"""LoRA fine-tuning: frozen base weights + trainable low-rank adapters.

The reference has no training stack at all (SURVEY.md §0); this module
supplies the parameter-efficient fine-tuning path a fleet of provisioned
containers actually runs against a pretrained base. TPU-first shape:

- **Two forwards, one model.** ``forward="merged"`` computes
  ``W' = W + (alpha/rank) * A @ B`` per adapted projection and runs
  the ORDINARY model forward on the merged tree. The model code stays
  untouched (one source of truth for block math), the merge is a tiny
  batched einsum per adapted weight, and autodiff through it yields
  exactly the LoRA gradients (d/dA, d/dB of the low-rank delta) with the
  base held constant — the base enters as a closed-over device constant,
  so no gradient buffers and no optimizer moments exist for it. That is
  the LoRA memory win: at adamw the moments are 2/3 of training HBM, and
  here they exist only for the (rank-sized) adapters. The transient
  merged copy XLA materializes per step is bf16 weight-sized and freed
  after use (remat applies to it like any activation).
  ``forward="attached"`` (QLoRA, round 4) skips even that transient:
  :func:`attach_lora` wraps each adapted projection in an
  ``ops.quant.LoraLinear`` leaf evaluating ``Wx + s·B(Ax)`` unmerged —
  with an int8 base (``quantize_base`` + the straight-through vjp on
  ``int8_linear``) an 8B fine-tune fits ONE 16 GB chip: ~8 GB frozen
  int8 base + rank-sized f32 adapters and moments, vs a 16 GB bf16
  merged copy that alone would overflow it.
- **Adapters shard like their base.** ``A (L, d_in, r)`` inherits the
  base weight's (layer, in) axes, ``B (L, r, d_out)`` its (layer, out)
  axis — derived mechanically from the base sharding rules, so tp/fsdp
  meshes run unchanged and the merged tree keeps the base's layout
  (``lora_shardings``).
- **Adapter-only checkpoints.** The ``TrainState`` under training holds
  ONLY the adapters; orbax saves are rank-sized (MBs, not GBs) and
  restore onto any mesh shape like every other checkpoint in
  train/checkpoint.py. Serving merges once at load
  (``python -m tpu_docker_api.serve --lora-ckpt ...``).

Targets match by LEAF NAME anywhere in the tree (default ``("wq", "wv")``
— the classic LoRA attention pair), so the same code adapts any family
whose projections are stacked 2-D/3-D arrays (llama, moe, encdec).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_docker_api.models import model_fns
from tpu_docker_api.models.common import trunc_normal_init
from tpu_docker_api.parallel.sharding import param_shardings, spec_for

DEFAULT_TARGETS = ("wq", "wv")


def _walk_matched(params: dict, targets, prefix: str = ""):
    """Yield (path, leaf) for every matched projection, in traversal
    order (deterministic — dict order is insertion order everywhere the
    param trees are built)."""
    for k, v in params.items():
        path = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            yield from _walk_matched(v, targets, path)
        elif k in targets and len(getattr(v, "shape", ())) >= 2:
            yield path, v


def lora_init(params: dict, rank: int, key: jax.Array,
              targets=DEFAULT_TARGETS, dtype=jnp.float32) -> dict:
    """Adapter pytree mirroring the matched projections of ``params``:
    each matched ``(..., d_in, d_out)`` weight gets
    ``{"a": (..., d_in, rank), "b": (..., rank, d_out)}`` with A
    fan-in-scaled normal and B zero (so the merged model starts EXACTLY
    at the base). ``params`` may be abstract (eval_shape) — only
    shapes are read. Adapters default to f32: they are tiny, and Adam
    updates accumulate without bf16 rounding."""
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    # _walk_matched is the ONE match predicate; build() keys off the
    # resulting path→index map (index also seeds each pair's RNG fold)
    index = {p: i for i, (p, _) in enumerate(_walk_matched(params, targets))}
    if not index:
        raise ValueError(f"no parameters matched targets {targets!r}")

    def build(subtree: dict, prefix: str = "") -> dict:
        out = {}
        for k, v in subtree.items():
            path = f"{prefix}/{k}" if prefix else k
            if isinstance(v, dict):
                sub = build(v, path)
                if sub:
                    out[k] = sub
            elif path in index:
                *lead, d_in, d_out = v.shape
                out[k] = {
                    "a": trunc_normal_init(
                        jax.random.fold_in(key, index[path]),
                        (*lead, d_in, rank), d_in, dtype),
                    "b": jnp.zeros((*lead, rank, d_out), dtype),
                }
        return out

    return build(params)


def merge_lora(params: dict, adapters: dict, alpha: float = 16.0) -> dict:
    """Base tree with ``W + (alpha/rank) * A @ B`` at every adapted leaf
    (rank read off A). The delta computes in the adapter dtype (f32) and
    casts to the base dtype at the add — bf16 bases keep their storage
    dtype so the merged tree serves/trains exactly like the base."""

    def walk(p: dict, a: dict) -> dict:
        out = {}
        for k, v in p.items():
            if k in a and isinstance(a[k], dict) and "a" in a[k] \
                    and not isinstance(v, dict):
                if not hasattr(v, "astype"):
                    raise ValueError(
                        f"cannot merge adapters into a {type(v).__name__}"
                        f" base at {k!r} — int8 bases need the unmerged "
                        f"forward (attach_lora / forward='attached')")
                pa, pb = a[k]["a"], a[k]["b"]
                scale = alpha / pa.shape[-1]
                delta = scale * jnp.matmul(pa, pb)
                out[k] = (v.astype(delta.dtype) + delta).astype(v.dtype)
            elif isinstance(v, dict):
                out[k] = walk(v, a.get(k, {}))
            else:
                out[k] = v
        return out

    return walk(params, adapters)


def attach_lora(params: dict, adapters: dict, alpha: float = 16.0) -> dict:
    """Base tree with :class:`~tpu_docker_api.ops.quant.LoraLinear`
    leaves at every adapted projection — the UNMERGED (QLoRA) forward:
    ``y = linear(x, W) + (alpha/r)·(x@A)@B`` evaluated per projection,
    so the merged weight tree never materializes. With an int8-quantized
    base (``quantize_base``) this is what makes llama3-8b fine-tuning a
    one-chip reality: base ~8 GB int8 + rank-sized adapters/moments,
    instead of a 16 GB bf16 merged copy that alone overflows a v5e.
    Gradients flow to A/B through ``ops.quant.linear``'s dispatch
    (int8 bases use the straight-through vjp); the base stays frozen."""
    from tpu_docker_api.ops.quant import LoraLinear

    def walk(p: dict, a: dict) -> dict:
        out = {}
        for k, v in p.items():
            if k in a and isinstance(a[k], dict) and "a" in a[k] \
                    and not isinstance(v, dict):
                pa = a[k]["a"]
                out[k] = LoraLinear(v, pa, a[k]["b"],
                                    alpha / pa.shape[-1])
            elif isinstance(v, dict):
                out[k] = walk(v, a.get(k, {}))
            else:
                out[k] = v
        return out

    return walk(params, adapters)


def quantize_base(base_params: dict) -> dict:
    """Int8-quantize a llama-family frozen base for QLoRA training —
    the serving quantizer reused verbatim (infer/quantize.py), so the
    trained-adapter → ``serve --quantize --lora-forward attached``
    round trip sees EXACTLY the base numerics it was trained against."""
    from tpu_docker_api.infer.quantize import quantize_llama_params

    return quantize_llama_params(base_params)


def lora_specs(adapters: dict, rules=None, prefix: str = ""):
    """PartitionSpecs for an adapter tree, derived from the BASE weight's
    rule: A keeps the base's leading+input axes (rank dim unsharded), B
    keeps leading+output axes."""
    out = {}
    for k, v in adapters.items():
        path = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict) and "a" in v and not isinstance(v["a"], dict):
            base = spec_for(path, rules)
            # pad the base spec to the weight's rank with leading Nones
            # (spec_for may return a short spec for fallback rules)
            nd = len(v["a"].shape)
            spec = (None,) * (nd - len(base)) + tuple(base)
            out[k] = {"a": P(*spec[:-1], None),
                      "b": P(*spec[:-2], None, spec[-1])}
        elif isinstance(v, dict):
            out[k] = lora_specs(v, rules, path)
    return out


def lora_shardings(adapters: dict, mesh: Mesh, rules=None):
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        lora_specs(adapters, rules),
        is_leaf=lambda x: isinstance(x, P),
    )


def init_base_params(cfg, mesh: Mesh, key: jax.Array, rules=None) -> dict:
    """Base params initialized directly into their shards — the
    params-only half of trainer.create_train_state (no optimizer state:
    the base is frozen under LoRA)."""
    model_init, _, model_rules = model_fns(cfg)
    rules = rules if rules is not None else model_rules
    abstract = jax.eval_shape(lambda k: model_init(cfg, k), key)
    shardings = param_shardings(abstract, mesh, rules)
    with mesh:
        return jax.jit(lambda k: model_init(cfg, k),
                       out_shardings=shardings)(key)


def create_lora_state(cfg, mesh: Mesh, key: jax.Array, rank: int,
                      targets=DEFAULT_TARGETS, optimizer=None, rules=None):
    """(TrainState over ADAPTERS, optimizer) — the trainable half. The
    frozen base comes separately (``init_base_params`` or a restored
    checkpoint)."""
    from tpu_docker_api.train.trainer import (
        TrainState, _opt_shardings, default_optimizer)

    optimizer = optimizer or default_optimizer()
    model_init, _, model_rules = model_fns(cfg)
    rules = rules if rules is not None else model_rules
    abstract_base = jax.eval_shape(lambda k: model_init(cfg, k), key)
    abstract = jax.eval_shape(
        lambda k: lora_init(abstract_base, rank, k, targets), key)
    a_sh = lora_shardings(abstract, mesh, rules)
    with mesh:
        adapters = jax.jit(
            lambda k: lora_init(abstract_base, rank, k, targets),
            out_shardings=a_sh)(key)
        opt_state = jax.jit(
            optimizer.init,
            out_shardings=_opt_shardings(optimizer, abstract, mesh, rules,
                                         param_sh=a_sh),
        )(adapters)
    return TrainState(step=jnp.zeros((), jnp.int32), params=adapters,
                      opt_state=opt_state), optimizer


def make_lora_train_step(cfg, mesh: Mesh, optimizer, base_params: dict,
                         alpha: float = 16.0, forward: str = "merged"):
    """jitted (state, batch) → (state, metrics) where ``state.params``
    are the adapters. ``forward="merged"`` merges per step and runs the
    family's ordinary loss (transient weight-sized copy, exact classic
    LoRA); ``"attached"`` runs the unmerged QLoRA forward via
    :func:`attach_lora` — required when the merged bf16 tree wouldn't
    fit (8B on one chip) and the only choice that is EXACT over an
    int8 base (merging onto int8 would quantize the delta away).
    ``base_params`` ride as non-donated jit operands — never
    differentiated, never copied into the program (const_args)."""
    from tpu_docker_api.train.trainer import make_train_step

    if forward not in ("merged", "attached"):
        raise ValueError(f"forward must be merged|attached, got {forward!r}")
    _, model_loss, _ = model_fns(cfg)
    combine = merge_lora if forward == "merged" else attach_lora

    def loss_fn(adapters, batch, base):
        # base rides as a jit OPERAND via const_args — closing over an
        # 8B int8 tree captured 8.56 GB of constants into the lowering
        # and stalled compilation (r4 hardware lesson)
        return model_loss(combine(base, adapters, alpha), batch,
                          cfg, mesh)

    return make_train_step(cfg, mesh, optimizer, loss_fn=loss_fn,
                           const_args=(base_params,))


def lora_abstract_state(cfg, rank: int, targets, mesh: Mesh,
                        optimizer, rules=None):
    """Abstract TrainState (ShapeDtypeStruct + shardings) for restoring
    adapter-only checkpoints onto ``mesh``."""
    import numpy as np

    from tpu_docker_api.train.trainer import TrainState, _opt_shardings

    model_init, _, model_rules = model_fns(cfg)
    rules = rules if rules is not None else model_rules
    key = jax.random.PRNGKey(0)
    abstract_base = jax.eval_shape(lambda k: model_init(cfg, k), key)
    abstract = jax.eval_shape(
        lambda k: lora_init(abstract_base, rank, k, targets), key)
    a_sh = lora_shardings(abstract, mesh, rules)
    abstract_opt = jax.eval_shape(optimizer.init, abstract)
    o_sh = _opt_shardings(optimizer, abstract, mesh, rules, param_sh=a_sh,
                          abstract_opt=abstract_opt)

    def as_abstract(tree, shardings):
        return jax.tree_util.tree_map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            tree, shardings)

    return TrainState(
        step=jax.ShapeDtypeStruct((), np.int32,
                                  sharding=NamedSharding(mesh, P())),
        params=as_abstract(abstract, a_sh),
        opt_state=as_abstract(abstract_opt, o_sh),
    )


def restore_adapters(directory, cfg, mesh: Mesh, rank: int,
                     targets=DEFAULT_TARGETS, rules=None) -> dict:
    """Adapter params from an adapter-only checkpoint — metadata-driven
    (works regardless of the optimizer that trained them; raises
    FileNotFoundError for a missing/empty directory)."""
    from tpu_docker_api.train.checkpoint import CheckpointManager

    model_init, _, model_rules = model_fns(cfg)
    rules = rules if rules is not None else model_rules
    key = jax.random.PRNGKey(0)
    abstract_base = jax.eval_shape(lambda k: model_init(cfg, k), key)
    abstract = jax.eval_shape(
        lambda k: lora_init(abstract_base, rank, k, targets), key)
    with CheckpointManager(directory) as mgr:
        return mgr.restore_params(lora_shardings(abstract, mesh, rules))


def restore_base_params(directory, cfg, mesh: Mesh, rules=None) -> dict:
    """Frozen-base params from a FULL training checkpoint — params-only
    and optimizer-agnostic (a base pretrained with adamw-int8 loads
    fine); raises FileNotFoundError if the directory holds no steps (an
    explicit base flag must never silently fall back to random init)."""
    from tpu_docker_api.train.checkpoint import restore_model_params

    params, _ = restore_model_params(directory, cfg, mesh, rules)
    return params


def lora_resume_or_init(directory, cfg, mesh: Mesh, key: jax.Array,
                        rank: int, targets=DEFAULT_TARGETS,
                        optimizer=None, rules=None, max_to_keep: int = 3):
    """Adapter-state analog of train.checkpoint.resume_or_init: restore
    the latest adapter checkpoint if one exists, else fresh-init."""
    from tpu_docker_api.train.checkpoint import CheckpointManager
    from tpu_docker_api.train.trainer import default_optimizer

    optimizer = optimizer or default_optimizer()
    mgr = CheckpointManager(directory, max_to_keep=max_to_keep)
    if mgr.latest_step() is not None:
        target = lora_abstract_state(cfg, rank, targets, mesh, optimizer,
                                     rules)
        state = mgr.restore_with_target(target)
        return state, optimizer, mgr
    state, optimizer = create_lora_state(cfg, mesh, key, rank,
                                         targets=targets,
                                         optimizer=optimizer, rules=rules)
    return state, optimizer, mgr
