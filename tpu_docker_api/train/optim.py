"""AdamW with 8-bit quantized moments (blockwise, TPU-friendly).

The bench-profiled adamw update is pure HBM traffic (~21 GB/step at
llama3-1b shapes: read params+mu+nu+grads, write params+mu+nu). Storing both
moments in int8 with per-block f32 scales halves the moment bytes — ~3 GB
less traffic per step and ~3 GB less resident HBM on a 16 GB chip.

Scheme (8-bit-Adam style, adapted to XLA/TPU):

- quantization blocks of ``block`` elements run along each leaf's LAST dim
  (falling back to the largest divisor), so the int8 moment keeps the
  PARAM'S SHAPE and carries the param's sharding spec unchanged;
- ``mu`` (signed): linear, scale = blockmax(|mu|)/127;
- ``nu`` (non-negative, huge dynamic range): linear in the **sqrt domain**
  — storing q ≈ sqrt(nu)/scale compresses nu's dynamic range enough for
  8 bits per block (nu's relative error ≈ 2× sqrt(nu)'s);
- scales are f32 with shape ``(lane_segments, blocks_per_segment, rows)``
  — rows on the LANE dim so buffers and tiles are dense (a trailing
  small dim lane-pads up to 128x; the first attempt cost 2MB of VMEM per
  scale tile and OOM'd the kernel), segments on the leading (untiled)
  dim, and blocks-per-segment on sublanes where the tile always spans
  the full dim (Mosaic's tiling rule: divisible by 8 OR equal to the
  array dim).

On TPU the update runs as a **Pallas kernel**. Two failure modes shaped it:

1. Left to XLA, the blockmax reductions inside requantization break its
   elementwise fusion and the f32 dequantized moments (6 GB each at
   llama3-1b shapes) materialize in HBM — measured 1.8x SLOWER than bf16
   adamw. The kernel keeps the f32 moments in VMEM tiles only.
2. Kernel I/O must use each leaf's NATIVE trailing dim: a
   ``(…, L) → (n_blocks, block)`` view is NOT a bitcast under TPU tiled
   layouts (lane-width changes re-tile memory) and cost ~46 ms/step of
   pure reshape copies. The kernel therefore takes ``(rows, L)`` blocks —
   merging leading dims IS a bitcast — and walks the quantization
   segments internally.

A pure-jax path remains for CPU/tests (bit-identical op ordering).

No reference analog (the reference has no training stack, SURVEY.md §0);
this exists for the workload layer of BASELINE.json's north star.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl


def _block_of(last_dim: int, block: int) -> int:
    b = min(block, last_dim)
    while last_dim % b:
        b -= 1
    return b


def _layout_of(last_dim: int, block: int) -> tuple[int, int]:
    """(b, lb): quantization block width and kernel lane-segment width —
    lb is the largest multiple of b dividing last_dim with lb ≤ 1536
    (bounds the kernel's f32 working tiles to ~0.75MB at t=128)."""
    b = _block_of(last_dim, block)
    lb = b
    for mult in range(last_dim // b, 0, -1):
        if last_dim % (b * mult) == 0 and b * mult <= 1536:
            lb = b * mult
            break
    return b, lb


def _quant_signed(x: jnp.ndarray, block: int):
    """x (any shape) → (int8 same shape, f32 scales (segs, bpseg, rows))."""
    b, lb = _layout_of(x.shape[-1], block)
    rows = x.size // x.shape[-1] if x.ndim > 1 else 1
    xb = x.reshape(rows, -1, b)
    s = jnp.max(jnp.abs(xb), axis=-1) / 127.0 + 1e-30   # (rows, bpr)
    q = jnp.round(xb * (1.0 / s)[..., None]).astype(jnp.int8).reshape(x.shape)
    segs, bpseg = x.shape[-1] // lb, lb // b
    return q, s.reshape(rows, segs, bpseg).transpose(1, 2, 0)


def _dequant_signed(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    segs, bpseg, rows = scale.shape
    qb = q.reshape(rows, segs * bpseg, -1)
    s = scale.transpose(2, 0, 1).reshape(rows, segs * bpseg)
    return (qb.astype(jnp.float32) * s[..., None]).reshape(q.shape)


def _quant_sqrt(x: jnp.ndarray, block: int):
    """Non-negative x stored as int8 in the sqrt domain."""
    return _quant_signed(jnp.sqrt(x), block)


def _dequant_sqrt(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    r = _dequant_signed(q, scale)
    return r * r


def _adam8_kernel(
    bc_ref, g_ref, mq_ref, ms_ref, vq_ref, vs_ref,
    upd_ref, mqo_ref, mso_ref, vqo_ref, vso_ref, *, b1, b2, eps, b,
    p_ref=None,
):
    """One (t, Lb) tile in the leaf's native trailing dim; quantization
    segments of width ``b`` are walked with a python-unrolled lane-slice
    loop (static, Lb//b steps). Dequant → moment update → bias-corrected
    Adam step → requant, all in VMEM — the f32 moments never exist in HBM.

    Transcendentals are the VPU cost: ONE divide + ONE sqrt per element on
    the main path; all other divisions are per-segment reciprocals."""
    g = g_ref[...].astype(jnp.float32)
    nseg = g.shape[-1] // b

    def seg(x, k):
        return x[:, k * b:(k + 1) * b]

    m_segs, sv_segs = [], []
    for k in range(nseg):
        ms_k = ms_ref[0, k:k + 1, :].T                   # (t, 1)
        vs_k = vs_ref[0, k:k + 1, :].T
        gk = seg(g, k)
        mk = b1 * (seg(mq_ref[...], k).astype(jnp.float32) * ms_k) \
            + (1.0 - b1) * gk
        rk = seg(vq_ref[...], k).astype(jnp.float32) * vs_k
        vk = b2 * rk * rk + (1.0 - b2) * gk * gk
        m_segs.append(mk)
        sv_segs.append(jnp.sqrt(vk))

    m = jnp.concatenate(m_segs, axis=-1) if nseg > 1 else m_segs[0]
    sv = jnp.concatenate(sv_segs, axis=-1) if nseg > 1 else sv_segs[0]
    ibc1 = bc_ref[0, 2]                                  # 1/bc1
    isbc2 = bc_ref[0, 3]                                 # 1/sqrt(bc2)
    adam = (m * ibc1) / (sv * isbc2 + eps)
    if p_ref is None:
        upd_ref[...] = adam.astype(upd_ref.dtype)
    else:
        # weight decay + learning rate folded in: the final update is
        # -lr·(adam + wd·p), killing optax's separate decay/scale passes
        lr, wd = bc_ref[0, 4], bc_ref[0, 5]
        pt = p_ref[...].astype(jnp.float32)
        upd_ref[...] = (-lr * (adam + wd * pt)).astype(upd_ref.dtype)

    for k in range(nseg):
        mk, svk = m_segs[k], sv_segs[k]
        ms_new = jnp.max(jnp.abs(mk), axis=-1, keepdims=True) / 127.0 + 1e-30
        mso_ref[0, k:k + 1, :] = ms_new.T
        mqo_ref[:, k * b:(k + 1) * b] = jnp.round(
            mk * (1.0 / ms_new)).astype(jnp.int8)
        vs_new = jnp.max(svk, axis=-1, keepdims=True) / 127.0 + 1e-30
        vso_ref[0, k:k + 1, :] = vs_new.T
        vqo_ref[:, k * b:(k + 1) * b] = jnp.round(
            svk * (1.0 / vs_new)).astype(jnp.int8)


def _adam8_update_leaf(g, mq, ms, vq, vs, p=None, *, bc, b1, b2, eps,
                       block, interpret):
    """(upd, mq', ms', vq', vs') for one leaf via the Pallas kernel. q
    arrays keep the leaf's shape; the kernel sees (rows, L) views (leading
    dims merged — a true bitcast) and (segs, bpseg, rows) scales. Grid is
    (row tiles, lane segments)."""
    last = g.shape[-1]
    b, lb = _layout_of(last, block)
    rows = g.size // last
    g2 = g.reshape(rows, last)
    mq2, vq2 = mq.reshape(rows, last), vq.reshape(rows, last)
    # t=128: the scale tile's lane dim must be 128-divisible or equal to
    # the whole array dim (small leaves take t=rows)
    t = 128 if rows % 128 == 0 else rows
    bprl = lb // b
    segs = last // lb
    grid = (rows // t, segs)
    data = lambda i, j: (i, j)
    scale = lambda i, j: (j, 0, i)
    all_ = lambda i, j: (0, 0)
    operands = [bc, g2, mq2, ms, vq2, vs]
    in_specs = [
        pl.BlockSpec((1, 6), all_),         # bias corrections + lr/wd
        pl.BlockSpec((t, lb), data),        # g
        pl.BlockSpec((t, lb), data),        # mq
        pl.BlockSpec((1, bprl, t), scale),  # ms
        pl.BlockSpec((t, lb), data),        # vq
        pl.BlockSpec((1, bprl, t), scale),  # vs
    ]
    kernel = functools.partial(_adam8_kernel, b1=b1, b2=b2, eps=eps, b=b)
    if p is not None:
        operands.append(p.reshape(rows, last))
        in_specs.append(pl.BlockSpec((t, lb), data))
        kernel = functools.partial(
            _kernel_with_params, kernel=functools.partial(
                _adam8_kernel, b1=b1, b2=b2, eps=eps, b=b))
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((t, lb), data),
            pl.BlockSpec((t, lb), data),
            pl.BlockSpec((1, bprl, t), scale),
            pl.BlockSpec((t, lb), data),
            pl.BlockSpec((1, bprl, t), scale),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((rows, last), g.dtype),     # upd
            jax.ShapeDtypeStruct((rows, last), jnp.int8),    # mq'
            jax.ShapeDtypeStruct((segs, bprl, rows), jnp.float32),
            jax.ShapeDtypeStruct((rows, last), jnp.int8),    # vq'
            jax.ShapeDtypeStruct((segs, bprl, rows), jnp.float32),
        ),
        interpret=interpret,
    )(*operands)
    upd, mq3, ms3, vq3, vs3 = out
    return (upd.reshape(g.shape), mq3.reshape(mq.shape), ms3,
            vq3.reshape(vq.shape), vs3)


def _kernel_with_params(bc_ref, g_ref, mq_ref, ms_ref, vq_ref, vs_ref,
                        p_ref, *out_refs, kernel):
    """Adapter: pallas passes the extra params operand positionally before
    the outputs; re-route it to the kernel's p_ref keyword."""
    kernel(bc_ref, g_ref, mq_ref, ms_ref, vq_ref, vs_ref, *out_refs,
           p_ref=p_ref)


class ScaleByAdamInt8State(NamedTuple):
    count: jnp.ndarray
    mu_q: optax.Updates
    mu_scale: optax.Updates
    nu_q: optax.Updates
    nu_scale: optax.Updates


def _resolve_impl(impl: str) -> str:
    """"auto" → pallas only on a single-device TPU: pallas_call has no GSPMD
    partitioning rule, so on a multi-device mesh XLA would replicate the int8
    moment buffers around the custom call; the xla path shards leaf-wise for
    free under GSPMD."""
    if impl != "auto":
        return impl
    return ("pallas" if jax.default_backend() == "tpu"
            and jax.device_count() == 1 else "xla")


def scale_by_adam_int8(
    b1: float = 0.9, b2: float = 0.95, eps: float = 1e-8, block: int = 256,
    impl: str = "auto", fused_wd_lr: tuple[float, float] | None = None,
) -> optax.GradientTransformation:
    """``impl``: "auto" (pallas on single-device TPU, xla elsewhere),
    "pallas", "pallas_interpret" (CPU test coverage of the kernel), or
    "xla". The pallas kernel carries no GSPMD partitioning rule, so under a
    multi-device mesh "auto" selects the xla path (which GSPMD shards
    leaf-wise for free); forcing ``impl="pallas"`` on a sharded mesh would
    make XLA replicate the moment buffers around the custom call, negating
    the memory win.
    ``fused_wd_lr=(weight_decay, lr)`` folds decoupled weight decay and the
    learning rate into the update (the transform then emits the FINAL
    -lr·(adam + wd·p) step and requires ``params`` at update time)."""
    def init_fn(params):
        def zq(p):
            return jnp.zeros(p.shape, jnp.int8)

        def zs(p):
            b, lb = _layout_of(p.shape[-1], block)
            rows = p.size // p.shape[-1] if p.ndim > 1 else 1
            return jnp.zeros(
                (p.shape[-1] // lb, lb // b, rows), jnp.float32)

        return ScaleByAdamInt8State(
            count=jnp.zeros((), jnp.int32),
            mu_q=jax.tree_util.tree_map(zq, params),
            mu_scale=jax.tree_util.tree_map(zs, params),
            nu_q=jax.tree_util.tree_map(zq, params),
            nu_scale=jax.tree_util.tree_map(zs, params),
        )

    def update_fn(updates, state, params=None):
        if fused_wd_lr is not None and params is None:
            raise ValueError("fused_wd_lr requires params at update time")
        count = state.count + 1
        cf = count.astype(jnp.float32)
        bc1 = 1.0 - b1 ** cf
        bc2 = 1.0 - b2 ** cf
        wd, lr = fused_wd_lr if fused_wd_lr is not None else (0.0, 0.0)
        mode = _resolve_impl(impl)

        def one_xla(g, mq, ms, vq, vs, p=None):
            g = g.astype(jnp.float32)
            m = b1 * _dequant_signed(mq, ms) + (1.0 - b1) * g
            v = b2 * _dequant_sqrt(vq, vs) + (1.0 - b2) * g * g
            # same op ordering as the Pallas kernel (bit-identical results)
            upd = (m * (1.0 / bc1)) / (jnp.sqrt(v) * jax.lax.rsqrt(bc2) + eps)
            if p is not None:
                upd = -lr * (upd + wd * p.astype(jnp.float32))
            mq2, ms2 = _quant_signed(m, block)
            vq2, vs2 = _quant_sqrt(v, block)
            return upd, mq2, ms2, vq2, vs2

        if mode == "xla":
            one = one_xla
        else:
            bc = jnp.stack([
                bc1, bc2, 1.0 / bc1, jax.lax.rsqrt(bc2),
                jnp.float32(lr), jnp.float32(wd)]).reshape(1, 6)
            one = functools.partial(
                _adam8_update_leaf, bc=bc, b1=b1, b2=b2, eps=eps,
                block=block, interpret=(mode == "pallas_interpret"))

        trees = [updates, state.mu_q, state.mu_scale,
                 state.nu_q, state.nu_scale]
        if fused_wd_lr is not None:
            trees.append(params)
        flat = jax.tree_util.tree_map(
            one, *trees,
            is_leaf=lambda x: isinstance(x, jnp.ndarray),
        )
        # unzip the 5-tuples back into parallel trees
        def pick(i):
            return jax.tree_util.tree_map(
                lambda t: t[i], flat, is_leaf=lambda t: isinstance(t, tuple))

        new_updates = jax.tree_util.tree_map(
            lambda u, g: u.astype(g.dtype), pick(0), updates)
        return new_updates, ScaleByAdamInt8State(
            count=count, mu_q=pick(1), mu_scale=pick(2),
            nu_q=pick(3), nu_scale=pick(4),
        )

    return optax.GradientTransformation(init_fn, update_fn)


def adamw_int8(
    lr: float = 3e-4,
    b1: float = 0.9,
    b2: float = 0.95,
    eps: float = 1e-8,
    weight_decay: float = 0.1,
    clip_norm: float = 1.0,
    block: int = 256,
    impl: str = "auto",
) -> optax.GradientTransformation:
    """Drop-in for ``trainer.default_optimizer`` with int8 moments. Weight
    decay and lr are folded into the update kernel (one fused pass instead
    of optax's separate decay and scale passes over the full update)."""
    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        scale_by_adam_int8(b1, b2, eps, block, impl,
                           fused_wd_lr=(weight_decay, lr)),
    )
