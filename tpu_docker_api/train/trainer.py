"""Sharded training step.

GSPMD recipe (scaling-book style): build the mesh, annotate param/optimizer
shardings from the rules, jit ONE train step with donated state, and let XLA
insert the ICI collectives (reduce-scatter/all-gather for fsdp, all-reduce for
dp, point-to-point for tp). No per-rank code, no NCCL-style plumbing — this is
the TPU-native replacement for the torch DDP/FSDP wrappers the reference's
GPU jobs would use.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_docker_api.models import model_fns
from tpu_docker_api.parallel.sharding import param_shardings


@dataclasses.dataclass
class TrainState:
    step: jnp.ndarray
    params: dict
    opt_state: Any


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.step, s.params, s.opt_state), None),
    lambda _, kids: TrainState(*kids),
)


def default_optimizer(
    lr: float = 3e-4, weight_decay: float = 0.1, clip_norm: float = 1.0
) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


def create_train_state(
    cfg,
    mesh: Mesh,
    key: jax.Array,
    optimizer: optax.GradientTransformation | None = None,
    rules=None,
) -> tuple[TrainState, optax.GradientTransformation]:
    """Init params DIRECTLY into their shards: jit the initializer with
    sharded out_shardings so no host ever materializes the full model.
    ``cfg`` may be any registered model config (Llama, MoE, ...); ``rules``
    overrides the model's sharding rules (e.g. parallel.pipeline's pp-aware
    variant)."""
    optimizer = optimizer or default_optimizer()
    model_init, _, model_rules = model_fns(cfg)
    rules = rules if rules is not None else model_rules
    abstract = jax.eval_shape(lambda k: model_init(cfg, k), key)
    p_shardings = param_shardings(abstract, mesh, rules)

    init_fn = jax.jit(
        lambda k: model_init(cfg, k), out_shardings=p_shardings
    )
    with mesh:
        params = init_fn(key)
        opt_state = jax.jit(
            optimizer.init,
            out_shardings=_opt_shardings(optimizer, abstract, mesh, rules),
        )(params)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=opt_state), optimizer


def _opt_shardings(optimizer, abstract_params, mesh: Mesh, rules=None,
                   param_sh=None, abstract_opt=None):
    """Optimizer-state shardings: any subtree with the params' structure
    (adam mu/nu) reuses the param shardings — leaf by leaf, only where the
    leaf's shape matches the param's (int8 moments keep the param shape and
    inherit its spec; blockwise quantization SCALES share the tree structure
    but not the shapes, and replicate — they are ~1.6% of the moment bytes).
    Everything else (step counts) replicates. Walks optax's NamedTuple
    states recursively. Callers that already traced
    ``param_sh``/``abstract_opt`` pass them in to skip the re-trace
    (train/checkpoint.py restores)."""
    if param_sh is None:
        param_sh = param_shardings(abstract_params, mesh, rules)
    param_def = jax.tree_util.tree_structure(abstract_params)
    replicated = NamedSharding(mesh, P())
    if abstract_opt is None:
        abstract_opt = jax.eval_shape(optimizer.init, abstract_params)

    def assign(node):
        if jax.tree_util.tree_structure(node) == param_def:
            return jax.tree_util.tree_map(
                lambda sh, pl, ol: sh if ol.shape == pl.shape else replicated,
                param_sh, abstract_params, node,
            )
        if isinstance(node, tuple):
            rebuilt = (assign(x) for x in node)
            return type(node)(*rebuilt) if hasattr(node, "_fields") else tuple(rebuilt)
        return jax.tree_util.tree_map(lambda _: replicated, node)

    return assign(abstract_opt)


def make_train_step(
    cfg,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    loss_fn: Callable | None = None,
    grad_fn: Callable | None = None,
    const_args: tuple = (),
) -> Callable:
    """jitted (state, tokens) → (state, metrics); state buffers donated.
    ``cfg`` may be any registered model config (Llama, MoE, ...).
    ``grad_fn(params, tokens) -> (loss, grads)`` bypasses autodiff for
    schedules that hand-compute their backward (parallel.pipeline's 1F1B);
    mutually exclusive with ``loss_fn``. ``const_args``: extra pytrees
    appended to every loss_fn/grad_fn call AS JIT OPERANDS — large
    frozen trees (a QLoRA base) must ride here, not as closure
    captures, or jax lowers them as embedded constants (measured: the
    8B int8 base captured 8.56 GB into the lowering and stalled the
    compile; as operands the program is weight-free)."""
    if grad_fn is not None and loss_fn is not None:
        raise ValueError("pass loss_fn or grad_fn, not both")
    if grad_fn is None and loss_fn is None:
        _, model_loss, _ = model_fns(cfg)
        loss_fn = lambda params, tokens: model_loss(params, tokens, cfg, mesh)

    def _batch_sharding(x):
        # leading axis = batch rows (dp+fsdp), everything else replicated —
        # per leaf, so tuple batches (ViT's (images, labels)) work too
        return NamedSharding(
            mesh, P(("dp", "fsdp"), *([None] * (jnp.ndim(x) - 1))))

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, tokens: jnp.ndarray, *consts):
        if grad_fn is not None:
            loss, grads = grad_fn(state.params, tokens, *consts)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens,
                                                      *consts)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            "step": state.step + 1,
        }
        return TrainState(state.step + 1, new_params, new_opt), metrics

    def step(state, tokens):
        # contract: ``tokens`` is this process's rows of the global batch
        # (== the whole batch in single-process runs). Multi-process runs
        # must assemble the global array from per-process shards — a plain
        # device_put would reinterpret the local rows as the global batch.
        if jax.process_count() > 1:
            tokens = jax.tree_util.tree_map(
                lambda x: jax.make_array_from_process_local_data(
                    _batch_sharding(x), x), tokens)
        else:
            tokens = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, _batch_sharding(x)), tokens)
        with mesh:
            return train_step(state, tokens, *const_args)

    return step


def synthetic_batch(key: jax.Array, batch: int, seq: int, vocab: int) -> jnp.ndarray:
    """Deterministic synthetic token stream (data layer for bench/tests)."""
    return jax.random.randint(key, (batch, seq + 1), 0, vocab, dtype=jnp.int32)
