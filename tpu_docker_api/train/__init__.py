"""Training loop layer: sharded train step, checkpointing, data."""

from tpu_docker_api.train.trainer import (  # noqa: F401
    TrainState,
    create_train_state,
    make_train_step,
)
