"""tpu-docker-api: a TPU-native container control plane.

A REST service that provisions TPU-chip-attached Docker containers with
versioned rolling updates, sized volumes, in-container exec, commit-to-image,
and resource views — backed by a pluggable KV state store (etcd-compatible),
an async work queue, and exclusive device + host-port schedulers.

Feature-parity target: `henrywangx/gpu-docker-api` (Go, surveyed in SURVEY.md).
The architecture here is TPU-first: the GPU-UUID bitmap scheduler becomes an
ICI-topology-aware chip/slice allocator (`tpu_docker_api.scheduler`), the
nvidia-container-runtime `DeviceRequests` become `/dev/accel*` mounts plus
libtpu + JAX distributed env injection (`tpu_docker_api.runtime.spec`), and the
NVML sidecar becomes a libtpu telemetry shim (`tpu_docker_api.telemetry`).

The compute path (`models/`, `ops/`, `parallel/`, `train/`) is the JAX/XLA
workload layer the control plane provisions: Llama-family transformers sharded
over a `jax.sharding.Mesh` with dp/fsdp/tp/sp axes, ring attention for long
context, and Pallas TPU kernels for the hot ops.
"""

__version__ = "0.1.0"
