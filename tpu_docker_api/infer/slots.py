"""Continuous-batching ("slot") serving engine.

Round 2 served generations one at a time: one compiled whole-generation
program per shape bucket, a global lock in front of the chip
(serve/__main__.py ``gen_lock``), so two clients halved each other's
throughput. This module multiplexes N request streams onto one chip the
way the reference multiplexes containers onto one host
(/root/reference/internal/service/container.go:463-535 — capability
analog; the reference itself has no serving).

TPU-first shape of the design:

- **One fixed-capacity KV cache of S slots** ``(layers, S, max_seq, kv,
  head_dim)`` allocated once; a request is admitted into a free slot and
  the slot is recycled when the request completes. Static shapes — XLA
  compiles exactly two kinds of program (per-bucket prefill, one decode
  chunk) and every dispatch reuses them.
- **Per-slot positions**: each slot sits at its own sequence length, so
  decode runs the per-row cached forward (models/llama.py ``_attention``
  per-row scatter write, ops/attention.py per-row causal mask). The
  whole batch decodes in lockstep regardless of where each slot is in
  its sequence.
- **K-step decode chunks**: the decode loop is a ``lax.scan`` over K
  steps per dispatch, amortizing host→device dispatch latency (tens of
  ms through the axon tunnel) over K tokens; admission happens between
  chunks. K trades admission latency against tail waste (a request
  finishing mid-chunk wastes the rest of the chunk for its slot).
- **Right-padded prefill into the slot**: a prompt is padded to a bucket
  length and prefilled batch=1 into a fresh (layers, 1, bucket) cache,
  then one dynamic_update_slice drops it into the big cache at the slot
  row. Garbage k/v at padded positions sits strictly at FUTURE positions
  of the slot, and the per-row causal mask never attends a position
  ``> pos``; decode overwrites position p before the first query that
  could see it. The first-token logit is read at ``actual_len - 1`` via
  the traced ``last_only`` index.
- **Exact sampling in one program**: greedy is ``argmax``; per-slot
  temperature sampling is Gumbel-argmax (``argmax(logits/T + G)`` is an
  exact categorical draw), so mixed greedy/sampled slots share one
  compiled chunk. top-k/top-p need a sort and stay on the legacy
  whole-generation path (serve/__main__.py routes them there).

Correctness contract (tests/test_slots.py): per-stream outputs are
token-exact vs an isolated greedy ``make_generate_fn`` decode of the
same prompt, for any admission order and slot reuse.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpu_docker_api.models import cached_forward_fn
from tpu_docker_api.infer.engine import init_kv_cache


def _default_buckets(max_seq: int) -> tuple[int, ...]:
    """Power-of-two prefill buckets from 32 up to max_seq (inclusive)."""
    out = []
    b = 32
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return tuple(out)


@dataclasses.dataclass
class Handle:
    """Per-request future. ``result()`` blocks until the request completes
    and returns {"tokens": [...], "length": n} (tokens truncated at eos,
    inclusive, like the legacy engine's lengths contract)."""

    _done: threading.Event = dataclasses.field(default_factory=threading.Event)
    _result: dict | None = None
    _error: Exception | None = None

    def result(self, timeout: float | None = None) -> dict:
        if not self._done.wait(timeout):
            raise TimeoutError("request not complete")
        if self._error is not None:
            raise self._error
        return self._result

    def done(self) -> bool:
        return self._done.is_set()

    def _complete(self, result: dict) -> None:
        self._result = result
        self._done.set()

    def _fail(self, err: Exception) -> None:
        self._error = err
        self._done.set()


@dataclasses.dataclass
class _Slot:
    handle: Handle
    tokens: list[int]          # emitted so far (starts with prefill token)
    max_new: int
    last_tok: int
    pos: int                   # next cache position to write
    temperature: float


class SlotEngine:
    """Slot-based continuous-batching engine for the decoder families
    (llama + moe via ``models.cached_forward_fn``).

    Single-accelerator by design: serving one chip is the unit the control
    plane provisions (one container = one slice); meshes serve via one
    process per chip. ``submit()`` is thread-safe; the decode loop runs on
    the caller's thread via :meth:`step` or on a background thread via
    :meth:`start`.
    """

    def __init__(
        self,
        cfg,
        params,
        *,
        slots: int = 8,
        max_seq: int | None = None,
        chunk: int = 8,
        buckets: tuple[int, ...] | None = None,
        eos_id: int | None = None,
        pad_id: int = 0,
        cache_dtype: Any = jnp.bfloat16,
        seed: int = 0,
    ):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.max_seq = max_seq or cfg.max_seq_len
        self.chunk = chunk
        self.buckets = tuple(sorted(buckets or _default_buckets(self.max_seq)))
        if self.buckets[-1] > self.max_seq:
            raise ValueError(
                f"largest bucket {self.buckets[-1]} exceeds cache capacity "
                f"{self.max_seq}")
        self.eos_id = eos_id
        self.pad_id = pad_id
        self._fwd = cached_forward_fn(cfg)
        cache = init_kv_cache(cfg, slots, self.max_seq, mesh=None,
                              dtype=cache_dtype)
        self._k, self._v = cache.k, cache.v
        self._key = jax.random.PRNGKey(seed)

        self._pending: queue.SimpleQueue = queue.SimpleQueue()
        self._table: dict[int, _Slot | None] = {i: None for i in range(slots)}
        self._lock = threading.Lock()      # guards _table mutation vs stats
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self._closed = False
        self._dead: Exception | None = None

        self._prefill_fns: dict[int, Any] = {}
        self._decode_fn = None
        # aggregate counters for /healthz-style introspection
        self.stats = {"completed": 0, "decode_chunks": 0, "prefills": 0,
                      "wasted_steps": 0, "emitted_tokens": 0}

    # ---- compiled programs -------------------------------------------------

    @staticmethod
    def _sample(logits, temp, key):
        """(S, vocab) f32 logits + per-slot temperature → (S,) int32.
        Gumbel-argmax is an exact categorical draw at temperature T;
        T == 0 rows take the plain argmax (token-exact greedy)."""
        g = jax.random.gumbel(key, logits.shape, logits.dtype)
        z = jnp.where(temp[:, None] > 0,
                      logits / jnp.maximum(temp, 1e-6)[:, None] + g,
                      logits)
        return jnp.argmax(z, axis=-1).astype(jnp.int32)

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        cfg, fwd = self.cfg, self._fwd
        cache_dtype = self._k.dtype

        def prefill(params, prompt, actual_len, slot, temp, key, k_all, v_all):
            shape = (cfg.n_layers, 1, bucket, cfg.n_kv_heads, cfg.head_dim)
            kc = jnp.zeros(shape, cache_dtype)
            vc = jnp.zeros(shape, cache_dtype)
            logits, kc, vc = fwd(params, prompt, cfg, kc, vc, jnp.int32(0),
                                 None, last_only=actual_len - 1)
            tok = self._sample(logits[:, -1], temp[None], key)
            zero = jnp.int32(0)
            k_all = lax.dynamic_update_slice(
                k_all, kc, (zero, slot, zero, zero, zero))
            v_all = lax.dynamic_update_slice(
                v_all, vc, (zero, slot, zero, zero, zero))
            return tok[0], k_all, v_all

        fn = jax.jit(prefill, donate_argnums=(6, 7))
        self._prefill_fns[bucket] = fn
        return fn

    def _decode(self):
        if self._decode_fn is not None:
            return self._decode_fn
        cfg, fwd, K = self.cfg, self._fwd, self.chunk

        def decode_chunk(params, tok, pos, temp, key, k_all, v_all):
            def body(carry, step_key):
                tok, pos, k_all, v_all = carry
                logits, k_all, v_all = fwd(
                    params, tok[:, None], cfg, k_all, v_all, pos, None)
                nxt = self._sample(logits[:, -1], temp, step_key)
                return (nxt, pos + 1, k_all, v_all), nxt

            keys = jax.random.split(key, K)
            (tok, pos, k_all, v_all), out = lax.scan(
                body, (tok, pos, k_all, v_all), keys)
            return out.T, k_all, v_all  # (S, K)

        self._decode_fn = jax.jit(decode_chunk, donate_argnums=(5, 6))
        return self._decode_fn

    def warmup(self, buckets: tuple[int, ...] | None = None) -> None:
        """Actually compile the decode chunk and the given (default: all)
        prefill buckets by running them on dummy data — ``jax.jit`` alone
        compiles nothing until the first call, and a mid-service compile
        on the engine thread stalls every active slot for its duration.
        Pass ``buckets=()`` to warm only the decode chunk (the program
        every request shares; per-bucket prefill compiles then amortize
        one stall per bucket size ever). Call BEFORE :meth:`start` — this
        runs dispatches on the caller's thread and scribbles garbage into
        the (empty) cache, which admission later overwrites."""
        if self._thread is not None:
            raise RuntimeError("warmup must run before start()")
        key = jax.random.PRNGKey(0)
        for b in (self.buckets if buckets is None else buckets):
            _, self._k, self._v = self._prefill_fn(b)(
                self.params, jnp.zeros((1, b), jnp.int32), jnp.int32(1),
                jnp.int32(0), jnp.float32(0.0), key, self._k, self._v)
        zero_i = jnp.zeros((self.slots,), jnp.int32)
        _, self._k, self._v = self._decode()(
            self.params, zero_i, zero_i,
            jnp.zeros((self.slots,), jnp.float32), key, self._k, self._v)

    # ---- request API -------------------------------------------------------

    def submit(self, prompt: list[int], max_new: int,
               temperature: float = 0.0) -> Handle:
        """Queue a request; returns a Handle resolving to
        {"tokens": [...], "length": n}. Raises ValueError for requests
        that can never fit (capacity is checked before queueing)."""
        handle = Handle()
        if self._closed:
            raise RuntimeError("engine is closed")
        if self._dead is not None:
            raise RuntimeError(f"engine failed: {self._dead!r}")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        n = len(prompt)
        if n < 1:
            raise ValueError("prompt must be non-empty")
        if n > self.buckets[-1]:
            raise ValueError(
                f"prompt ({n}) exceeds the largest prefill bucket "
                f"({self.buckets[-1]})")
        if n + max_new - 1 > self.max_seq:
            raise ValueError(
                f"prompt ({n}) + max_new ({max_new}) exceeds cache "
                f"capacity {self.max_seq}")
        self._pending.put((list(prompt), max_new, float(temperature), handle))
        self._wake.set()
        return handle

    # ---- engine loop -------------------------------------------------------

    def _admit(self) -> bool:
        """Move pending requests into free slots (one prefill dispatch
        each). Returns True if anything was admitted."""
        admitted = False
        free = [i for i, s in self._table.items() if s is None]
        while free:
            try:
                prompt, max_new, temp, handle = self._pending.get_nowait()
            except queue.Empty:
                break
            slot = free.pop()
            bucket = next(b for b in self.buckets if b >= len(prompt))
            padded = np.full((1, bucket), self.pad_id, np.int32)
            padded[0, :len(prompt)] = prompt
            self._key, sub = jax.random.split(self._key)
            tok, self._k, self._v = self._prefill_fn(bucket)(
                self.params, jnp.asarray(padded),
                jnp.int32(len(prompt)), jnp.int32(slot),
                jnp.float32(temp), sub, self._k, self._v)
            first = int(tok)
            self.stats["prefills"] += 1
            st = _Slot(handle=handle, tokens=[first], max_new=max_new,
                       last_tok=first, pos=len(prompt), temperature=temp)
            with self._lock:
                self._table[slot] = st
            self._finish_if_done(slot, st)  # max_new == 1 / instant eos
            admitted = True
        return admitted

    def _finish_if_done(self, slot: int, st: _Slot) -> bool:
        hit_eos = self.eos_id is not None and st.tokens and (
            st.tokens[-1] == self.eos_id)
        if hit_eos or len(st.tokens) >= st.max_new:
            st.handle._complete(
                {"tokens": st.tokens, "length": len(st.tokens)})
            with self._lock:
                self._table[slot] = None
                self.stats["completed"] += 1
                self.stats["emitted_tokens"] += len(st.tokens)
            return True
        return False

    def step(self) -> bool:
        """One engine iteration: admit pending requests, then (if any slot
        is active) run one K-step decode chunk and distribute its tokens.
        Returns True if any work was done. Tests drive this directly; the
        background thread loops it."""
        did = self._admit()
        active = {i: s for i, s in self._table.items() if s is not None}
        if not active:
            return did

        tok = np.full((self.slots,), self.pad_id, np.int32)
        pos = np.zeros((self.slots,), np.int32)
        temp = np.zeros((self.slots,), np.float32)
        for i, s in active.items():
            tok[i], pos[i], temp[i] = s.last_tok, s.pos, s.temperature
        self._key, sub = jax.random.split(self._key)
        out, self._k, self._v = self._decode()(
            self.params, jnp.asarray(tok), jnp.asarray(pos),
            jnp.asarray(temp), sub, self._k, self._v)
        out = np.asarray(out)  # (S, K)
        self.stats["decode_chunks"] += 1

        for i, s in active.items():
            s.pos += self.chunk
            s.last_tok = int(out[i, -1])
            for j in range(self.chunk):
                s.tokens.append(int(out[i, j]))
                if self._finish_if_done(i, s):
                    self.stats["wasted_steps"] += self.chunk - 1 - j
                    break
        return True

    def _loop(self) -> None:
        while not self._closed:
            try:
                if not self.step():
                    self._wake.clear()
                    self._wake.wait(timeout=0.05)
            except Exception as e:  # noqa: BLE001 — a dead engine thread
                # must not leave clients hanging on 10-minute timeouts:
                # fail every in-flight and queued handle, mark the engine
                # dead so submit() rejects fast, and surface the cause
                self._die(e)
                return

    def _die(self, err: Exception) -> None:
        self._dead = err
        with self._lock:
            for i, s in self._table.items():
                if s is not None:
                    s.handle._fail(RuntimeError(f"engine failed: {err!r}"))
                    self._table[i] = None
        while True:
            try:
                *_, handle = self._pending.get_nowait()
            except queue.Empty:
                break
            handle._fail(RuntimeError(f"engine failed: {err!r}"))

    @property
    def dead(self) -> str | None:
        """repr of the error that killed the engine loop, or None."""
        return repr(self._dead) if self._dead is not None else None

    def start(self) -> "SlotEngine":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="slot-engine")
            self._thread.start()
        return self

    def close(self) -> None:
        self._closed = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # fail anything still queued so callers don't hang
        while True:
            try:
                *_, handle = self._pending.get_nowait()
            except queue.Empty:
                break
            handle._fail(RuntimeError("engine closed"))
        for i, s in list(self._table.items()):
            if s is not None:
                s.handle._fail(RuntimeError("engine closed"))
                self._table[i] = None

    def __enter__(self) -> "SlotEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
