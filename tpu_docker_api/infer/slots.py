"""Continuous-batching ("slot") serving engine.

Round 2 served generations one at a time: one compiled whole-generation
program per shape bucket, a global lock in front of the chip
(serve/__main__.py ``gen_lock``), so two clients halved each other's
throughput. This module multiplexes N request streams onto one chip the
way the reference multiplexes containers onto one host
(/root/reference/internal/service/container.go:463-535 — capability
analog; the reference itself has no serving).

TPU-first shape of the design:

- **One fixed-capacity KV cache of S slots** ``(layers, S, max_seq, kv,
  head_dim)`` allocated once; a request is admitted into a free slot and
  the slot is recycled when the request completes. Static shapes — XLA
  compiles exactly two kinds of program (per-bucket prefill, one decode
  chunk) and every dispatch reuses them.
- **Per-slot positions**: each slot sits at its own sequence length, so
  decode runs the per-row cached forward (models/llama.py ``_attention``
  per-row scatter write, ops/attention.py per-row causal mask). The
  whole batch decodes in lockstep regardless of where each slot is in
  its sequence.
- **K-step decode chunks, chained on device**: the decode loop is a
  ``lax.scan`` over K steps per dispatch, and the chunk's inputs
  (current token, position, temperature per slot) live in DEVICE arrays
  that each chunk returns for the next — so successive chunks dispatch
  back-to-back with no host round-trip between them. The host reads
  chunk outputs at a pipeline lag of ``pipeline`` chunks: through the
  axon tunnel a device→host fetch costs ~100 ms of latency, and the
  lag hides it behind the next chunks' compute (the same reason the
  legacy engine's one-program-per-generation looked fast: one sync per
  request).
- **One jitted dispatch per engine action, zero eager ops**: measured on
  the axon tunnel, every EAGER device op — a ``jax.random.split``, a
  bare ``.at[].set`` — costs 100-200 ms of round-trip latency, while
  host→device transfers of small arrays are ~0.2 ms and jitted
  dispatches pipeline. So nothing here runs eagerly: RNG keys derive
  from a host int-counter seed INSIDE the programs, admission is one
  prefill dispatch that also updates the per-slot device state itself,
  and the decode chunk prepends its input token to the output so the
  prefill's first token needs no separate fetch.
- **Right-padded prefill into the slot**: a prompt is padded to a bucket
  length and prefilled batch=1 into a fresh (layers, 1, bucket) cache,
  then one dynamic_update_slice drops it into the big cache at the slot
  row. Garbage k/v at padded positions sits strictly at FUTURE positions
  of the slot, and the per-row causal mask never attends a position
  ``> pos``; decode overwrites position p before the first query that
  could see it. The first-token logit is read at ``actual_len - 1`` via
  the traced ``last_only`` index, and the sampled token stays on device
  until the slot's first chunk is processed (its output column 0) — an
  admission is pure dispatch, no sync.
- **Exact sampling in one program**: greedy is ``argmax``; per-slot
  temperature sampling is Gumbel-argmax (``argmax(logits/T + G)`` is an
  exact categorical draw), so mixed greedy/sampled slots share one
  compiled chunk. Per-slot top-k/top-p run with TRACED k and p in a
  second chunk variant that pays a per-step (S, vocab) sort — compiled
  and dispatched only while a filtered slot is active, so pure
  greedy/temperature traffic never pays for it.
- **Length-bucketed decode reads**: decode programs are compiled per
  geometric cache-prefix bucket (``kv_limit`` through the cached
  forward) and read only the positions any active slot can reach —
  writes still target the full buffer, and the host derives the bucket
  from dispatch counts so the pipeline lag never under-reads. At 16
  slots × 512 capacity this took 1,396 → 2,095 tok/s on v5e.
- **Prefix caching**: :meth:`register_prefix` prefills a shared prompt
  prefix (system prompt, few-shot header) ONCE into a device-resident
  (layers, pbucket, kv, head_dim) pair; admission auto-matches the
  longest registered strict prefix of each prompt and runs a
  suffix-only prefill — the prefix k/v are dropped into the slot row
  and the suffix forward starts at the traced absolute position
  ``plen`` (the per-row rope/mask machinery is position-based, so no
  model change). Prefill cost for an N-token prompt with a P-token
  cached prefix is O(N−P); prompts longer than the largest prefill
  bucket become servable when a prefix covers the overflow. Garbage at
  prefix-pad positions sits strictly at future positions of the slot —
  the same just-in-time-overwrite argument as bucket padding.
- **Production edges**: bounded admission queue (``max_pending`` →
  :class:`QueueFull`, HTTP 503), per-request ``eos_id``, token
  streaming (:meth:`Handle.stream`), graceful drain
  (``close(drain=...)``), dead-engine fast-fail.

Correctness contract (tests/test_slots.py): per-stream outputs are
token-exact vs an isolated greedy ``make_generate_fn`` decode of the
same prompt, for any admission order and slot reuse. (On TPU, bf16
matmul tilings differ between batch shapes, so argmax near-ties can
flip vs a batch-1 reference on near-uniform random-init logits — the
f32 CPU suite is the exactness proof; hardware runs report a match
rate.)

A slot that completes mid-chunk keeps decoding garbage until the host
processes that chunk (bounded by ``pipeline``+1 chunks); its writes land
in its own row and are either overwritten by the next admission's
prefill or dropped past capacity (``mode="drop"``), so stale state never
leaks into other requests.
"""

from __future__ import annotations

import collections
import dataclasses
import queue
import threading
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_docker_api.models import cached_forward_fn
from tpu_docker_api.infer.engine import init_kv_cache


def _default_buckets(max_seq: int) -> tuple[int, ...]:
    """Power-of-two prefill buckets from 32 up to max_seq (inclusive)."""
    out = []
    b = 32
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return tuple(out)


@dataclasses.dataclass
class Handle:
    """Per-request future. ``result()`` blocks until the request completes
    and returns {"tokens": [...], "length": n} (tokens truncated at eos,
    inclusive, like the legacy engine's lengths contract). Streaming
    requests (``submit(stream=True)``) additionally expose
    :meth:`stream` — an iterator of tokens as the engine resolves them
    (per processed chunk, so latency ≈ chunk × step time + pipeline
    lag); ``result()`` still returns the full payload afterwards."""

    _done: threading.Event = dataclasses.field(default_factory=threading.Event)
    _result: dict | None = None
    _error: Exception | None = None
    _stream: queue.SimpleQueue | None = None
    #: time.perf_counter() at completion — benchmarks read latency off
    #: the handle instead of polling (a poll quantizes to its cadence)
    completed_at: float | None = None
    #: perf_counter at submit / at the first host-resolved token — the
    #: engine derives per-request TTFT/ITL from these on completion
    #: (SLO export, VERDICT r4 next #5). First-token time is when the
    #: host PROCESSES the chunk — exactly when a streaming client sees
    #: the token, so it is the honest client-facing TTFT.
    submitted_at: float | None = None
    first_token_at: float | None = None

    def result(self, timeout: float | None = None) -> dict:
        if not self._done.wait(timeout):
            raise TimeoutError("request not complete")
        if self._error is not None:
            raise self._error
        return self._result

    def done(self) -> bool:
        return self._done.is_set()

    def stream(self, timeout: float | None = None):
        """Yield tokens as they resolve; raises the engine error (if any)
        at the end, and TimeoutError if ``timeout`` seconds pass without
        a new token (a wedged — not dead — engine must not block
        consumers forever). Only valid for ``submit(stream=True)``
        requests."""
        if self._stream is None:
            raise RuntimeError("not a streaming request")
        while True:
            try:
                item = self._stream.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    f"no token within {timeout}s") from None
            if item is None:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def _complete(self, result: dict) -> None:
        # _done BEFORE the stream sentinel: a consumer unblocking from
        # stream() may immediately call result(0)
        self._result = result
        self.completed_at = time.perf_counter()
        self._done.set()
        if self._stream is not None:
            self._stream.put(None)

    def _fail(self, err: Exception) -> None:
        self._error = err
        self._done.set()
        if self._stream is not None:
            self._stream.put(None)


@dataclasses.dataclass
class _Slot:
    handle: Handle
    tokens: list[int]          # emitted so far, host-resolved
    max_new: int
    pos: int                   # host mirror of the cache write position
    temperature: float
    eos_id: int | None = None  # per-request; host-side check only, so it
    #                            costs nothing in the compiled programs
    top_k: int = 0             # per-slot traced filters; any nonzero/<1
    top_p: float = 1.0         # active slot selects the filtered chunk
    fresh: bool = True         # no chunk processed yet: the first chunk's
    #                            column 0 is this slot's prefill token
    base_len: int = 0          # prompt length at admission (immutable —
    #                            pos mutates at processing lag)
    dispatched: int = 0        # chunks dispatched since admission; bounds
    #                            this slot's reachable cache position
    pending: list[int] | None = None  # chunked prefill: prompt tokens not
    #                            yet prefilled; the slot joins decode only
    #                            once this drains (None = fully prefilled)
    prefill_pos: int = 0       # next absolute segment write offset
    src_len: int = 0           # encdec: true source length (drives the
    #                            cross-K/V read bucket)
    preseed: int = 0           # tokens already in ``tokens`` at admission
    #                            (paged preemption restore: the re-prefill
    #                            prompt carries them, so reach/remaining
    #                            math must subtract them from max_new)

    def emit(self, t: int) -> None:
        self.tokens.append(t)
        if self.handle.first_token_at is None:
            self.handle.first_token_at = time.perf_counter()
        if self.handle._stream is not None:
            self.handle._stream.put(t)


class QueueFull(Exception):
    """Admission queue at capacity — callers should shed load (HTTP 503)
    rather than let latency grow unbounded."""


@dataclasses.dataclass(frozen=True, eq=False)
class _Prefix:
    """A registered prompt prefix with its device-resident KV pair.
    ``eq=False``: identity semantics — the jax arrays must never be
    compared elementwise by dict/dedup machinery."""

    pid: str
    tokens: tuple[int, ...]
    length: int                # actual token count
    bucket: int                # padded device length (static shape)
    k: Any                     # (layers, bucket, n_kv_heads, head_dim)
    v: Any
    nbytes: int = 0            # device bytes both arrays pin (HBM budget)


class SlotEngine:
    """Slot-based continuous-batching engine for the decoder families
    (llama + moe via ``models.cached_forward_fn``).

    Single accelerator by default; a tensor-parallel ``mesh`` (tp, and
    optionally fsdp for weight sharding — dp/sp must be 1, since the
    slot dim stays replicated and decode's seq is 1) serves models
    larger than one chip with the same continuous batching: the cache's
    kv-head dim shards over tp, every program runs under the mesh, and
    XLA inserts the collectives. ``submit()`` is thread-safe; the decode
    loop runs on the caller's thread via :meth:`step` or on a background
    thread via :meth:`start`.
    """

    def __init__(
        self,
        cfg,
        params,
        *,
        slots: int = 8,
        max_seq: int | None = None,
        chunk: int = 8,
        pipeline: int = 2,
        buckets: tuple[int, ...] | None = None,
        eos_id: int | None = None,
        pad_id: int = 0,
        cache_dtype: Any = jnp.bfloat16,
        seed: int = 0,
        max_pending: int = 0,
        mesh=None,
        max_prefixes: int = 8,
        max_prefix_bytes: int = 0,
        prefill_chunk: int = 0,
    ):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        if pipeline < 0:
            raise ValueError(f"pipeline must be >= 0, got {pipeline}")
        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.max_seq = max_seq or cfg.max_seq_len
        self.chunk = chunk
        self.pipeline = pipeline
        self.buckets = tuple(sorted(buckets or self._default_buckets()))
        self._check_buckets()
        self.eos_id = eos_id
        self.pad_id = pad_id
        #: admission-queue bound (0 = unbounded). Checked approximately —
        #: SimpleQueue.qsize() races under concurrent submitters, but the
        #: point is load shedding, not an exact ceiling.
        self.max_pending = max_pending
        #: > 0: prompts longer than this prefill in ``prefill_chunk``-token
        #: SEGMENTS, one per engine step, interleaved with decode chunks —
        #: a long admission can then stall active streams by at most one
        #: segment's compute instead of the whole prompt's. 0 = whole-
        #: prompt admission (the batched/prefix paths).
        if prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0, got {prefill_chunk}")
        self.prefill_chunk = prefill_chunk
        if mesh is not None and getattr(mesh, "empty", False):
            mesh = None
        if mesh is not None:
            bad = {ax: n for ax, n in mesh.shape.items()
                   if ax not in ("tp", "fsdp") and n > 1}
            if bad:
                raise ValueError(
                    f"slot engine meshes are tp/fsdp-only (slots stay "
                    f"replicated; decode seq is 1): got {bad}")
        self.mesh = mesh
        self._fwd = self._cached_forward()
        self._k, self._v = self._alloc_cache(cache_dtype)
        # RNG = a host counter folded into PRNGKey INSIDE the programs:
        # an eager jax.random.split costs a ~150 ms tunnel round-trip
        self._seed = seed
        self._dispatches = 0
        # device-resident per-slot decode inputs: each chunk consumes and
        # returns them, so chunks chain with no host round-trip (on a
        # mesh: replicated, so they compose with the sharded operands)
        def vec(fill, dtype):
            x = jnp.full((slots,), fill, dtype)
            if mesh is not None:
                x = jax.device_put(x, NamedSharding(mesh, P()))
            return x

        self._dtok = vec(0, jnp.int32)
        self._dpos = vec(0, jnp.int32)
        self._dtemp = vec(0.0, jnp.float32)
        self._dtopk = vec(0, jnp.int32)
        self._dtopp = vec(1.0, jnp.float32)

        self._pending: queue.SimpleQueue = queue.SimpleQueue()
        self._table: dict[int, _Slot | None] = {i: None for i in range(slots)}
        #: dispatched-but-unprocessed chunks: (slot snapshot, device out)
        self._outstanding: collections.deque = collections.deque()
        self._lock = threading.Lock()      # guards _table mutation vs stats
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self._closed = False
        self._draining = False
        self._drained = threading.Event()
        self._dead: Exception | None = None

        #: prefix registry: pid → _Prefix. ``_px_lock`` serializes whole
        #: register/unregister operations (device compute included);
        #: ``_lock`` guards the dict itself for the engine thread's reads
        self.max_prefixes = max_prefixes
        #: byte ceiling for device-resident prefix K/V (0 = unbounded).
        #: Each prefix pins 2 × layers × bucket × kv_heads × head_dim ×
        #: itemsize of HBM that the engine's cache sizing never accounted
        #: for — at 8B shapes a large bucket is tens of MB per prefix, so
        #: mid-service registration could OOM an engine sized to fit
        #: (ADVICE r3). The running total rides in stats["prefix_bytes"].
        self.max_prefix_bytes = max_prefix_bytes
        self._prefixes: dict[str, _Prefix] = {}
        self._px_lock = threading.Lock()
        self._px_seq = 0
        self._prefix_fns: dict[int, Any] = {}
        self._px_prefill_fns: dict[tuple, Any] = {}
        self._prefill_fns: dict[int, Any] = {}
        #: decode programs keyed by kv read limit (None = full buffer).
        #: Decode is bandwidth-bound and reads the whole cache prefix it
        #: attends; when every active slot sits far below capacity, a
        #: bucketed program reading only cache[:limit] skips the dead
        #: bytes. Geometric buckets bound the program count.
        self._decode_fns: dict[int | None, Any] = {}
        self._kv_buckets = tuple(
            b for b in (128, 256, 512, 1024, 2048, 4096, 8192)
            if b < self.max_seq)
        # aggregate counters for /healthz-style introspection
        # ALL keys pre-seeded: /healthz **-unpacks this dict from other
        # threads, and inserting a key mid-iteration raises RuntimeError
        self.stats = {"completed": 0, "decode_chunks": 0, "prefills": 0,
                      "wasted_steps": 0, "emitted_tokens": 0,
                      "bucketed_chunks": 0, "accepted_tokens": 0,
                      "prefix_hits": 0, "segment_prefills": 0,
                      "prefix_bytes": 0}
        #: per-request (ttft, mean_itl) ring for latency_stats(); the
        #: serve layer additionally points ``metrics_hook`` at the
        #: Prometheus registry (ttft, itl, n_tokens per completion)
        self._lat_samples: collections.deque = collections.deque(
            maxlen=512)
        self.metrics_hook = None

    def _cached_forward(self):
        """The family's KV-cached forward (llama/moe). The encdec
        engine overrides — its decode body lives in models/encdec.py
        with a different signature."""
        return cached_forward_fn(self.cfg)

    def _default_buckets(self) -> tuple[int, ...]:
        return _default_buckets(self.max_seq)

    def _check_buckets(self) -> None:
        """Prompt buckets must fit the decode cache — prompts and
        generated tokens share positions. The encdec engine overrides:
        its prompts are SOURCE tokens with their own capacity."""
        if self.buckets[-1] > self.max_seq:
            raise ValueError(
                f"largest bucket {self.buckets[-1]} exceeds cache "
                f"capacity {self.max_seq}")

    def _alloc_cache(self, cache_dtype):
        """The big per-slot KV buffers — dense (slots, max_seq) here;
        the paged engine (infer/paged.py) overrides with a page pool.
        Slots stay REPLICATED (engine.CACHE_SPEC would shard them over
        dp/fsdp); only the kv-head dim shards, over tp."""
        cache = init_kv_cache(
            self.cfg, self.slots, self.max_seq, mesh=self.mesh,
            dtype=cache_dtype, spec=P(None, None, None, "tp", None))
        return cache.k, cache.v

    # ---- compiled programs -------------------------------------------------

    @staticmethod
    def _sample(logits, temp, key):
        """(S, vocab) f32 logits + per-slot temperature → (S,) int32.
        Gumbel-argmax is an exact categorical draw at temperature T;
        T == 0 rows take the plain argmax (token-exact greedy)."""
        g = jax.random.gumbel(key, logits.shape, logits.dtype)
        z = jnp.where(temp[:, None] > 0,
                      logits / jnp.maximum(temp, 1e-6)[:, None] + g,
                      logits)
        return jnp.argmax(z, axis=-1).astype(jnp.int32)

    @staticmethod
    def _sample_filtered(logits, temp, topk, topp, key):
        """Per-slot top-k/top-p sampling with TRACED k and p — the
        variant compiled only for chunks with a filtered slot active (it
        pays one (S, vocab) descending sort per step). Mirrors
        infer/sampling.py's semantics exactly: temperature scale, then
        value-based top-k mask, then nucleus filtering of the
        (k-masked) sorted distribution, then an exact categorical draw
        (Gumbel-argmax). temp == 0 rows stay plain argmax."""
        neg = jnp.float32(-1e30)
        V = logits.shape[-1]
        z = logits.astype(jnp.float32) / jnp.maximum(temp, 1e-6)[:, None]
        zs = -jnp.sort(-z, axis=-1)                       # descending
        has_k = (topk > 0)[:, None]
        kth = jnp.take_along_axis(
            zs, jnp.clip(topk[:, None] - 1, 0, V - 1), axis=1)
        z1 = jnp.where(has_k & (z < kth), neg, z)
        zs1 = jnp.where(has_k & (zs < kth), neg, zs)      # same multiset
        probs = jax.nn.softmax(zs1, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < topp[:, None]              # first always kept
        threshold = jnp.min(jnp.where(keep, zs1, jnp.inf), axis=-1,
                            keepdims=True)
        z2 = jnp.where(z1 < threshold, neg, z1)
        g = jax.random.gumbel(key, z2.shape, z2.dtype)
        return jnp.where(temp > 0,
                         jnp.argmax(z2 + g, axis=-1),
                         jnp.argmax(logits, axis=-1)).astype(jnp.int32)

    def _prefill_fn(self, bucket: int, rows: int = 1):
        """Batched prefill program: ``rows`` prompts (same bucket) in ONE
        forward + ONE dispatch. An admission burst of N batch-1 prefills
        pays N dispatch latencies on an under-filled MXU; grouping
        same-bucket admissions into power-of-two row batches collapses
        both (a group of 5 runs as 4+1 — no padding rows)."""
        fn = self._prefill_fns.get((bucket, rows))
        if fn is not None:
            return fn
        cfg, fwd = self.cfg, self._fwd
        cache_dtype = self._k.dtype

        def prefill(params, prompts, actual_lens, slots, temps, topks,
                    topps, seed, k_all, v_all, dtok, dpos, dtemp, dtopk,
                    dtopp):
            # prompts (R, bucket); per-row vectors (R,). The per-row
            # last_only index keeps the head at (R, 1, vocab) — the full
            # (R, bucket, vocab) f32 logits would be GBs at 8B shapes
            shape = (cfg.n_layers, rows, bucket, cfg.n_kv_heads,
                     cfg.head_dim)
            kc = jnp.zeros(shape, cache_dtype)
            vc = jnp.zeros(shape, cache_dtype)
            logits, kc, vc = fwd(params, prompts, cfg, kc, vc,
                                 jnp.int32(0), self.mesh,
                                 last_only=actual_lens - 1)
            toks = self._sample_filtered(
                logits[:, 0], temps, topks, topps,
                jax.random.PRNGKey(seed))
            # drop each row's bucket-length cache into its slot row
            k_all = k_all.at[:, slots, :bucket].set(kc)
            v_all = v_all.at[:, slots, :bucket].set(vc)
            dtok = dtok.at[slots].set(toks)
            dpos = dpos.at[slots].set(actual_lens)
            dtemp = dtemp.at[slots].set(temps)
            dtopk = dtopk.at[slots].set(topks)
            dtopp = dtopp.at[slots].set(topps)
            return toks, k_all, v_all, dtok, dpos, dtemp, dtopk, dtopp

        fn = jax.jit(prefill, donate_argnums=(8, 9, 10, 11, 12, 13, 14))
        self._prefill_fns[(bucket, rows)] = fn
        return fn

    def _prefix_fn(self, bucket: int):
        """Program that prefills ONE prefix row into a fresh bucket-length
        cache and returns the (layers, bucket, kv, head_dim) pair — the
        registration-time half of prefix caching."""
        fn = self._prefix_fns.get(bucket)
        if fn is not None:
            return fn
        cfg, fwd = self.cfg, self._fwd
        cache_dtype = self._k.dtype

        def build(params, prompt):  # prompt (1, bucket)
            shape = (cfg.n_layers, 1, bucket, cfg.n_kv_heads, cfg.head_dim)
            kc = jnp.zeros(shape, cache_dtype)
            vc = jnp.zeros(shape, cache_dtype)
            _, kc, vc = fwd(params, prompt, cfg, kc, vc, jnp.int32(0),
                            self.mesh, last_only=True)
            return kc[:, 0], vc[:, 0]

        fn = jax.jit(build)
        self._prefix_fns[bucket] = fn
        return fn

    def _px_prefill_fn(self, pbucket: int, sbucket: int, rows: int = 1):
        """Suffix-only batched prefill: the cached prefix k/v land in the
        row cache first, then the suffix forward runs at the traced
        absolute position ``plen`` (rope phases and the causal q_offset
        mask are position-derived, so the math is identical to a full
        prefill of prefix+suffix — the prefix FLOPs are just skipped).
        Prefix-pad garbage in (plen, pbucket) is at future positions of
        every suffix query and is overwritten just-in-time by decode."""
        fn = self._px_prefill_fns.get((pbucket, sbucket, rows))
        if fn is not None:
            return fn
        cfg, fwd = self.cfg, self._fwd
        cache_dtype = self._k.dtype

        tsize = min(pbucket + sbucket, self.max_seq)

        def prefill(params, pk, pv, plen, prompts, actual_lens, slots,
                    temps, topks, topps, seed, k_all, v_all, dtok, dpos,
                    dtemp, dtopk, dtopp):
            # prompts (R, sbucket) = SUFFIX tokens; actual_lens (R,) =
            # suffix lengths; plen = the prefix's true token count.
            # The temp cache is clamped to max_seq (a near-capacity
            # prefix + a rounded-up suffix bucket can nominally overrun
            # it); start_pos rides as a PER-ROW vector so the cache
            # writes take the scatter path with mode="drop" — pad-tail
            # positions past capacity drop silently instead of the
            # scalar dynamic_update_slice CLAMPING the whole block back
            # into bounds (which would corrupt real positions).
            shape = (cfg.n_layers, rows, tsize,
                     cfg.n_kv_heads, cfg.head_dim)
            # pbucket <= tsize always: pbucket <= max_seq (registration
            # bucket list) and pbucket <= pbucket + sbucket
            kc = jnp.zeros(shape, cache_dtype).at[:, :, :pbucket].set(
                pk[:, None])
            vc = jnp.zeros(shape, cache_dtype).at[:, :, :pbucket].set(
                pv[:, None])
            starts = jnp.full((rows,), plen, jnp.int32)
            logits, kc, vc = fwd(params, prompts, cfg, kc, vc, starts,
                                 self.mesh, last_only=actual_lens - 1)
            toks = self._sample_filtered(
                logits[:, 0], temps, topks, topps,
                jax.random.PRNGKey(seed))
            k_all = k_all.at[:, slots, :tsize].set(kc)
            v_all = v_all.at[:, slots, :tsize].set(vc)
            dtok = dtok.at[slots].set(toks)
            dpos = dpos.at[slots].set(plen + actual_lens)
            dtemp = dtemp.at[slots].set(temps)
            dtopk = dtopk.at[slots].set(topks)
            dtopp = dtopp.at[slots].set(topps)
            return toks, k_all, v_all, dtok, dpos, dtemp, dtopk, dtopp

        fn = jax.jit(prefill,
                     donate_argnums=(11, 12, 13, 14, 15, 16, 17))
        self._px_prefill_fns[(pbucket, sbucket, rows)] = fn
        return fn

    def _seg_prefill_fn(self, bucket: int, final: bool,
                        kv_limit: int | None = None):
        """One chunked-prefill SEGMENT for one slot: slice the slot's
        cache row out, run the cached forward at the segment's absolute
        offset (per-row vector start → scatter writes, pad tail drops),
        write the row back. Non-final segments park the slot's decode
        position at ``max_seq`` so interleaved decode chunks' writes for
        this row drop harmlessly; the FINAL segment samples the first
        token and arms the real decode state — from then on the slot is
        indistinguishable from a whole-prompt admission. ``kv_limit``
        (geometric bucket >= the segment's reach) keeps each segment's
        attention from reading the slot's full max_seq row — without it
        an N-token prompt in K-token segments pays ~(N/K)× the
        whole-prompt admission's cache reads."""
        key = ("seg", bucket, final, kv_limit)
        fn = self._px_prefill_fns.get(key)
        if fn is not None:
            return fn
        cfg, fwd = self.cfg, self._fwd
        park = jnp.int32(self.max_seq)

        def seg(params, tokens, actual_len, slot, start, temp, topk,
                topp, seed, k_all, v_all, dtok, dpos, dtemp, dtopk,
                dtopp):
            # tokens (1, bucket); actual_len/slot/start scalars
            kr = lax.dynamic_slice_in_dim(k_all, slot, 1, axis=1)
            vr = lax.dynamic_slice_in_dim(v_all, slot, 1, axis=1)
            logits, kr, vr = fwd(params, tokens, cfg, kr, vr,
                                 start[None], self.mesh,
                                 last_only=actual_len[None] - 1,
                                 kv_limit=kv_limit)
            k_all = lax.dynamic_update_slice_in_dim(k_all, kr, slot,
                                                    axis=1)
            v_all = lax.dynamic_update_slice_in_dim(v_all, vr, slot,
                                                    axis=1)
            if final:
                toks = self._sample_filtered(
                    logits[:, 0], temp[None], topk[None], topp[None],
                    jax.random.PRNGKey(seed))
                dtok = dtok.at[slot].set(toks[0])
                dpos = dpos.at[slot].set(start + actual_len)
                dtemp = dtemp.at[slot].set(temp)
                dtopk = dtopk.at[slot].set(topk)
                dtopp = dtopp.at[slot].set(topp)
            else:
                toks = jnp.zeros((1,), jnp.int32)
                dpos = dpos.at[slot].set(park)
            return toks, k_all, v_all, dtok, dpos, dtemp, dtopk, dtopp

        fn = jax.jit(seg, donate_argnums=(9, 10, 11, 12, 13, 14, 15))
        self._px_prefill_fns[key] = fn
        return fn

    def _decode(self, kv_limit: int | None = None, filtered: bool = False):
        fn = self._decode_fns.get((kv_limit, filtered))
        if fn is not None:
            return fn
        cfg, fwd, K = self.cfg, self._fwd, self.chunk

        def decode_chunk(params, seed, dtok, dpos, dtemp, dtopk, dtopp,
                         k_all, v_all):
            def body(carry, step_key):
                tok, pos, k_all, v_all = carry
                logits, k_all, v_all = fwd(
                    params, tok[:, None], cfg, k_all, v_all, pos,
                    self.mesh, kv_limit=kv_limit)
                if filtered:  # any active slot needs top-k/top-p: pay
                    # the per-step (S, vocab) sort in this variant only
                    nxt = self._sample_filtered(
                        logits[:, -1], dtemp, dtopk, dtopp, step_key)
                else:
                    nxt = self._sample(logits[:, -1], dtemp, step_key)
                return (nxt, pos + 1, k_all, v_all), nxt

            keys = jax.random.split(jax.random.PRNGKey(seed), K)
            (tok, pos, k_all, v_all), out = lax.scan(
                body, (dtok, dpos, k_all, v_all), keys)
            # column 0 = the INPUT token (a fresh slot's prefill token —
            # saves the host a separate scalar fetch), columns 1..K = new
            out_full = jnp.concatenate([dtok[:, None], out.T], axis=1)
            return out_full, tok, pos, k_all, v_all  # out: (S, K+1)

        fn = jax.jit(decode_chunk, donate_argnums=(2, 3, 7, 8))
        self._decode_fns[(kv_limit, filtered)] = fn
        return fn

    @staticmethod
    def _reach_bound(active, chunk: int) -> int:
        """Highest cache position the NEXT chunk can touch across
        ``active`` slots — derived from dispatch counts, not processed
        state (the host lags by the pipeline depth). THE bound behind
        both the dense engine's kv read buckets and the paged engine's
        table width (infer/paged.py)."""
        return max(st.base_len + (st.dispatched + 1) * chunk
                   for st in active.values())

    def _kv_limit_for_chunk(self, active) -> int | None:
        """Smallest geometric bucket covering every position the NEXT
        chunk can touch, or None (full buffer)."""
        if not self._kv_buckets:
            return None
        bound = self._reach_bound(active, self.chunk)
        for b in self._kv_buckets:
            if b >= bound:
                return b
        return None

    def warmup(self, buckets: tuple[int, ...] | None = None,
               rows: tuple[int, ...] = (1,)) -> None:
        """Actually compile the decode chunk and the given (default: all)
        prefill buckets by running them on dummy data — ``jax.jit`` alone
        compiles nothing until the first call, and a mid-service compile
        on the engine thread stalls every active slot for its duration.
        Pass ``buckets=()`` to warm only the decode chunk (the program
        every request shares). ``rows`` warms the batched-admission
        prefill variants too — a same-bucket burst of N requests runs a
        power-of-two row-batched program per (bucket, R) pair, each a
        one-time mid-service stall if cold. Call BEFORE :meth:`start` —
        this runs dispatches on the caller's thread and scribbles
        garbage into the (empty) cache, which admission overwrites."""
        if self._thread is not None:
            raise RuntimeError("warmup must run before start()")
        for b in (self.buckets if buckets is None else buckets):
            for R in sorted({min(r, self.slots) for r in rows}):
                (_, self._k, self._v, self._dtok, self._dpos, self._dtemp,
                 self._dtopk, self._dtopp) = self._prefill_fn(b, R)(
                    self.params, np.zeros((R, b), np.int32),
                    np.ones((R,), np.int32),
                    np.arange(R, dtype=np.int32),
                    np.zeros((R,), np.float32), np.zeros((R,), np.int32),
                    np.ones((R,), np.float32), np.uint32(0),
                    self._k, self._v, self._dtok, self._dpos, self._dtemp,
                    self._dtopk, self._dtopp)
        _, self._dtok, self._dpos, self._k, self._v = self._decode()(
            self.params, np.uint32(0), self._dtok, self._dpos, self._dtemp,
            self._dtopk, self._dtopp, self._k, self._v)

    # ---- prefix cache ------------------------------------------------------

    def register_prefix(self, tokens: list[int]) -> str:
        """Prefill ``tokens`` once and register them as a shared prompt
        prefix; returns the prefix id. Subsequent submits whose prompt
        STRICTLY starts with these tokens (at least one suffix token)
        prefill only the suffix. Registering an already-registered token
        sequence returns the existing id. Costs one compile per new
        prefix-bucket size plus one per (pbucket, sbucket, rows) combo at
        first matched admission — register before :meth:`start` (or
        accept the one-time mid-service stall)."""
        tokens = list(tokens)
        if not tokens:
            raise ValueError("prefix must be non-empty")
        if len(tokens) + 2 > self.max_seq:
            # a usable prefix needs >= 1 suffix token + >= 1 generated
            raise ValueError(
                f"prefix ({len(tokens)}) leaves no room for a suffix and "
                f"a generated token in cache capacity {self.max_seq}")
        bucket = next((b for b in self.buckets if b >= len(tokens)), None)
        if bucket is None:
            raise ValueError(
                f"prefix ({len(tokens)}) exceeds the largest prefill "
                f"bucket ({self.buckets[-1]})")
        with self._px_lock:
            key = tuple(tokens)
            with self._lock:
                if self._closed:
                    raise RuntimeError("engine is closed")
                if self._dead is not None:
                    raise RuntimeError(f"engine failed: {self._dead!r}")
                for ent in self._prefixes.values():
                    if ent.tokens == key:
                        return ent.pid
                if len(self._prefixes) >= self.max_prefixes:
                    raise ValueError(
                        f"prefix registry full ({self.max_prefixes}) — "
                        f"unregister one first")
                nbytes = (2 * self.cfg.n_layers * bucket
                          * self.cfg.n_kv_heads * self.cfg.head_dim
                          * self._k.dtype.itemsize)
                if (self.max_prefix_bytes
                        and self.stats["prefix_bytes"] + nbytes
                        > self.max_prefix_bytes):
                    raise ValueError(
                        f"prefix K/V ({nbytes} B) would exceed the "
                        f"registry byte budget ({self.max_prefix_bytes} B;"
                        f" {self.stats['prefix_bytes']} B registered) — "
                        f"unregister one first")
                self._px_seq += 1
                pid = f"px-{self._px_seq}"
            prompt = np.full((1, bucket), self.pad_id, np.int32)
            prompt[0, :len(tokens)] = tokens
            k, v = self._prefix_fn(bucket)(self.params, prompt)
            ent = _Prefix(pid=pid, tokens=key, length=len(tokens),
                          bucket=bucket, k=k, v=v, nbytes=nbytes)
            with self._lock:
                self._prefixes[pid] = ent
                self.stats["prefix_bytes"] += nbytes
            return pid

    def unregister_prefix(self, pid: str) -> bool:
        with self._px_lock, self._lock:
            ent = self._prefixes.pop(pid, None)
            if ent is not None:
                self.stats["prefix_bytes"] -= ent.nbytes
            return ent is not None

    def prefixes(self) -> list[dict]:
        """Snapshot of the registry for introspection (serve GET)."""
        with self._lock:
            return [{"id": p.pid, "length": p.length, "bytes": p.nbytes}
                    for p in self._prefixes.values()]

    def _resolve_prefix(self, prompt: list[int]) -> _Prefix | None:
        """Longest registered STRICT prefix of ``prompt`` (identity holds
        even if unregistered concurrently — the arrays are immutable)."""
        best = None
        with self._lock:
            for ent in self._prefixes.values():
                if (ent.length < len(prompt)
                        and (best is None or ent.length > best.length)
                        and tuple(prompt[:ent.length]) == ent.tokens):
                    best = ent
        return best

    def _px_plan(self, prompt: list[int]) -> tuple[_Prefix, int] | None:
        """(prefix, suffix_bucket) if a registered prefix applies to this
        prompt. The temp-cache size is clamped to capacity inside the
        program (pad-tail writes drop), so the only structural limit is
        that the suffix fits a prefill bucket; absolute capacity
        (prompt + max_new) is validate()'s job."""
        ent = self._resolve_prefix(prompt)
        if ent is None:
            return None
        sfx = len(prompt) - ent.length
        sbucket = next((b for b in self.buckets if b >= sfx), None)
        if sbucket is None:
            return None
        return ent, sbucket

    # ---- request API -------------------------------------------------------

    def validate(self, prompt: list[int], max_new: int,
                 top_k: int = 0, top_p: float = 1.0) -> None:
        """The submit-time request checks WITHOUT queueing — callers with
        multi-request bodies validate every request up front so a bad
        later row can't orphan earlier rows into the engine."""
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        n = len(prompt)
        if n < 1:
            raise ValueError("prompt must be non-empty")
        if (n > self.buckets[-1] and not self.prefill_chunk
                and self._px_plan(prompt) is None):
            # two ways past the bucket ceiling: a registered prefix
            # covering the overflow (suffix-only prefill), or chunked
            # prefill (segments clamp to the largest bucket, so ANY
            # length up to capacity admits). NB the admission-time px
            # re-resolve can still fall to a failed handle if the prefix
            # is unregistered in between
            raise ValueError(
                f"prompt ({n}) exceeds the largest prefill bucket "
                f"({self.buckets[-1]}) and no registered prefix covers it")
        if n + max_new - 1 > self.max_seq:
            raise ValueError(
                f"prompt ({n}) + max_new ({max_new}) exceeds cache "
                f"capacity {self.max_seq}")

    def has_room(self, n_requests: int = 1) -> bool:
        """Approximate admission-queue room for a batch of requests —
        checked before submitting a multi-row body so a QueueFull
        mid-list doesn't orphan the rows already queued (approximate:
        qsize races concurrent submitters, same caveat as max_pending)."""
        if not self.max_pending:
            return True
        return self._pending.qsize() + n_requests <= self.max_pending

    def submit(self, prompt: list[int], max_new: int,
               temperature: float = 0.0,
               eos_id: int | None = None,
               stream: bool = False,
               top_k: int = 0,
               top_p: float = 1.0) -> Handle:
        """Queue a request; returns a Handle resolving to
        {"tokens": [...], "length": n} (tokens truncated at eos,
        inclusive). ``eos_id`` overrides the engine default per request —
        the check is host-side, so mixed-eos slots share the compiled
        programs. ``top_k``/``top_p`` are per-slot traced values; chunks
        with any filtered slot active run the sort-based sampler variant.
        Raises ValueError for requests that can never fit (capacity is
        checked before queueing)."""
        handle = Handle(_stream=queue.SimpleQueue() if stream else None)
        handle.submitted_at = time.perf_counter()
        self.validate(prompt, max_new, top_k=top_k, top_p=top_p)
        # state check + put are ONE atomic section vs close()/_die():
        # a check-then-put window would let a racing shutdown drain the
        # queue between them and orphan this handle forever
        with self._lock:
            if self._closed or self._draining:
                raise RuntimeError("engine is closed")
            if self._dead is not None:
                raise RuntimeError(f"engine failed: {self._dead!r}")
            if (self.max_pending
                    and self._pending.qsize() >= self.max_pending):
                raise QueueFull(
                    f"admission queue at capacity ({self.max_pending})")
            self._pending.put((list(prompt), max_new, float(temperature),
                               self.eos_id if eos_id is None else eos_id,
                               int(top_k), float(top_p), handle))
        self._wake.set()
        return handle

    # ---- engine loop -------------------------------------------------------

    def _next_seed(self) -> np.uint32:
        """Per-dispatch RNG stream id: deterministic in the engine seed,
        derived on the host (no device ops)."""
        self._dispatches += 1
        return np.uint32((self._seed * 1000003 + self._dispatches)
                         % (2 ** 31))

    def _prefill_dispatch(self, bucket, R, prompts_np, lens, slots_v,
                          temps, topks, topps):
        """The engine-specific half of admission: ONE prefill dispatch
        for an R-row same-bucket group (updates the per-slot device
        state itself). Returns the device vector of first tokens.
        Overridden by :class:`SpeculativeSlotEngine` (which also fills
        the draft cache); the grouping/bookkeeping loop in ``_admit``
        is shared."""
        (toks, self._k, self._v, self._dtok, self._dpos,
         self._dtemp, self._dtopk,
         self._dtopp) = self._prefill_fn(bucket, R)(
            self.params, prompts_np, lens, slots_v, temps, topks, topps,
            self._next_seed(),
            self._k, self._v, self._dtok, self._dpos,
            self._dtemp, self._dtopk, self._dtopp)
        return toks

    def _px_prefill_dispatch(self, prefix, sbucket, R, prompts_np, lens,
                             slots_v, temps, topks, topps):
        """Suffix-only admission against a registered prefix: the cached
        k/v pair rides in as a (non-donated) operand and the suffix
        prefill starts at the prefix's true length."""
        (toks, self._k, self._v, self._dtok, self._dpos,
         self._dtemp, self._dtopk,
         self._dtopp) = self._px_prefill_fn(prefix.bucket, sbucket, R)(
            self.params, prefix.k, prefix.v,
            np.int32(prefix.length), prompts_np, lens, slots_v,
            temps, topks, topps, self._next_seed(),
            self._k, self._v, self._dtok, self._dpos,
            self._dtemp, self._dtopk, self._dtopp)
        return toks

    def _admit(self) -> bool:
        """Move pending requests into free slots. Same-bucket requests
        admit as power-of-two row batches through ONE prefill dispatch
        (which updates the per-slot device state itself) — fully async
        unless max_new == 1. Prompts matching a registered prefix group
        separately per (prefix, suffix-bucket) and run the suffix-only
        prefill. Returns True if anything was admitted."""
        admitted = False
        free = [i for i, s in self._table.items() if s is None]
        batch = []
        while len(batch) < len(free):
            try:
                batch.append(self._pending.get_nowait())
            except queue.Empty:
                break
        if not batch:
            return False
        # group key: (prefix-or-None, bucket). For prefix groups the
        # bucket is the SUFFIX bucket; the _Prefix object itself rides
        # the key (identity hash) so a concurrent unregister can't drop
        # the entry out from under the dispatch below.
        groups: dict[tuple, list] = {}
        for req in batch:
            prompt = req[0]
            plan = self._px_plan(prompt)
            if plan is not None and (
                    not self.prefill_chunk
                    or len(prompt) - plan[0].length <= self.prefill_chunk):
                # prefix hit with a SHORT suffix: the whole point of the
                # registry. A long suffix would break --prefill-chunk's
                # bounded-stall promise as one dispatch, so it falls
                # through to segmentation instead (redundant prefix
                # compute, bounded stalls — the flag's contract wins)
                groups.setdefault(plan, []).append(req)
                continue
            if self.prefill_chunk and (
                    len(prompt) > self.prefill_chunk
                    or len(prompt) > self.buckets[-1]):
                # chunked prefill: reserve the slot now; segments are
                # dispatched by _dispatch_segments, interleaved with
                # decode chunks (the slot joins decode after the final
                # segment arms its state)
                prompt, max_new, temp, eos_id, tk, tp, handle = req
                st = _Slot(handle=handle, tokens=[], max_new=max_new,
                           pos=len(prompt), temperature=temp,
                           eos_id=eos_id, top_k=tk, top_p=tp,
                           base_len=len(prompt), pending=list(prompt))
                with self._lock:
                    self._table[free.pop()] = st
                admitted = True
                continue
            bucket = next((b for b in self.buckets if b >= len(prompt)),
                          None)
            if bucket is None:
                # admitted past validate() via a prefix unregistered in
                # between — fail the handle, not the engine loop
                req[-1]._fail(ValueError(
                    f"prompt ({len(prompt)}) exceeds the largest prefill "
                    f"bucket and its covering prefix is gone"))
                continue
            groups.setdefault((None, bucket), []).append(req)
        for (prefix, bucket), reqs in groups.items():
            plen = prefix.length if prefix is not None else 0
            while reqs:
                R = 1
                while R * 2 <= len(reqs) and R * 2 <= self.slots:
                    R *= 2
                group, reqs = reqs[:R], reqs[R:]
                slots_v = [free.pop() for _ in group]
                prompts_np = np.full((R, bucket), self.pad_id, np.int32)
                lens = np.empty((R,), np.int32)
                temps = np.empty((R,), np.float32)
                topks = np.empty((R,), np.int32)
                topps = np.empty((R,), np.float32)
                for r, (prompt, _mn, temp, _eos, tk, tp, _h) in enumerate(
                        group):
                    sfx = prompt[plen:]
                    prompts_np[r, :len(sfx)] = sfx
                    lens[r] = len(sfx)
                    temps[r], topks[r], topps[r] = temp, tk, tp
                if prefix is not None:
                    toks = self._px_prefill_dispatch(
                        prefix, bucket, R, prompts_np, lens,
                        np.asarray(slots_v, np.int32), temps, topks, topps)
                    self.stats["prefix_hits"] += R
                else:
                    toks = self._prefill_dispatch(
                        bucket, R, prompts_np, lens,
                        np.asarray(slots_v, np.int32), temps, topks, topps)
                self.stats["prefills"] += 1
                for r, (prompt, max_new, temp, eos_id, tk, tp,
                        handle) in enumerate(group):
                    st = self._new_slot(prompt, max_new, temp, eos_id,
                                        tk, tp, handle)
                    with self._lock:
                        self._table[slots_v[r]] = st
                    if max_new == 1:
                        self._finish_admission_only(slots_v[r], st,
                                                    toks, r)
                admitted = True
        return admitted

    def _new_slot(self, prompt, max_new, temp, eos_id, tk, tp,
                  handle) -> _Slot:
        """Slot bookkeeping for one admitted request. Decoder-only
        families start decode AFTER the prompt; the encdec engine
        overrides (decode starts at BOS/position 0, and the admission
        program samples no token)."""
        return _Slot(handle=handle, tokens=[], max_new=max_new,
                     pos=len(prompt), temperature=temp, eos_id=eos_id,
                     top_k=tk, top_p=tp, base_len=len(prompt))

    def _finish_admission_only(self, slot: int, st: _Slot, toks,
                               r: int) -> None:
        """max_new == 1 on a prefill-sampling family: the admission
        already produced the only token — resolve now (the one
        admission path that syncs). Families whose admission samples
        nothing (encdec) override to a no-op and take a decode chunk."""
        st.emit(int(toks[r]))
        st.fresh = False
        self._finish_if_done(slot, st)

    def _dispatch_segments(self) -> bool:
        """ONE prefill segment per engine step, round-robin across
        prefilling slots — so the bounded-stall guarantee (active
        streams wait at most one segment's compute per step) holds even
        when several long admissions prefill concurrently; the
        admissions themselves serialize against each other. Segment
        length additionally clamps to the largest prefill bucket, so a
        bucket always exists regardless of prefill_chunk/buckets
        interplay."""
        filling = [(i, st) for i, st in self._table.items()
                   if st is not None and st.pending is not None]
        if not filling:
            return False
        # rotate: pick the first prefilling slot past the last-served one
        start = getattr(self, "_seg_rr", -1)
        filling.sort(key=lambda p: (p[0] <= start, p[0]))
        for i, st in filling[:1]:
            self._seg_rr = i
            seg = st.pending[:min(self.prefill_chunk, self.buckets[-1])]
            final = len(seg) == len(st.pending)
            bucket = next(b for b in self.buckets if b >= len(seg))
            # read only the cache prefix this segment can attend
            reach = st.prefill_pos + bucket
            kvl = next((b for b in self._kv_buckets if b >= reach), None)
            tokens_np = np.full((1, bucket), self.pad_id, np.int32)
            tokens_np[0, :len(seg)] = seg
            (toks, self._k, self._v, self._dtok, self._dpos, self._dtemp,
             self._dtopk, self._dtopp) = self._seg_prefill_fn(
                bucket, final, kvl)(
                self.params, tokens_np, np.int32(len(seg)), np.int32(i),
                np.int32(st.prefill_pos), np.float32(st.temperature),
                np.int32(st.top_k), np.float32(st.top_p),
                self._next_seed(), self._k, self._v, self._dtok,
                self._dpos, self._dtemp, self._dtopk, self._dtopp)
            st.prefill_pos += len(seg)
            st.pending = st.pending[len(seg):] if not final else None
            self.stats["segment_prefills"] += 1
            if final:
                self.stats["prefills"] += 1
                if st.max_new == 1:
                    # nothing to decode (same sync path as _admit)
                    st.emit(int(toks[0]))
                    st.fresh = False
                    self._finish_if_done(i, st)
        return True

    def _finish_if_done(self, slot: int, st: _Slot) -> bool:
        hit_eos = st.eos_id is not None and st.tokens and (
            st.tokens[-1] == st.eos_id)
        if hit_eos or len(st.tokens) >= st.max_new:
            # stats + table BEFORE resolving the handle: the HTTP worker
            # it wakes may immediately read /healthz counters
            with self._lock:
                self._table[slot] = None
                self.stats["completed"] += 1
                self.stats["emitted_tokens"] += len(st.tokens)
            st.handle._complete(
                {"tokens": st.tokens, "length": len(st.tokens)})
            self._record_latency(st.handle, len(st.tokens))
            return True
        return False

    def _record_latency(self, handle: Handle, n_tokens: int) -> None:
        """Per-request SLO sample on completion (VERDICT r4 next #5):
        TTFT = submit → first host-resolved token; ITL = mean gap over
        the remaining tokens (chunk-granular by design — tokens resolve
        per processed chunk, so the MEAN is the cadence a client
        experiences, same definition as servebench.bench_tail_latency).
        Samples land in a bounded ring (engine-side percentiles for
        /healthz cross-checks) and fan out to ``metrics_hook`` — the
        serve layer points that at the Prometheus registry."""
        if handle.submitted_at is None or handle.first_token_at is None:
            return
        ttft = handle.first_token_at - handle.submitted_at
        itl = ((handle.completed_at - handle.first_token_at)
               / (n_tokens - 1)) if n_tokens > 1 else None
        with self._lock:
            self._lat_samples.append((ttft, itl))
        hook = self.metrics_hook
        if hook is not None:
            try:
                hook(ttft, itl, n_tokens)
            except Exception:  # a metrics sink must never kill serving
                pass

    def reset_latency_stats(self) -> None:
        """Drop recorded samples (benchmarks call this after warmup so
        compile-time requests don't pollute measured percentiles)."""
        with self._lock:
            self._lat_samples.clear()

    def latency_stats(self) -> dict:
        """Engine-side percentiles over the last ``maxlen`` completed
        requests — the cross-check target for client-side tail-latency
        measurements and the /healthz SLO snapshot."""
        with self._lock:
            samples = list(self._lat_samples)
        ttfts = sorted(s[0] for s in samples)
        itls = sorted(s[1] for s in samples if s[1] is not None)

        def pct(xs, q):
            if not xs:
                return None
            i = min(len(xs) - 1, int(round(q / 100 * (len(xs) - 1))))
            return round(xs[i] * 1e3, 1)

        return {
            "n": len(samples),
            "ttft_p50_ms": pct(ttfts, 50), "ttft_p99_ms": pct(ttfts, 99),
            "itl_p50_ms": pct(itls, 50), "itl_p99_ms": pct(itls, 99),
        }

    def _decode_call_args(self) -> tuple:
        """Operands of one decode-chunk dispatch, in program order —
        the seam the encdec engine widens (its chunk also consumes the
        per-slot source lengths and the static cross-K/V pools)."""
        return (self.params, self._next_seed(), self._dtok, self._dpos,
                self._dtemp, self._dtopk, self._dtopp, self._k, self._v)

    def _select_decode(self, snap):
        """(compiled chunk program, kv read limit) for this dispatch —
        the seam the encdec engine widens with its cross-K/V read
        bucket."""
        limit = self._kv_limit_for_chunk(snap)
        filtered = any(s.top_k > 0 or s.top_p < 1.0
                       for s in snap.values())
        return self._decode(limit, filtered), limit

    def _dispatch_chunk(self) -> None:
        # prefilling slots are excluded: their decode lanes compute
        # garbage (writes drop at the parked position) and their tokens
        # must never be processed
        snap = {i: s for i, s in self._table.items()
                if s is not None and s.pending is None}
        fn, limit = self._select_decode(snap)
        out, self._dtok, self._dpos, self._k, self._v = fn(
            *self._decode_call_args())
        for st in snap.values():
            st.dispatched += 1
        # start the device→host copy now: by the time this chunk is
        # processed (``pipeline`` chunks later) the tokens are already on
        # the host, so the fetch doesn't stall the dispatch loop for a
        # tunnel round-trip (~100 ms — 2x a whole chunk's compute)
        out.copy_to_host_async()
        self._outstanding.append((snap, out))
        self.stats["decode_chunks"] += 1
        if limit is not None:
            self.stats["bucketed_chunks"] += 1

    def _process_oldest(self) -> None:
        """Host-side half of one chunk: fetch its tokens (the only sync in
        the steady state) and distribute them to the slots that were
        active at its dispatch; complete/free slots that hit eos or
        max_new. Slots freed by an EARLIER chunk are skipped by identity
        (the snapshot holds the _Slot object, not just the index)."""
        snap, out = self._outstanding.popleft()
        out = np.asarray(out)  # (S, K+1); column 0 is the chunk's input
        for i, st in snap.items():
            if self._table.get(i) is not st:
                continue  # completed in an earlier chunk; this is garbage
            start = 0 if st.fresh else 1  # col 0: prefill token, once
            st.fresh = False
            st.pos += self.chunk
            for j in range(start, self.chunk + 1):
                st.emit(int(out[i, j]))
                if self._finish_if_done(i, st):
                    self.stats["wasted_steps"] += self.chunk - j
                    break

    def step(self) -> bool:
        """One engine iteration: admit pending requests, dispatch one
        decode chunk if any slot is active, and process chunk outputs at
        the pipeline lag (drain fully when idle). Returns True if any
        work was done. Tests drive this directly; the background thread
        loops it."""
        did = False
        # a waiting request with no free slot: process ONE outstanding
        # chunk (completions hide in them, and admission latency beats
        # pipeline depth) — but only one per step, or sustained load
        # would collapse the pipeline to fully-synchronous exactly when
        # it matters most (each chunk paying the ~100 ms fetch serially)
        if not self._pending.empty() and not any(
                s is None for s in self._table.values()):
            if self._outstanding:
                self._process_oldest()
                did = True
        did = self._admit() or did
        did = self._dispatch_segments() or did
        active = any(s is not None and s.pending is None
                     for s in self._table.values())
        if active:
            self._dispatch_chunk()
            did = True
        lag = self.pipeline if active else 0
        while len(self._outstanding) > lag:
            self._process_oldest()
            did = True
        return did

    def _loop(self) -> None:
        try:
            while not self._closed:
                try:
                    if not self.step():
                        if self._draining and self._pending.empty():
                            # quiescence is decided HERE, between whole
                            # steps — an outside poll of table/queue
                            # state would race the admission window
                            # (popped from pending, not yet in table)
                            return
                        self._wake.clear()
                        self._wake.wait(timeout=0.05)
                except Exception as e:  # noqa: BLE001 — a dead engine
                    # thread must not leave clients hanging on 10-minute
                    # timeouts: fail every in-flight and queued handle,
                    # mark the engine dead so submit() rejects fast
                    self._die(e)
                    return
        finally:
            # every exit path must release a drain waiter
            self._drained.set()

    def _die(self, err: Exception) -> None:
        with self._lock:
            self._dead = err
            for i, s in self._table.items():
                if s is not None:
                    s.handle._fail(RuntimeError(f"engine failed: {err!r}"))
                    self._table[i] = None
        while True:
            try:
                *_, handle = self._pending.get_nowait()
            except queue.Empty:
                break
            handle._fail(RuntimeError(f"engine failed: {err!r}"))

    @property
    def dead(self) -> str | None:
        """repr of the error that killed the engine loop, or None."""
        return repr(self._dead) if self._dead is not None else None

    def start(self) -> "SlotEngine":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="slot-engine")
            self._thread.start()
        return self

    def close(self, drain: float = 0.0) -> None:
        """Stop the engine. ``drain`` seconds > 0: reject new submits but
        keep decoding until in-flight requests complete (or the deadline
        passes) — the SIGTERM path for serving; 0: fail everything in
        flight immediately."""
        if drain > 0 and self._thread is not None and self._dead is None:
            with self._lock:
                self._draining = True
            self._wake.set()
            self._drained.wait(timeout=drain)
        with self._lock:
            self._closed = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # fail anything still queued or in flight so callers don't hang
        while True:
            try:
                *_, handle = self._pending.get_nowait()
            except queue.Empty:
                break
            handle._fail(RuntimeError("engine closed"))
        for i, s in list(self._table.items()):
            if s is not None:
                s.handle._fail(RuntimeError("engine closed"))
                self._table[i] = None
        self._outstanding.clear()

    def __enter__(self) -> "SlotEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


class SpeculativeSlotEngine(SlotEngine):
    """Continuous batching × speculative decoding (greedy): every engine
    round, a small DRAFT model proposes ``n_spec`` tokens per slot
    autoregressively, and the TARGET verifies all of them in ONE forward
    of (slots, n_spec+1) tokens — the per-row multi-token cached forward
    (vector ``start_pos`` with seq > 1) the slot machinery already
    supports. Accepted prefix + the target's own correction token emit
    per round, so a slot advances 1..n_spec+1 positions per dispatch.

    Exactness: greedy speculative verification is token-exact vs plain
    greedy decode REGARDLESS of draft quality (a bad draft only costs
    speed) — tests/test_slots.py proves it with a garbage draft. The
    rollback story is the same just-in-time-overwrite argument as the
    base engine: rejected positions' k/v (in both caches) are rewritten
    by the round that legitimately crosses them, before the causal mask
    lets anything attend them.

    Greedy-only (temperature/top-k/top-p submits are rejected) and
    single-device for now; decode reads are unbucketed (verify reads
    scale with n_spec, not chunk)."""

    def __init__(self, cfg, params, *, draft_cfg, draft_params,
                 n_spec: int = 4, **kwargs):
        if kwargs.get("mesh") is not None:
            raise ValueError("speculative slots are single-device for now")
        if kwargs.get("prefill_chunk"):
            raise ValueError(
                "chunked prefill is not supported on the speculative "
                "engine (segments fill the target cache only)")
        if n_spec < 1:
            raise ValueError(f"n_spec must be >= 1, got {n_spec}")
        # chunk drives the position-bound math (a round advances at most
        # n_spec+1) and the host emit loop's column count
        kwargs["chunk"] = n_spec + 1
        super().__init__(cfg, params, **kwargs)
        self.n_spec = n_spec
        self.draft_cfg = draft_cfg
        self.draft_params = draft_params
        self._dfwd = cached_forward_fn(draft_cfg)
        dcache = init_kv_cache(draft_cfg, self.slots, self.max_seq,
                               mesh=None, dtype=self._k.dtype)
        self._dk, self._dv = dcache.k, dcache.v
        self._kv_buckets = ()  # verify reads stay unbucketed

    def submit(self, prompt, max_new, temperature=0.0, eos_id=None,
               stream=False, top_k=0, top_p=1.0):
        if temperature != 0.0 or top_k != 0 or top_p != 1.0:
            raise ValueError(
                "speculative slots are greedy-only (temperature 0, no "
                "top-k/top-p)")
        return super().submit(prompt, max_new, 0.0, eos_id=eos_id,
                              stream=stream)

    def register_prefix(self, tokens):
        # the suffix-only prefill fills the TARGET cache only; a draft
        # cache left unfilled would silently collapse acceptance
        raise ValueError(
            "prefix caching is not supported on the speculative engine")

    # ---- compiled programs -------------------------------------------------

    def _prefill_fn(self, bucket: int, rows: int = 1):
        """Batched prefill that fills BOTH caches: the target's (and its
        first sampled token) exactly like the base engine, plus the
        draft's — the draft's next proposal round must attend the full
        prompt prefix."""
        fn = self._prefill_fns.get((bucket, rows))
        if fn is not None:
            return fn
        cfg, dcfg = self.cfg, self.draft_cfg
        fwd, dfwd = self._fwd, self._dfwd
        cache_dtype = self._k.dtype

        def prefill(params, dparams, prompts, actual_lens, slots,
                    k_all, v_all, dk_all, dv_all, dtok, dpos):
            shape = (cfg.n_layers, rows, bucket, cfg.n_kv_heads,
                     cfg.head_dim)
            kc = jnp.zeros(shape, cache_dtype)
            vc = jnp.zeros(shape, cache_dtype)
            logits, kc, vc = fwd(params, prompts, cfg, kc, vc,
                                 jnp.int32(0), None,
                                 last_only=actual_lens - 1)
            toks = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            dshape = (dcfg.n_layers, rows, bucket, dcfg.n_kv_heads,
                      dcfg.head_dim)
            dkc = jnp.zeros(dshape, cache_dtype)
            dvc = jnp.zeros(dshape, cache_dtype)
            _, dkc, dvc = dfwd(dparams, prompts, dcfg, dkc, dvc,
                               jnp.int32(0), None, last_only=True)
            k_all = k_all.at[:, slots, :bucket].set(kc)
            v_all = v_all.at[:, slots, :bucket].set(vc)
            dk_all = dk_all.at[:, slots, :bucket].set(dkc)
            dv_all = dv_all.at[:, slots, :bucket].set(dvc)
            dtok = dtok.at[slots].set(toks)
            dpos = dpos.at[slots].set(actual_lens)
            return toks, k_all, v_all, dk_all, dv_all, dtok, dpos

        fn = jax.jit(prefill, donate_argnums=(5, 6, 7, 8, 9, 10))
        self._prefill_fns[(bucket, rows)] = fn
        return fn

    def _spec_round_fn(self):
        fn = self._decode_fns.get("spec")
        if fn is not None:
            return fn
        cfg, dcfg, K = self.cfg, self.draft_cfg, self.n_spec
        fwd, dfwd = self._fwd, self._dfwd
        pad = jnp.int32(self.pad_id)

        def spec_round(params, dparams, dtok, dpos, k_all, v_all,
                       dk_all, dv_all):
            # 1. draft proposes K tokens per slot (its cache fills
            # dpos..dpos+K-1 with [dtok, p0..p_{K-2}])
            def dbody(carry, _):
                tok, pos, dk, dv = carry
                lg, dk, dv = dfwd(dparams, tok[:, None], dcfg, dk, dv,
                                  pos, None)
                nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
                return (nxt, pos + 1, dk, dv), nxt

            (last_prop, _, dk_all, dv_all), props = lax.scan(
                dbody, (dtok, dpos, dk_all, dv_all), None, length=K)
            props = props.T  # (S, K)
            # feed the FINAL proposal once more so its k/v lands in the
            # draft cache at dpos+K: on a fully-accepted round the next
            # round starts PAST that position and would never rewrite it,
            # leaving a permanent garbage hole the draft attends forever
            # (acceptance collapses even for a perfect draft). On partial
            # acceptance this write sits at a future position and is
            # rewritten just-in-time like everything else.
            _, dk_all, dv_all = dfwd(dparams, last_prop[:, None], dcfg,
                                     dk_all, dv_all, dpos + K, None)

            # 2. target verifies all K+1 positions in ONE forward
            seq_in = jnp.concatenate([dtok[:, None], props], axis=1)
            logits, k_all, v_all = fwd(params, seq_in, cfg, k_all, v_all,
                                       dpos, None)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

            # 3. accepted prefix length + the target's correction token
            match = (props == greedy[:, :K]).astype(jnp.int32)
            n_acc = jnp.cumprod(match, axis=1).sum(axis=1)    # (S,)
            corr = jnp.take_along_axis(greedy, n_acc[:, None],
                                       axis=1)[:, 0]
            idx = jnp.arange(K + 1, dtype=jnp.int32)[None, :]
            props_ext = jnp.pad(props, ((0, 0), (0, 1)))
            newt = jnp.where(
                idx < n_acc[:, None], props_ext,
                jnp.where(idx == n_acc[:, None], corr[:, None], pad))
            out_full = jnp.concatenate([dtok[:, None], newt], axis=1)
            counts = n_acc + 1
            return (out_full, counts, corr, dpos + counts,
                    k_all, v_all, dk_all, dv_all)

        fn = jax.jit(spec_round, donate_argnums=(4, 5, 6, 7))
        self._decode_fns["spec"] = fn
        return fn

    def warmup(self, buckets=None, rows=(1,)):
        if self._thread is not None:
            raise RuntimeError("warmup must run before start()")
        for b in (self.buckets if buckets is None else buckets):
            for R in sorted({min(r, self.slots) for r in rows}):
                (_, self._k, self._v, self._dk, self._dv, self._dtok,
                 self._dpos) = self._prefill_fn(b, R)(
                    self.params, self.draft_params,
                    np.zeros((R, b), np.int32), np.ones((R,), np.int32),
                    np.arange(R, dtype=np.int32),
                    self._k, self._v, self._dk, self._dv,
                    self._dtok, self._dpos)
        (_, _, self._dtok, self._dpos, self._k, self._v, self._dk,
         self._dv) = self._spec_round_fn()(
            self.params, self.draft_params, self._dtok, self._dpos,
            self._k, self._v, self._dk, self._dv)

    # ---- engine loop overrides ---------------------------------------------

    def _prefill_dispatch(self, bucket, R, prompts_np, lens, slots_v,
                          temps, topks, topps):
        # speculative admission is greedy-only (submit enforces it), so
        # temps/topks/topps are ignored; the shared _admit loop in the
        # base class does all grouping/bookkeeping
        (toks, self._k, self._v, self._dk, self._dv, self._dtok,
         self._dpos) = self._prefill_fn(bucket, R)(
            self.params, self.draft_params, prompts_np, lens, slots_v,
            self._k, self._v, self._dk, self._dv,
            self._dtok, self._dpos)
        return toks

    def _dispatch_chunk(self) -> None:
        snap = {i: s for i, s in self._table.items() if s is not None}
        (out, counts, self._dtok, self._dpos, self._k, self._v,
         self._dk, self._dv) = self._spec_round_fn()(
            self.params, self.draft_params, self._dtok, self._dpos,
            self._k, self._v, self._dk, self._dv)
        for st in snap.values():
            st.dispatched += 1
        out.copy_to_host_async()
        counts.copy_to_host_async()
        self._outstanding.append((snap, (out, counts)))
        self.stats["decode_chunks"] += 1

    def _process_oldest(self) -> None:
        snap, (out, counts) = self._outstanding.popleft()
        out = np.asarray(out)        # (S, n_spec+2); col 0 = input token
        counts = np.asarray(counts)  # (S,) valid NEW tokens this round
        for i, st in snap.items():
            if self._table.get(i) is not st:
                continue
            start = 0 if st.fresh else 1
            st.fresh = False
            st.pos += int(counts[i])
            self.stats["accepted_tokens"] += int(counts[i]) - 1
            for j in range(start, 1 + int(counts[i])):
                st.emit(int(out[i, j]))
                if self._finish_if_done(i, st):
                    break
